#!/usr/bin/env python
"""Pretty-print flight-recorder artifacts (ISSUE 18 satellite).

A fleet process with ``ServeConfig.flightrec_dir`` set keeps a bounded
on-disk ring of its last spans + metric deltas
(``pyconsensus_tpu.obs.flightrec``), dumped at boot, fence, SIGTERM,
shutdown, and router takeovers — the artifacts a ``kill -9`` chaos run
leaves behind. This tool renders a directory of them for a human:

    python tools/flightrec_dump.py /var/log/fleet-flightrec/w0
    python tools/flightrec_dump.py /var/log/fleet-flightrec --all
    python tools/flightrec_dump.py DIR --json       # machine-readable

``--all`` recurses one level (the per-process subdirectories the fleet
lays out: ``router/``, ``w0/``, ...). Exit 0 if any artifact was
readable, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

__all__ = ["render_flight", "main"]


def render_flight(rec: dict) -> str:
    """One flight record -> human-readable text block."""
    lines = [f"=== {rec.get('_path', '<memory>')} ===",
             f"source={rec.get('source', '?')} "
             f"reason={rec.get('reason', '?')} seq={rec.get('seq', '?')}"]
    spans = rec.get("spans") or []
    lines.append(f"-- last {len(spans)} span(s) --")
    for sp in spans:
        dur = sp.get("duration_s")
        dur_txt = f"{dur * 1e3:9.3f}ms" if isinstance(dur, (int, float)) \
            else "         ?"
        trace = sp.get("trace_id")
        lines.append(
            "  " + "  " * int(sp.get("depth", 0))
            + f"{sp.get('name', '?')} {dur_txt} "
            + f"[{sp.get('status', '?')}]"
            + (f" trace={trace}" if trace else ""))
    deltas = rec.get("metric_deltas") or {}
    lines.append(f"-- {len(deltas)} metric delta(s) since previous "
                 f"dump --")
    for name in sorted(deltas):
        entry = deltas[name]
        kind = entry.get("kind", "?")
        series = entry.get("series") or {}
        for skey in sorted(series):
            d = series[skey]
            if kind == "histogram" and isinstance(d, dict):
                txt = (f"+{d.get('count', 0)} obs, "
                       f"+{d.get('sum', 0.0):.6g}s")
            else:
                txt = f"+{d}"
            lines.append(f"  {name}{skey or ''} ({kind}) {txt}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render flight-recorder dump directories "
                    "(obs.flightrec) for humans")
    ap.add_argument("dir", help="a flight-recorder directory (one "
                                "process's ring of flight-*.json)")
    ap.add_argument("--all", action="store_true", dest="recurse",
                    help="treat DIR as the fleet root and render every "
                         "per-process subdirectory (router/, w0/, ...)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the parsed records as one JSON array")
    args = ap.parse_args(argv)

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from pyconsensus_tpu.obs import read_flight_dir

    root = pathlib.Path(args.dir)
    dirs = ([p for p in sorted(root.iterdir()) if p.is_dir()]
            if args.recurse else [root])
    records: list = []
    for d in dirs:
        records.extend(read_flight_dir(d))
    if args.as_json:
        print(json.dumps(records, indent=2, sort_keys=True))
    else:
        for rec in records:
            print(render_flight(rec))
            print()
        print(f"{len(records)} flight record(s) from "
              f"{len(dirs)} director(ies)")
    return 0 if records else 1


if __name__ == "__main__":
    sys.exit(main())
