#!/usr/bin/env bash
# Round-5 (VERDICT r4 item 6): rehearse .github/workflows/ci.yml locally.
# This environment has no network, so the one step that cannot be
# rehearsed is the dependency FETCH — a plain venv gets a .pth into the
# SESSION environment's site-packages (see below: the session
# interpreter is itself a venv, so --system-site-packages would link to
# the bare base python) and the editable install runs --no-deps
# --no-build-isolation against the baked-in jax/flax/pytest stack.
# Everything else follows ci.yml verbatim: editable install, the full
# suite on the 8-virtual-device CPU mesh, the example smokes against the
# INSTALLED package, both CLI entry points, and the bench JSON contract.
set -euo pipefail
cd "$(dirname "$0")/.."

VENV=/tmp/ci-rehearsal-venv
rm -rf "$VENV"
python -m venv "$VENV"
PY="$VENV/bin/python"
# the session interpreter is ITSELF a venv, so --system-site-packages
# would link the rehearsal venv to the bare base python (no jax, no
# setuptools); a .pth into the session env's site-packages exposes the
# baked-in dependency stack instead
SITE=$("$PY" -c "import site; print(site.getsitepackages()[0])")
python - "$SITE" <<'PYEOF'
import site, sys, pathlib
pathlib.Path(sys.argv[1], "_session_env.pth").write_text(
    site.getsitepackages()[0] + "\n")
PYEOF

echo "=== Install (pip install -e ., --no-deps: no network) ==="
"$PY" -m pip install -e . --no-deps --no-build-isolation --quiet
"$PY" -c "import pyconsensus_tpu; print('installed', pyconsensus_tpu.__version__, pyconsensus_tpu.__file__)"

export JAX_PLATFORMS=cpu
export PALLAS_AXON_POOL_IPS=
export XLA_FLAGS=--xla_force_host_platform_device_count=8

echo "=== Lint (consensus-lint: AST rules + contracts + deadlock pass) ==="
# Layer 1 (JAX/TPU AST rules) + Layer 3a (interprocedural host-
# divergence taint, CL401-404) over the package, Layer 2 (collective
# inventory / f64 / host-callback / retrace contracts, compiled on the
# 8-virtual-device CPU mesh), Layer 3b (collective-schedule deadlock
# detection over the ring/fused/pipeline jaxprs, CL410-413),
# Layer 4 (host-concurrency: lock-order cycles, blocking-under-lock,
# guarded-by inference, fault-site drift, CL801-805), Layer 5
# (distributed protocol: durability-order happens-before, RPC surface
# drift, error-taxonomy soundness, idempotency threading, retry scope,
# CL901-905), and Layer 6 (bit determinism: order/completion/host-
# nondeterminism taint into digest/journal/artifact sinks, float-fold
# hazards, and the CL1005 compiled-artifact StableHLO pin + scatter
# scan inside the traced layer, CL1001-1005). Fails on any
# non-baselined finding or stale baseline entry; see
# docs/STATIC_ANALYSIS.md.
"$PY" -m pyconsensus_tpu.analysis --strict
# SARIF artifact (ISSUE 17 satellite): the SAME static gate re-emitted
# as SARIF 2.1.0 for code-scanning UIs — exit code must stay 0 on the
# clean tree and the payload must parse as the declared version
"$PY" -m pyconsensus_tpu.analysis --strict --no-contracts --format sarif \
    > /tmp/consensus-lint.sarif
"$PY" - <<'PYEOF'
import json
doc = json.load(open("/tmp/consensus-lint.sarif"))
assert doc["version"] == "2.1.0", doc.get("version")
assert doc["runs"][0]["tool"]["driver"]["name"] == "consensus-lint"
print("SARIF artifact OK:", len(doc["runs"][0]["results"]), "result(s)")
PYEOF
# The static layers — everything Layers 5 and 6 extend — must stay
# under the 30 s pre-push budget (raised from 25 s to cover Layer 6's
# determinism fixpoint, ISSUE 17) so the lint remains a habit, not a
# CI-only chore. Timed with --no-contracts: the Layer 2/3b contract
# pass compiles real executables on the 8-virtual-device mesh, which
# is hardware-bound and already gated for correctness by the full
# --strict run above.
STRICT_T0=$(date +%s)
"$PY" -m pyconsensus_tpu.analysis --strict --no-contracts
STRICT_ELAPSED=$(( $(date +%s) - STRICT_T0 ))
if [ "$STRICT_ELAPSED" -ge 30 ]; then
  echo "--strict static layers took ${STRICT_ELAPSED}s (budget: < 30 s)"; exit 1
fi
echo "--strict static layers wall time ${STRICT_ELAPSED}s (< 30 s budget) OK"
"$VENV/bin/consensus-lint" --list-rules >/dev/null && echo "console script consensus-lint OK"

echo "=== Layer 4 seeded violations (ISSUE 9: each must exit 1) ==="
# The gate above proves the PACKAGE clean; these prove the rules can
# still see. A lock-order inversion and an unbounded blocking wait
# under a lock are planted in throwaway files — consensus-lint must
# fail each one, or the layer has gone blind.
L4DIR=$(mktemp -d /tmp/ci-l4-seed-XXXX)
cat > "$L4DIR/inversion.py" <<'SEED'
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()

    def alpha(self, journal):
        with self._lock:
            with journal._jlock:
                pass


class Journal:
    def __init__(self):
        self._jlock = threading.Lock()

    def beta(self, store):
        with self._jlock:
            with store._lock:
                pass
SEED
cat > "$L4DIR/blocking.py" <<'SEED'
import threading


class Dispatcher:
    def __init__(self):
        self._lock = threading.Lock()

    def flush(self, fut):
        with self._lock:
            return fut.result()
SEED
if "$PY" -m pyconsensus_tpu.analysis --select CL801 --no-baseline "$L4DIR/inversion.py" >/dev/null; then
  echo "seeded lock inversion NOT detected"; exit 1
fi
echo "seeded lock-order inversion -> exit 1 (CL801) OK"
if "$PY" -m pyconsensus_tpu.analysis --select CL802 --no-baseline "$L4DIR/blocking.py" >/dev/null; then
  echo "seeded blocking-under-lock NOT detected"; exit 1
fi
echo "seeded blocking-under-lock -> exit 1 (CL802) OK"
rm -rf "$L4DIR"

echo "=== Layer 5 seeded durability reorder (ISSUE 16: must exit 1) ==="
# The acceptance criterion for the distributed-protocol layer: a
# dispatch handler that resolves its Future BEFORE the journal write
# (the ack-before-journal reorder — an acknowledged request a crash
# can silently lose) is planted in a throwaway file, and the --strict
# gate must fail it under CL901 naming BOTH events, or the layer has
# gone blind to the one ordering it exists to forbid.
L5DIR=$(mktemp -d /tmp/ci-l5-seed-XXXX)
cat > "$L5DIR/reorder.py" <<'SEED'
class Worker:
    def handlers(self):
        return {"append": self.append}

    def append(self, params):
        self._fut.set_result(1)
        self._log.journal_block(params["block"])
        return {"total": 1}
SEED
L5OUT=$("$PY" -m pyconsensus_tpu.analysis --strict --no-contracts \
    --select CL901 --no-baseline "$L5DIR/reorder.py" 2>&1) && {
  echo "seeded ack-before-journal reorder NOT detected"; exit 1; }
echo "$L5OUT" | grep -q "set_result" || {
  echo "CL901 finding does not name the ack event"; exit 1; }
echo "$L5OUT" | grep -q "journal_block" || {
  echo "CL901 finding does not name the durability event"; exit 1; }
echo "seeded ack-before-journal -> exit 1 (CL901, names both events) OK"
rm -rf "$L5DIR"

echo "=== Layer 6 seeded determinism violation (ISSUE 17: must exit 1) ==="
# The acceptance criterion for the bit-determinism layer: a digest
# folded over dict iteration order (the bytes change run to run under
# a different insertion history) is planted in a throwaway file, and
# the --strict gate must fail it under CL1001 naming the sink, or the
# layer has gone blind to the one flow it exists to forbid.
L6DIR=$(mktemp -d /tmp/ci-l6-seed-XXXX)
cat > "$L6DIR/dictfold.py" <<'SEED'
import hashlib


def round_digest(votes: dict) -> str:
    h = hashlib.sha256()
    for name, vote in votes.items():
        h.update(f"{name}={vote}".encode())
    return h.hexdigest()
SEED
L6OUT=$("$PY" -m pyconsensus_tpu.analysis --strict --no-contracts \
    --select CL1001 --no-baseline "$L6DIR/dictfold.py" 2>&1) && {
  echo "seeded dict-ordered digest fold NOT detected"; exit 1; }
echo "$L6OUT" | grep -q "digest" || {
  echo "CL1001 finding does not name the digest sink"; exit 1; }
echo "$L6OUT" | grep -q "items()" || {
  echo "CL1001 finding does not name the unordered source"; exit 1; }
echo "seeded dict-ordered digest fold -> exit 1 (CL1001, names the sink) OK"
rm -rf "$L6DIR"

echo "=== Metric-name drift (code vs docs/OBSERVABILITY.md) ==="
"$PY" tools/check_metric_docs.py

echo "=== Error-code drift (code vs docs/ROBUSTNESS.md) ==="
"$PY" tools/check_error_docs.py

echo "=== Lint-rule drift (code vs docs/STATIC_ANALYSIS.md) ==="
"$PY" tools/check_lint_docs.py

echo "=== Test suite (8-virtual-device CPU mesh) ==="
"$PY" -m pytest tests/ -q --durations=15

echo "=== Example smoke runs (installed package) ==="
"$PY" examples/quickstart.py
"$PY" examples/fault_tolerant_sweep.py /tmp/ci-rehearsal-sweep

echo "=== CLI entry points ==="
"$PY" -m pyconsensus_tpu --example
"$PY" -m pyconsensus --example --missing --scaled
# the console scripts ci.yml's install creates
"$VENV/bin/pyconsensus-tpu" --example >/dev/null && echo "console script OK"

echo "=== Observability smoke (ISSUE 3: prom exposition + retrace stability) ==="
# Run the light pipeline through the real CLI with --metrics-out twice in
# ONE process: the exposition must contain the convergence-iteration,
# phase-duration, and retrace metrics; the span JSONL must reconstruct
# the nested phase tree; and the identical second run must keep the
# retrace counter at exactly 1 (the CL304 invariant, observed at runtime).
"$PY" - <<'PYEOF'
import pathlib
from pyconsensus_tpu import obs
from pyconsensus_tpu.cli import main

out = pathlib.Path("/tmp/ci-rehearsal-obs")
out.mkdir(exist_ok=True)
main(["--example", "--metrics-out", str(out / "m1.prom"),
      "--trace-out", str(out / "t1.jsonl")])
main(["--example", "--metrics-out", str(out / "m2.prom")])
text = (out / "m2.prom").read_text()
required = ["pyconsensus_consensus_iterations",     # convergence
            "pyconsensus_phase_seconds",            # phase durations
            "pyconsensus_jit_retraces_total",       # compile observability
            "pyconsensus_consensus_total"]
missing = [m for m in required if m not in text]
assert not missing, f"metrics missing from exposition: {missing}"
v = obs.value("pyconsensus_jit_retraces_total", entry="consensus_core")
assert v == 1, f"retrace counter must stay 1 after an identical re-run, got {v}"
tree = obs.span_tree(obs.read_jsonl(out / "t1.jsonl"))
roots = [t["name"] for t in tree]
assert "oracle.consensus" in roots, f"span roots: {roots}"
assert any(c["name"] == "pipeline.dispatch"
           for t in tree for c in t["children"]), "span nesting lost"
print("obs smoke OK: required metrics present, retrace counter stable at 1, "
      "span JSONL reconstructs the phase tree")
PYEOF

echo "=== Chaos smoke (ISSUE 4: kill -9 resume + checkpoint corruption + NaN storm) ==="
# Three acceptance criteria, end to end: (1) a sweep worker killed with
# SIGKILL mid-chunk resumes bit-identical to an uninterrupted run;
# (2) a corrupted chunk checkpoint is detected by content checksum and
# transparently recomputed; (3) a seeded NaN/Inf-storm fault plan yields
# finite outcomes with quarantined rows reported, and replaying the same
# plan reproduces the run exactly (see docs/ROBUSTNESS.md).
"$PY" - <<'PYEOF'
import json, os, pathlib, signal, subprocess, sys, tempfile, textwrap, time
import numpy as np

work = pathlib.Path(tempfile.mkdtemp(prefix="ci-chaos-"))
ck = work / "ck"

# -- (1) kill -9 mid-sweep, resume, compare digests ----------------------
worker = work / "worker.py"
worker.write_text(textwrap.dedent("""
    import sys, time
    from pyconsensus_tpu.sim import CheckpointedSweep, CollusionSimulator
    sim = CollusionSimulator(n_reporters=6, n_events=4, max_iterations=2)
    sweep = CheckpointedSweep(sim, [0.0, 0.4], [0.1], 4, seed=11,
                              checkpoint_dir=sys.argv[1],
                              trials_per_chunk=2)
    for c in sweep.pending():
        sweep._run_chunk(c)
        time.sleep(0.5)
"""))
proc = subprocess.Popen([sys.executable, str(worker), str(ck)])
deadline = time.monotonic() + 180
while time.monotonic() < deadline:
    if ck.exists() and list(ck.glob("chunk_*.npz")):
        break
    assert proc.poll() is None, "chaos worker died before first chunk"
    time.sleep(0.05)
else:
    raise SystemExit("chaos worker never committed a chunk")
os.kill(proc.pid, signal.SIGKILL)
proc.wait(timeout=30)
assert proc.returncode == -signal.SIGKILL

from pyconsensus_tpu.sim import CheckpointedSweep, CollusionSimulator
sim = CollusionSimulator(n_reporters=6, n_events=4, max_iterations=2)
sweep = CheckpointedSweep(sim, [0.0, 0.4], [0.1], 4, seed=11,
                          checkpoint_dir=ck, trials_per_chunk=2)
assert sweep.pending(), "kill -9 landed after the sweep finished"
sweep.run(host_id=0, n_hosts=1)
got = sweep.gather()
mono = sim.run([0.0, 0.4], [0.1], 4, seed=11)
for key in ("correct_rate", "capture_rate", "liar_rep_share"):
    assert np.array_equal(got[key], mono[key]), key
print("chaos (1) OK: kill -9 mid-sweep resume is bit-identical")

# -- (2) corrupt one chunk -> checksum detects, recompute matches --------
victim = sweep._chunk_path(1)
raw = bytearray(victim.read_bytes())
raw[len(raw) // 2] ^= 0xFF
victim.write_bytes(bytes(raw))
resumed = CheckpointedSweep(sim, [0.0, 0.4], [0.1], 4, seed=11,
                            checkpoint_dir=ck, trials_per_chunk=2)
assert resumed.run(host_id=0, n_hosts=1) == 1     # exactly the scrubbed one
got = resumed.gather()
for key in ("correct_rate", "capture_rate", "liar_rep_share"):
    assert np.array_equal(got[key], mono[key]), key
print("chaos (2) OK: corrupted chunk detected by checksum and recomputed")

# -- (3) NaN-storm plan: finite + quarantined + replayable ---------------
from pyconsensus_tpu import Oracle, faults
plan_dict = {"seed": 5, "rules": [
    {"site": "oracle.reports", "kind": "inf_storm", "occurrences": [0],
     "args": {"fraction": 0.1}}]}
rng = np.random.default_rng(0)
reports = rng.choice([0.0, 1.0], size=(12, 8))

def storm():
    with faults.armed(faults.FaultPlan.from_dict(plan_dict)):
        return Oracle(reports=reports, backend="jax",
                      max_iterations=2).consensus()
r1, r2 = storm(), storm()
assert np.isfinite(r1["agents"]["smooth_rep"]).all()
assert np.isfinite(r1["events"]["outcomes_final"]).all()
assert r1["quarantined_rows"].size > 0
assert np.array_equal(r1["quarantined_rows"], r2["quarantined_rows"])
assert np.array_equal(r1["events"]["outcomes_final"],
                      r2["events"]["outcomes_final"])
print("chaos (3) OK: NaN storm finite + quarantined, replay identical")
PYEOF

echo "=== Serve smoke (ISSUE 5: warmup + 50 concurrent requests through 2 buckets + drain) ==="
# Start the micro-batching service with two warmed buckets, drive 50
# concurrent closed-loop requests whose shapes map to BOTH buckets,
# and assert: every request succeeds, coalescing is measurably active
# (mean batch occupancy > 1), the steady-state retrace counter equals
# the warmed bucket count (the executable-cache contract — the runtime
# CL304), and graceful drain completes. See docs/SERVING.md.
"$PY" - <<'PYEOF'
from pyconsensus_tpu import obs
from pyconsensus_tpu.serve import ConsensusService, ServeConfig
from pyconsensus_tpu.serve.loadgen import LoadGenerator

cfg = ServeConfig(warmup=((16, 64), (32, 128)), batch_window_ms=3.0)
svc = ConsensusService(cfg).start()
gen = LoadGenerator(svc, shapes=((12, 48), (24, 100)), na_frac=0.1,
                    seed=7)
stats = gen.run_closed(n_requests=50, concurrency=10)
svc.close(drain=True)

assert stats["failed"] == 0, f"failed requests: {stats['errors']}"
assert stats["succeeded"] == 50, stats
retraces = obs.value("pyconsensus_jit_retraces_total",
                     entry="serve_bucket")
assert retraces == 2, (
    f"steady-state retraces {retraces} != warmed bucket count 2 — "
    f"a bucket executable retraced under traffic")
from pyconsensus_tpu.serve.loadgen import mean_batch_occupancy
mean_occ = mean_batch_occupancy()
assert mean_occ and mean_occ > 1.0, \
    f"coalescing inactive: occupancy {mean_occ}"
print(f"serve smoke OK: 50/50 succeeded at "
      f"{stats['throughput_rps']} req/s "
      f"(p50 {stats['latency_p50_ms']} ms / "
      f"p99 {stats['latency_p99_ms']} ms), mean occupancy "
      f"{mean_occ:.2f}, retraces pinned at warmed bucket count (2), "
      f"drain clean")
PYEOF
"$VENV/bin/pyconsensus-serve" --warmup-only --shapes 8x32 >/dev/null && echo "console script pyconsensus-serve OK"

echo "=== Zero-cold-start serve smoke (ISSUE 10: warm -> SIGKILL -> restart with retraces==0; corrupt -> refuse+recompile) ==="
# Phase 1 warms two buckets with an AOT cache dir, serves a probe
# (result saved), and dies by REAL SIGKILL. The restarted phase 2 must
# warm BOTH buckets from disk with the serve_bucket retrace counter at
# 0 (zero pipeline retraces — the zero-cold-start acceptance bar) and
# serve the same request bit-identically. Phase 3 then boots against a
# bit-flipped cache entry: it must be REFUSED (PYC302 digest reject),
# deleted, recompiled (retraces == 1, exactly the damaged bucket),
# re-persisted, and the probe must still serve the pre-kill bits — a
# corrupted executable is never loaded. See docs/SERVING.md
# "Zero cold start".
AOTDIR=$(mktemp -d /tmp/ci-aot-XXXX)
set +e
"$PY" - "$AOTDIR" <<'PYEOF'
import os, signal, sys
import numpy as np
from pyconsensus_tpu.serve import ConsensusService, ServeConfig

aot = sys.argv[1]
cfg = ServeConfig(warmup=((16, 64), (32, 128)), sharded_buckets=False,
                  pallas_buckets=False, aot_cache_dir=aot)
svc = ConsensusService(cfg).start()
rng = np.random.default_rng(11)
m = rng.choice([0.0, 1.0, np.nan], size=(12, 48), p=[.45, .45, .1])
r = svc.submit(reports=m).result(300)
np.savez(os.path.join(aot, "prekill.npz"),
         outcomes=np.asarray(r["events"]["outcomes_final"]),
         smooth=np.asarray(r["agents"]["smooth_rep"]),
         iters=np.asarray(r["iterations"]))
n = len([f for f in os.listdir(aot) if f.endswith(".aotx")])
assert n == 2, f"expected 2 persisted entries, found {n}"
print(f"aot phase 1: warmed 2 buckets, persisted {n} entries, served "
      f"probe; dying by SIGKILL", flush=True)
os.kill(os.getpid(), signal.SIGKILL)
PYEOF
rc=$?
set -e
[ "$rc" -eq 137 ] || { echo "aot phase 1 should die by SIGKILL (rc 137), got $rc"; exit 1; }
"$PY" - "$AOTDIR" <<'PYEOF'
import os, sys
import numpy as np
from pyconsensus_tpu import obs
from pyconsensus_tpu.serve import ConsensusService, ServeConfig
from pyconsensus_tpu.serve.aotcache import AotExecutable

aot = sys.argv[1]
cfg = ServeConfig(warmup=((16, 64), (32, 128)), sharded_buckets=False,
                  pallas_buckets=False, aot_cache_dir=aot)
svc = ConsensusService(cfg)
assert svc.warm_buckets() == 2
retr = obs.value("pyconsensus_jit_retraces_total",
                 entry="serve_bucket") or 0
assert retr == 0, (
    f"restart retraced the pipeline {retr} time(s) — the persisted AOT "
    f"entries were not adopted")
assert obs.value("pyconsensus_aot_load_total", outcome="loaded") == 2
assert all(isinstance(svc.cache.get(k), AotExecutable)
           for k in svc.cache.keys())
svc.start(warmup=False)
rng = np.random.default_rng(11)
m = rng.choice([0.0, 1.0, np.nan], size=(12, 48), p=[.45, .45, .1])
r = svc.submit(reports=m).result(300)
svc.close(drain=True)
pre = np.load(os.path.join(aot, "prekill.npz"))
assert np.array_equal(pre["outcomes"],
                      np.asarray(r["events"]["outcomes_final"]))
assert np.array_equal(pre["smooth"],
                      np.asarray(r["agents"]["smooth_rep"]))
assert int(pre["iters"]) == int(r["iterations"])
print("aot phase 2 OK: restart warmed 2/2 from disk, "
      "serve_bucket retraces == 0, probe bit-identical to pre-kill")
PYEOF
"$PY" - "$AOTDIR" <<'PYEOF'
import pathlib, sys

p = sorted(pathlib.Path(sys.argv[1]).glob("*.aotx"))[0]
data = bytearray(p.read_bytes())
data[-64] ^= 0xFF
p.write_bytes(bytes(data))
print(f"corrupted {p.name} (bit flip in the serialized module)")
PYEOF
"$PY" - "$AOTDIR" <<'PYEOF'
import os, sys
import numpy as np
from pyconsensus_tpu import obs
from pyconsensus_tpu.serve import ConsensusService, ServeConfig

aot = sys.argv[1]
cfg = ServeConfig(warmup=((16, 64), (32, 128)), sharded_buckets=False,
                  pallas_buckets=False, aot_cache_dir=aot)
svc = ConsensusService(cfg)
assert svc.warm_buckets() == 2
assert obs.value("pyconsensus_aot_reject_total", reason="digest") == 1, \
    "the bit-flipped entry must be refused on its content digest"
retr = obs.value("pyconsensus_jit_retraces_total",
                 entry="serve_bucket") or 0
assert retr == 1, (
    f"exactly the damaged bucket must recompile, got {retr} retraces")
assert obs.value("pyconsensus_aot_load_total", outcome="loaded") == 1
assert obs.value("pyconsensus_aot_persist_total", outcome="written") == 1
svc.start(warmup=False)
rng = np.random.default_rng(11)
m = rng.choice([0.0, 1.0, np.nan], size=(12, 48), p=[.45, .45, .1])
r = svc.submit(reports=m).result(300)
svc.close(drain=True)
pre = np.load(os.path.join(aot, "prekill.npz"))
assert np.array_equal(pre["outcomes"],
                      np.asarray(r["events"]["outcomes_final"]))
assert np.array_equal(pre["smooth"],
                      np.asarray(r["agents"]["smooth_rep"]))
print("aot phase 3 OK: corrupted entry refused (PYC302 digest) + "
      "deleted + recompiled + re-persisted; probe still bit-identical")
PYEOF
rm -rf "$AOTDIR"

echo "=== Sharded serve smoke (ISSUE 6: mesh-bucketed dispatch on the 8-virtual-device mesh) ==="
# The mesh-sharded serving hot path, end to end: a service with
# sharded_buckets forced on engages the 2x4 (batch x event) mesh, warms
# BOTH configured buckets as shard_map executables, serves a concurrent
# closed-loop burst with zero failures, keeps the serve_bucket_sharded
# retrace counter pinned at the warmed-bucket count (the runtime CL304
# mirror of the serve-bucket-sharded lint contract, which the --strict
# gate above already compiled), emits the mesh-width gauge from the
# bucket dispatch, and reports bit-identical outcomes to a direct
# Oracle resolution. See docs/SERVING.md "Mesh-sharded buckets".
"$PY" - <<'PYEOF'
import numpy as np
from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.serve import ConsensusService, ServeConfig
from pyconsensus_tpu.serve.loadgen import (LoadGenerator, device_block,
                                           mean_batch_occupancy)
from pyconsensus_tpu.serve.sharded import SINGLE_TOPOLOGY

cfg = ServeConfig(warmup=((16, 64), (32, 128)), batch_window_ms=3.0,
                  sharded_buckets=True)
svc = ConsensusService(cfg).start()
assert svc.mesh is not None and svc.n_devices == 8, svc.mesh
assert dict(svc.mesh.shape) == {"batch": 2, "event": 4}
topos = {k.topology for k in svc.cache.keys()}
assert topos and SINGLE_TOPOLOGY not in topos, (
    f"warmed buckets did not take the mesh topology: {topos}")

# parity probe: one request vs a direct Oracle resolution, bit-identical
rng = np.random.default_rng(6)
probe = rng.choice([0.0, 1.0], size=(12, 48))
probe[rng.random(probe.shape) < 0.1] = np.nan
got = svc.submit(reports=probe).result(timeout=120)
ref = Oracle(reports=probe, backend="jax", pca_method="power").consensus()
assert np.array_equal(got["events"]["outcomes_final"],
                      ref["events"]["outcomes_final"])
assert got["iterations"] == ref["iterations"]

gen = LoadGenerator(svc, shapes=((12, 48), (24, 100)), na_frac=0.1,
                    seed=7)
stats = gen.run_closed(n_requests=40, concurrency=8)
svc.close(drain=True)
assert stats["failed"] == 0, f"failed requests: {stats['errors']}"
retraces = obs.value("pyconsensus_jit_retraces_total",
                     entry="serve_bucket_sharded")
assert retraces == 2, (
    f"steady-state sharded retraces {retraces} != warmed bucket count 2 "
    f"— a mesh bucket executable retraced under traffic")
assert obs.value("pyconsensus_mesh_event_shards") == 4, \
    "bucket dispatch did not emit the mesh-width gauge"
dev = device_block(svc)
assert dev["n_devices"] == 8 and dev["per_device_occupancy"] is not None
print(f"sharded serve smoke OK: parity probe bit-identical to direct "
      f"Oracle; 40/40 loadgen requests succeeded at "
      f"{stats['throughput_rps']} req/s on the 2x4 mesh "
      f"(p50 {stats['latency_p50_ms']} ms / p99 {stats['latency_p99_ms']} ms), "
      f"mean occupancy {mean_batch_occupancy():.2f} "
      f"({dev['per_device_occupancy']} per device lane), sharded "
      f"retraces pinned at warmed bucket count (2), drain clean")
PYEOF

echo "=== Autotune + Pallas serve-tier smoke (ISSUE 7) ==="
# (1) Autotune: a tiny interpret-mode sweep produces a deterministic
# winner and persists it through the atomic-write machinery; a SECOND
# process reloads the winner from the cache with
# pyconsensus_autotune_sweeps_total == 0 (pure cache hit, no re-sweep).
# (2) bucket_pallas: the low-latency fused tier (pallas_buckets forced
# on; kernels through the Pallas interpreter) serves a request with
# catch-snapped outcomes + iteration count bit-identical to a direct
# Oracle resolution, retraces pinned under the serve_bucket_pallas
# entry, and the kernel-path counter showing pallas traffic.
AUTOTUNE_CACHE=/tmp/ci-rehearsal-autotune.json
rm -f "$AUTOTUNE_CACHE"
"$PY" - "$AUTOTUNE_CACHE" <<'PYEOF'
import json, sys
from pyconsensus_tpu import obs
from pyconsensus_tpu.tune import autotune_cov, autotune_resolve

path = sys.argv[1]
cov = autotune_cov(256, n_reporters=24, interpret=True, path=path)
res = autotune_resolve(64, n_events=96, interpret=True, path=path)
assert cov["value"] in cov["candidates"] and cov["mode"] == "interpret"
assert res["value"] in res["candidates"]
assert obs.value("pyconsensus_autotune_sweeps_total",
                 kind="cov_tile_rows") == 1
raw = json.loads(open(path).read())
assert raw["version"] == 1 and len(raw["entries"]) == 2
print(f"autotune sweep OK: winners cov_tile_rows={cov['value']} "
      f"resolve_block_cols={res['value']}, cache written atomically")
json.dump({"cov": cov["value"], "res": res["value"]},
          open(path + ".winners", "w"))
PYEOF
"$PY" - "$AUTOTUNE_CACHE" <<'PYEOF'
import json, sys
from pyconsensus_tpu import obs
from pyconsensus_tpu.tune import autotune_cov, autotune_resolve

path = sys.argv[1]
cov = autotune_cov(256, n_reporters=24, interpret=True, path=path)
res = autotune_resolve(64, n_events=96, interpret=True, path=path)
winners = json.load(open(path + ".winners"))
assert (cov["value"], res["value"]) == (winners["cov"], winners["res"]), \
    "second-run winners differ from the persisted sweep"
# query PER KIND: the counter only has labeled series, so a label-less
# obs.value is always None and `assert not` would be vacuously green
for kind in ("cov_tile_rows", "resolve_block_cols"):
    s = obs.value("pyconsensus_autotune_sweeps_total", kind=kind)
    assert not s, f"second run re-swept {kind} ({s}) instead of reloading"
    assert obs.value("pyconsensus_autotune_cache_hits_total",
                     kind=kind) == 1, kind
print("autotune reload OK: second process served both winners from the "
      "cache, pyconsensus_autotune_sweeps_total == 0")
PYEOF
"$PY" - <<'PYEOF'
import numpy as np
from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.serve import ConsensusService, ServeConfig

rng = np.random.default_rng(11)
probe = rng.choice([0.0, 1.0], size=(14, 44))
probe[rng.random(probe.shape) < 0.1] = np.nan
with ConsensusService(ServeConfig(pallas_buckets=True)) as svc:
    got = svc.submit(reports=probe).result(timeout=120)
    again = svc.submit(reports=probe).result(timeout=120)
ref = Oracle(reports=probe).consensus()
assert np.array_equal(got["events"]["outcomes_adjusted"],
                      ref["events"]["outcomes_adjusted"])
assert got["iterations"] == ref["iterations"]
for sec in ("agents", "events"):
    for k in got[sec]:
        assert np.array_equal(np.asarray(got[sec][k]),
                              np.asarray(again[sec][k])), (sec, k)
retr = obs.value("pyconsensus_jit_retraces_total",
                 entry="serve_bucket_pallas")
assert retr == 1, f"serve_bucket_pallas retraces {retr} != 1 cached exec"
assert obs.value("pyconsensus_kernel_path_total", path="pallas") == 2
print("bucket_pallas smoke OK: outcomes + iterations bit-identical to "
      "direct Oracle, repeat dispatch bitwise, retraces pinned at the "
      "cached executable count, kernel-path counter shows pallas traffic")
PYEOF

echo "=== Fleet chaos smoke (ISSUE 8: kill a worker mid-traffic, zero lost resolutions) ==="
# The replicated-fleet acceptance criterion end to end: (1) a 3-worker
# fleet with warmed buckets serves concurrent traffic while one worker
# is hard-killed mid-run — every accepted request either resolves with
# bits identical to a direct Oracle run or sheds with a PYC-coded
# structured error a bounded retry absorbs (zero abandoned), the killed
# worker's session resumes bit-identical on the survivor, and drain
# completes clean; (2) a REAL `kill -9` lands on a worker process
# mid-round and the standby adopts its session via the verify-preflight
# + ledger replay, finishing the rounds bit-identical to the
# never-killed run; (3) consensus-lint confirms CL601/CL701 stay green
# over the new fleet modules. See docs/SERVING.md "Replicated fleet".
# The whole in-process stage runs under the RUNTIME LOCK WITNESS
# (ISSUE 9): every package lock acquisition is recorded, and the
# observed order must come out acyclic and consistent with the static
# CL801 may-hold-before graph, or this stage fails with the witness
# JSON dumped to /tmp/ci-fleet-witness.json. It ALSO runs under the
# RUNTIME PROTOCOL WITNESS (ISSUE 16): every journal/commit/ship on
# the chaos path is recorded against its enclosing replicated
# operation, and the observed order must come out consistent with the
# static CL901 happens-before graph — an ack that beat its durability
# write in any real interleaving fails this stage with the witness
# JSON at /tmp/ci-fleet-protocol-witness.json. And it runs under the
# RUNTIME DIGEST WITNESS (ISSUE 17): every digest journaled, recorded,
# or computed on the chaos path is replayed through the durable
# artifact it claims to describe — a digest the artifact cannot
# reproduce fails this stage with the witness JSON at
# /tmp/ci-fleet-digest-witness.json.
"$PY" - <<'PYEOF'
import tempfile, threading, time
import numpy as np
from pyconsensus_tpu.analysis.witness import LockWitness, static_lock_graph
from pyconsensus_tpu.analysis.protocol_witness import (ProtocolWitness,
                                                       static_protocol_graph)
from pyconsensus_tpu.analysis.determinism_witness import DigestWitness

_static = static_lock_graph()
_pstatic = static_protocol_graph()
_witness = LockWitness().install()
_pwitness = ProtocolWitness().install()
_dwitness = DigestWitness().install()

from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.serve import (ConsensusFleet, FleetConfig,
                                   MarketSession, ServeConfig)
from pyconsensus_tpu.serve.loadgen import RETRYABLE_CODES

log_dir = tempfile.mkdtemp(prefix="ci-fleet-")
fleet = ConsensusFleet(FleetConfig(
    n_workers=3, log_dir=log_dir,
    worker=ServeConfig(warmup=((16, 64),), batch_window_ms=2.0),
    takeover_window_s=1.0)).start(warmup=True)   # warm buckets per worker

rng = np.random.default_rng(8)
matrix = rng.choice([0.0, 1.0], size=(12, 48))
matrix[rng.random(matrix.shape) < 0.1] = np.nan
ref = Oracle(reports=matrix, backend="jax", pca_method="power").consensus()

blocks = [rng.choice([0.0, 1.0], size=(10, 6)) for _ in range(3)]
fleet.create_session("mkt", n_reporters=10)
fleet.append("mkt", blocks[0])
round_results = [fleet.submit(session="mkt").result(timeout=120)]

results, errors, fatal = [], [], []
lock = threading.Lock()
mid = threading.Event()

def client(n):
    for i in range(n):
        if i == 3:
            mid.set()
        for attempt in range(6):
            try:
                r = fleet.submit(reports=matrix).result(120)
                with lock:
                    results.append(r)
                break
            except Exception as exc:
                code = getattr(exc, "error_code", "")
                with lock:
                    errors.append(exc)
                if code not in RETRYABLE_CODES:
                    # the one retry policy (loadgen.RETRYABLE_CODES):
                    # non-retryable PYC503/PYC301 regressions must fail
                    # the smoke, not be silently retried into a pass
                    with lock:   # surfaced on the main thread below —
                        fatal.append(exc)   # a raise here would vanish
                    return
                time.sleep(float(getattr(exc, "context", {})
                                 .get("retry_after_s", 0.05)))
        else:
            with lock:
                fatal.append(AssertionError(
                    "request abandoned after bounded retries"))
            return

threads = [threading.Thread(target=client, args=(8,)) for _ in range(5)]
for t in threads:
    t.start()
mid.wait(timeout=120)
victim = fleet.owner_of("mkt")
info = fleet.kill_worker(victim)                # SIGKILL model, mid-traffic
for t in threads:
    t.join(timeout=300)
if fatal:
    raise SystemExit(f"client thread failed: {fatal[0]!r}")
assert fleet.owner_of("mkt") != victim, "session did not migrate"

# the killed worker's session continues on the survivor
fleet.append("mkt", blocks[1])
round_results.append(fleet.submit(session="mkt").result(timeout=120))
fleet.append("mkt", blocks[2])
round_results.append(fleet.submit(session="mkt").result(timeout=120))
fleet.close(drain=True)                        # drain clean

assert len(results) == 40, f"lost resolutions: {len(results)}/40"
for r in results:
    # zero corrupted bits: the serve equivalence contract
    # (docs/SERVING.md) — catch-snapped outcomes + iteration counts
    # bit-identical to direct Oracle; continuous tails in the
    # documented band (f32 pipeline here: no x64 in this smoke)
    assert np.array_equal(r["events"]["outcomes_final"],
                          ref["events"]["outcomes_final"])
    assert np.array_equal(r["events"]["outcomes_adjusted"],
                          ref["events"]["outcomes_adjusted"])
    assert r["iterations"] == ref["iterations"]
    np.testing.assert_allclose(r["agents"]["smooth_rep"],
                               ref["agents"]["smooth_rep"],
                               rtol=1e-4, atol=1e-5)
    # the fleet determinism claim: identical request -> identical BITS
    # no matter which worker served it, before or after the kill
    assert np.array_equal(r["agents"]["smooth_rep"],
                          results[0]["agents"]["smooth_rep"])
    assert np.array_equal(r["events"]["outcomes_final"],
                          results[0]["events"]["outcomes_final"])
ref_session = MarketSession("ref", 10)         # uninterrupted single box
for b, got in zip(blocks, round_results):
    ref_session.append(b)
    want = ref_session.resolve()
    assert np.array_equal(np.asarray(got["agents"]["smooth_rep"]),
                          np.asarray(want["smooth_rep"]))
    assert np.array_equal(np.asarray(got["events"]["outcomes_final"]),
                          np.asarray(want["outcomes_final"]))
    assert got["iterations"] == int(np.asarray(want["iterations"]))
shed_codes = sorted({getattr(e, "error_code", "?") for e in errors})
assert obs.value("pyconsensus_fleet_workers") == 2
assert obs.value("pyconsensus_failovers_total") >= 1
assert obs.value("pyconsensus_sessions_migrated_total") >= 1
print(f"fleet chaos (1) OK: 40/40 resolutions bit-identical through the "
      f"kill ({info['shed_queued']} queued shed as PYC501, "
      f"{len(errors)} sheds retried, codes {shed_codes or 'none'}), "
      f"3 session rounds bit-identical to the single-box run across the "
      f"failover, drain clean")

_dwitness.uninstall()
_pwitness.uninstall()
_witness.uninstall()
rep = _witness.check(static=_static,
                     dump_path="/tmp/ci-fleet-witness.json")
print(f"lock witness OK: {len(rep['edges'])} observed acquisition "
      f"edge(s) over {len(rep['locks'])} lock site(s) — acyclic and "
      f"consistent with the static CL801 graph "
      f"({len(_static['edges'])} static edges)")
prep = _pwitness.check(static=_pstatic,
                       dump_path="/tmp/ci-fleet-protocol-witness.json")
acked = [r for r in prep["ops"] if r["ok"]]
assert acked, "protocol witness observed no acked replicated operation"
print(f"protocol witness OK: {len(acked)} acked operation(s) "
      f"({len(prep['ops'])} total) — every observed "
      f"journal/commit/ship/ack order consistent with the static CL901 "
      f"happens-before graph")
drep = _dwitness.check(dump_path="/tmp/ci-fleet-digest-witness.json")
assert drep["checked"], "digest witness observed no digest operation"
print(f"digest witness OK: {drep['checked']} digest(s) replayed "
      f"bit-identical through the durable artifacts "
      f"({drep['recorded']} recorded, {drep['skipped']} unreplayable)")
PYEOF
"$PY" - <<'PYEOF'
import os, signal, subprocess, sys, tempfile, time
import numpy as np

log_root = tempfile.mkdtemp(prefix="ci-fleet-kill9-")
env = dict(os.environ)
proc = subprocess.Popen(
    [sys.executable, "tests/fleet_worker.py", log_root, "mkt", "4", "0.1"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
try:
    deadline = time.monotonic() + 180
    seen = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        assert line, "worker exited early:\n" + "".join(seen)
        seen.append(line)
        if line.startswith("APPEND 1"):        # inside round 1: mid-traffic
            break
    else:
        raise SystemExit("worker never reached round 1")
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)
finally:
    if proc.poll() is None:
        proc.kill()
assert proc.returncode == -signal.SIGKILL

sys.path.insert(0, "tests")
from fleet_worker import BLOCKS_PER_ROUND, N_REPORTERS, make_block
from pyconsensus_tpu.serve import MarketSession, ReplicationLog, replay_session

# takeover preflight: the log verifies BEFORE adoption
summary = ReplicationLog(log_root, "mkt").verify()
standby = replay_session(log_root, "mkt")
assert standby.ledger.round >= 1
resumed_from = (standby.ledger.round, len(standby._blocks))
got = []
for k in range(standby.ledger.round, 4):
    for j in range(len(standby._blocks), BLOCKS_PER_ROUND):
        standby.append(make_block(k, j))
    got.append(standby.resolve())

ref_session = MarketSession("ref", N_REPORTERS)
ref = []
for k in range(4):
    for j in range(BLOCKS_PER_ROUND):
        ref_session.append(make_block(k, j))
    ref.append(ref_session.resolve())
for g, r in zip(got, ref[-len(got):]):
    assert np.array_equal(np.asarray(g["smooth_rep"]),
                          np.asarray(r["smooth_rep"]))
    assert np.array_equal(np.asarray(g["outcomes_final"]),
                          np.asarray(r["outcomes_final"]))
    assert int(np.asarray(g["iterations"])) == int(np.asarray(r["iterations"]))
np.testing.assert_array_equal(standby.reputation,
                              np.asarray(ref[-1]["smooth_rep"]))
print(f"fleet chaos (2) OK: real kill -9 mid-round, standby verified the "
      f"log and resumed from round={resumed_from[0]} "
      f"staged={resumed_from[1]}, all remaining rounds bit-identical to "
      f"the never-killed run")
PYEOF
# (3) CL601/CL701 + the Layer-4 lock rules stay green over the fleet
# modules (the full --strict gate above already covers the package;
# this names the check)
"$PY" -m pyconsensus_tpu.analysis --select CL601,CL701,CL801,CL802 \
  pyconsensus_tpu/serve/fleet.py pyconsensus_tpu/serve/failover.py \
  pyconsensus_tpu/serve/placement.py pyconsensus_tpu/serve/admission.py \
  && echo "fleet chaos (3) OK: CL601/CL701/CL801/CL802 green over the fleet modules"

echo "=== Multi-process fleet chaos (ISSUE 15: SIGKILL a worker PROCESS mid-traffic, shipped-log takeover, AOT warm) ==="
# The out-of-process contract end to end: a supervisor spawns REAL
# worker processes (socket RPC, fingerprint handshake, journal records
# shipped to the standby's disk before they are acknowledged), one
# worker process is SIGKILLed under concurrent traffic, and the
# standby adopts the SHIPPED log with zero lost resolutions, zero
# retraces (the shared AOT cache is the cross-process warm-start
# medium), serving bits identical to the never-killed run. The parent
# runs under the RUNTIME PROTOCOL WITNESS (ISSUE 16): the reference
# DurableSession's journal/commit order — the same code path the
# workers execute in their own processes — is recorded across the real
# cross-process chaos and checked against the static CL901
# happens-before graph (/tmp/ci-mp-protocol-witness.json on failure).
# It ALSO runs under the RUNTIME DIGEST WITNESS (ISSUE 17): every
# digest the reference session journals or commits must replay
# bit-identical from the durable artifact
# (/tmp/ci-mp-digest-witness.json on failure).
MPDIR=$(mktemp -d)
"$PY" - "$MPDIR" <<'PYEOF'
import os
import signal
import sys
import threading
import time

import numpy as np

from pyconsensus_tpu.analysis.protocol_witness import (ProtocolWitness,
                                                       static_protocol_graph)
from pyconsensus_tpu.analysis.determinism_witness import DigestWitness

_pstatic = static_protocol_graph()
_pwitness = ProtocolWitness().install()
_dwitness = DigestWitness().install()

from pyconsensus_tpu.faults import (FailoverInProgressError,
                                    ServiceOverloadError, TransportError,
                                    WorkerLostError)
from pyconsensus_tpu.serve import ServeConfig
from pyconsensus_tpu.serve.failover import DurableSession
from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig

base = sys.argv[1]
cfg = ServeConfig(warmup=((16, 64),), pallas_buckets=False,
                  batch_window_ms=1.0,
                  aot_cache_dir=os.path.join(base, "aot"))

# boot 1: one worker process compiles the warmup bucket and persists it
fleet = ConsensusFleet(FleetConfig(
    n_workers=1, transport="socket",
    log_dir=os.path.join(base, "seed"), worker=cfg)).start()
persisted = fleet.workers["w0"].call("metric", {
    "name": "pyconsensus_aot_persist_total",
    "labels": {"outcome": "written"}})["value"]
assert persisted and persisted >= 1, persisted
fleet.close(drain=True)

# boot 2: THREE worker processes adopt it — zero retraces everywhere
fleet = ConsensusFleet(FleetConfig(
    n_workers=3, transport="socket", monitor=True,
    heartbeat_timeout_s=1.0, heartbeat_interval_s=0.25,
    log_dir=os.path.join(base, "fleet"), worker=cfg)).start()
pids = set()
for name, w in fleet.workers.items():
    pids.add(w.process.proc.pid)
    r = w.call("metric", {"name": "pyconsensus_jit_retraces_total",
                          "labels": {"entry": "serve_bucket"}})["value"]
    assert (r or 0) == 0, (name, r)
    loaded = w.call("metric", {"name": "pyconsensus_aot_load_total",
                               "labels": {"outcome": "loaded"}})["value"]
    assert loaded and loaded >= 1, (name, loaded)
assert len(pids) == 3 and os.getpid() not in pids


def make_block(k, j):
    rng = np.random.default_rng([7, k, j])
    b = rng.choice([0.0, 1.0], size=(12, 5))
    b[rng.random(b.shape) < 0.1] = np.nan
    return b


RETRYABLE = (WorkerLostError, FailoverInProgressError,
             ServiceOverloadError, TransportError, OSError)


def retried(fn, attempts=60):
    last = None
    for _ in range(attempts):
        try:
            return fn()
        except RETRYABLE as exc:
            last = exc
            hint = getattr(exc, "context", {})
            time.sleep(float(hint.get("retry_after_s", 0.25) or 0.25))
    raise last


# concurrent stateless traffic across the kill — with NaN non-reports,
# so it maps to the WARMED has_na=True bucket (a dense 16x64 matrix
# derives has_na=False, a different BucketKey the warmup never
# compiled, and the zero-retrace pin below would measure that instead)
rng = np.random.default_rng(0)
matrix = rng.choice([0.0, 1.0], size=(16, 64))
matrix[rng.random(matrix.shape) < 0.05] = np.nan
stop, errs, served = threading.Event(), [], [0]


def traffic():
    while not stop.is_set():
        try:
            fleet.submit(reports=matrix).result(timeout=60)
            served[0] += 1
        except RETRYABLE:
            time.sleep(0.1)
        except Exception as exc:        # noqa: BLE001 — fail the stage
            errs.append(exc)
            return


t = threading.Thread(target=traffic)
t.start()

owner = fleet.create_session("ci-market", n_reporters=12)
results = []
fleet.append("ci-market", make_block(0, 0))
fleet.append("ci-market", make_block(0, 1))
results.append(fleet.submit(session="ci-market").result(timeout=120))
fleet.append("ci-market", make_block(1, 0))     # round 1 mid-flight

# the REAL kill: SIGKILL the owning worker PROCESS, no cooperation
handle = fleet.workers[owner]
os.kill(handle.process.proc.pid, signal.SIGKILL)
handle.process.proc.wait(timeout=30)

st = retried(lambda: fleet.session_state("ci-market"))
assert st["rounds_resolved"] == 1 and st["staged_blocks"] == 1, st
new_owner = fleet.owner_of("ci-market")
assert new_owner != owner
# the adopting standby process is still at zero retraces: it warmed
# from the shared AOT cache, and adoption added no compiles
r = fleet.workers[new_owner].call("metric", {
    "name": "pyconsensus_jit_retraces_total",
    "labels": {"entry": "serve_bucket"}})["value"]
assert (r or 0) == 0, r
# the retried append carries a stable idempotency token: an attempt
# that lands-but-loses-its-ack must not double-fold on the retry
retried(lambda: fleet.append("ci-market", make_block(1, 1),
                             append_id="ci-r1b1"))
results.append(retried(
    lambda: fleet.submit(session="ci-market").result(120)))
stop.set()
t.join(30)
assert not errs, errs
assert served[0] > 0

# zero lost resolutions, bit-identical to the never-killed run
ref = DurableSession.create(os.path.join(base, "ref"), "ci-market", 12)
for k, got in enumerate(results):
    for j in range(2):
        ref.append(make_block(k, j))
    want = ref.resolve()
    np.testing.assert_array_equal(
        np.asarray(got["events"]["outcomes_adjusted"]),
        np.asarray(want["outcomes_adjusted"]), err_msg=f"round {k}")
    np.testing.assert_array_equal(
        np.asarray(got["agents"]["smooth_rep"]),
        np.asarray(want["smooth_rep"]), err_msg=f"round {k}")
fleet.close(drain=True)
_dwitness.uninstall()
_pwitness.uninstall()
prep = _pwitness.check(static=_pstatic,
                       dump_path="/tmp/ci-mp-protocol-witness.json")
acked = [r for r in prep["ops"] if r["ok"]]
assert acked, "protocol witness observed no acked replicated operation"
drep = _dwitness.check(dump_path="/tmp/ci-mp-digest-witness.json")
assert drep["checked"], "digest witness observed no digest operation"
print(f"multi-process chaos OK: worker process {owner} SIGKILLed "
      f"mid-traffic ({served[0]} stateless requests served around the "
      f"kill), standby {new_owner} adopted the shipped log with zero "
      f"retraces, both session rounds bit-identical to the "
      f"never-killed run; protocol witness consistent over "
      f"{len(acked)} acked op(s); digest witness replayed "
      f"{drep['checked']} digest(s) bit-identical")
PYEOF
rm -rf "$MPDIR"
# the taint/lock/protocol layers stay green over the new transport
# modules (shipped baseline EMPTY — the full --strict gate above
# already covers the package; this names the check the ISSUE asks for)
"$PY" -m pyconsensus_tpu.analysis \
  --select CL401,CL402,CL403,CL404,CL801,CL802,CL803,CL804,CL805,CL901,CL902,CL903,CL904,CL905 \
  pyconsensus_tpu/serve/transport \
  && echo "multi-process chaos lint OK: CL401-404 + CL801-805 + CL901-905 green over serve/transport"

echo "=== Telemetry plane smoke (ISSUE 18: merged /metrics + cross-process traces + SLO accounting + bench diff) ==="
# The fleet-wide telemetry plane end to end, through the REAL CLI
# against a 2-PROCESS socket fleet: (1) the merged /metrics endpoint
# is scraped over live HTTP inside the --metrics-hold-s window (the
# workers must still be up — the merged render asks them over the
# wire), and the worker-labeled ok-request sums must equal the
# client-observed success total; (2) the per-process span files the
# workers ship at shutdown plus the router's --trace-out reconstruct
# ONE forest whose router-rooted traces descend into worker
# processes; (3) a deliberately impossible p99 target (0.0001 ms)
# makes the SLO monitor charge provably nonzero
# pyconsensus_slo_violation_seconds, visible in the CLI JSON summary
# AND in the merged scrape; (4) two bench artifacts of the same build
# must agree under tools/bench_diff.py — digests exactly, numerics
# within tolerance.
TELDIR=$(mktemp -d /tmp/ci-telemetry.XXXXXX)
"$VENV/bin/pyconsensus-serve" --fleet-workers 2 --transport socket \
  --requests 32 --concurrency 4 --shapes 12x48 \
  --slo-p99-ms 0.0001 --slo-window-s 30 \
  --metrics-port 0 --metrics-hold-s 8 \
  --log-dir "$TELDIR/fleet" --trace-out "$TELDIR/router-trace.jsonl" \
  >"$TELDIR/stats.json" 2>"$TELDIR/stderr.log" &
TEL_PID=$!
# discover the bound port from the CLI's stderr announcement, then
# scrape the merged endpoint once the hold window opens (the counters
# are final by then — the hold starts after the load run)
"$PY" - "$TELDIR" <<'PYEOF'
import pathlib, re, sys, time, urllib.request

d = pathlib.Path(sys.argv[1])
deadline = time.monotonic() + 180

def stderr_text():
    p = d / "stderr.log"
    return p.read_text() if p.exists() else ""

port = None
while time.monotonic() < deadline and port is None:
    m = re.search(r"metrics endpoint: http://127\.0\.0\.1:(\d+)/metrics",
                  stderr_text())
    port = int(m.group(1)) if m else None
    port or time.sleep(0.25)
assert port, "CLI never announced the metrics endpoint"
while time.monotonic() < deadline and \
        "holding /metrics open" not in stderr_text():
    time.sleep(0.25)
body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                              timeout=30).read().decode("utf-8")
(d / "scrape.prom").write_text(body)
print(f"scraped merged /metrics on port {port}: {len(body)} bytes")
PYEOF
wait "$TEL_PID"
"$PY" - "$TELDIR" <<'PYEOF'
import json, pathlib, re, sys

from pyconsensus_tpu import obs

d = pathlib.Path(sys.argv[1])
stats = json.loads((d / "stats.json").read_text())
scrape = (d / "scrape.prom").read_text()

# (1) aggregation: worker-labeled ok-request sums == client total
pat = re.compile(
    r'^pyconsensus_serve_requests_total\{([^}]*)\}\s+(\S+)$', re.M)
per_worker = {}
for labels, val in pat.findall(scrape):
    lab = dict(kv.split("=", 1) for kv in labels.split(","))
    w = lab.get("worker", '""').strip('"')
    if w.startswith("w") and lab.get("outcome") == '"ok"':
        per_worker[w] = per_worker.get(w, 0.0) + float(val)
assert stats["succeeded"] == 32 and stats["failed"] == 0, stats
total = int(sum(per_worker.values()))
assert total == stats["succeeded"], (
    f"worker-labeled sums {per_worker} != client total "
    f"{stats['succeeded']}")
assert len(per_worker) == 2, per_worker
hb = re.findall(r'pyconsensus_fleet_heartbeat_seconds_count'
                r'\{[^}]*worker="w\d+"[^}]*\}', scrape)
assert len(hb) >= 2, "merged scrape lost the per-worker heartbeats"

# (3) SLO: the impossible target charged real seconds, in the summary
# AND in the merged scrape (router-registry series, worker-labeled)
viol = stats["slo"]["violation_s"].get("p99_ms", 0)
assert viol and viol > 0, stats["slo"]
assert re.search(
    r'pyconsensus_slo_violation_seconds\{[^}]*slo="p99_ms"', scrape), \
    "violation counter missing from the merged scrape"

# (2) tracing: one merged forest; router-rooted traces must descend
# across the RPC hop into worker-side spans
trace_files = sorted(
    str(p) for p in (d / "fleet").glob("*/trace-*.jsonl"))
assert len(trace_files) == 2, trace_files
events = obs.merge_jsonl(trace_files
                         + [str(d / "router-trace.jsonl")])
forest = obs.trace_forest(events)

def crosses(node, src):
    return (node.get("source") != src
            or any(crosses(c, src) for c in node["children"]))

cross = sum(
    1 for roots in forest.values() for r in roots
    if r.get("source") == "router" and r["name"] == "fleet.submit"
    and crosses(r, "router"))
assert cross > 0, "no router-rooted trace descended into a worker"
print(f"telemetry plane OK: {total} worker-labeled ok requests == "
      f"client total over {len(per_worker)} workers in one scrape, "
      f"{cross} cross-process trace(s), "
      f"slo_violation_seconds[p99_ms]={viol}s")
PYEOF

# (4) bench_diff over two artifacts of the same build: digests must
# match exactly; throughput wobble stays inside the default tolerance
for run in a b; do
  "$PY" bench.py --reporters 48 --events 128 --repeats 1 --batches 1 \
    --max-iterations 1 --no-latency --no-roofline --no-device-scaling \
    --no-incremental --no-serve --no-cold-start --no-econ \
    --no-multiproc --no-telemetry --no-fleet --bench-timeout 300 \
    | tail -1 >"$TELDIR/bench-$run.json"
done
"$PY" tools/bench_diff.py "$TELDIR/bench-a.json" "$TELDIR/bench-b.json" \
  && echo "bench_diff OK: two same-build artifacts agree (digests exact)"
rm -rf "$TELDIR"

echo "=== Autoscale chaos smoke (ISSUE 19: flash crowd, kill -9 replacement, idle drain-down) ==="
# The elastic-fleet acceptance criterion end to end, over REAL worker
# processes (socket transport): a 1-worker fleet under a flash-crowd
# burst breaches the windowed shed_ratio SLO and the autoscaler grows
# it — the NEW process adopts the shared AOT disk cache at ZERO
# retraces; a mid-run `kill -9` lands on the session owner's process
# and the loop REPLACES it (fresh name) after the heartbeat monitor's
# declaration + takeover, never double-firing against it; the burst
# ends and sustained idleness drains the fleet back down gracefully
# with live sessions migrated. Every stateless resolution must be
# bit-identical to a direct Oracle run, every shed PYC401-coded, and
# every session round bit-identical to a single-box DurableSession
# replay of the same blocks. See docs/SERVING.md "Elastic fleet".
ASDIR=$(mktemp -d)
"$PY" - "$ASDIR" <<'PYEOF'
import os
import signal
import sys
import threading
import time

import numpy as np

from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.faults import (FailoverInProgressError,
                                    ServiceOverloadError, TransportError,
                                    WorkerLostError)
from pyconsensus_tpu.obs import SloMonitor
from pyconsensus_tpu.serve import ServeConfig
from pyconsensus_tpu.serve.autoscale import AutoScaler, AutoscaleConfig
from pyconsensus_tpu.serve.failover import DurableSession
from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig

base = sys.argv[1]
# per-worker admission capacity is the scaling signal on a one-box CI
# host: each worker's token bucket admits ~6 rps, the flash crowd
# pushes well past one worker's budget, and the windowed shed_ratio is
# what the autoscaler watches (the bench autoscale block uses the same
# model)
cfg = ServeConfig(warmup=((16, 64),), pallas_buckets=False,
                  batch_window_ms=1.0, rate_limit_rps=6.0,
                  aot_cache_dir=os.path.join(base, "aot"))

# ONE-worker fleet: w0 compiles the warmup bucket and persists it — the
# shared AOT disk cache is the warm-start medium every scaled-up worker
# adopts
fleet = ConsensusFleet(FleetConfig(
    n_workers=1, transport="socket", monitor=True,
    heartbeat_timeout_s=8.0, heartbeat_interval_s=0.5,
    log_dir=os.path.join(base, "fleet"), worker=cfg)).start()
persisted = fleet.workers["w0"].call("metric", {
    "name": "pyconsensus_aot_persist_total",
    "labels": {"outcome": "written"}})["value"]
assert persisted and persisted >= 1, persisted

slo = SloMonitor(targets={"shed_ratio": 0.05}, window_s=2.0,
                 snapshot_fn=fleet.merged_snapshot)
slo.run_in_thread(interval_s=0.25)
scaler = AutoScaler(fleet, slo, AutoscaleConfig(
    min_workers=1, max_workers=2, interval_s=0.25,
    up_signals=2, down_signals=5, cooldown_s=1.0)).run_in_thread()


def decisions(action):
    return int(obs.value("pyconsensus_autoscale_decisions_total",
                         action=action) or 0)


def make_block(k, j):
    rng = np.random.default_rng([7, k, j])
    b = rng.choice([0.0, 1.0], size=(12, 5))
    b[rng.random(b.shape) < 0.1] = np.nan
    return b


RETRYABLE = (WorkerLostError, FailoverInProgressError,
             ServiceOverloadError, TransportError, OSError)


def retried(fn, attempts=60):
    last = None
    for _ in range(attempts):
        try:
            return fn()
        except RETRYABLE as exc:
            last = exc
            hint = getattr(exc, "context", {})
            time.sleep(float(hint.get("retry_after_s", 0.25) or 0.25))
    raise last


# flash-crowd traffic: NaN'd so it maps to the WARMED has_na=True
# bucket; every resolution must be bit-identical to a direct Oracle
# run, every shed must carry the structured PYC taxonomy
rng = np.random.default_rng(0)
matrix = rng.choice([0.0, 1.0], size=(16, 64))
matrix[rng.random(matrix.shape) < 0.05] = np.nan
want = Oracle(reports=matrix, backend="jax",
              pca_method="power").consensus()
stop, burst = threading.Event(), threading.Event()
burst.set()
errs, served, sheds = [], [0], [0]


def traffic():
    while not stop.is_set():
        try:
            r = fleet.submit(reports=matrix,
                             tenant="crowd").result(timeout=60)
            assert np.array_equal(
                np.asarray(r["events"]["outcomes_final"]),
                np.asarray(want["events"]["outcomes_final"]))
            assert np.array_equal(
                np.asarray(r["events"]["outcomes_adjusted"]),
                np.asarray(want["events"]["outcomes_adjusted"]))
            served[0] += 1
        except ServiceOverloadError as exc:
            if exc.error_code != "PYC401" or \
                    not exc.context.get("reason"):
                errs.append(exc)
                return
            sheds[0] += 1
        except RETRYABLE:
            time.sleep(0.05)
        except Exception as exc:        # noqa: BLE001 — fail the stage
            errs.append(exc)
            return
        # the flash crowd is paced (a 1-core CI host must not drown the
        # heartbeat plane in shed round-trips); still ~5x one worker's
        # admission budget
        time.sleep(0.03 if burst.is_set() else 0.5)


t = threading.Thread(target=traffic)
t.start()

# an acknowledged round BEFORE any chaos
fleet.create_session("ci-elastic", n_reporters=12)
results = []
fleet.append("ci-elastic", make_block(0, 0))
fleet.append("ci-elastic", make_block(0, 1))
results.append(fleet.submit(session="ci-elastic").result(timeout=120))

# (1) the flash crowd breaches the windowed shed_ratio SLO: the loop
# grows the fleet; the NEW process must adopt the shared AOT cache —
# zero retraces
deadline = time.time() + 120
while len(fleet.ring.workers()) < 2 and time.time() < deadline:
    assert not errs, errs
    time.sleep(0.1)
ring = sorted(fleet.ring.workers())
assert len(ring) == 2, (ring, scaler.status())
assert decisions("scale_up") >= 1
grown = [n for n in ring if n != "w0"]
assert grown and grown[0] != "w0"
new = fleet.workers[grown[0]]
assert new.process.proc.pid != fleet.workers["w0"].process.proc.pid
r = new.call("metric", {"name": "pyconsensus_jit_retraces_total",
                        "labels": {"entry": "serve_bucket"}})["value"]
assert (r or 0) == 0, r
loaded = new.call("metric", {"name": "pyconsensus_aot_load_total",
                             "labels": {"outcome": "loaded"}})["value"]
assert loaded and loaded >= 1, loaded
scaled_to = grown[0]

# (2) mid-run kill -9: SIGKILL the session owner's PROCESS. The
# heartbeat monitor declares the death and the survivor adopts the
# session (exactly-once); the autoscaler — which only ever ADDS
# capacity — replaces the lost worker with a FRESH name, composing
# with (never double-firing against) the declaration
owner = fleet.owner_of("ci-elastic")
fleet.append("ci-elastic", make_block(1, 0))
handle = fleet.workers[owner]
os.kill(handle.process.proc.pid, signal.SIGKILL)
handle.process.proc.wait(timeout=30)

deadline = time.time() + 120
while time.time() < deadline:
    assert not errs, errs
    ring = sorted(fleet.ring.workers())
    if len(ring) == 2 and owner not in ring and decisions("replace"):
        break
    time.sleep(0.1)
ring = sorted(fleet.ring.workers())
assert len(ring) == 2 and owner not in ring, (owner, ring)
assert decisions("replace") >= 1
fresh = [n for n in ring if n not in ("w0", scaled_to)]
assert fresh, (ring, "replacement must mint a FRESH name")
new_owner = retried(lambda: fleet.owner_of("ci-elastic"))
assert new_owner != owner
retried(lambda: fleet.append("ci-elastic", make_block(1, 1),
                             append_id="ci-r1b1"))
results.append(retried(
    lambda: fleet.submit(session="ci-elastic").result(120)))

# (3) the burst ends: sustained idleness scales the fleet back down
# via graceful DRAIN — live sessions migrated, zero lost rounds
burst.clear()
deadline = time.time() + 120
while time.time() < deadline:
    assert not errs, errs
    if (len(fleet.ring.workers()) == 1
            and decisions("scale_down") >= 1):
        break
    time.sleep(0.1)
ring = list(fleet.ring.workers())
assert len(ring) == 1, (ring, scaler.status())
assert decisions("scale_down") >= 1, scaler.status()
last = scaler.status()
victims = [n for n in ("w0", scaled_to, *fresh) if n != owner
           and n not in ring and n in fleet.workers]
assert victims and all(not fleet.workers[v].alive for v in victims), \
    victims                              # drain clean: victim shut down
assert fleet.owner_of("ci-elastic") == ring[0]

stop.set()
t.join(30)
assert not errs, errs
assert served[0] > 0 and sheds[0] > 0, (served, sheds)

# the surviving worker serves the next round; every resolved round —
# across scale-up, kill -9 + replacement, and drain-down — must be
# bit-identical to a direct single-box DurableSession run
fleet.append("ci-elastic", make_block(2, 0))
fleet.append("ci-elastic", make_block(2, 1))
results.append(fleet.submit(session="ci-elastic").result(timeout=120))

ref = DurableSession.create(os.path.join(base, "ref"), "ci-elastic", 12)
for k, got in enumerate(results):
    for j in range(2):
        ref.append(make_block(k, j))
    wantr = ref.resolve()
    np.testing.assert_array_equal(
        np.asarray(got["events"]["outcomes_adjusted"]),
        np.asarray(wantr["outcomes_adjusted"]), err_msg=f"round {k}")
    np.testing.assert_array_equal(
        np.asarray(got["agents"]["smooth_rep"]),
        np.asarray(wantr["smooth_rep"]), err_msg=f"round {k}")

scaler.stop()
slo.stop()
fleet.close(drain=True)
print(f"autoscale chaos OK: flash crowd scaled 1->2 ({scaled_to} "
      f"adopted the AOT cache at 0 retraces), kill -9 on {owner} "
      f"replaced by {fresh[0]} without double-firing the takeover, "
      f"idle drain scaled back to {ring[0]}; {served[0]} stateless "
      f"resolutions bit-identical to direct Oracle, {sheds[0]} sheds "
      f"all PYC401-coded, 3 session rounds bit-identical to the "
      f"single-box run; decisions: up={decisions('scale_up')} "
      f"replace={decisions('replace')} down={decisions('scale_down')}")
PYEOF
rm -rf "$ASDIR"
# the taint/lock/protocol/determinism layers stay green over the new
# autoscale module (shipped baseline EMPTY — the full --strict gate
# above already covers the package; this names the check the ISSUE
# asks for)
"$PY" -m pyconsensus_tpu.analysis \
  --select CL401,CL402,CL403,CL404,CL801,CL802,CL803,CL804,CL805,CL901,CL902,CL903,CL904,CL905 \
  pyconsensus_tpu/serve/autoscale.py \
  && echo "autoscale lint OK: CL401-404 + CL801-805 + CL901-905 green over serve/autoscale"

echo "=== State-plane smoke (ISSUE 20: 5k sessions, hot-capacity 256, compaction + rebalance) ==="
# The million-session acceptance criterion end to end: 5k durable
# sessions on a 2-worker fleet whose hot tier holds only 256 — drip
# traffic forces thousands of cold-session hydrations (each paid
# exactly once, from the compacted LOCAL log), a mid-run compaction
# sweep folds every session's journal into its digest-verified
# snapshot (staged-journal bytes must SHRINK), one live rebalance
# migrates 50 sessions between the two healthy workers over the
# shipping path, and every resolved round — hydrated, compacted,
# migrated or not — must be bit-identical to a direct single-box
# DurableSession run of the same blocks. See docs/SERVING.md
# "State plane".
SPDIR=$(mktemp -d)
"$PY" - "$SPDIR" <<'PYEOF'
import os
import sys
import time

import numpy as np

from pyconsensus_tpu import obs
from pyconsensus_tpu.serve import ServeConfig
from pyconsensus_tpu.serve.failover import DurableSession
from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig

base = sys.argv[1]
N, VARIANTS, N_REPORTERS, HOT = 5000, 4, 12, 256
t_all = time.time()


def make_block(v, k, j):
    rng = np.random.default_rng([11, v, k, j])
    return rng.choice([0.0, 1.0], size=(N_REPORTERS, 5))


def staged_bytes():
    # the truncatable journal only — what compaction shrinks (each
    # session's snapshot.npz lives OUTSIDE its staged/ dir)
    total = 0
    for root, dirs, files in os.walk(os.path.join(base, "fleet")):
        if os.path.basename(root) == "staged":
            for f in files:
                try:
                    total += os.stat(os.path.join(root, f)).st_size
                except OSError:
                    pass
    return total


# hot-capacity 256 against 5k sessions: almost every touch after the
# seed pass lands COLD and must pay exactly one hydration
cfg = ServeConfig(warmup=(), pallas_buckets=False, batch_window_ms=1.0,
                  hot_sessions=HOT, compact_rounds=1,
                  compact_interval_s=3600.0)
fleet = ConsensusFleet(FleetConfig(
    n_workers=2, log_dir=os.path.join(base, "fleet"),
    worker=cfg)).start()
names = [f"sp-{i:05d}" for i in range(N)]

# phase A: seed every session with one ACKNOWLEDGED round plus two
# staged open-round appends — the journal prefix compaction will fold
# into snapshots
rounds0 = {}
for i, name in enumerate(names):
    v = i % VARIANTS
    fleet.create_session(name, n_reporters=N_REPORTERS)
    fleet.append(name, make_block(v, 0, 0))
    fleet.append(name, make_block(v, 0, 1))
    rounds0[name] = fleet.submit(session=name).result(timeout=120)
    fleet.append(name, make_block(v, 1, 0))
bytes_before = staged_bytes()
assert bytes_before > 0, bytes_before

# phase B: drip traffic over every session, with the mid-run
# compaction: sweeping each worker's compactor every 200 touches
# catches every session while it is still hot (the sweep walks the
# hot tier only — compaction never forces a hydration)
hyd0 = obs.value("pyconsensus_sessions_hydrated_total") or 0
compacted = 0
for i, name in enumerate(names):
    fleet.append(name, make_block(i % VARIANTS, 1, 1))
    if (i + 1) % 200 == 0:
        for w in fleet.workers.values():
            compacted += w.service.compactor.sweep()["compacted"]
for w in fleet.workers.values():
    compacted += w.service.compactor.sweep()["compacted"]
hydrated = int((obs.value("pyconsensus_sessions_hydrated_total") or 0)
               - hyd0)
assert hydrated >= N - 2 * HOT, hydrated
assert compacted >= N * 0.9, compacted
bytes_after = staged_bytes()
assert bytes_after < bytes_before, (bytes_before, bytes_after)

# phase C: one rebalance — live-migrate 50 sessions between the two
# HEALTHY workers (snapshot + suffix over the shipping path, counted
# by pyconsensus_sessions_rebalanced_total)
reb0 = obs.value("pyconsensus_sessions_rebalanced_total") or 0
w0, w1 = sorted(fleet.workers)
for name in names[:50]:
    dst = w1 if fleet.owner_of(name) == w0 else w0
    fleet.migrate_session(name, dst)
    assert fleet.owner_of(name) == dst, name
moved = int((obs.value("pyconsensus_sessions_rebalanced_total") or 0)
            - reb0)
assert moved == 50, moved

# phase D: resolve round 1 everywhere (cold sessions hydrate from
# snapshot + suffix; 50 just crossed the wire) and pin every round of
# every session bit-identical to a direct single-box DurableSession
# run of the same blocks
refs = {}
for v in range(VARIANTS):
    ref = DurableSession.create(os.path.join(base, f"ref{v}"),
                                f"ref{v}", N_REPORTERS)
    ref.append(make_block(v, 0, 0))
    ref.append(make_block(v, 0, 1))
    r0 = ref.resolve()
    ref.append(make_block(v, 1, 0))
    ref.append(make_block(v, 1, 1))
    refs[v] = (r0, ref.resolve())
for i, name in enumerate(names):
    got1 = fleet.submit(session=name).result(timeout=120)
    want0, want1 = refs[i % VARIANTS]
    for got, want in ((rounds0[name], want0), (got1, want1)):
        np.testing.assert_array_equal(
            np.asarray(got["events"]["outcomes_final"]),
            np.asarray(want["outcomes_final"]), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(got["agents"]["smooth_rep"]),
            np.asarray(want["smooth_rep"]), err_msg=name)

fleet.close(drain=True)
print(f"state-plane smoke OK: {N} sessions on 2 workers at "
      f"hot-capacity {HOT}, {hydrated} cold hydrations, "
      f"{compacted} compactions shrank the staged journal "
      f"{bytes_before} -> {bytes_after} bytes, {moved} live "
      f"migrations, all {2 * N} session rounds bit-identical to the "
      f"single-box run; {time.time() - t_all:.0f}s")
PYEOF
rm -rf "$SPDIR"
# the taint/lock/protocol layers stay green over the new state-plane
# module (shipped baseline EMPTY — the full --strict gate above
# already covers the package; this names the check the ISSUE asks for)
"$PY" -m pyconsensus_tpu.analysis \
  --select CL401,CL402,CL403,CL404,CL801,CL802,CL803,CL804,CL805,CL901,CL902,CL903,CL904,CL905 \
  pyconsensus_tpu/serve/stateplane.py \
  && echo "state-plane lint OK: CL401-404 + CL801-805 + CL901-905 green over serve/stateplane"

echo "=== Adversarial economy smoke (ISSUE 11: adaptive cartels through a 2-worker fleet) ==="
# The economic-soundness acceptance criterion end to end: (1) a 3-round
# camouflage-cartel economy runs through a 2-worker fleet — honest
# reporters end every round at or above their starting reputation
# share (honest yield >= 1), the adaptive cartel's ROI comes out < 1
# (attacking destroyed value), every shed is a structured PYC-coded
# error the bounded retry absorbs, and drain completes clean;
# (2) a REAL `kill -9` lands mid-economy and a fresh fleet RESUMES the
# economy from the replication log alone, finishing with a mechanism
# digest bit-identical to the never-killed run (the econ determinism
# contract — docs/ECONOMY.md).
"$PY" - <<'PYEOF'
import tempfile
import numpy as np
from pyconsensus_tpu.econ import MarketEconomy, build_scenario
from pyconsensus_tpu.serve import ServeConfig
from pyconsensus_tpu.serve.fleet import ConsensusFleet, FleetConfig

log_dir = tempfile.mkdtemp(prefix="ci-econ-")
fleet = ConsensusFleet(FleetConfig(
    n_workers=2, log_dir=log_dir,
    worker=ServeConfig(batch_window_ms=1.0))).start(warmup=False)
scenario = build_scenario(seed=101, rounds=3,
                          strategies=("camouflage",),
                          markets_per_strategy=3, concurrency=6)
result = MarketEconomy(fleet, scenario).run()
fleet.close(drain=True)                        # drain clean

block = result["per_strategy"]["camouflage"]
assert block["cartel_roi"] < 1.0, \
    f"adaptive cartel captured value: ROI {block['cartel_roi']}"
yld = np.asarray(result["trajectories"]["honest_yield"])[0]
assert (yld >= 1.0 - 1e-12).all(), \
    f"honest share fell below its stake: {yld}"
bad = [c for c in result["service"]["errors"] if not c.startswith("PYC")]
assert not bad, f"unstructured shed codes: {bad}"
print(f"econ smoke OK: 9 markets x 3 rounds through the 2-worker fleet "
      f"— cartel ROI {block['cartel_roi']:.3f} (< 1), honest yield "
      f"{block['honest_yield']:.3f} every round >= 1, "
      f"time-to-catch {block['time_to_catch_rounds']} round(s), "
      f"{result['service']['sheds_observed']} sheds all PYC-coded "
      f"({result['service']['retried']} retried), drain clean")
PYEOF
"$PY" - <<'PYEOF'
import json, os, signal, subprocess, sys, tempfile, time

log_root = tempfile.mkdtemp(prefix="ci-econ-kill9-")
proc = subprocess.Popen(
    [sys.executable, "tests/econ_worker.py", log_root],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
try:
    deadline = time.monotonic() + 300
    seen = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        assert line, "econ worker exited early:\n" + "".join(seen)
        seen.append(line)
        if line.startswith("ROUND 1\n") or line.strip() == "ROUND 1":
            break
    else:
        raise SystemExit("econ worker never reached round 1")
    # kill IMMEDIATELY on the marker: round 1 plus round 2 plus the
    # digest print are still entirely ahead of the worker, so the kill
    # always preempts exit — a fixed post-marker sleep would race the
    # economy's completion on a fast machine
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)
finally:
    if proc.poll() is None:
        proc.kill()
assert proc.returncode == -signal.SIGKILL

sys.path.insert(0, "tests")
from econ_worker import make_fleet, make_scenario
from pyconsensus_tpu.econ import MarketEconomy
from pyconsensus_tpu.serve import ConsensusService, ServeConfig

# uninterrupted reference: the same scenario through a single service
# (fleet-vs-service bit-parity is pinned by tests/test_econ.py)
svc = ConsensusService(ServeConfig(batch_window_ms=1.0)).start(warmup=False)
ref = MarketEconomy(svc, make_scenario()).run()
svc.close(drain=True)

fleet = make_fleet(log_root)
resumed = MarketEconomy(fleet, make_scenario()).run()
fleet.close(drain=True)
assert resumed["resumed_markets"] > 0, "resume adopted nothing"
assert resumed["mechanism_digest"] == ref["mechanism_digest"], (
    f"resumed economy diverged: {resumed['mechanism_digest']} != "
    f"{ref['mechanism_digest']}")
print(f"econ kill -9 OK: worker killed inside round 1, fresh fleet "
      f"adopted {resumed['resumed_markets']} market log(s) and finished "
      f"the economy replay-identical to the never-killed run "
      f"(digest {ref['mechanism_digest'][:16]}...)")
PYEOF

echo "=== Incremental serve smoke (ISSUE 12: bucket_incremental marginal resolves) ==="
# The staleness-bound contract end to end on the live service: a warm
# session absorbs small appended blocks across rounds, the marginal
# resolves are SERVED by the bucket_incremental tier (kernel-path
# counter), continuous drift vs the exact resolve of the same
# statistics stays inside the documented band, the exact-refresh round
# is bit-identical to a direct Oracle resolution under the carried
# reputation, and the steady-state serve_bucket_incremental retrace
# counter pins at 1 (one compile per warmed (roster, params)).
"$PY" - <<'PYEOF'
import numpy as np
from pyconsensus_tpu import Oracle, obs
from pyconsensus_tpu.serve import ConsensusService, ServeConfig
from pyconsensus_tpu.serve.incremental import incremental_drift_band
import jax.numpy as jnp

R = 12
def blk(e, seed):
    r = np.random.default_rng(seed)
    b = r.choice([0.0, 1.0], size=(R, e)).astype(float)
    b[r.random((R, e)) < 0.1] = np.nan
    return b

band = incremental_drift_band(jnp.asarray(0.0).dtype)
svc = ConsensusService(ServeConfig(incremental_sessions=True,
                                   incremental_refresh_every=3,
                                   batch_window_ms=1.0)).start(warmup=False)
svc.create_session("inc-market", n_reporters=R)
sess = svc.sessions.get("inc-market")
paths, refresh_checked = [], 0
for k in range(4):
    b = blk(6, 400 + k)
    rep_in = sess.reputation.copy()
    svc.append("inc-market", b)
    exact = sess.peek_resolve()
    got = svc.submit(session="inc-market").result(timeout=120)
    paths.append(sess.last_resolve_path)
    if paths[-1] == "incremental":
        drift = max(float(np.max(np.abs(
            np.asarray(got["agents"][key] if key in got["agents"]
                       else got["events"][key]) - np.asarray(exact[key]))))
            for key in ("smooth_rep", "certainty"))
        assert drift <= band, f"round {k}: drift {drift} > band {band}"
        assert np.array_equal(np.asarray(got["events"]["outcomes_adjusted"]),
                              exact["outcomes_adjusted"])
    else:
        # exact-refresh round: bit-identical to a direct Oracle resolve
        # of the staged round under the carried reputation
        ref = Oracle(reports=b, reputation=rep_in,
                     backend="jax").consensus()
        assert np.array_equal(
            np.asarray(got["events"]["outcomes_adjusted"]),
            np.asarray(ref["events"]["outcomes_adjusted"]))
        assert int(got["iterations"]) == int(ref["iterations"])
        refresh_checked += 1
svc.close(drain=True)
assert paths == ["incremental_exact", "incremental", "incremental",
                 "incremental_exact"], paths
assert (obs.value("pyconsensus_kernel_path_total", path="incremental")
        or 0) == 2, "warm resolves not served by the incremental kernel"
assert (obs.value("pyconsensus_serve_requests_total",
                  path="bucket_incremental", outcome="ok") or 0) == 4
assert (obs.value("pyconsensus_jit_retraces_total",
                  entry="serve_bucket_incremental") or 0) == 1
print(f"incremental smoke OK: 4 rounds (2 warm, 2 exact anchors incl. "
      f"{refresh_checked} Oracle-bitwise refresh check), drift inside "
      f"the {band:g} band, kernel-path counter shows the "
      f"bucket_incremental tier, retraces pinned at 1")
PYEOF
# The econ camouflage smoke routed through the incremental tier: at
# refresh cadence 1 every resolve is the tier's exact anchor, so the
# mechanism digest must be BIT-IDENTICAL to the full-resolve run; at
# cadence 2 the warm kernel serves between anchors and the economy
# must still be deterministic (two runs, one digest).
"$PY" - <<'PYEOF'
from pyconsensus_tpu.econ import MarketEconomy, build_scenario
from pyconsensus_tpu.serve import ConsensusService, ServeConfig

def digest(**cfg):
    svc = ConsensusService(ServeConfig(batch_window_ms=1.0,
                                       **cfg)).start(warmup=False)
    scenario = build_scenario(seed=77, rounds=3,
                              strategies=("camouflage",),
                              markets_per_strategy=3, concurrency=6)
    result = MarketEconomy(svc, scenario).run()
    svc.close(drain=True)
    return result["mechanism_digest"]

full = digest()
anchored = digest(incremental_sessions=True, incremental_refresh_every=1)
assert anchored == full, (
    f"incremental tier at refresh cadence 1 changed the mechanism "
    f"digest: {anchored} != {full}")
warm_a = digest(incremental_sessions=True, incremental_refresh_every=2)
warm_b = digest(incremental_sessions=True, incremental_refresh_every=2)
assert warm_a == warm_b, "warm-path economy is not deterministic"
print(f"econ-through-incremental OK: cadence-1 digest identical to the "
      f"full-resolve run ({full[:16]}...), cadence-2 warm economy "
      f"deterministic across runs ({warm_a[:16]}...)")
PYEOF

echo "=== Pipelined-ingest smoke (ISSUE 13: device encode parity + depth-2 digest + aliasing contract) ==="
# (1) the device encoder is bit-identical to the host reference on
# lattice AND off-lattice (rounding) panels; (2) a depth-2 pipelined
# serve run is digest-identical to the synchronous depth-1 run with
# retraces pinned at the warmed bucket count; (3) the CL306 aliasing
# contract holds on the live donated bucket executables (also gated by
# --strict above — this asserts the alias table directly so a silent
# contract-scoping regression cannot hide it).
"$PY" - <<'PYEOF'
import hashlib
import numpy as np
import jax.numpy as jnp
from pyconsensus_tpu import obs
from pyconsensus_tpu.models.pipeline import (encode_reports_device,
                                             encode_reports_host)
rng = np.random.default_rng(5)
lat = rng.choice([0.0, 0.5, 1.0, np.nan], size=(64, 256),
                 p=[.4, .2, .3, .1]).astype(np.float32)
off = (rng.random((32, 64), dtype=np.float32) * 1.4 - 0.2)
for panel in (lat, off):
    host = encode_reports_host(panel)
    dev = np.asarray(encode_reports_device(jnp.asarray(panel)))
    assert np.array_equal(host, dev), "device encode != host encode"
assert (obs.value("pyconsensus_ingest_encodes_total", path="device")
        or 0) >= 2
print("device-encode parity probe OK (lattice + off-lattice rounding)")

from pyconsensus_tpu.serve import ConsensusService, ServeConfig
panels = [rng.choice([0.0, 1.0, np.nan], size=(12, 48),
                     p=[.45, .45, .1]) for _ in range(10)]

def run(depth):
    obs.reset()
    cfg = ServeConfig(warmup=((16, 64),), batch_window_ms=1.0,
                      pipeline_depth=depth, sharded_buckets=False,
                      pallas_buckets=False)
    with ConsensusService(cfg) as svc:
        outs = [svc.submit(reports=p).result(60) for p in panels]
        retr = obs.value("pyconsensus_jit_retraces_total",
                         entry="serve_bucket")
    h = hashlib.sha256()
    for o in outs:
        for sec in ("events", "agents"):
            for k in sorted(o[sec]):
                h.update(np.ascontiguousarray(
                    np.asarray(o[sec][k])).tobytes())
    return h.hexdigest(), retr

d1, r1 = run(1)
d2, r2 = run(2)
assert d1 == d2, f"depth-2 digest {d2[:16]} != sync digest {d1[:16]}"
assert r1 == r2 == 1, f"retraces drifted: sync {r1}, depth-2 {r2}"
print(f"depth-2 pipelined serve digest-identical to sync "
      f"({d1[:16]}...), retraces pinned at 1")

from pyconsensus_tpu.analysis.contracts import (input_output_aliases,
                                                run_contracts)
findings = run_contracts(names=["serve-bucket",
                                "serve-bucket-scaled-alias",
                                "serve-bucket-sharded"])
assert not findings, findings
from pyconsensus_tpu.models.pipeline import ConsensusParams
from pyconsensus_tpu.serve.kernels import make_bucket_executable
import jax
p = ConsensusParams(algorithm="sztorc", pca_method="power",
                    has_na=True, any_scaled=True, n_scaled=0)
dt = jnp.asarray(0.0).dtype
args = (jax.ShapeDtypeStruct((16, 64), dt),
        jax.ShapeDtypeStruct((16,), dt),
        jax.ShapeDtypeStruct((64,), bool),
        jax.ShapeDtypeStruct((64,), dt),
        jax.ShapeDtypeStruct((64,), dt),
        jax.ShapeDtypeStruct((16,), bool),
        jax.ShapeDtypeStruct((64,), bool),
        jax.ShapeDtypeStruct((64,), dt))
txt = make_bucket_executable(p, donate=True).lower(*args, p)\
    .compile().as_text()
aliases = input_output_aliases(txt)
assert len(aliases) >= 4, f"expected >= 4 donated aliases, {aliases}"
print(f"aliasing contract OK: {len(aliases)} donated pad buffers "
      f"aliased in the compiled module")
PYEOF

echo "=== bench.py JSON contract (tiny shape, CPU) ==="
"$PY" bench.py --reporters 64 --events 256 --repeats 2 --batches 2 \
  --econ-sessions 48 --econ-rounds 2 --bench-timeout 420 \
  --state-plane-sessions 200 --state-plane-hot 32 \
  --incremental-shape 128x512 --incremental-append-sizes 4,16 \
  --incremental-samples 2 | tail -1 | "$PY" -c \
  "import json,sys; d=json.load(sys.stdin); e=d['economy']; i=d['incremental']; \
assert all(a['drift_within_band'] and a['outcomes_match_exact'] \
           for a in i['appends']) and i['refresh_bitwise_outcomes']; \
p=d['pipeline']; assert p['digest_match'] and p['added_retraces'] == 0 \
    and p['depth'] >= 2; \
r=d['roofline']; assert r['rungs'] and all(x['bound_rps'] > 0 \
    for x in r['rungs']); \
assert 'path' in d['encode']; \
assert all('backend' in x for x in d['device_scaling'] or []); \
m=d['multiproc']; assert m and m['socket']['throughput_rps'] > 0 \
    and m['socket']['takeover_ms'] > 0 \
    and m['socket']['rpc_overhead_ms_p50'] > 0 \
    and m['inprocess']['throughput_rps'] > 0; \
sp=d['state_plane']; assert sp and sp['bit_identical_sample'] \
    and sp['hydrations'] > 0 and sp['touch_ms_p99_tiered'] > 0 \
    and sp['takeover_ms_compacted'] > 0 \
    and sp['journal_bytes_compacted'] < sp['journal_bytes_uncompacted']; \
print('bench JSON ok:', d['metric'], '| economy:', e['sessions'], \
'sessions,', len(e['strategies']), 'strategies', '| incremental:', \
len(i['appends']), 'append sizes, drift in band, refresh bitwise', \
'| pipeline: depth', p['depth'], 'speedup', p['speedup'], \
'digests match | roofline:', len(r['rungs']), 'rungs', \
'| multiproc: socket', m['socket']['throughput_rps'], 'rps,', \
m['socket']['rpc_overhead_ms_p50'], 'ms/rpc, takeover', \
m['socket']['takeover_ms'], 'ms', '| state_plane:', sp['sessions'], \
'sessions at hot', sp['hot_capacity'], ',', sp['hydrations'], \
'hydrations, bit-identical sample')"

echo "=== CI rehearsal GREEN ==="
