#!/usr/bin/env python
"""Closed/open-loop load generator for the consensus service — thin
launcher for :mod:`pyconsensus_tpu.serve.loadgen` (the implementation
lives in the package so the installed ``pyconsensus-serve`` console
script can reach it; this shim keeps the documented ``tools/loadgen.py``
front door working from a checkout).

    python tools/loadgen.py --requests 64 --concurrency 8
"""

import sys

from pyconsensus_tpu.serve.loadgen import main

if __name__ == "__main__":
    sys.exit(main())
