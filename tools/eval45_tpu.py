"""Round-5 (VERDICT r4 item 4): first on-chip numbers for eval configs 4
and 5 (BASELINE.json:10-11) — the clustering variants that don't fit
bench.py (hybrid host paths) and the Monte-Carlo collusion sweep.

Banked to docs/MEASUREMENTS_r05.json with the suite's keyed-upsert
convention. The jit clustering variants (k-means / dbscan-jit) at the
bench shape are bench.py modes, run via
``tools/tpu_measurements.py --only kmeans,dbscan_jit``.

Usage: python tools/eval45_tpu.py [--stage sweep,hybrid]
           [--out docs/MEASUREMENTS_r05.json]
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

import numpy as np  # noqa: E402


def _bank(out_path: pathlib.Path, entry: dict) -> None:
    results = []
    if out_path.exists():
        try:
            results = [m for m in json.loads(out_path.read_text())
                       if isinstance(m, dict)]
        except ValueError:
            results = []
    for i, m in enumerate(results):
        if m.get("_name") == entry["_name"]:
            results[i] = entry
            break
    else:
        results.append(entry)
    out_path.write_text(json.dumps(results, indent=1) + "\n")
    print(f"banked {entry['_name']} -> {out_path}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", default="sweep,hybrid")
    ap.add_argument("--out", default=str(ROOT / "docs/MEASUREMENTS_r05.json"))
    args = ap.parse_args()
    stages = set(args.stage.split(","))
    out_path = pathlib.Path(args.out)

    import jax

    backend = jax.default_backend()
    print(f"backend: {backend}", flush=True)

    if "sweep" in stages:
        # config 5: (liar_fraction x variance x seed) grid, 10k trials,
        # one batched XLA program; scalar-only egress. Shape mirrors eval
        # config 1's 50 x 25 oracle (the reference simulator's scale).
        from pyconsensus_tpu.sim import CollusionSimulator

        sim = CollusionSimulator(n_reporters=50, n_events=25,
                                 max_iterations=1, pca_method="power")
        lfs, variances, n_trials = [0.0, 0.1, 0.2, 0.3, 0.4], [0.05, 0.1], \
            1000
        n_total = len(lfs) * len(variances) * n_trials
        t0 = time.time()
        sim.run(lfs, variances, n_trials, seed=0)       # compile + run
        t_cold = time.time() - t0
        t0 = time.time()
        out = sim.run(lfs, variances, n_trials, seed=1)
        t_warm = time.time() - t0
        _bank(out_path, {
            "_name": "mc_sweep_10k_trials",
            "backend": backend,
            "oracle_shape": [50, 25], "n_trials": n_total,
            "grid": {"liar_fractions": lfs, "variances": variances,
                     "trials_per_cell": n_trials},
            "cold_s": round(t_cold, 3), "warm_s": round(t_warm, 3),
            "trials_per_sec_warm": round(n_total / t_warm, 1),
            "correct_rate_at_0": float(out["mean"]["correct_rate"][0, 0]),
            "_note": "eval config 5 on chip: 10k-trial collusion sweep "
                     "as ONE vmapped XLA dispatch (warm = steady-state "
                     "throughput; cold includes compile)"})

    if "hybrid" in stages:
        # config 4's hybrid variants: device kernels for fill + R x R
        # distances, host C++ NN-chain / DBSCAN for the merge loop
        from pyconsensus_tpu.models.pipeline import ConsensusParams
        from pyconsensus_tpu.parallel import make_mesh, sharded_consensus

        mesh = make_mesh(batch=1, event=len(jax.devices()))
        rng = np.random.default_rng(0)
        R, E = 4096, 32768
        r = rng.random((R, E), dtype=np.float32)
        reports = np.where(r < 0.45, 0.0,
                           np.where(r < 0.95, 1.0, 0.5)).astype(np.float32)
        reports[rng.random((R, E)) < 0.02] = np.nan
        for algo, kw in (("hierarchical", {"hierarchy_threshold": 1.5}),
                         ("dbscan", {"dbscan_eps": 1.0})):
            p = ConsensusParams(algorithm=algo, has_na=True, **kw)
            t0 = time.time()
            out = sharded_consensus(reports, mesh=mesh, params=p)
            outc = np.asarray(out["outcomes_adjusted"])
            t_cold = time.time() - t0
            t0 = time.time()
            out = sharded_consensus(reports, mesh=mesh, params=p)
            outc = np.asarray(out["outcomes_adjusted"])
            t_warm = time.time() - t0
            ok = bool(np.isin(outc, [0.0, 0.5, 1.0]).all())
            _bank(out_path, {
                "_name": f"hybrid_{algo}_{R}x{E}",
                "backend": backend, "shape": [R, E],
                "cold_s": round(t_cold, 3),
                "latency_s": round(t_warm, 3),
                "outcomes_snapped": ok,
                "_note": "eval config 4 on chip: hybrid variant — device "
                         "fill + R x R Gram distances, host native "
                         "clustering; warm latency is the honest "
                         "number (cold includes compile)"})
            assert ok

    print("eval45 complete", flush=True)


if __name__ == "__main__":
    main()
