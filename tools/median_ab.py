"""A/B microbench: weighted-median implementations at scaled-heavy shape.

Legacy baseline (inlined below — this WAS ``_weighted_median_cols_block``
until round 3): stable argsort + 2x take_along_axis gathers + cumsum.
Landed implementation (``ops.jax_kernels.weighted_median_cols``): one
variadic ``lax.sort`` carrying (values, weights) — same stable order
(num_keys=1 keeps the iota tie-break via stability), no (R, C) gathers.
Measured 2026-07-31 on v5e at 10k x 4096: legacy 1052-1330 ms, landed
113-132 ms (~8.7x) — the number cited in docs/PERFORMANCE.md's round-3
kernel lesson; re-run this tool to reproduce it.

Timing note: fetch a dependent scalar per call — on the tunneled axon
platform ``block_until_ready`` returns before remote execution finishes.

Usage: PYTHONPATH must include the repo root alongside the axon site dir:
    env PYTHONPATH=/root/.axon_site:/root/repo python tools/median_ab.py
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pyconsensus_tpu.ops.jax_kernels import weighted_median_cols


def legacy_argsort_block(values, weights, present):
    """The pre-round-3 block implementation, kept verbatim as baseline."""
    if weights.ndim == 1:
        weights = jnp.broadcast_to(weights[:, None], values.shape)
    values = values.astype(jnp.promote_types(values.dtype, weights.dtype))
    R = values.shape[0]
    big = jnp.where(present, values, jnp.inf)
    w_raw = jnp.where(present, weights, 0.0)
    order = jnp.argsort(big, axis=0, stable=True)
    v = jnp.take_along_axis(big, order, axis=0)
    w = jnp.take_along_axis(w_raw, order, axis=0)
    total = jnp.sum(w, axis=0)
    safe_total = jnp.where(total > 0.0, total, 1.0)
    cw = jnp.cumsum(w / safe_total[None, :], axis=0)
    ge = cw >= 0.5
    idx = jnp.argmax(ge, axis=0)
    idx = jnp.where(jnp.any(ge, axis=0), idx, R - 1)
    take_col = lambda a, i: jnp.take_along_axis(a, i[None, :], axis=0)[0]  # noqa: E731
    cw_i = take_col(cw, idx)
    v_i = take_col(v, idx)
    nxt = jnp.clip(idx + 1, 0, R - 1)
    v_n = take_col(v, nxt)
    exact = jnp.abs(cw_i - 0.5) <= (1e-8 + 1e-5 * 0.5)
    has_next = (idx + 1 < R) & jnp.isfinite(v_n)
    med = jnp.where(exact & has_next, 0.5 * (v_i + v_n), v_i)
    return jnp.where(total > 0.0, med, 0.5)


def legacy_argsort_median(values, weights, present, block_cols=1024):
    R, E = values.shape
    if block_cols > 0 and E > block_cols:
        n_full = E // block_cols

        def one_block(i):
            sl = lambda a: lax.dynamic_slice_in_dim(  # noqa: E731
                a, i * block_cols, block_cols, axis=1)
            w = weights if weights.ndim == 1 else sl(weights)
            return legacy_argsort_block(sl(values), w, sl(present))

        blocks = lax.map(one_block, jnp.arange(n_full)).reshape(-1)
        tail = E - n_full * block_cols
        if not tail:
            return blocks
        start = n_full * block_cols
        return jnp.concatenate([blocks, legacy_argsort_block(
            values[:, start:],
            weights if weights.ndim == 1 else weights[:, start:],
            present[:, start:])])
    return legacy_argsort_block(values, weights, present)


def _time(f, *a):
    float(np.asarray(f(*a).sum()))            # compile + honest barrier
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(np.asarray(f(*a).sum()))
        ts.append(time.perf_counter() - t0)
    return min(ts) * 1e3


def main():
    R = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    C = int(sys.argv[2]) if len(sys.argv) > 2 else 4_096
    k1, k2 = jax.random.split(jax.random.key(0))
    vals = jax.random.uniform(k1, (R, C))
    pres = jax.random.bernoulli(k2, 0.98, (R, C))
    rep = jnp.full((R,), 1.0 / R)
    for blk in (0, 1024, 2048):
        cur = jax.jit(lambda v, w, p, b=blk: weighted_median_cols(v, w, p, b))
        old = jax.jit(lambda v, w, p, b=blk: legacy_argsort_median(v, w, p, b))
        # equality is checked loosely: crossing selection is ulp-sensitive
        # to the cumsum lowering across graphs (see the kernel docstring)
        a = np.asarray(cur(vals, rep, pres))
        b = np.asarray(old(vals, rep, pres))
        n_diff = int((a != b).sum())
        print(f"blk={blk}: legacy argsort+gather {_time(old, vals, rep, pres):.1f} ms"
              f"  landed variadic-sort {_time(cur, vals, rep, pres):.1f} ms"
              f"  (value diffs at uniform-weight ties: {n_diff}/{C})")


if __name__ == "__main__":
    main()
