#!/usr/bin/env python
"""Error-code drift check: code vs docs/ROBUSTNESS.md (ISSUE 16).

Every class in the ``faults.ERROR_CODES`` taxonomy must have a row in
docs/ROBUSTNESS.md's error-code table carrying its stable PYC code and
its class name, and every table row must correspond to a registered
class — the table is what operators grep when a structured refusal
crosses the wire, so a missing or stale row is a lie at debug time.
The ``check_metric_docs.py`` pattern (which caught real drift at 44
metrics), applied to the error taxonomy; the registry's internal
soundness (registration, marshalability, retry semantics) is
consensus-lint CL903's job — this script only pins the docs.

Zero dependencies; importable — :func:`check` returns the drift lists
so the test suite can assert on them directly.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import Dict, List, Tuple

REPO = pathlib.Path(__file__).resolve().parents[1]
ERRORS = REPO / "pyconsensus_tpu" / "faults" / "errors.py"
CATALOG = REPO / "docs" / "ROBUSTNESS.md"

#: a catalog table row: first cell the bare PYC code, second cell the
#: backticked class name (later cells are prose and may mention other
#: codes/classes — only the leading pair identifies the row)
_ROW_RE = re.compile(r"^\|\s*(PYC\d+)\s*\|\s*`(\w+)`")


def collect_registered(errors: pathlib.Path = ERRORS) -> Dict[str, str]:
    """{code: class name} for every class in faults/errors.py that is
    both taxonomy-shaped (class-level ``error_code`` string) and named
    in the ``ERROR_CODES`` registry tuple."""
    tree = ast.parse(errors.read_text(encoding="utf-8"),
                     filename=str(errors))
    by_class: Dict[str, str] = {}
    registered: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.Assign) \
                        and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and sub.targets[0].id == "error_code" \
                        and isinstance(sub.value, ast.Constant) \
                        and isinstance(sub.value.value, str):
                    by_class[node.name] = sub.value.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "ERROR_CODES" \
                and isinstance(node.value, ast.DictComp):
            it = node.value.generators[0].iter
            if isinstance(it, (ast.Tuple, ast.List)):
                registered |= {e.id for e in it.elts
                               if isinstance(e, ast.Name)}
    return {code: name for name, code in sorted(by_class.items())
            if name in registered}


def collect_documented(catalog: pathlib.Path = CATALOG) -> Dict[str, str]:
    """{code: class name} from the error-code table rows of
    docs/ROBUSTNESS.md."""
    out: Dict[str, str] = {}
    for line in catalog.read_text(encoding="utf-8").splitlines():
        m = _ROW_RE.match(line.strip())
        if m:
            out[m.group(1)] = m.group(2)
    return out


def check() -> Tuple[List[str], List[str], List[str]]:
    """(undocumented, unregistered, mismatched) drift lists — each
    entry human-readable. Empty lists = green."""
    registered = collect_registered()
    documented = collect_documented()
    undocumented = [f"{code} ({registered[code]})"
                    for code in sorted(set(registered) - set(documented))]
    unregistered = [f"{code} ({documented[code]})"
                    for code in sorted(set(documented) - set(registered))]
    mismatched = [f"{code}: code has {registered[code]}, docs say "
                  f"{documented[code]}"
                  for code in sorted(set(registered) & set(documented))
                  if registered[code] != documented[code]]
    return undocumented, unregistered, mismatched


def main() -> int:
    undocumented, unregistered, mismatched = check()
    rel = CATALOG.relative_to(REPO)
    for entry in undocumented:
        print(f"DRIFT: error code {entry} is in faults.ERROR_CODES but "
              f"has no row in {rel}")
    for entry in unregistered:
        print(f"DRIFT: {rel} catalogs error code {entry} but "
              f"faults.ERROR_CODES does not register it")
    for entry in mismatched:
        print(f"DRIFT: class-name mismatch for {entry} ({rel})")
    if undocumented or unregistered or mismatched:
        return 1
    print(f"error-code docs in sync: {len(collect_registered())} "
          f"registered code(s) all cataloged, no dead rows")
    return 0


if __name__ == "__main__":
    sys.exit(main())
