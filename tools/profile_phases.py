"""Differential phase timing of the fused light pipeline at bench shape.

Method (docs/PERFORMANCE.md): marginal time = (t(1+N dispatches) - t(1)) / N
with one device-combined scalar fetched per batch, cancelling tunnel RTT and
fixed dispatch costs. All large arrays are passed as jit ARGUMENTS (closing
over them bakes 4 GB constants into the lowering).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from pyconsensus_tpu.models.pipeline import (ConsensusParams, _fill_stats,
                                             _consensus_core_fused)
from pyconsensus_tpu.ops.pallas_kernels import (power_iteration_fused,
                                                scores_dirfix_pass,
                                                resolve_certainty_fused)
from bench import generate_reports_device

R, E = 10_000, 100_000
gen = jax.jit(generate_reports_device, static_argnums=(1, 2))
reports = gen(jax.random.key(0), R, E, 0.02, 0.1, 0.05)
jax.block_until_ready(reports)

rep0 = jnp.full((R,), 1.0 / R)
scaled = jnp.zeros((E,), bool)
zeros = jnp.zeros((E,))
ones = jnp.ones((E,))


def timeit(fn, *args, n=8):
    float(np.asarray(fn(*args)))      # warm + force
    t0 = time.perf_counter()
    float(np.asarray(fn(*args)))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(n + 1)]
    float(np.asarray(jnp.stack(outs).sum()))
    tN = time.perf_counter() - t0
    return (tN - t1) / n


@jax.jit
def ph_fill(reports, rep):
    x, fill, tw, numer = _fill_stats(reports, rep, 0.1, "bfloat16")
    return jnp.sum(fill) + jnp.sum(tw) + x[0, 0].astype(jnp.float32)


fillout = jax.jit(lambda r, p: _fill_stats(r, p, 0.1, "bfloat16"))
x_s, fill_s, tw_s, numer_s = fillout(reports, rep0)
jax.block_until_ready(x_s)
mu1 = numer_s + (1.0 - tw_s) * fill_s
denom = 1.0 - jnp.sum(rep0 ** 2)


@jax.jit
def ph_power1(x, mu, dn, rep, fill):
    return jnp.sum(power_iteration_fused(x, mu, dn, rep, 1, -1.0, fill=fill))


@jax.jit
def ph_power(x, mu, dn, rep, fill):
    return jnp.sum(power_iteration_fused(x, mu, dn, rep, 128, 0.0, fill=fill))


loading_s = jax.jit(lambda x, mu, dn, rep, fill: power_iteration_fused(
    x, mu, dn, rep, 128, 0.0, fill=fill))(x_s, mu1, denom, rep0, fill_s)
jax.block_until_ready(loading_s)


@jax.jit
def ph_dirfix(x, rep, loading, fill):
    t, q, c, o = scores_dirfix_pass(x, rep, loading, fill=fill)
    return jnp.sum(t) + jnp.sum(q)


@jax.jit
def ph_resolve(x, rep, fill):
    raw, adj, cert, pcol, prow, narow = resolve_certainty_fused(
        x, rep, fill, jnp.sum(rep), 0.1)
    return jnp.sum(cert) + jnp.sum(adj) + jnp.sum(prow)


P = ConsensusParams(algorithm="sztorc", max_iterations=1, pca_method="auto",
                    power_iters=128, storage_dtype="bfloat16",
                    any_scaled=False, has_na=True, fused_resolution=True)


@jax.jit
def ph_full(reports, rep, scaled, zeros, ones):
    return _consensus_core_fused(reports, rep, scaled, zeros, ones,
                                 P)["avg_certainty"]


for name, fn, args in [
        ("fill_stats", ph_fill, (reports, rep0)),
        ("power_1sweep", ph_power1, (x_s, mu1, denom, rep0, fill_s)),
        ("power_earlyexit", ph_power, (x_s, mu1, denom, rep0, fill_s)),
        ("scores_dirfix", ph_dirfix, (x_s, rep0, loading_s, fill_s)),
        ("resolve_cert", ph_resolve, (x_s, rep0, fill_s)),
        ("FULL_PIPELINE", ph_full, (reports, rep0, scaled, zeros, ones))]:
    ms = timeit(fn, *args) * 1e3
    print(f"{name:18s} {ms:8.2f} ms", flush=True)
