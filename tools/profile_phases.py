"""Differential phase timing of the fused light pipeline at bench shape.

Round-5 rewrite (VERDICT r4 item 3): the old tool timed each phase as an
ISOLATED jit program, and the isolated fill-stats program compiled to a
pathological form (measured 258 ms vs a 37 ms full pipeline — XLA picks
different layouts/fusions without the downstream consumers), so the
"Where the time goes" table never reconciled. This version times a
CUMULATIVE chain — stats; stats+power; stats+power+dirfix; the full
pipeline — at the REAL bench configuration (int8 sentinel storage,
pre-encoded input), so each phase's marginal is the difference of two
programs that both carry the real consumer context. Fixed per-batch
costs cancel via the (t(1+N) - t(1)) / N differential (see
docs/PERFORMANCE.md methodology); per-dispatch overhead remains in the
stats row and is labeled as such.

Each row prints next to its MINIMUM HBM bytes x the v5e's ~819 GB/s —
the roofline statement VERDICT r4 asked for.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from pyconsensus_tpu.models.pipeline import (ConsensusParams, _fill_stats,
                                             _consensus_core_fused,
                                             encode_reports)
from pyconsensus_tpu.ops.pallas_kernels import (power_iteration_fused,
                                                scores_dirfix_pass)
from bench import generate_reports_device

R, E = 10_000, 100_000
HBM_GBPS = 819e9          # v5e spec sheet; the roofline denominator
STORAGE = "int8"
ITEM = 1                  # int8: one byte per element

gen = jax.jit(generate_reports_device, static_argnums=(1, 2))
reports_f32 = gen(jax.random.key(0), R, E, 0.02, 0.1, 0.05)
jax.block_until_ready(reports_f32)
enc = jax.jit(encode_reports)(reports_f32)
jax.block_until_ready(enc)

rep0 = jnp.full((R,), 1.0 / R)
scaled = jnp.zeros((E,), bool)
zeros = jnp.zeros((E,))
ones = jnp.ones((E,))


def timeit(fn, *args, n=10, pick=None):
    """pick: map the program output to the scalar fetched as the
    completion barrier (default: the output IS the scalar)."""
    pick = pick or (lambda o: o)
    float(np.asarray(pick(fn(*args))))      # warm + force
    t0 = time.perf_counter()
    float(np.asarray(pick(fn(*args))))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [pick(fn(*args)) for _ in range(n + 1)]
    float(np.asarray(jnp.stack(outs).sum()))
    tN = time.perf_counter() - t0
    return (tN - t1) / n


# -- cumulative chain (all from pre-encoded int8 input) ----------------------

@jax.jit
def chain_stats(x, rep):
    _, fill, tw, numer = _fill_stats(x, rep, 0.1, STORAGE)
    return jnp.sum(fill) + jnp.sum(tw) + jnp.sum(numer)


@jax.jit
def chain_power(x, rep):
    _, fill, tw, numer = _fill_stats(x, rep, 0.1, STORAGE)
    mu1 = numer + (1.0 - tw) * fill
    denom = 1.0 - jnp.sum(rep ** 2)
    loading = power_iteration_fused(x, mu1, denom, rep, 128, 1e-5, fill=fill)
    return jnp.sum(loading)


@jax.jit
def chain_power_1sweep(x, rep):
    _, fill, tw, numer = _fill_stats(x, rep, 0.1, STORAGE)
    mu1 = numer + (1.0 - tw) * fill
    denom = 1.0 - jnp.sum(rep ** 2)
    loading = power_iteration_fused(x, mu1, denom, rep, 1, -1.0, fill=fill)
    return jnp.sum(loading)


@jax.jit
def chain_dirfix(x, rep):
    _, fill, tw, numer = _fill_stats(x, rep, 0.1, STORAGE)
    mu1 = numer + (1.0 - tw) * fill
    denom = 1.0 - jnp.sum(rep ** 2)
    loading = power_iteration_fused(x, mu1, denom, rep, 128, 1e-5, fill=fill)
    t, q, c, o = scores_dirfix_pass(x, rep, loading, fill=fill)
    return jnp.sum(t) + jnp.sum(q)


P = ConsensusParams(algorithm="sztorc", max_iterations=1,
                    pca_method="power-fused", power_iters=128, power_tol=1e-5,
                    storage_dtype=STORAGE, any_scaled=False, has_na=True,
                    fused_resolution=True)


# NOTE: the full-pipeline program returns the ENTIRE result dict, like
# the bench's consensus_light_jit — jitting a reduced-output wrapper
# (only avg_certainty) lets XLA DCE the other consumers and pin two
# (1, E) resolve-kernel outputs into scoped VMEM, which EXCEEDS the
# 16 MB budget at this shape (measured 18.08M, compile failure). The
# dict-output form is both the honest headline program and the one
# that compiles. One definition serves both input dtypes — jit
# specializes per dtype.
@jax.jit
def chain_full(x, rep, scaled, zeros, ones):
    return _consensus_core_fused(x, rep, scaled, zeros, ones, P)


GB = R * E * ITEM / 1e9

t_stats = timeit(chain_stats, enc, rep0)
t_p1 = timeit(chain_power_1sweep, enc, rep0)
t_power = timeit(chain_power, enc, rep0)
t_dirfix = timeit(chain_dirfix, enc, rep0)
t_full = timeit(chain_full, enc, rep0, scaled, zeros, ones,
                pick=lambda o: o["avg_certainty"])
t_full_f32 = timeit(chain_full, reports_f32, rep0, scaled, zeros, ones,
                    pick=lambda o: o["avg_certainty"])

per_sweep = t_p1 - t_stats
n_sweeps = (t_power - t_stats) / per_sweep if per_sweep > 0 else float("nan")


def row(name, ms, min_bytes, note=""):
    roof = min_bytes / HBM_GBPS * 1e3
    frac = roof / ms if ms > 0 else float("nan")
    print(f"{name:26s} {ms * 1e3:8.2f} ms   roofline {roof:6.2f} ms "
          f"({frac * 100:5.1f}% of peak)  {note}", flush=True)


print(f"shape {R}x{E}, storage int8 (pre-encoded), matrix {GB:.2f} GB")
row("stats (+dispatch ovh)", t_stats, R * E * ITEM)
row("power marginal", t_power - t_stats, R * E * ITEM * n_sweeps,
    f"~{n_sweeps:.1f} sweeps @ {per_sweep * 1e3:.2f} ms/sweep")
row("one sweep", per_sweep, R * E * ITEM)
row("scores+dirfix marginal", t_dirfix - t_power, R * E * ITEM)
row("resolve+back marginal", t_full - t_dirfix, R * E * ITEM)
row("FULL (pre-encoded)", t_full,
    R * E * ITEM * (3 + n_sweeps))
row("FULL (f32 input)", t_full_f32,
    R * E * (4 + ITEM * (3 + n_sweeps)),
    "per-resolution encode: f32 read + int8 write, then the storage "
    "passes")
print(f"pre-encode win per resolution: "
      f"{(t_full_f32 - t_full) * 1e3:.2f} ms", flush=True)
