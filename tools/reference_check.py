"""First-contact verification against the reference mount (SURVEY.md §8).

The reference mount ``/root/reference/`` has been EMPTY in every session
so far, so the rebuild's semantics are reconstructed (SURVEY.md header;
provisional golden vectors in tests/test_oracle.py pin the
reconstruction, not the reference). SURVEY.md §8 mandates: if the mount
is ever populated, STOP and re-verify before building further. This
script automates first contact so that session starts in minutes:

1. inventories the mount (files, sizes, languages);
2. greps for the load-bearing symbols the rebuild mirrors and prints
   file:line anchors for each (the citations SURVEY.md could never
   have);
3. extracts the reference ``Oracle.__init__`` signature (AST parse of
   any file defining ``class Oracle``) and diffs its kwarg names against
   ours;
4. prints the §8 checklist items that still need a human (fill-rule
   semantics, catch boundary, result-dict key set, golden vectors).

Run: ``python tools/reference_check.py`` (exit 0 with "mount empty" when
there is nothing to verify — safe to run every session).
"""

from __future__ import annotations

import ast
import pathlib
import sys

REFERENCE = pathlib.Path("/root/reference")

#: the symbols SURVEY.md reconstructs — each should anchor to file:line
SYMBOLS = [
    "class Oracle", "def consensus", "def interpolate", "weighted_cov",
    "weighted_prin_comp", "nonconformity", "def catch", "row_reward_weighted",
    "smooth", "event_bounds", "weightedstats", "algorithm",
]

#: our Oracle's reference-mirroring kwargs (oracle.py __init__)
OUR_KWARGS = [
    "reports", "event_bounds", "reputation", "catch_tolerance", "alpha",
    "variance_threshold", "max_components", "max_iterations",
    "convergence_tolerance", "num_clusters", "hierarchy_threshold",
    "dbscan_eps", "dbscan_min_samples", "algorithm", "verbose",
]

CHECKLIST = """\
Manual §8 items remaining (automation cannot decide these):
  3. interpolate's exact fill rule and catch boundary (±tol/2 vs ±tol)
     -> compare against ops/numpy_kernels.py interpolate/catch
  4. result-dict key set -> tests/test_oracle.py result contract test
  6. port the reference test matrices + expected vectors -> REPLACE the
     provisional GOLDEN dict in tests/test_oracle.py (frozen from our own
     reconstruction, 2026-07-30)
  7. replace every [R]/[R?] tag in SURVEY.md with real file:line cites
"""


def main() -> int:
    files = sorted(p for p in REFERENCE.rglob("*") if p.is_file())
    if not files:
        print("reference mount EMPTY — nothing to verify (status quo; "
              "provisional golden vectors remain authoritative)")
        return 0

    print(f"REFERENCE MOUNT POPULATED: {len(files)} files — SURVEY.md §8 "
          f"says STOP and verify before building further.\n")
    by_ext: dict = {}
    for p in files:
        by_ext.setdefault(p.suffix or "(none)", []).append(p)
    for ext, ps in sorted(by_ext.items(), key=lambda kv: -len(kv[1])):
        total = sum(p.stat().st_size for p in ps)
        print(f"  {ext:10s} {len(ps):4d} files  {total/1024:.0f} KB")
    print()

    py_files = by_ext.get(".py", [])
    print("symbol anchors (the citations SURVEY.md could not make):")
    for sym in SYMBOLS:
        hits = []
        for p in files:
            if p.stat().st_size > 2_000_000 or p.suffix in (".png", ".npz"):
                continue
            try:
                text = p.read_text(errors="replace")
            except OSError:
                continue
            for i, line in enumerate(text.splitlines(), 1):
                if sym in line:
                    hits.append(f"{p.relative_to(REFERENCE)}:{i}")
                    if len(hits) >= 3:
                        break
            if len(hits) >= 3:
                break
        status = ", ".join(hits) if hits else "NOT FOUND — survey wrong?"
        print(f"  {sym:22s} {status}")
    print()

    for p in py_files:
        try:
            tree = ast.parse(p.read_text(errors="replace"))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == "Oracle":
                init = next((f for f in node.body
                             if isinstance(f, ast.FunctionDef)
                             and f.name == "__init__"), None)
                if init is None:
                    continue
                ref_kwargs = [a.arg for a in init.args.args[1:]] + \
                             [a.arg for a in init.args.kwonlyargs]
                print(f"reference Oracle.__init__ "
                      f"({p.relative_to(REFERENCE)}:{node.lineno}): "
                      f"{ref_kwargs}")
                ours, theirs = set(OUR_KWARGS), set(ref_kwargs)
                if theirs - ours:
                    print(f"  MISSING from our Oracle: "
                          f"{sorted(theirs - ours)}")
                if ours - theirs:
                    print(f"  ours-only (rebuild extensions): "
                          f"{sorted(ours - theirs)}")
    print()
    print(CHECKLIST)
    return 2   # populated: non-zero so automation notices


if __name__ == "__main__":
    sys.exit(main())
