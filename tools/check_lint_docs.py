#!/usr/bin/env python
"""Lint-rule drift check: code vs docs/STATIC_ANALYSIS.md (ISSUE 17).

Every rule ID consensus-lint can emit (the union of the seven rule
tables behind ``--list-rules``) must appear in docs/STATIC_ANALYSIS.md,
and every ``CLxxx`` the doc mentions must be a rule the linter actually
implements. Additionally, wherever the doc carries a catalog table row
of the form ``| CL101 | error | ... |``, the severity column must match
the code's severity for that rule. Layers 1-6 each grew both sides by
hand; this script is what CI trusts instead (tools/ci_rehearsal.sh runs
it, and tests/test_determinism.py pins the live tree clean).

Importable — :func:`check` returns the drift lists so the test suite
can assert on them directly.
"""

from __future__ import annotations

import pathlib
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = pathlib.Path(__file__).resolve().parents[1]
DOC = REPO / "docs" / "STATIC_ANALYSIS.md"

#: any full rule ID mentioned anywhere in the doc — prose counts:
#: CL300-306 are documented in running text, not a table. Shorthand
#: like "CL80x" deliberately does not match: each rule must be spelled
#: out in full somewhere so a grep for an emitted ID finds its docs.
_ID_RE = re.compile(r"\bCL\d{3,4}\b")

#: a catalog table row whose second cell is the severity
_ROW_RE = re.compile(r"^\|\s*(CL\d{3,4})\s*\|\s*(\w+)\s*\|")


def collect_implemented() -> Dict[str, str]:
    """{rule ID: severity} for every rule the linter can emit — the
    same seven tables ``--list-rules`` prints."""
    sys.path.insert(0, str(REPO))
    from pyconsensus_tpu.analysis.cli import _all_rule_meta

    return {rid: sev for rid, (sev, _desc) in _all_rule_meta().items()}


def collect_documented(doc: pathlib.Path = DOC
                       ) -> Tuple[Set[str], Dict[str, str]]:
    """(all rule IDs mentioned, {rule ID: severity} for table rows)."""
    mentioned: Set[str] = set()
    table_sev: Dict[str, str] = {}
    for line in doc.read_text(encoding="utf-8").splitlines():
        mentioned.update(_ID_RE.findall(line))
        m = _ROW_RE.match(line.strip())
        if m:
            table_sev[m.group(1)] = m.group(2)
    return mentioned, table_sev


def check() -> Tuple[List[str], List[str], List[str]]:
    """(undocumented, unimplemented, severity-drift). Empty = green."""
    implemented = collect_implemented()
    mentioned, table_sev = collect_documented()
    undocumented = sorted(set(implemented) - mentioned)
    unimplemented = sorted(mentioned - set(implemented))
    sev_drift = sorted(
        rid for rid, sev in table_sev.items()
        if rid in implemented and sev != implemented[rid])
    return undocumented, unimplemented, sev_drift


def main() -> int:
    undocumented, unimplemented, sev_drift = check()
    rel = DOC.relative_to(REPO)
    for rid in undocumented:
        print(f"DRIFT: rule {rid} is implemented (--list-rules) but "
              f"never mentioned in {rel}")
    for rid in unimplemented:
        print(f"DRIFT: {rel} mentions {rid} but no rule table "
              f"implements it")
    for rid in sev_drift:
        print(f"DRIFT: {rel} catalogs {rid} with a severity different "
              f"from the implementation's")
    if undocumented or unimplemented or sev_drift:
        return 1
    implemented = collect_implemented()
    print(f"lint-rule docs in sync: {len(implemented)} implemented "
          f"rule(s) all documented, no phantom IDs, table severities "
          f"match")
    return 0


if __name__ == "__main__":
    sys.exit(main())
