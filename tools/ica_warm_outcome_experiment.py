"""Round-5 experiment (VERDICT r4 item 9): re-test the rejected iterated-ica
warm start under the OUTCOME contract.

Round 4 measured +61% on iterated ica from threading the previous
iteration's whitening subspace into the orth-iter (the sztorc /
fixed-variance warm-start rule) and REJECTED it on reputation-drift
grounds: 58% of ``this_rep`` entries moved beyond the 2e-3 fused-vs-XLA
parity tolerance at max_iterations=3 (the documented FastICA basis
sensitivity). But snapped *outcomes* were never recorded — and the fuzz
already grants iterated power the weaker contract "snapped outcomes
exact, reputation tail unbounded". This script measures exactly that:

for a fuzz-style corpus of iterated-ica cases, with the warm start OFF
(production default) and ON (``pipeline._ICA_WARM_START``), record

- snapped-outcome equality cold-vs-warm on the XLA path,
- snapped-outcome equality warm-XLA vs warm-FUSED (the parity the round-4
  rejection was measured against),
- warm-vs-cold smooth_rep drift (context, not a criterion).

Decision rule (written into MEASUREMENTS_r05): ADOPT iff zero outcome
flips in BOTH comparisons across the corpus; otherwise the rejection
stands with outcome-level evidence this time.

The flag is flipped in-process via the module global; ``jax.clear_caches``
runs after every flip because the jit cache is keyed on (shapes, params)
and would otherwise replay traces from the other setting.

Usage: python tools/ica_warm_outcome_experiment.py [--seeds 120] [--out -]
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# CPU is the right backend here: the contract is a semantics question and
# the corpus is hundreds of small jit cases (tunnel dispatch would dwarf
# them); the on-chip perf side is bench.py --algorithm ica. FORCE the
# override — the session environment pins JAX_PLATFORMS=axon, so
# setdefault would silently leave the experiment on the tunneled TPU.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import numpy as np  # noqa: E402


#: fixed shape/iteration grid so the jit cache amortizes across seeds —
#: per-seed random shapes would recompile every program for every seed
#: (measured prohibitive on the 1-core test host)
_SHAPES = [(24, 16, 3), (32, 24, 5), (40, 20, 3)]


def _case(rng, seed):
    R, E, mi = _SHAPES[seed % len(_SHAPES)]
    reports = rng.choice([0.0, 0.5, 1.0], size=(R, E),
                         p=[0.35, 0.15, 0.5]).astype(np.float64)
    if rng.random() < 0.7:
        na = rng.random((R, E)) < rng.uniform(0.02, 0.2)
        reports[na] = np.nan
    rep = rng.dirichlet(np.ones(R)) if rng.random() < 0.5 else None
    return reports, rep, mi


def run_corpus(n_seeds: int) -> dict:
    import jax

    # the session sitecustomize pre-imports jax on the axon TPU backend,
    # so the env vars above arrive too late on their own — the config
    # update is what actually moves an already-imported jax to CPU
    # (docs/PERFORMANCE.md methodology / verify-skill gotcha)
    jax.config.update("jax_platforms", "cpu")
    # match the CPU test suite's x64 anchor environment — the round-4
    # rejection measurements were against the same anchor
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from pyconsensus_tpu.models import pipeline
    from pyconsensus_tpu.models.pipeline import (ConsensusParams,
                                                 _consensus_core_fused,
                                                 consensus_jax)

    def resolve_xla(reports, rep, mi):
        R, E = reports.shape
        if rep is None:
            rep = np.full(R, 1.0 / R)
        p = ConsensusParams(algorithm="ica", max_iterations=mi,
                            pca_method="power", any_scaled=False,
                            has_na=bool(np.isnan(reports).any()))
        out = consensus_jax(reports, rep, np.zeros(E, bool), np.zeros(E),
                            np.ones(E), p)
        return (np.asarray(out["outcomes_adjusted"]),
                np.asarray(out["smooth_rep"]))

    def resolve_fused(reports, rep, mi):
        R, E = reports.shape
        if rep is None:
            rep = np.full(R, 1.0 / R)
        p = ConsensusParams(algorithm="ica", max_iterations=mi,
                            pca_method="power", any_scaled=False,
                            has_na=True, fused_resolution=True)
        out = _consensus_core_fused(
            jnp.asarray(reports, jnp.float64), jnp.asarray(rep),
            jnp.zeros(E, bool), jnp.zeros(E), jnp.ones(E), p)
        return (np.asarray(out["outcomes_adjusted"]),
                np.asarray(out["smooth_rep"]))

    results = {"n_seeds": n_seeds, "outcome_flips_cold_vs_warm_xla": 0,
               "outcome_flips_warm_xla_vs_warm_fused": 0,
               "flip_seeds": [], "max_rep_drift_warm_vs_cold": 0.0,
               "mean_rep_drift_warm_vs_cold": 0.0}

    def corpus():
        for seed in range(n_seeds):
            yield seed, _case(np.random.default_rng(7000 + seed), seed)

    # two passes, ONE flag flip each way: the jit cache stays valid
    # within a pass (the fixed shape grid amortizes the compiles)
    pipeline._ICA_WARM_START = False
    jax.clear_caches()
    cold = {seed: resolve_xla(reports, rep, mi)
            for seed, (reports, rep, mi) in corpus()}

    pipeline._ICA_WARM_START = True
    jax.clear_caches()
    warm, warm_fused = {}, {}
    for seed, (reports, rep, mi) in corpus():
        warm[seed] = resolve_xla(reports, rep, mi)
        warm_fused[seed] = resolve_fused(reports, rep, mi)
    pipeline._ICA_WARM_START = False
    jax.clear_caches()

    drifts = []
    for seed, (reports, rep, mi) in corpus():
        cold_out, cold_rep = cold[seed]
        warm_out, warm_rep = warm[seed]
        warm_f_out, _ = warm_fused[seed]
        flips_cw = int((cold_out != warm_out).sum())
        flips_xf = int((warm_out != warm_f_out).sum())
        if flips_cw:
            results["outcome_flips_cold_vs_warm_xla"] += flips_cw
        if flips_xf:
            results["outcome_flips_warm_xla_vs_warm_fused"] += flips_xf
        if flips_cw or flips_xf:
            results["flip_seeds"].append(
                {"seed": 7000 + seed, "shape": list(reports.shape),
                 "mi": mi, "cold_vs_warm": flips_cw,
                 "xla_vs_fused": flips_xf})
        drifts.append(float(np.max(np.abs(warm_rep - cold_rep))))
    results["max_rep_drift_warm_vs_cold"] = max(drifts)
    results["mean_rep_drift_warm_vs_cold"] = float(np.mean(drifts))
    results["adopt"] = (results["outcome_flips_cold_vs_warm_xla"] == 0
                        and results["outcome_flips_warm_xla_vs_warm_fused"]
                        == 0)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=120)
    ap.add_argument("--out", default="-")
    args = ap.parse_args()
    res = run_corpus(args.seeds)
    line = json.dumps(res, indent=1)
    if args.out == "-":
        print(line)
    else:
        pathlib.Path(args.out).write_text(line + "\n")
        print(f"wrote {args.out}")
    print(f"DECISION: {'ADOPT' if res['adopt'] else 'REJECTION STANDS'} "
          f"(cold-vs-warm flips={res['outcome_flips_cold_vs_warm_xla']}, "
          f"xla-vs-fused flips="
          f"{res['outcome_flips_warm_xla_vs_warm_fused']}, "
          f"max rep drift={res['max_rep_drift_warm_vs_cold']:.3g})")


if __name__ == "__main__":
    main()
