"""Stdlib ``/metrics`` exposition endpoint (ISSUE 18 tentpole (a)).

A thread-backed ``http.server`` serving the Prometheus text exposition
on ``GET /metrics`` — on ``pyconsensus-serve --metrics-port`` it serves
the *merged cluster view* (every fleet worker's registry labeled
``worker=<name>`` plus the router's own, re-rendered per scrape), so one
scrape sees the whole fleet. Zero dependencies, like every obs sink.

``render_fn`` is called per request; exceptions become a 500 with the
error text (a scrape must never hang on a half-dead fleet)."""

from __future__ import annotations

import http.server
import threading
from typing import Callable, Optional

__all__ = ["MetricsServer", "start_metrics_server"]


class MetricsServer:
    """Owns the listening socket + serve thread; ``close()`` is
    idempotent. ``port`` reports the bound port (useful with port 0)."""

    def __init__(self, port: int, render_fn: Callable[[], str],
                 host: str = "127.0.0.1") -> None:
        render = render_fn

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):               # noqa: N802 — http.server API
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404, "only /metrics is served")
                    return
                try:
                    body = render().encode("utf-8")
                    status, ctype = 200, \
                        "text/plain; version=0.0.4; charset=utf-8"
                except Exception as exc:    # noqa: BLE001 — scrape must
                    body = f"# render failed: {exc!r}\n".encode("utf-8")
                    status, ctype = 500, "text/plain; charset=utf-8"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not stderr news
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-httpd",
            daemon=True)
        self._thread.start()
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def start_metrics_server(port: int, render_fn: Callable[[], str],
                         host: str = "127.0.0.1"
                         ) -> Optional[MetricsServer]:
    """Start the endpoint; returns ``None`` (with a warning on stderr)
    when the port cannot be bound — an unscrapable endpoint must not
    take the serve run down."""
    import sys

    try:
        return MetricsServer(port, render_fn, host=host)
    except OSError as exc:
        print(f"WARNING: metrics endpoint on port {port} unavailable: "
              f"{exc}", file=sys.stderr)
        return None
