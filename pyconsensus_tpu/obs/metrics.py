"""Metrics registry: Prometheus-style counters, gauges, and fixed-bucket
histograms with labeled series (ISSUE 3 tentpole (b)).

Design constraints, in order:

- **zero dependencies** — plain stdlib; the Prometheus *text exposition
  format* is emitted (``MetricsRegistry.render_prom``), not the client
  library wire protocol, so nothing needs to be installed to scrape a
  file written by ``--metrics-out``;
- **host-side only** — metric mutation is Python dict arithmetic; calling
  it from jit-traced or shard_map code is a bug (the value would be a
  tracer and the call would run once per *trace*, not per execution) and
  is rejected statically by consensus-lint CL501;
- **cheap enough to leave on** — one lock acquire + dict update per
  emission; no I/O until a sink is rendered. There is deliberately no
  global on/off switch: conditional telemetry rots, and every call site
  here is O(R)-or-smaller host work per *resolution* (never per element).

The metric catalog (names, labels, units) is documented in
docs/OBSERVABILITY.md; metric names follow Prometheus conventions
(``_total`` counters, ``_seconds`` durations, base units).
"""

from __future__ import annotations

import json
import math
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DURATION_BUCKETS", "ITERATION_BUCKETS", "MAGNITUDE_BUCKETS"]

#: span/phase durations, seconds — log-ish spacing from sub-ms host work
#: to the minutes a cold multi-chip compile can take
DURATION_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
#: reputation-redistribution iteration counts (Fibonacci-ish — the loop
#: converges geometrically, so resolution at the low end matters most)
ITERATION_BUCKETS = (1.0, 2.0, 3.0, 4.0, 5.0, 8.0, 13.0, 21.0, 34.0)
#: reputation-mass / residual magnitudes (dimensionless, [0, 1] mass)
MAGNITUDE_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.05, 0.1, 0.25, 0.5,
                     1.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label(value: str) -> str:
    """Prometheus text-format label-value escaping (backslash first)."""
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(v: float) -> str:
    """Float rendering matching Prometheus text conventions: integers
    without a trailing .0, +Inf spelled that way."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v != v:                              # NaN
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared series bookkeeping: one value slot per label-value tuple.

    ``label_names`` is fixed at registration; every emission must supply
    exactly those labels (a typo'd label name is a programming error worth
    raising on, not a series silently split in two).
    """

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = ()) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._series: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name} declared labels {self.label_names}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[ln]) for ln in self.label_names)

    def _series_name(self, key: Tuple[str, ...],
                     extra: Sequence[Tuple[str, str]] = ()) -> str:
        pairs = list(zip(self.label_names, key)) + list(extra)
        if not pairs:
            return self.name
        body = ",".join(f'{ln}="{_escape_label(lv)}"' for ln, lv in pairs)
        return f"{self.name}{{{body}}}"

    def series(self) -> Dict[Tuple[str, ...], object]:
        with self._lock:
            return dict(self._series)


class Counter(_Metric):
    """Monotonically-increasing accumulator (``inc`` only)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc by {amount})")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))

    def render(self) -> List[str]:
        with self._lock:
            return [f"{self._series_name(k)} {_fmt(v)}"
                    for k, v in sorted(self._series.items())]


class Gauge(_Metric):
    """Set-to-current-value metric (last write wins)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def value(self, **labels) -> Optional[float]:
        key = self._key(labels)
        with self._lock:
            v = self._series.get(key)
            return None if v is None else float(v)

    def render(self) -> List[str]:
        with self._lock:
            return [f"{self._series_name(k)} {_fmt(v)}"
                    for k, v in sorted(self._series.items())]


class Histogram(_Metric):
    """Fixed-bucket histogram: cumulative ``le`` buckets plus ``_sum`` /
    ``_count``, per labeled series — the Prometheus histogram model. The
    bucket edges are fixed at registration (upper bounds, ascending; an
    implicit ``+Inf`` bucket is always appended), so ``observe`` is one
    bisect + three adds and exposition needs no re-aggregation."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = DURATION_BUCKETS) -> None:
        super().__init__(name, help, label_names)
        edges = tuple(float(b) for b in buckets)
        if not edges:
            raise ValueError(f"histogram {self.name} needs >= 1 bucket edge")
        if list(edges) != sorted(set(edges)):
            raise ValueError(f"histogram {self.name} bucket edges must be "
                             f"strictly ascending, got {edges}")
        if edges[-1] == math.inf:           # +Inf is implicit
            edges = edges[:-1]
        self.buckets = edges

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        key = self._key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = {"counts": [0] * (len(self.buckets) + 1),
                      "sum": 0.0, "count": 0}
                self._series[key] = st
            i = 0
            for i, edge in enumerate(self.buckets):   # noqa: B007
                if v <= edge:
                    break
            else:
                i = len(self.buckets)
            st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1

    def value(self, **labels) -> Optional[dict]:
        key = self._key(labels)
        with self._lock:
            st = self._series.get(key)
            return None if st is None else {"sum": st["sum"],
                                            "count": st["count"]}

    def absorb(self, counts: Sequence[int], sum: float, count: int,
               **labels) -> None:
        """Fold an already-bucketed series (another registry's snapshot)
        into this one — the merge primitive of the fleet-wide telemetry
        plane (ISSUE 18). ``counts`` must match this histogram's bucket
        layout (len(edges) + 1, the trailing +Inf bucket included)."""
        counts = [int(c) for c in counts]
        if len(counts) != len(self.buckets) + 1:
            raise ValueError(
                f"histogram {self.name} has {len(self.buckets) + 1} "
                f"buckets (+Inf included); cannot absorb {len(counts)}")
        key = self._key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = {"counts": [0] * (len(self.buckets) + 1),
                      "sum": 0.0, "count": 0}
                self._series[key] = st
            for i, c in enumerate(counts):
                st["counts"][i] += c
            st["sum"] += float(sum)
            st["count"] += int(count)

    def render(self) -> List[str]:
        lines: List[str] = []
        with self._lock:
            for key, st in sorted(self._series.items()):
                cum = 0
                for edge, c in zip(self.buckets, st["counts"]):
                    cum += c
                    lines.append(
                        f"{self._series_name(key, [('le', _fmt(edge))])}"
                        .replace(self.name + "{", self.name + "_bucket{")
                        + f" {cum}")
                cum += st["counts"][-1]
                lines.append(
                    f"{self._series_name(key, [('le', '+Inf')])}"
                    .replace(self.name + "{", self.name + "_bucket{")
                    + f" {cum}")
                base = self._series_name(key)
                if key:
                    lines.append(base.replace(self.name + "{",
                                              self.name + "_sum{")
                                 + f" {_fmt(st['sum'])}")
                    lines.append(base.replace(self.name + "{",
                                              self.name + "_count{")
                                 + f" {st['count']}")
                else:
                    lines.append(f"{self.name}_sum {_fmt(st['sum'])}")
                    lines.append(f"{self.name}_count {st['count']}")
        return lines


class MetricsRegistry:
    """Process-wide named-metric table. ``counter``/``gauge``/``histogram``
    are get-or-create: repeat registration with the same (kind, labels)
    returns the existing metric — library code can declare its metrics at
    the call site without import-order coordination — while a conflicting
    redeclaration raises (two call sites disagreeing about a metric's
    shape is a bug, not a merge)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str,
                       label_names: Sequence[str], **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) \
                        or m.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.label_names}; "
                        f"conflicting redeclaration as {cls.kind} "
                        f"with labels {tuple(label_names)}")
                if "buckets" in kw:
                    # histogram shape includes its edges: a silent merge
                    # of two bucket layouts would pile one call site's
                    # scale into the other's lowest/highest bucket
                    want = tuple(float(b) for b in kw["buckets"])
                    if want and want[-1] == math.inf:
                        want = want[:-1]
                    if m.buckets != want:
                        raise ValueError(
                            f"metric {name!r} already registered with "
                            f"buckets {m.buckets}; conflicting "
                            f"redeclaration with {want}")
                return m
            m = cls(name, help, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DURATION_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def value(self, name: str, **labels):
        """Convenience lookup for consumers that must *fail soft* when a
        metric was never emitted (bench.py's contract): returns None for
        an unknown metric or an unseen label combination instead of
        raising."""
        m = self.get(name)
        if m is None:
            return None
        try:
            return m.value(**labels)
        except ValueError:
            return None

    def render_prom(self) -> str:
        """The full registry in Prometheus text exposition format v0.0.4
        (HELP/TYPE headers + one line per series; histograms expand to
        cumulative ``_bucket``/``_sum``/``_count``). Ends with a newline,
        as scrapers expect."""
        out: List[str] = []
        for m in self.metrics():
            series = m.render()
            if not series:
                continue
            if m.help:
                out.append(f"# HELP {m.name} "
                           f"{m.help.replace(chr(10), ' ')}")
            out.append(f"# TYPE {m.name} {m.kind}")
            out.extend(series)
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> dict:
        """JSON-ready nested dict of every series' current value — the
        programmatic mirror of ``render_prom`` (bench.py consumes this)."""
        out: dict = {}
        for m in self.metrics():
            entry: dict = {"kind": m.kind, "labels": list(m.label_names),
                           "series": {}}
            for key, v in m.series().items():
                skey = json.dumps(dict(zip(m.label_names, key)),
                                  sort_keys=True) if key else ""
                if m.kind == "histogram":
                    # counts + edges make the snapshot mergeable (the
                    # fleet collector re-renders cluster-wide buckets,
                    # ISSUE 18); sum/count stay for bench.py consumers
                    entry["series"][skey] = {"sum": v["sum"],
                                             "count": v["count"],
                                             "counts": list(v["counts"])}
                else:
                    entry["series"][skey] = v
            if m.kind == "histogram":
                entry["edges"] = list(m.buckets)
            out[m.name] = entry
        return out

    def merge_snapshot(self, snap: dict, **extra_labels) -> None:
        """Fold another registry's :meth:`snapshot` into this one, every
        series widened by ``extra_labels`` (the fleet collector passes
        ``worker=<name>``) — ISSUE 18 tentpole (a). Counters add, gauges
        take the snapshot value, histograms absorb bucket counts (a
        pre-ISSUE-18 snapshot without ``counts``/``edges`` cannot be
        re-bucketed and is skipped). Iteration is sorted throughout: the
        merged registry feeds serialized artifacts (``/metrics`` scrape,
        ``--metrics-out``) and must not depend on dict order (CL1001)."""
        extra_names = tuple(sorted(extra_labels))
        extra_vals = {ln: str(extra_labels[ln]) for ln in extra_names}
        for name in sorted(snap):
            entry = snap[name]
            kind = entry.get("kind")
            own_names = tuple(entry.get("labels") or ())
            # a metric that already carries one of the extra labels
            # (e.g. the router's own per-worker heartbeat histogram vs
            # worker=<name>) keeps its OWN value — overwriting would
            # collapse distinct series onto one key
            add_names = tuple(ln for ln in extra_names
                              if ln not in own_names)
            label_names = own_names + add_names
            if kind == "histogram":
                edges = entry.get("edges")
                if not edges:
                    continue
                m = self.histogram(name, labels=label_names, buckets=edges)
            elif kind == "counter":
                m = self.counter(name, labels=label_names)
            elif kind == "gauge":
                m = self.gauge(name, labels=label_names)
            else:
                continue
            series = entry.get("series") or {}
            for skey in sorted(series):
                v = series[skey]
                labels = dict(json.loads(skey)) if skey else {}
                labels.update({ln: extra_vals[ln] for ln in add_names})
                if kind == "histogram":
                    if "counts" not in v:
                        continue
                    m.absorb(v["counts"], v["sum"], v["count"], **labels)
                elif kind == "counter":
                    m.inc(float(v), **labels)
                else:
                    m.set(float(v), **labels)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
