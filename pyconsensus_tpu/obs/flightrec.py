"""Flight recorder: bounded on-disk ring of recent telemetry for
postmortems (ISSUE 18 satellite).

A ``kill -9``'d worker cannot flush anything, so the recorder's job is
to make sure *something recent* is already on disk when the chaos stages
tear a fleet apart: each :meth:`FlightRecorder.dump` writes one
self-contained JSON artifact — the last N finished spans, the metric
*deltas* since the previous dump, and the dump reason — into a fixed
ring of ``flight-<slot>.json`` files (``seq % keep``), so disk usage is
bounded no matter how long the process lives. Writes go through
:func:`pyconsensus_tpu.io.atomic_write`: a reader (or a crash) never
sees a torn artifact.

Dump triggers (wired in ISSUE 18): worker process boot + SIGTERM +
session fence, and the fleet router's staleness declaration / takeover —
so a kill-9 run leaves both the victim's boot-time artifact and the
router's takeover artifact behind. ``tools/flightrec_dump.py`` pretty-
prints a recorder directory.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, List, Optional

__all__ = ["FlightRecorder", "read_flight_dir"]


def _metric_delta(prev: dict, cur: dict) -> dict:
    """Per-series numeric deltas between two registry snapshots (new
    series delta from zero). Histogram series diff their ``count`` and
    ``sum``; counters/gauges diff the value. Sorted iteration: the
    artifact is serialized (CL1001)."""
    out: Dict[str, dict] = {}
    for name in sorted(cur):
        entry = cur[name]
        pseries = (prev.get(name) or {}).get("series") or {}
        series = entry.get("series") or {}
        dseries: Dict[str, object] = {}
        for skey in sorted(series):
            v = series[skey]
            p = pseries.get(skey)
            if isinstance(v, dict):
                dv = {"count": int(v.get("count", 0))
                      - int((p or {}).get("count", 0)),
                      "sum": float(v.get("sum", 0.0))
                      - float((p or {}).get("sum", 0.0))}
                if dv["count"] or dv["sum"]:
                    dseries[skey] = dv
            else:
                d = float(v) - float(p or 0.0)
                if d:
                    dseries[skey] = d
        if dseries:
            out[name] = {"kind": entry.get("kind"), "series": dseries}
    return out


class FlightRecorder:
    """Bounded on-disk telemetry ring for one process.

    ``source`` labels the artifacts (worker name / "router");
    ``max_spans`` bounds spans per dump; ``keep`` is the ring size in
    files. The recorder is pull-based — it reads the process-wide tracer
    and registry at dump time, so nothing is on any hot path between
    dumps."""

    def __init__(self, dir, source: str = "main", max_spans: int = 200,
                 keep: int = 8) -> None:
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.dir = pathlib.Path(dir)
        self.source = str(source)
        self.max_spans = int(max_spans)
        self.keep = int(keep)
        self._seq = 0
        self._last_snapshot: Optional[dict] = None

    def dump(self, reason: str) -> pathlib.Path:
        """Write one artifact into the ring and return its path. Never
        raises on telemetry-read trouble — a postmortem aid must not
        crash the shutdown path it instruments — but I/O errors do
        propagate (the caller decides whether a dead disk is fatal)."""
        from . import REGISTRY, TRACER          # late: obs exports this
        from ..io import atomic_write

        try:
            spans = [sp.to_dict()
                     for sp in TRACER.spans()[-self.max_spans:]]
        except Exception:                       # noqa: BLE001
            spans = []
        try:
            snap = REGISTRY.snapshot()
        except Exception:                       # noqa: BLE001
            snap = {}
        record = {
            "format": "pyconsensus-flightrec-v1",
            "source": self.source,
            "reason": str(reason),
            "seq": self._seq,
            "spans": spans,
            "metric_deltas": _metric_delta(self._last_snapshot or {},
                                           snap),
        }
        path = self.dir / f"flight-{self._seq % self.keep:03d}.json"
        text = json.dumps(record, sort_keys=True, indent=1)

        def _write(tmp):
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(text + "\n")

        atomic_write(path, _write)
        self._seq += 1
        self._last_snapshot = snap
        return path


def read_flight_dir(dir) -> List[dict]:
    """Parse every artifact in a recorder directory, oldest first (by
    ``seq`` — slot order wraps). Unreadable/torn files are skipped:
    ``atomic_write`` makes torn files impossible from the recorder
    itself, but a postmortem reader must survive anything."""
    out: List[dict] = []
    d = pathlib.Path(dir)
    if not d.is_dir():
        return out
    for path in sorted(d.glob("flight-*.json")):
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict):
            rec["_path"] = str(path)
            out.append(rec)
    out.sort(key=lambda r: int(r.get("seq", 0)))
    return out
