"""Windowed SLO monitor (ISSUE 18 tentpole (c)).

The metrics registry is cumulative — counters only ever grow — but an
SLO ("p99 under 50 ms", "shed ratio under 1%") is a statement about a
recent *window*, and the ROADMAP-1 autoscaler needs exactly that
windowed signal. :class:`SloMonitor` keeps a bounded ring of registry
samples and differences them:

- **windowed request rate** (req/s) and **shed ratio** from the
  ``pyconsensus_serve_requests_total`` / ``pyconsensus_serve_shed_total``
  counter deltas;
- **p50/p99 latency** from the ``pyconsensus_serve_request_seconds``
  histogram's *bucket-count deltas* over the window (the cumulative
  histogram would average in every request since process start);
- **queue depth** from the ``pyconsensus_serve_queue_depth`` gauge.

Targets are declarative (``ServeConfig.slo_*`` fields, or a plain dict);
every second the window spends in violation of a target accumulates into
``pyconsensus_slo_violation_seconds{slo=<target>}`` — the accounting
counter the autoscaler (and the CI telemetry stage) consumes.

The monitor reads *snapshots*, not live metric objects, so the same
window math runs over the local registry, a fleet's merged cluster view
(``ConsensusFleet.merged_snapshot``), or hand-built fixtures in tests.
``sample(now=...)`` takes an explicit clock for deterministic fixtures;
production sampling uses ``time.monotonic`` (fine under Layer 6: the
summary is serialized with ``sort_keys=True`` and never digested).
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["SloMonitor", "quantile_from_counts", "targets_from_config",
           "TARGET_KEYS"]

#: recognized target names — ``slo_violation_seconds``' label vocabulary
TARGET_KEYS = ("p50_ms", "p99_ms", "shed_ratio", "queue_depth")


def quantile_from_counts(edges: List[float], counts: List[int],
                         q: float) -> Optional[float]:
    """Nearest-rank quantile over cumulative histogram buckets: the
    upper edge of the bucket where the rank lands (``+Inf`` for the
    overflow bucket, ``None`` for an empty window) — the conservative
    read (a true p99 is never above the reported edge's bound)."""
    total = sum(int(c) for c in counts)
    if total <= 0:
        return None
    rank = max(1, math.ceil(q * total))
    cum = 0
    for i, c in enumerate(counts):
        cum += int(c)
        if cum >= rank:
            return float(edges[i]) if i < len(edges) else math.inf
    return math.inf


def targets_from_config(cfg) -> Dict[str, float]:
    """Extract the declarative SLO targets from a ``ServeConfig`` (its
    ``slo_p50_ms``/``slo_p99_ms``/``slo_shed_ratio``/``slo_queue_depth``
    fields; 0 = target disabled). Returns ``{}`` when no SLO is
    declared, so callers can gate the monitor on truthiness."""
    out: Dict[str, float] = {}
    for key in TARGET_KEYS:
        v = getattr(cfg, "slo_" + key, 0.0)
        if v:
            out[key] = float(v)
    return out


def _counter_series(snap: dict, name: str) -> Dict[str, float]:
    """Per-series counter values — the window math differences each
    labeled series independently (ISSUE 19): a worker joining or
    leaving mid-window must not bend the cluster-wide delta."""
    entry = snap.get(name)
    if not entry:
        return {}
    series = entry.get("series") or {}
    return {k: float(series[k]) for k in series}


def _delta_counter(first: Dict[str, float],
                   last: Dict[str, float]) -> float:
    """Windowed counter delta, summed over per-series deltas:

    - series present in both samples: ``last - first``, and a NEGATIVE
      per-series delta means the series' process restarted (counter
      reset to 0) — charge ``last`` (requests since the reset), the
      Prometheus ``rate()`` convention;
    - series born inside the window (absent from ``first``): the whole
      cumulative value is window-local (a fresh worker's counters start
      at 0 when it joins) — charge ``last``;
    - series gone by ``last`` (worker drained/died): contributes 0 —
      conservative, never negative, never a phantom rate.
    """
    d = 0.0
    # sorted: float accumulation order must not depend on dict order
    for k in sorted(last):
        cur = float(last[k])
        step = cur - float(first.get(k, 0.0))
        d += cur if step < 0 else step
    return d


def _last_gauge(snap: dict, name: str) -> Optional[float]:
    entry = snap.get(name)
    if not entry:
        return None
    series = entry.get("series") or {}
    if not series:
        return None
    # gauges in a merged cluster snapshot are per-worker — depth is the
    # cluster total
    return float(sum(float(series[k]) for k in sorted(series)))


def _hist_series(snap: dict, name: str):
    """(edges, per-series bucket counts) of a histogram snapshot entry.
    Series stay separate until the WINDOW delta is taken — collapsing
    first would let a disappearing series (worker drain/death) drive
    bucket deltas negative (ISSUE 19)."""
    entry = snap.get(name)
    if not entry:
        return None, None
    edges = entry.get("edges")
    series = entry.get("series") or {}
    if edges is None or not series:
        return None, None
    n = len(edges) + 1
    out: Dict[str, List[int]] = {}
    for k in series:
        counts = series[k].get("counts")
        if not counts or len(counts) != n:
            continue
        out[k] = [int(c) for c in counts]
    return list(edges), (out or None)


def _delta_hist(first: Optional[Dict[str, List[int]]],
                last: Dict[str, List[int]], n: int) -> List[int]:
    """Windowed bucket-count deltas, per series then summed — the same
    membership rules as :func:`_delta_counter` (born-inside-window
    series charge their full counts; a reset series charges its
    post-reset counts; a vanished series charges nothing)."""
    total = [0] * n
    first = first or {}
    for k in sorted(last):
        cur = last[k]
        prev = first.get(k)
        if prev is None or len(prev) != len(cur) or any(
                int(b) < int(a) for a, b in zip(prev, cur)):
            delta = [int(c) for c in cur]
        else:
            delta = [int(b) - int(a) for a, b in zip(prev, cur)]
        for i, c in enumerate(delta):
            total[i] += c
    return total


class SloMonitor:
    """Ring-buffer time-series over registry snapshots with declarative
    targets. Thread-safe; :meth:`run_in_thread` starts the production
    sampler, tests drive :meth:`sample` with explicit clocks."""

    def __init__(self, targets: Optional[Dict[str, float]] = None,
                 window_s: float = 10.0,
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 max_samples: int = 4096,
                 latency_metric: str =
                 "pyconsensus_serve_request_seconds") -> None:
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        unknown = sorted(set(targets or ()) - set(TARGET_KEYS))
        if unknown:
            raise ValueError(f"unknown SLO target(s) {unknown}; "
                             f"known: {TARGET_KEYS}")
        self.targets = dict(targets or {})
        self.window_s = float(window_s)
        self.latency_metric = latency_metric
        self._snapshot_fn = snapshot_fn
        self._samples: "collections.deque[dict]" = collections.deque(
            maxlen=int(max_samples))
        self._violation_s: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _snapshot(self) -> dict:
        if self._snapshot_fn is not None:
            return self._snapshot_fn()
        from . import REGISTRY                  # late: obs exports slo

        return REGISTRY.snapshot()

    # -- sampling ----------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> dict:
        """Take one registry sample, update the windowed view, and charge
        any violated target's ``slo_violation_seconds`` with the time
        since the previous sample. Returns the current window summary."""
        t = time.monotonic() if now is None else float(now)
        snap = self._snapshot()
        edges, counts = _hist_series(snap, self.latency_metric)
        rec = {
            "t": t,
            "requests": _counter_series(
                snap, "pyconsensus_serve_requests_total"),
            "shed": _counter_series(
                snap, "pyconsensus_serve_shed_total"),
            "queue_depth": _last_gauge(
                snap, "pyconsensus_serve_queue_depth"),
            "edges": edges,
            "counts": counts,
        }
        with self._lock:
            prev_t = self._samples[-1]["t"] if self._samples else None
            self._samples.append(rec)
            win = self._window_locked()
            if prev_t is not None and t > prev_t:
                self._charge_locked(win, t - prev_t)
        return win

    def _charge_locked(self, win: dict, dt: float) -> None:
        violated = []
        for key in TARGET_KEYS:
            target = self.targets.get(key)
            if not target:
                continue
            observed = win.get(key)
            if observed is None:
                continue
            if float(observed) > float(target):
                violated.append(key)
        if not violated:
            return
        from . import counter                   # late: obs exports slo

        c = counter("pyconsensus_slo_violation_seconds",
                    "cumulative seconds the windowed view spent in "
                    "violation of a declared SLO target (ISSUE 18; the "
                    "ROADMAP-1 autoscaler's signal)", labels=("slo",))
        for key in violated:
            self._violation_s[key] = self._violation_s.get(key, 0.0) + dt
            c.inc(dt, slo=key)

    # -- windowed view -----------------------------------------------------

    def _window_locked(self) -> dict:
        if not self._samples:
            return {"samples": 0}
        last = self._samples[-1]
        first = last
        for rec in self._samples:       # deque is time-ordered
            if rec["t"] >= last["t"] - self.window_s:
                first = rec
                break
        dt = last["t"] - first["t"]
        single = first is last
        d_req = 0.0 if single else _delta_counter(
            first["requests"], last["requests"])
        d_shed = 0.0 if single else _delta_counter(
            first["shed"], last["shed"])
        out: dict = {
            "samples": len(self._samples),
            "window_s": round(min(self.window_s, max(dt, 0.0)), 3),
            "request_rate_rps": round(d_req / dt, 3) if dt > 0 else None,
            "shed_ratio": round(d_shed / d_req, 4) if d_req > 0 else
            (1.0 if d_shed > 0 else None),
            "queue_depth": last["queue_depth"],
            "p50_ms": None,
            "p99_ms": None,
        }
        if last["counts"] is not None:
            if (not single and last["edges"] == first["edges"]):
                # per-series bucket deltas: a series born inside the
                # window (new worker, or a latency metric the earliest
                # sample predates) charges its full — window-local —
                # counts; a vanished or reset series never drives a
                # bucket delta negative (ISSUE 19)
                delta = _delta_hist(first["counts"], last["counts"],
                                    len(last["edges"]) + 1)
            else:
                # a single sample or a changed bucket layout: the
                # cumulative distribution is the best available read —
                # better than reporting nothing
                delta = _delta_hist(None, last["counts"],
                                    len(last["edges"]) + 1)
            for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
                v = quantile_from_counts(last["edges"], delta, q)
                if v is not None:
                    out[key] = round(v * 1e3, 3) if v != math.inf \
                        else math.inf
        return out

    def window(self) -> dict:
        """The current windowed view (no sampling side effects)."""
        with self._lock:
            return self._window_locked()

    def summary(self) -> dict:
        """JSON-ready block for the loadgen summary / serve CLI / bench
        ``telemetry`` block: the windowed view plus declared targets and
        accumulated per-target violation seconds."""
        with self._lock:
            win = self._window_locked()
            win["targets"] = {k: self.targets[k]
                              for k in sorted(self.targets)}
            win["violation_s"] = {
                k: round(self._violation_s[k], 3)
                for k in sorted(self._violation_s)}
            if win["p99_ms"] == math.inf:       # JSON has no Infinity
                win["p99_ms"] = "overflow"
            if win["p50_ms"] == math.inf:
                win["p50_ms"] = "overflow"
            return win

    # -- production sampler ------------------------------------------------

    def run_in_thread(self, interval_s: float = 0.25) -> "SloMonitor":
        """Start the daemon sampling loop (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(float(interval_s),),
                name="slo-monitor", daemon=True)
            self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.sample()
            except Exception:           # noqa: BLE001 — telemetry must
                pass                    # never take the service down

    def stop(self) -> None:
        """Stop the sampler thread and take one final sample."""
        with self._lock:
            th, self._thread = self._thread, None
        if th is None:
            return
        self._stop.set()
        th.join(timeout=5.0)
        try:
            self.sample()
        except Exception:               # noqa: BLE001
            pass
