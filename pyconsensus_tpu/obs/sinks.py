"""Telemetry sinks (ISSUE 3 tentpole (c)): JSONL span/event log,
Prometheus text exposition, and the human report tree.

The exposition itself lives with its data structure
(``MetricsRegistry.render_prom`` / ``Tracer.report``); this module owns
the file formats — JSONL writing, reading, and span-tree reconstruction —
so tests and external consumers have one round-trip contract to pin.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, List, Optional, Sequence

__all__ = ["write_jsonl", "read_jsonl", "span_tree", "write_prom",
           "merge_jsonl", "trace_forest"]


def write_jsonl(path, events: Sequence[dict], meta: Optional[dict] = None
                ) -> int:
    """Write one JSON object per line: an optional leading ``meta`` record
    (``{"type": "meta", ...}``) followed by the events (normally
    ``Tracer.events()``). Returns the number of records written. Parent
    directories are created."""
    p = pathlib.Path(path)
    if p.parent and not p.parent.exists():
        p.parent.mkdir(parents=True, exist_ok=True)
    n = 0
    with open(p, "w", encoding="utf-8") as f:
        if meta is not None:
            f.write(json.dumps({"type": "meta", **meta}, sort_keys=True)
                    + "\n")
            n += 1
        for ev in events:
            f.write(json.dumps(ev, sort_keys=True) + "\n")
            n += 1
    return n


def read_jsonl(path) -> List[dict]:
    """Read a JSONL file back to a list of dicts (blank lines skipped) —
    the round-trip inverse of :func:`write_jsonl`."""
    out: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def span_tree(events: Sequence[dict]) -> List[dict]:
    """Reconstruct the nested span forest from flat span events (any
    order): returns the list of root spans, each a copy carrying a
    ``children`` list sorted by start time. Non-span records (meta) are
    ignored; a span whose parent is missing from ``events`` (e.g. a
    truncated log) becomes a root rather than being dropped."""
    spans = [dict(ev) for ev in events if ev.get("type") == "span"]
    # ids are keyed per (process_index, span_id): each host's tracer
    # numbers span_ids from 1, so merged fleet JSONL would otherwise
    # collide ids across hosts and mis-parent children (the per-host
    # trees the tracer promises)
    by_id: Dict[tuple, dict] = {}
    for sp in spans:
        sp["children"] = []
        by_id[(sp.get("process_index", 0), sp["span_id"])] = sp
    roots: List[dict] = []
    for sp in spans:
        # a span whose parent lives in ANOTHER source (the far side of an
        # RPC hop, ISSUE 18) roots the local tree; trace_forest resolves
        # the cross-source edge over merged logs
        parent = None if sp.get("parent_src") is not None else by_id.get(
            (sp.get("process_index", 0), sp.get("parent_id", 0)))
        if parent is not None and parent is not sp:
            parent["children"].append(sp)
        else:
            roots.append(sp)
    def _sort(nodes: List[dict]) -> None:
        nodes.sort(key=lambda s: s.get("start_s", 0.0))
        for n in nodes:
            _sort(n["children"])
    _sort(roots)
    return roots


def merge_jsonl(paths: Sequence) -> List[dict]:
    """Concatenate several span JSONL files (one per process — the router
    plus each fleet worker, ISSUE 18 tentpole (b)) into one flat event
    list. Each file's records are tagged with that file's ``source``:
    spans written since ISSUE 18 self-stamp it; older records inherit the
    file's meta ``source`` field. Files are read in the order given —
    callers globbing a directory must sort first (CL1001)."""
    merged: List[dict] = []
    for path in paths:
        events = read_jsonl(path)
        file_src = None
        for ev in events:
            if ev.get("type") == "meta" and ev.get("source"):
                file_src = str(ev["source"])
                break
        for ev in events:
            ev = dict(ev)
            if ev.get("type") == "span" and not ev.get("source"):
                ev["source"] = file_src or str(path)
            merged.append(ev)
    return merged


def trace_forest(events: Sequence[dict]) -> Dict[str, List[dict]]:
    """Reconstruct distributed traces from merged multi-process events:
    ``{trace_id: [root spans]}``, each root carrying nested ``children``
    sorted by start time. Spans are keyed ``(source, span_id)`` — every
    process numbers span_ids from 1, so the source label is what keeps a
    router span and a worker span distinct — and a cross-source parent
    edge (``parent_src``, the RPC hop) resolves against the parent's
    source. Untraced spans (no ``trace_id``) are ignored; a traced span
    whose parent is missing from ``events`` becomes a root."""
    spans = [dict(ev) for ev in events
             if ev.get("type") == "span" and ev.get("trace_id")]
    by_id: Dict[tuple, dict] = {}
    for sp in spans:
        sp["children"] = []
        by_id[(sp.get("source", ""), sp["span_id"])] = sp
    forest: Dict[str, List[dict]] = {}
    for sp in spans:
        src = sp.get("parent_src") or sp.get("source", "")
        parent = by_id.get((src, sp.get("parent_id", 0)))
        if parent is not None and parent is not sp \
                and parent.get("trace_id") == sp.get("trace_id"):
            parent["children"].append(sp)
        else:
            forest.setdefault(str(sp["trace_id"]), []).append(sp)

    def _sort(nodes: List[dict]) -> None:
        nodes.sort(key=lambda s: s.get("start_s", 0.0))
        for n in nodes:
            _sort(n["children"])

    for tid in sorted(forest):
        _sort(forest[tid])
    return {tid: forest[tid] for tid in sorted(forest)}


def write_prom(path, registry) -> str:
    """Render ``registry`` to Prometheus text format and write it to
    ``path`` (parent directories created). Returns the rendered text."""
    text = registry.render_prom()
    p = pathlib.Path(path)
    if p.parent and not p.parent.exists():
        p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text, encoding="utf-8")
    return text
