"""pyconsensus_tpu.obs — zero-dependency observability subsystem
(ISSUE 3 tentpole): span tracer + metrics registry + sinks + JAX compile
observability, instrumenting every layer of the pipeline.

Quick use::

    from pyconsensus_tpu import obs

    with obs.span("resolve", algorithm="sztorc") as sp:
        out = oracle.consensus()
        sp.observe(out)                   # block device time into the span
    obs.counter("my_total").inc()
    print(obs.report())                   # human span tree
    print(obs.render_prom())              # Prometheus text exposition
    obs.write_jsonl("trace.jsonl", obs.events())

Rules of engagement:

- **host-side only.** Spans and metrics are Python; inside jit-traced /
  shard_map / pallas code they would run once per trace and try to sync
  the device mid-graph. consensus-lint CL501/CL502 reject this statically.
- **process-wide singletons.** ``REGISTRY`` and ``TRACER`` are the
  default sinks so library code needs no plumbing; ``reset()`` clears
  both (tests, CLI runs). Constructing private ``MetricsRegistry`` /
  ``Tracer`` instances is supported for isolation.
- **metric catalog** lives in docs/OBSERVABILITY.md — names follow
  Prometheus conventions; add new metrics there when instrumenting code.
"""

from __future__ import annotations

from .compilemon import (InstrumentedJit, install_compile_monitor,
                         instrument_jit)
from .flightrec import FlightRecorder, read_flight_dir
from .httpd import MetricsServer, start_metrics_server
from .metrics import (DURATION_BUCKETS, ITERATION_BUCKETS, MAGNITUDE_BUCKETS,
                      Counter, Gauge, Histogram, MetricsRegistry)
from .sinks import (merge_jsonl, read_jsonl, span_tree, trace_forest,
                    write_jsonl, write_prom)
from .slo import SloMonitor, quantile_from_counts, targets_from_config
from .tracer import Span, Tracer

__all__ = [
    "REGISTRY", "TRACER",
    "span", "observe", "current_span", "counter", "gauge", "histogram",
    "events", "report", "render_prom", "value", "reset",
    "trace_root", "span_under", "trace_context",
    "write_jsonl", "read_jsonl", "span_tree", "write_prom",
    "merge_jsonl", "trace_forest",
    "instrument_jit", "install_compile_monitor", "InstrumentedJit",
    "MetricsRegistry", "Tracer", "Span", "Counter", "Gauge", "Histogram",
    "SloMonitor", "quantile_from_counts", "targets_from_config",
    "FlightRecorder", "read_flight_dir",
    "MetricsServer", "start_metrics_server",
    "DURATION_BUCKETS", "ITERATION_BUCKETS", "MAGNITUDE_BUCKETS",
]

#: process-wide metrics registry (the default sink for library code)
REGISTRY = MetricsRegistry()
#: process-wide tracer; finished spans also feed
#: ``pyconsensus_phase_seconds{phase=...}`` in REGISTRY
TRACER = Tracer(registry=REGISTRY)


def span(name: str, **attrs):
    """Open a span on the process-wide tracer (context manager)."""
    return TRACER.span(name, **attrs)


def trace_root(name: str, trace_id: str, **attrs):
    """Open a span rooting a distributed trace (ISSUE 18): ``trace_id``
    from the request's deterministic identity, never ``uuid``/``time``."""
    return TRACER.trace_root(name, trace_id, **attrs)


def span_under(name: str, ctx, **attrs):
    """Open a span under an explicit wire-propagated trace context
    (``None`` degrades to a plain span)."""
    return TRACER.span_under(name, ctx, **attrs)


def trace_context():
    """The current span's propagation context (``None`` when untraced) —
    what the RPC client injects into the envelope."""
    return TRACER.context()


def observe(value):
    """Attach a device value to the current span's completion barrier."""
    return TRACER.observe(value)


def current_span():
    return TRACER.current()


def counter(name: str, help: str = "", labels=()):
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels=()):
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels=(),
              buckets=DURATION_BUCKETS):
    return REGISTRY.histogram(name, help, labels, buckets)


def value(name: str, **labels):
    """Fail-soft metric lookup (None when never emitted) — see
    ``MetricsRegistry.value``."""
    return REGISTRY.value(name, **labels)


def events():
    return TRACER.events()


def report(max_spans: int = 200) -> str:
    return TRACER.report(max_spans=max_spans)


def render_prom() -> str:
    return REGISTRY.render_prom()


def reset() -> None:
    """Clear the process-wide tracer and registry (tests / fresh CLI
    runs). Compile-monitor installation state is NOT reset — the
    jax.monitoring listener stays registered (jax has no unregister) and
    both it and the per-entry jit wrappers resolve their metrics from the
    registry lazily, so they repopulate a freshly-reset registry on the
    next event."""
    TRACER.reset()
    REGISTRY.reset()
