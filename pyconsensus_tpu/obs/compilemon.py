"""JAX compile observability (ISSUE 3 tentpole (b), compile leg):
per-entry-point retrace counters + compile-time gauges, plus the global
``jax.monitoring`` compile-event feed when this jax version exposes it.

Two complementary mechanisms:

- :func:`instrument_jit` wraps a jitted callable and watches its
  ``_cache_size()`` across calls — growth means this call traced and
  compiled a new specialization. This is the *per entry point* signal:
  ``pyconsensus_jit_retraces_total{entry=...}`` counts compiles (the
  first compile counts as 1, so an entry point called twice with
  identical (shape, dtype, params) must show the counter stable at 1 —
  the same invariant consensus-lint CL304 pins statically), and
  ``pyconsensus_jit_compile_seconds{entry=...}`` holds the wall time of
  the most recent compiling call. When ``_cache_size`` is unavailable
  (non-jit callables, exotic wrappers), the wrapper degrades to a plain
  pass-through — never a crash.
- :func:`install_compile_monitor` registers a ``jax.monitoring`` duration
  listener (when this jax has one) feeding
  ``pyconsensus_jax_compile_events_total{event=...}`` /
  ``pyconsensus_jax_compile_seconds_total{event=...}`` — the global
  backend-compile feed that catches compiles the wrappers can't see
  (colliding lru-cached builds, library-internal jits).

Both are host-side. The wrapper deliberately no-ops its bookkeeping when
called under an active trace (``consensus_light_jit`` is re-entered
inside ``jax.jit`` by the schedule analyzer): cache-size deltas observed
mid-trace describe tracing, not execution.
"""

from __future__ import annotations

import time

__all__ = ["instrument_jit", "install_compile_monitor", "InstrumentedJit"]

#: jax.monitoring event substrings worth surfacing (the full event
#: namespace is an implementation detail; compile cost is the contract)
_COMPILE_EVENT_MARKERS = ("compil", "trace", "lower")


def _tracing_active() -> bool:
    """True when called under an active jax trace — bookkeeping must
    no-op there (and must never raise on jax-version drift). Fails
    CLOSED: when trace-state introspection is unavailable (the API moves
    across jax versions), assume tracing and skip bookkeeping — a
    silently disabled counter degrades observability, but counting
    per-trace phantom retraces breaks the CL304 ci-gate invariant."""
    try:
        import jax

        return not jax.core.trace_state_clean()
    except Exception:
        return True


class InstrumentedJit:
    """Transparent wrapper around a jitted callable: ``__call__`` adds
    retrace bookkeeping; every other attribute (``lower``,
    ``_cache_size``, ``clear_cache``, ...) is forwarded untouched, so
    existing callers that introspect the jit object keep working."""

    def __init__(self, fn, entry: str, registry) -> None:
        self._fn = fn
        self._entry = entry
        self._registry = registry

    def __call__(self, *args, **kwargs):
        cache_size = getattr(self._fn, "_cache_size", None)
        if cache_size is None or _tracing_active():
            return self._fn(*args, **kwargs)
        try:
            before = cache_size()
        except Exception:
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        try:
            grew = cache_size() - before
        except Exception:
            return out
        if grew > 0:
            dt = time.perf_counter() - t0
            self._registry.counter(
                "pyconsensus_jit_retraces_total",
                "jit cache growth per entry point (1 = the initial "
                "compile; >1 for repeat shapes/params means a retrace "
                "leak)", labels=("entry",)).inc(grew, entry=self._entry)
            self._registry.gauge(
                "pyconsensus_jit_compile_seconds",
                "wall time of the most recent compiling call (trace + "
                "backend compile + first dispatch)",
                labels=("entry",)).set(dt, entry=self._entry)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self) -> str:
        return f"InstrumentedJit({self._entry}, {self._fn!r})"


def instrument_jit(fn, entry: str, registry=None):
    """Wrap jitted ``fn`` so compiles are counted under ``entry`` in the
    metrics registry (the process-wide default when ``registry`` is
    omitted)."""
    if registry is None:
        from . import REGISTRY as registry          # noqa: N813
    return InstrumentedJit(fn, entry, registry)


_installed = [False]


def install_compile_monitor(registry=None) -> bool:
    """Register the global ``jax.monitoring`` duration listener feeding
    the compile-event counters (idempotent; returns whether a listener is
    active). Falls back to False — with the :func:`instrument_jit`
    wrappers still covering the entry points — when this jax version has
    no monitoring hooks."""
    if _installed[0]:
        return True
    if registry is None:
        from . import REGISTRY as registry          # noqa: N813
    try:
        import jax.monitoring as monitoring

        register = monitoring.register_event_duration_secs_listener
    except Exception:
        return False

    def _listener(event: str, duration: float, **kw) -> None:
        if any(m in event for m in _COMPILE_EVENT_MARKERS):
            # normalize the namespaced event to its leaf for label
            # hygiene ("/jax/core/compile/backend_compile_duration" ->
            # "backend_compile_duration"); metrics are resolved from the
            # registry per event (compiles are rare) so an obs.reset()
            # between events repopulates the fresh registry instead of
            # feeding orphaned metric objects
            leaf = event.rstrip("/").rsplit("/", 1)[-1] or event
            registry.counter(
                "pyconsensus_jax_compile_events_total",
                "jax.monitoring compile/trace/lower events observed "
                "process-wide", labels=("event",)).inc(1.0, event=leaf)
            registry.counter(
                "pyconsensus_jax_compile_seconds_total",
                "cumulative seconds in jax.monitoring compile/trace/"
                "lower events", labels=("event",)).inc(
                    max(float(duration), 0.0), event=leaf)

    try:
        register(_listener)
    except Exception:
        return False
    _installed[0] = True
    return True
