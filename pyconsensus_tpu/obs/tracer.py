"""Span tracer: nested, exception-safe phase spans with device-time
attribution (ISSUE 3 tentpole (a)).

The model is a Dapper-style span tree flattened to an event list: every
``span(...)`` context manager opens a child of the innermost open span on
the *current thread*, and closing it appends one finished-span record to
the tracer. Library code emits spans without plumbing a timer object
through call signatures — the process-wide default tracer lives in
``pyconsensus_tpu.obs`` — and the streaming prefetch thread gets its own
span stack (``threading.local``), so cross-thread nesting can never
corrupt the tree.

Device-time attribution: JAX dispatch is asynchronous, so a span that
merely *dispatches* device work would charge the compute to whichever
later span happens to block. ``Span.observe(value)`` marks values the
span must wait on; span exit calls ``jax.block_until_ready`` on ALL of
them (a list — the single-slot ``PhaseTimer._pending`` bug this subsystem
replaces lost every value but the last). The block happens host-side at
span exit; emitting spans *inside* jit-traced or shard_map code is
statically rejected by consensus-lint CL501 (the span would time tracing,
not execution, and the block would be a host sync in the graph).

Multi-host: every span is tagged with the JAX process index (0 when the
distributed runtime is uninitialized), so merged JSONL from a fleet still
reconstructs per-host trees.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Dict, Iterator, List, Optional

__all__ = ["Span", "Tracer"]

_ids = itertools.count(1)
_ids_lock = threading.Lock()


def _next_id() -> int:
    with _ids_lock:
        return next(_ids)


_proc_index_cache: List[Optional[int]] = [None]


def _process_index() -> int:
    """The JAX process index, resolved lazily on first span and cached —
    import-time resolution would initialize the backend before the
    launcher configures it. Falls back to 0 without jax or before
    distributed init."""
    if _proc_index_cache[0] is None:
        try:
            import jax

            _proc_index_cache[0] = int(jax.process_index())
        except Exception:
            _proc_index_cache[0] = 0
    return _proc_index_cache[0]


def _block_all(values: list) -> None:
    """``jax.block_until_ready`` over every observed value (it accepts
    pytrees, so one call covers the list). Values without device buffers
    (numpy, scalars) pass through untouched; without jax this is a no-op."""
    if not values:
        return
    try:
        import jax
    except Exception:                       # pragma: no cover - no jax
        return
    jax.block_until_ready(values)


class Span:
    """One finished-or-open phase. Attributes are small JSON-able values
    (strings/numbers/bools); anything else is stringified at export.

    Distributed tracing (ISSUE 18): ``trace_id`` names the end-to-end
    request this span belongs to (inherited from the parent span, or set
    explicitly at the trace root — always derived from the request's
    deterministic identity, never ``uuid``/``time``, so serialized trace
    artifacts stay CL1003-clean). ``source`` is the emitting tracer's
    label (worker name / "router"); ``parent_src`` is set when the parent
    span lives in ANOTHER source (the RPC hop) — ``parent_id`` then refers
    to ``(parent_src, parent_id)`` in the merged forest, and the span is a
    root of its local tree."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "depth",
                 "process_index", "start_wall_s", "duration_s", "status",
                 "error", "trace_id", "source", "parent_src", "_t0",
                 "_pending")

    def __init__(self, name: str, attrs: Dict[str, object], parent_id: int,
                 depth: int, trace_id: Optional[str] = None,
                 source: str = "main",
                 parent_src: Optional[str] = None) -> None:
        self.name = name
        self.attrs = attrs
        self.span_id = _next_id()
        self.parent_id = parent_id          # 0 = root
        self.depth = depth
        self.process_index = _process_index()
        self.start_wall_s = time.time()
        self.duration_s: Optional[float] = None
        self.status = "open"
        self.error: Optional[str] = None
        self.trace_id = trace_id
        self.source = source
        self.parent_src = parent_src
        self._t0 = time.perf_counter()
        self._pending: list = []

    def observe(self, value):
        """Mark a (possibly asynchronous) device value this span must wait
        on before its clock stops. May be called any number of times; ALL
        observed values are blocked on at exit. Returns ``value`` so call
        sites can wrap an expression in place."""
        self._pending.append(value)
        return value

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def to_dict(self) -> dict:
        attrs = {}
        for k, v in self.attrs.items():
            attrs[str(k)] = (v if isinstance(v, (str, int, float, bool))
                             or v is None else str(v))
        out = {
            "type": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "depth": self.depth,
            "process_index": self.process_index,
            "source": self.source,
            "start_s": self.start_wall_s,
            "duration_s": self.duration_s,
            "status": self.status,
            "error": self.error,
            "attrs": attrs,
        }
        # trace context only when traced: untraced spans keep the
        # pre-ISSUE-18 record shape
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.parent_src is not None:
            out["parent_src"] = self.parent_src
        return out


class Tracer:
    """Thread-aware span collector. ``registry`` (a
    :class:`~pyconsensus_tpu.obs.metrics.MetricsRegistry`) is optional;
    when given, every finished span also observes
    ``pyconsensus_phase_seconds{phase=<name>}`` so phase durations show up
    in the Prometheus exposition with zero extra call-site code."""

    #: completed-span ring bound — a multi-hour sweep must not grow host
    #: memory without bound; the metrics registry keeps the aggregates,
    #: the span ring keeps the most recent trees for report()/JSONL
    MAX_SPANS = 100_000

    def __init__(self, registry=None, max_spans: Optional[int] = None,
                 source: str = "main") -> None:
        self._registry = registry
        #: this tracer's identity in merged multi-process trace logs
        #: (ISSUE 18): fleet worker processes set it to their worker name,
        #: the routing process to "router". A deterministic label, never
        #: pid/uuid — trace artifacts are diffable across runs.
        self.source = str(source)
        self._max_spans = int(max_spans if max_spans is not None
                              else self.MAX_SPANS)
        self._local = threading.local()
        self._lock = threading.Lock()
        # deque(maxlen): O(1) eviction — a list.pop(0) ring would go
        # quadratic under the lock exactly on the long sweeps the bound
        # exists for
        self._finished: "collections.deque[Span]" = collections.deque(
            maxlen=self._max_spans)
        self._dropped = 0

    # -- emission ----------------------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def span(self, name: str, **attrs) -> Iterator[Span]:
        """Open a child span of the innermost open span on this thread.
        Exception-safe: an exception inside the body marks the span
        ``status="error"`` (with the exception repr) and re-raises; the
        span is recorded either way, and the stack is always popped.
        The child inherits its parent's ``trace_id`` (ISSUE 18)."""
        return self._open(name, attrs)

    def trace_root(self, name: str, trace_id: str, **attrs
                   ) -> Iterator[Span]:
        """Open a span that ROOTS a distributed trace: ``trace_id`` must
        come from the request's deterministic identity (routing key,
        session round) — not ``uuid``/``time`` (CL1003). Nests normally
        under any open local span; descendants and RPC hops inherit the
        id (ISSUE 18 tentpole (b))."""
        return self._open(name, attrs, trace_id=str(trace_id))

    def span_under(self, name: str, ctx: Optional[dict], **attrs
                   ) -> Iterator[Span]:
        """Open a span whose parent is an EXPLICIT trace context
        (``{"trace_id", "src", "span_id"}`` from :meth:`context` — the
        wire-propagated form, ISSUE 18) instead of the thread-local
        stack: the worker-side RPC extraction point, and the batcher's
        cross-thread dispatch linkage. ``ctx=None`` degrades to a plain
        :meth:`span`, so call sites need no branching."""
        if not ctx:
            return self._open(name, attrs)
        src = str(ctx.get("src") or "")
        parent_id = int(ctx.get("span_id") or 0)
        trace_id = ctx.get("trace_id")
        return self._open(
            name, attrs,
            trace_id=str(trace_id) if trace_id is not None else None,
            parent_override=(src, parent_id))

    def context(self) -> Optional[dict]:
        """The current span's propagation context — ``None`` when no span
        is open or the span is untraced (so untraced RPC envelopes stay
        byte-identical to the pre-ISSUE-18 wire form)."""
        sp = self.current()
        if sp is None or sp.trace_id is None:
            return None
        return {"trace_id": sp.trace_id, "src": self.source,
                "span_id": sp.span_id}

    @contextlib.contextmanager
    def _open(self, name: str, attrs: dict,
              trace_id: Optional[str] = None,
              parent_override: Optional[tuple] = None) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1] if stack else None
        if parent_override is not None:
            src, pid = parent_override
            remote = src != self.source
            sp = Span(name, dict(attrs), pid,
                      0 if remote else 1, trace_id=trace_id,
                      source=self.source,
                      parent_src=src if remote else None)
        else:
            if trace_id is None and parent is not None:
                trace_id = parent.trace_id
            sp = Span(name, dict(attrs),
                      parent.span_id if parent is not None else 0,
                      parent.depth + 1 if parent is not None else 0,
                      trace_id=trace_id, source=self.source)
        stack.append(sp)
        try:
            yield sp
            sp.status = "ok"
        except BaseException as exc:
            sp.status = "error"
            sp.error = repr(exc)
            raise
        finally:
            try:
                _block_all(sp._pending)
            except BaseException as exc:
                # an observed value that failed ASYNCHRONOUSLY surfaces
                # here (XlaRuntimeError at block time) — the span must
                # not be recorded green for the phase that crashed; a
                # body exception's status wins (it came first)
                if sp.status != "error":
                    sp.status = "error"
                    sp.error = repr(exc)
                raise
            finally:
                sp._pending = []
                sp.duration_s = time.perf_counter() - sp._t0
                stack.pop()
                self._record(sp)

    def observe(self, value):
        """``Span.observe`` on the current span; a no-op pass-through when
        no span is open (library code needn't care whether a caller
        traced it)."""
        sp = self.current()
        if sp is not None:
            return sp.observe(value)
        return value

    def _record(self, sp: Span) -> None:
        with self._lock:
            if len(self._finished) == self._max_spans:
                self._dropped += 1          # deque(maxlen) evicts oldest
            self._finished.append(sp)
        if self._registry is not None:
            self._registry.histogram(
                "pyconsensus_phase_seconds",
                "wall-clock span durations (device time attributed via "
                "observed-value blocking)",
                labels=("phase",)).observe(sp.duration_s, phase=sp.name)

    # -- export ------------------------------------------------------------

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def events(self) -> List[dict]:
        """Finished spans as JSON-ready dicts, in finish order (children
        before parents — a JSONL reader rebuilds the tree from
        parent_id)."""
        return [sp.to_dict() for sp in self.spans()]

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def report(self, max_spans: int = 200) -> str:
        """Human tree: one line per span, indented by nesting, slowest
        roots first. ``max_spans`` caps the output (a long sweep has
        thousands of identical panel spans; the metrics registry carries
        the aggregates)."""
        spans = self.spans()
        known = {sp.span_id for sp in spans}
        by_parent: Dict[int, List[Span]] = {}
        for sp in spans:
            # a child whose parent was evicted from the ring becomes a
            # root (matching sinks.span_tree) instead of silently
            # vanishing from the report; a remote parent (parent_src set
            # — the other side of an RPC hop) is never local, so those
            # spans root the local tree too
            parent = sp.parent_id if (sp.parent_src is None
                                      and sp.parent_id in known) else 0
            by_parent.setdefault(parent, []).append(sp)
        lines: List[str] = []

        def emit(sp: Span, indent: int) -> None:
            if len(lines) >= max_spans:
                return
            ms = (sp.duration_s or 0.0) * 1e3
            attrs = " ".join(f"{k}={v}" for k, v in sorted(
                sp.to_dict()["attrs"].items()))
            flag = "" if sp.status == "ok" else f" [{sp.status}]"
            lines.append(f"{'  ' * indent}{sp.name:<{max(1, 40 - 2 * indent)}}"
                         f" {ms:10.3f} ms{flag}"
                         + (f"  ({attrs})" if attrs else ""))
            for child in sorted(by_parent.get(sp.span_id, []),
                                key=lambda s: s.start_wall_s):
                emit(child, indent + 1)

        roots = sorted(by_parent.get(0, []),
                       key=lambda s: -(s.duration_s or 0.0))
        for root in roots:
            emit(root, 0)
        if len(spans) > max_spans:
            lines.append(f"... ({len(spans) - max_spans} more spans; "
                         f"aggregates in the metrics registry)")
        return "\n".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()
            self._dropped = 0
        self._local = threading.local()

    def __repr__(self) -> str:
        return (f"Tracer(spans={len(self._finished)}, "
                f"dropped={self._dropped}, max_spans={self._max_spans})")
