"""Report-matrix IO: load/save on host, and event-sharded loading straight
onto a device mesh.

The reference library has no IO layer — reports matrices are Python lists
built inline (SURVEY.md §2: 100% Python, no data loader). This module is the
TPU-native framework's ingestion path:

- :func:`save_reports` / :func:`load_reports` — ``.npy`` (binary, mmap-able)
  and ``.csv`` (human-readable; parsed by the multithreaded native loader in
  ``native/loader.cpp`` when built, a strict pure-Python parser with the
  same error contract otherwise). NaN is the non-participation marker in
  both formats.
- :func:`load_reports_sharded` — build a global jax array whose event
  (column) axis is sharded over a mesh **without ever materializing the full
  matrix in host RAM**: the ``.npy`` file is memory-mapped and each device's
  column block is copied out and ``device_put`` individually, then assembled
  with ``jax.make_array_from_single_device_arrays``. This is how a
  north-star-scale matrix (10k × 100k = 4 GB fp32, larger in future rounds)
  gets from disk to an 8-chip mesh with peak host memory of one shard.
"""

from __future__ import annotations

import os
import pathlib
import re as _re
import tempfile

import numpy as np

from .faults import InputError
from .faults import plan as _faults

__all__ = ["save_reports", "load_reports", "load_reports_sharded",
           "load_reports_encoded", "csv_to_npy", "ensure_parent",
           "atomic_write"]


def ensure_parent(path) -> pathlib.Path:
    """Create ``path``'s parent directory if missing and return ``path``
    as a Path — shared guard for every save site (plots, ledger, CLI):
    an expensive computation must not be lost to a missing output
    directory at write time."""
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    return path


def atomic_write(final, writer, suffix: str = ".tmp", dir=None,
                 fsync: bool = True) -> pathlib.Path:
    """All-or-nothing file creation: ``writer(tmp_path)`` fills a
    ``mkstemp``-unique temporary in the target directory, the data (and,
    after the rename, the directory entry) is fsynced, and ``os.replace``
    installs it — a reader never sees a partial file, and a crash at any
    point leaves either the old content or the new, never a torn write.
    Safe against CONCURRENT writers of ``final`` (several hosts racing on
    a shared checkpoint dir): each gets its own tmp — pids alone are not
    unique across hosts — and last-writer-wins is harmless when racers
    write identical content by construction.

    ``suffix`` must carry the real extension for numpy writers
    (``.tmp.npy`` / ``.tmp.npz`` — ``np.save``/``np.savez`` append one to
    unsuffixed paths). ``fsync=False`` skips both syncs for callers on
    throwaway data. Returns ``final`` as a Path."""
    final = ensure_parent(final)
    fd, tmp = tempfile.mkstemp(dir=dir if dir is not None else final.parent,
                               suffix=suffix)
    try:
        # mkstemp creates 0600 and os.replace preserves it — restore
        # umask-based permissions so a different account (gather / mop-up
        # on a shared filesystem) can read the installed file. The fd is
        # closed unconditionally: an fchmod failure (ACL'd filesystems)
        # must not leak one descriptor per retry attempt.
        try:
            umask = os.umask(0)
            os.umask(umask)
            os.fchmod(fd, 0o666 & ~umask)
        finally:
            os.close(fd)
        writer(tmp)
        if fsync:
            with open(tmp, "rb") as f:
                os.fsync(f.fileno())
        os.replace(tmp, final)
        if fsync:
            dfd = os.open(final.parent, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
    except BaseException:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        raise
    return final


def save_reports(path, reports) -> pathlib.Path:
    """Write a reports matrix to ``path`` (format by suffix: ``.npy`` binary
    or ``.csv`` text with ``NA`` for missing entries). The write is atomic
    (:func:`atomic_write`): a crash mid-save leaves the previous file (or
    nothing), never a torn matrix. Returns the path."""
    path = pathlib.Path(path)
    reports = np.asarray(reports, dtype=np.float64)
    if reports.ndim != 2:
        raise InputError(f"reports must be 2-D, got shape {reports.shape}",
                         shape=tuple(reports.shape))
    if path.suffix == ".npy":
        def write(tmp):
            np.save(tmp, reports)
            _faults.fire("io.write", path=tmp)
        return atomic_write(path, write, suffix=".tmp.npy")
    if path.suffix == ".csv":
        def write(tmp):
            with open(tmp, "w") as f:
                for row in reports:
                    f.write(",".join("NA" if np.isnan(v) else repr(float(v))
                                     for v in row))
                    f.write("\n")
            _faults.fire("io.write", path=tmp)
        return atomic_write(path, write, suffix=".tmp.csv")
    raise InputError(f"unsupported reports format {path.suffix!r} "
                     f"(use .npy or .csv)", path=str(path))


_NA_TOKENS = frozenset({"", "na", "nan", "null"})

#: the float grammar ``native/loader.cpp`` accepts — optional sign (the
#: native parser strips a leading '+' before std::from_chars), ASCII
#: decimal/scientific, inf/infinity. No digit separators, no hex, no
#: unicode digits.
_FLOAT_GRAMMAR = _re.compile(
    r"[+-]?(?:inf(?:inity)?|(?:[0-9]+\.?[0-9]*|\.[0-9]+)(?:[eE][+-]?[0-9]+)?)$",
    _re.IGNORECASE | _re.ASCII)


def _csv_header_lines(path) -> int:
    """1 if the first non-blank line is a header (any token neither numeric
    nor an NA marker), else 0 — mirrors the native parser's detection so the
    numpy fallback sees the same matrix."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            for tok in line.split(","):
                tok = tok.strip()
                if tok.lower() in _NA_TOKENS:
                    continue
                # classify with the native parser's grammar, not bare
                # float() — float('1_5') succeeds, std::from_chars doesn't,
                # and header detection must agree between the two parsers
                if not _FLOAT_GRAMMAR.match(tok):
                    return 1
            return 0
    return 0


def _csv_read_fallback(path) -> np.ndarray:
    """Strict pure-Python CSV parse with the native loader's exact contract:
    NA markers -> NaN, but a field that is neither numeric nor an NA marker,
    or a ragged/truncated row, raises a structured :class:`InputError`
    (a ValueError — the pre-taxonomy contract) with the same 0-based
    data-row index the native parser reports, plus the offending column.
    (``np.genfromtxt`` is NOT used: it silently coerces corrupt fields to
    NaN — i.e. to "non-participation" — which would make results differ
    between machines with and without a compiler.)"""
    skip = _csv_header_lines(path)
    rows: list = []
    width = -1
    with open(path) as f:
        data_row = 0
        header_left = skip
        for line in f:
            line = line.strip()
            if not line:
                continue
            if header_left > 0:
                header_left -= 1
                continue
            # bare float() is LOOSER than the native std::from_chars
            # grammar (it takes '1_5', unicode digits); _parse_csv_row
            # gates on the exact grammar so both parsers accept the same
            # files
            vals = _parse_csv_row(line, path, data_row)
            if width < 0:
                width = len(vals)
            elif len(vals) != width:
                raise _ragged(path, data_row, width, len(vals))
            rows.append(vals)
            data_row += 1
    if not rows:
        raise InputError(f"{path}: not a readable, non-empty CSV",
                         path=str(path))
    return np.asarray(rows, dtype=np.float64)


def _ragged(path, data_row: int, expected: int, got: int) -> InputError:
    """Shared ragged/truncated-row error: a short final row is what a
    truncated file looks like to the parser, so the message says so."""
    kind = "truncated or ragged" if got < expected else "ragged"
    return InputError(
        f"{path}: bad field or ragged row at data row {data_row} — "
        f"{kind} row has {got} field(s), expected {expected}",
        path=str(path), row=data_row, expected=expected, got=got)


def _parse_csv_row(line: str, path, data_row: int) -> list:
    """One CSV data line -> list of floats (NaN for NA markers), with the
    native loader's strict field contract; a bad field raises
    :class:`InputError` carrying the row AND column index."""
    vals = []
    for col, tok in enumerate(line.split(",")):
        tok = tok.strip()
        if tok.lower() in _NA_TOKENS:
            vals.append(np.nan)
            continue
        if not _FLOAT_GRAMMAR.match(tok):
            raise InputError(
                f"{path}: bad field or ragged row at data row {data_row} "
                f"— field {tok!r} at column {col} is neither numeric nor "
                f"an NA marker", path=str(path), row=data_row, column=col)
        vals.append(float(tok))
    return vals


def csv_to_npy(src, dst=None, chunk_rows: int = 4096) -> pathlib.Path:
    """Stage a ``.csv`` reports file into an ``.npy`` file **incrementally**:
    peak host memory is one ``chunk_rows`` x E block, never the full matrix
    — the ingestion step that lets :func:`streaming_consensus` (and
    :func:`load_reports_sharded`) consume text files bigger than host RAM.

    Field/NA/header semantics and error messages are identical to
    :func:`load_reports`'s CSV contract (the whole-file parsers — native or
    fallback — produce the same matrix). Two text passes: one to count data
    rows (the ``.npy`` header needs the shape up front), one to parse into
    the open memmap. ``dst`` defaults to ``src`` with an ``.npy`` suffix.
    Returns ``dst``.
    """
    src = pathlib.Path(src)
    if src.suffix != ".csv":
        raise InputError(f"{src}: csv_to_npy stages .csv files",
                         path=str(src))
    dst = pathlib.Path(dst) if dst is not None else src.with_suffix(".npy")
    if chunk_rows < 1:
        raise ValueError("chunk_rows must be >= 1")

    skip = _csv_header_lines(src)
    n_rows = 0
    width = -1
    with open(src) as f:
        header_left = skip
        for line in f:
            line = line.strip()
            if not line:
                continue
            if header_left > 0:
                header_left -= 1
                continue
            if width < 0:
                width = len(line.split(","))
            n_rows += 1
    if n_rows == 0:
        raise InputError(f"{src}: not a readable, non-empty CSV",
                         path=str(src))

    # stage into a same-directory tmp and os.replace at the end: a crash
    # (or malformed row / ENOSPC) mid-stage never leaves a partial .npy
    # under the final name for a later run to mmap as truth
    fd, tmp = tempfile.mkstemp(dir=ensure_parent(dst).parent,
                               suffix=".tmp.npy")
    os.close(fd)
    out = np.lib.format.open_memmap(tmp, mode="w+", dtype=np.float64,
                                    shape=(n_rows, width))
    try:
        # parse straight into a preallocated float64 block: a Python
        # list-of-lists chunk costs ~4x the block in PyFloat objects,
        # which at wide-E scale is the difference between fitting the
        # documented one-block budget and an OOM
        buf = np.empty((min(chunk_rows, n_rows), width), dtype=np.float64)
        fill = 0
        base = 0
        with open(src) as f:
            header_left = skip
            data_row = 0
            for line in f:
                line = line.strip()
                if not line:
                    continue
                if header_left > 0:
                    header_left -= 1
                    continue
                vals = _parse_csv_row(line, src, data_row)
                if len(vals) != width:
                    raise _ragged(src, data_row, width, len(vals))
                buf[fill] = vals
                fill += 1
                data_row += 1
                if fill == buf.shape[0]:
                    out[base:base + fill] = buf[:fill]
                    base += fill
                    fill = 0
        if fill:
            out[base:base + fill] = buf[:fill]
        out.flush()
        del out
        _faults.fire("io.stage", path=tmp)
        os.replace(tmp, dst)
    except BaseException:
        try:
            del out                   # already deleted on the replace path
        except NameError:
            pass
        pathlib.Path(tmp).unlink(missing_ok=True)
        raise
    return dst


def load_reports(path, mmap: bool = False) -> np.ndarray:
    """Load a reports matrix from ``.npy`` or ``.csv``.

    ``mmap=True`` memory-maps a ``.npy`` file read-only (no copy until
    sliced) — the building block for shard-wise ingestion of matrices
    larger than host RAM. A torn or truncated ``.npy`` (numpy's reader
    fails on it) surfaces as a structured :class:`InputError` naming the
    file, not a bare parser exception; a missing file stays
    ``FileNotFoundError``.
    """
    path = pathlib.Path(path)
    _faults.fire("io.read", path=path)
    if path.suffix == ".npy":
        try:
            arr = np.load(path, mmap_mode="r" if mmap else None)
        except FileNotFoundError:
            raise
        except (OSError, ValueError) as exc:
            raise InputError(
                f"{path}: unreadable .npy reports file — truncated, torn, "
                f"or not an .npy ({exc})", path=str(path)) from exc
        if arr.ndim != 2:
            raise InputError(f"{path}: expected a 2-D reports matrix, got "
                             f"shape {arr.shape}", path=str(path),
                             shape=tuple(arr.shape))
        return _faults.corrupt("io.decode", arr)
    if path.suffix == ".csv":
        from . import _native

        try:
            arr = _native.csv_read(path)
        except ValueError as exc:            # native parser: same taxonomy
            raise InputError(str(exc), path=str(path)) from exc
        if arr is None:                      # no compiler: pure-Python path
            arr = _csv_read_fallback(path)
        return _faults.corrupt("io.decode", arr)
    raise InputError(f"unsupported reports format {path.suffix!r} "
                     f"(use .npy or .csv)", path=str(path))


def load_reports_sharded(path, mesh=None, dtype=None):
    """Load a ``.npy`` reports matrix with its event axis sharded over
    ``mesh`` (default: all devices on one ``event`` axis), copying only one
    column block per device through host memory.

    Returns a global jax array placed like ``sharded_consensus`` expects
    (rows replicated per shard spec ``P(None, "event")``).
    """
    import jax

    from .parallel.mesh import event_sharding, make_mesh

    mesh = mesh if mesh is not None else make_mesh(batch=1)
    src = load_reports(path, mmap=True)
    if dtype is None:
        dtype = jax.numpy.asarray(0.0).dtype
    sharding = event_sharding(mesh)
    R, E = src.shape

    # one device_put per addressable device, each of one contiguous column
    # block — host peak = one shard, not the full matrix
    arrays = []
    for d, idx in sharding.addressable_devices_indices_map((R, E)).items():
        block = np.ascontiguousarray(src[idx], dtype=dtype)
        arrays.append(jax.device_put(block, d))
    return jax.make_array_from_single_device_arrays((R, E), sharding, arrays)


def load_reports_encoded(path, mesh=None, dtype=None):
    """Device-resident int8 sentinel ingestion (ISSUE 13 tentpole a):
    load a ``.npy`` reports matrix event-sharded over ``mesh``
    (:func:`load_reports_sharded` — host peak of one shard), then build
    the int8 sentinel + NaN mask ON DEVICE
    (:func:`~pyconsensus_tpu.models.pipeline.encode_reports_device`,
    elementwise, so GSPMD keeps the event sharding). The host never
    runs an encode pass over the panel: the one-time host cost is the
    shard copies, and every subsequent resolution reads one byte per
    element. Values off the {0, 0.5, 1} lattice are rounded onto it at
    the accumulation dtype (``encode_reports``'s documented contract —
    the rounding a float input would get from ``storage_dtype='int8'``
    anyway, just at ingestion time)."""
    from .models.pipeline import encode_reports_device

    return encode_reports_device(
        load_reports_sharded(path, mesh=mesh, dtype=dtype))
