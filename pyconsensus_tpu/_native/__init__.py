"""ctypes loaders for the native runtime (native/*.cpp).

Two shared libraries, both built by ``make -C native`` (g++, no external
deps): the clustering runtime (cluster.cpp — hybrid host path) and the CSV
report loader (loader.cpp — IO subsystem). If one is missing, the first use
builds it when a compiler is available; callers treat a ``None`` return as
"fall back to the pure-Python path". Results are binary-compatible with the
host fallbacks, verified by tests/test_native.py and tests/test_io.py.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading
from typing import Optional

import numpy as np

__all__ = ["load", "avg_linkage_labels", "dbscan_labels", "load_loader",
           "csv_read"]

_NATIVE_DIR = pathlib.Path(__file__).parent
_SRC_DIR = _NATIVE_DIR.parent.parent / "native"
_load_lock = threading.Lock()
#: lib name -> loaded CDLL, or None if a load attempt failed
_libs: dict = {}
#: lib name -> the Makefile target that builds only that library (so one
#: library failing to compile cannot block the other)
_MAKE_TARGETS = {"libconsensus_cluster.so": "cluster",
                 "libconsensus_loader.so": "loader"}


def _load_lib(name: str, configure) -> Optional[ctypes.CDLL]:
    """Load (building via ``make -C native <target>`` if needed, bounded at
    120 s) the shared library ``name``; None on failure. Concurrent callers
    serialize on a lock so a half-finished build is never dlopened and a
    lost race can't poison the failure cache."""
    if name in _libs:           # hit: loaded CDLL, or None = failed earlier
        return _libs[name]
    with _load_lock:
        if name in _libs:
            return _libs[name]
        path = _NATIVE_DIR / name
        try:
            if not path.exists() and (_SRC_DIR / "Makefile").exists():
                subprocess.run(["make", "-C", str(_SRC_DIR),
                                _MAKE_TARGETS[name]], check=True,
                               capture_output=True, timeout=120)
            lib = ctypes.CDLL(str(path))
            configure(lib)
        except (OSError, subprocess.SubprocessError, KeyError):
            lib = None
        _libs[name] = lib
        return lib


def _configure_cluster(lib: ctypes.CDLL) -> None:
    lib.pc_avg_linkage_labels.restype = ctypes.c_int
    lib.pc_avg_linkage_labels.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_double,
        ctypes.POINTER(ctypes.c_int32)]
    lib.pc_dbscan_labels.restype = ctypes.c_int
    lib.pc_dbscan_labels.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_double,
        ctypes.c_int, ctypes.POINTER(ctypes.c_int32)]


def _configure_loader(lib: ctypes.CDLL) -> None:
    lib.pc_reports_csv_open.restype = ctypes.c_void_p
    lib.pc_reports_csv_open.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.pc_reports_csv_read.restype = ctypes.c_int64
    lib.pc_reports_csv_read.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double)]
    lib.pc_reports_csv_close.restype = None
    lib.pc_reports_csv_close.argtypes = [ctypes.c_void_p]


def load() -> Optional[ctypes.CDLL]:
    """The clustering runtime library; None if unavailable."""
    return _load_lib("libconsensus_cluster.so", _configure_cluster)


def load_loader() -> Optional[ctypes.CDLL]:
    """The CSV report-loader library; None if unavailable."""
    return _load_lib("libconsensus_loader.so", _configure_loader)


def csv_read(path) -> Optional[np.ndarray]:
    """Parse a reports CSV (rows = reporters, NA/empty -> NaN, optional
    header auto-skipped) with the multithreaded native parser. Returns a
    float64 (R, E) array, None if the native library is unavailable.
    Raises ValueError on a malformed file (the caller should *not* fall
    back: the file itself is bad)."""
    lib = load_loader()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    handle = lib.pc_reports_csv_open(str(path).encode(),
                                     ctypes.byref(rows), ctypes.byref(cols))
    if not handle:
        raise ValueError(f"{path}: not a readable, non-empty CSV")
    try:
        out = np.empty((rows.value, cols.value), dtype=np.float64)
        rc = lib.pc_reports_csv_read(
            handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        if rc < 0:
            raise ValueError(f"{path}: bad field or ragged row at data row "
                             f"{-rc - 1}")
        return out
    finally:
        lib.pc_reports_csv_close(handle)


def _as_dist_ptr(dist: np.ndarray):
    d = np.ascontiguousarray(dist, dtype=np.float64)
    if d.ndim != 2 or d.shape[0] != d.shape[1]:
        raise ValueError(f"distance matrix must be square, got {d.shape}")
    return d, d.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def avg_linkage_labels(dist: np.ndarray, threshold: float) -> Optional[np.ndarray]:
    """Average-linkage labels cut at ``threshold`` (scipy fcluster
    "distance" semantics); None if the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    d, ptr = _as_dist_ptr(dist)
    n = d.shape[0]
    labels = np.empty(n, dtype=np.int32)
    rc = lib.pc_avg_linkage_labels(
        ptr, n, float(threshold),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc < 0:
        raise RuntimeError("pc_avg_linkage_labels failed")
    return labels


def dbscan_labels(dist: np.ndarray, eps: float,
                  min_samples: int) -> Optional[np.ndarray]:
    """DBSCAN labels (sklearn precomputed-metric semantics, noise = -1);
    None if the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    d, ptr = _as_dist_ptr(dist)
    n = d.shape[0]
    labels = np.empty(n, dtype=np.int32)
    rc = lib.pc_dbscan_labels(
        ptr, n, float(eps), int(min_samples),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc < 0:
        raise RuntimeError("pc_dbscan_labels failed")
    return labels
