"""ctypes loader for the native clustering runtime (native/cluster.cpp).

The shared library is built by ``make -C native`` (g++, no external deps).
If it is missing, :func:`load` builds it on first use when a compiler is
available; callers treat a ``None`` return as "fall back to scipy/sklearn".
Results are binary-compatible with the host fallbacks (same label
partitions), verified by tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading
from typing import Optional

import numpy as np

__all__ = ["load", "avg_linkage_labels", "dbscan_labels"]

_LIB_PATH = pathlib.Path(__file__).parent / "libconsensus_cluster.so"
_SRC_DIR = pathlib.Path(__file__).parent.parent.parent / "native"
_lib = None
_load_failed = False
_load_lock = threading.Lock()


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on failure.

    The first call may compile the library (``make -C native``, bounded at
    120 s) — concurrent callers serialize on a lock so a half-finished
    build is never dlopened and a lost race can't poison ``_load_failed``.
    """
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _load_lock:
        return _load_locked()


def _load_locked() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        if not _LIB_PATH.exists() and (_SRC_DIR / "Makefile").exists():
            subprocess.run(["make", "-C", str(_SRC_DIR)], check=True,
                           capture_output=True, timeout=120)
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.pc_avg_linkage_labels.restype = ctypes.c_int
        lib.pc_avg_linkage_labels.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int32)]
        lib.pc_dbscan_labels.restype = ctypes.c_int
        lib.pc_dbscan_labels.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_double,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int32)]
        _lib = lib
    except (OSError, subprocess.SubprocessError):
        _load_failed = True
    return _lib


def _as_dist_ptr(dist: np.ndarray):
    d = np.ascontiguousarray(dist, dtype=np.float64)
    if d.ndim != 2 or d.shape[0] != d.shape[1]:
        raise ValueError(f"distance matrix must be square, got {d.shape}")
    return d, d.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def avg_linkage_labels(dist: np.ndarray, threshold: float) -> Optional[np.ndarray]:
    """Average-linkage labels cut at ``threshold`` (scipy fcluster
    "distance" semantics); None if the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    d, ptr = _as_dist_ptr(dist)
    n = d.shape[0]
    labels = np.empty(n, dtype=np.int32)
    rc = lib.pc_avg_linkage_labels(
        ptr, n, float(threshold),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc < 0:
        raise RuntimeError("pc_avg_linkage_labels failed")
    return labels


def dbscan_labels(dist: np.ndarray, eps: float,
                  min_samples: int) -> Optional[np.ndarray]:
    """DBSCAN labels (sklearn precomputed-metric semantics, noise = -1);
    None if the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    d, ptr = _as_dist_ptr(dist)
    n = d.shape[0]
    labels = np.empty(n, dtype=np.int32)
    rc = lib.pc_dbscan_labels(
        ptr, n, float(eps), int(min_samples),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc < 0:
        raise RuntimeError("pc_dbscan_labels failed")
    return labels
