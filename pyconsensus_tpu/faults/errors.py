"""Structured consensus error taxonomy (ISSUE 4).

Every failure the pipeline can *diagnose* carries a stable ``error_code``
so operators (and the chaos suite) can alert on classes of failure
instead of grepping message strings. The classes double-inherit from the
builtin exception the pre-taxonomy code raised (``ValueError`` for input
and checkpoint problems, ``ArithmeticError`` for numeric ones), so every
existing ``except ValueError`` / ``pytest.raises(ValueError)`` caller
keeps working — the taxonomy *narrows* what is raised, it never widens
what must be caught.

Code space (documented in docs/ROBUSTNESS.md):

- ``PYC1xx`` — input: malformed files, ragged CSV rows, bad shapes,
  non-finite reputation, empty matrices. The caller's data is wrong.
- ``PYC2xx`` — numerics: non-finite values escaping into (or out of) the
  resolution after quarantine/fallback exhausted the degradation chain.
  ``PYC201`` is the generic case; ``PYC202`` marks a detected
  power-family PCA non-convergence (residual plateau / collapsed
  loading) that survived every fallback rung.
- ``PYC3xx`` — checkpoint: torn/corrupted/incomplete persisted state
  (ledger checkpoints, sweep chunks). Always names the offending field
  or file so a resume failure is actionable without a debugger.
- ``PYC4xx`` — service: the consensus serving layer
  (``pyconsensus_tpu.serve``) refused or shed a request by POLICY —
  bounded queue full, per-tenant rate limit exceeded, deadline passed
  before dispatch, or shutdown drain in progress. The request itself is
  well-formed; retrying later (the ``context`` carries ``retry``
  guidance) is the expected recovery.
- ``PYC5xx`` — fleet: the replicated serve fleet
  (``pyconsensus_tpu.serve.fleet``) could not place or complete a
  request because of a WORKER fault rather than load policy — the
  owning worker died with the request in flight (``PYC501``), its
  sessions are mid-takeover on the standby (``PYC502``), or no worker
  can own the key at all (``PYC503``). ``PYC501``/``PYC502`` carry an
  honest ``retry_after_s`` (the expected takeover window) — the client
  retries and lands on the survivor; ``PYC503`` is a deployment error
  (empty fleet / unknown worker), not retryable.
- ``PYC6xx`` — transport: the out-of-process socket/RPC layer
  (``pyconsensus_tpu.serve.transport``) refused a frame or a peer.
  ``PYC601`` is a damaged or ill-formed WIRE artifact (torn/truncated
  frame, payload digest mismatch, oversized frame, foreign magic) —
  the bytes are refused, never half-decoded; whether to reconnect is
  the caller's call (the fleet translates a dead peer into PYC501).
  ``PYC602`` is a HANDSHAKE refusal: the peer speaks a different
  protocol version or carries a different runtime fingerprint
  (jax/jaxlib version, platform, device generation, x64) — a
  wrong-toolchain worker must be refused at connect, before any
  request could be served with bits compiled by a different world.
  Neither is retryable through ``faults.retry`` (retrying identical
  bytes or an identical fingerprint cannot succeed); transient SOCKET
  errors stay ``OSError`` and ride the bounded-reconnect path.

``context`` keyword arguments are stored on the exception (``.context``)
for structured logging; the message stays human-first.
"""

from __future__ import annotations

__all__ = ["ConsensusError", "InputError", "NumericsError",
           "ConvergenceError", "CheckpointCorruptionError",
           "AotCacheCorruptionError", "SnapshotCorruptionError",
           "ServiceOverloadError",
           "WorkerLostError", "FailoverInProgressError",
           "PlacementError", "TransportError", "HandshakeError",
           "ERROR_CODES"]


class ConsensusError(Exception):
    """Base of the structured taxonomy. ``error_code`` is stable across
    releases; ``context`` carries machine-readable details (row/column
    indices, field names, file paths)."""

    error_code = "PYC000"

    def __init__(self, message: str = "", **context) -> None:
        super().__init__(message)
        self.context = dict(context)

    def __str__(self) -> str:  # "[PYC101] path: bad field ..." in logs
        return f"[{self.error_code}] {super().__str__()}"


class InputError(ConsensusError, ValueError):
    """The caller's data is malformed: ragged/truncated CSV rows, a
    non-2-D or empty reports matrix, non-finite reputation, unknown
    formats. Subclasses ``ValueError`` — the exception this replaced."""

    error_code = "PYC101"


class NumericsError(ConsensusError, ArithmeticError):
    """Non-finite values survived quarantine and the whole documented
    fallback chain (docs/ROBUSTNESS.md) — the resolution cannot produce
    a trustworthy answer and refuses to return a poisoned one."""

    error_code = "PYC201"


class ConvergenceError(NumericsError):
    """A power-family PCA scorer failed to converge (residual plateau /
    collapsed loading detected on the host result) and every fallback
    rung — exact Gram eigh, then the numpy reference path — failed too."""

    error_code = "PYC202"


class CheckpointCorruptionError(ConsensusError, ValueError):
    """Persisted state failed validation on restore: a missing or
    malformed field in a ledger checkpoint, a sweep chunk whose content
    checksum does not match, a torn npz. The message names the offending
    field/file; recovery (re-dispatch, re-compute) is the caller's call —
    ``CheckpointedSweep`` recomputes, ``ReputationLedger.load`` raises."""

    error_code = "PYC301"


class AotCacheCorruptionError(CheckpointCorruptionError):
    """A persisted AOT bucket executable failed verify-before-adopt
    (``serve.aotcache``, ISSUE 10): torn/truncated file, payload digest
    mismatch, or a compatibility-fingerprint miss (different jaxlib/XLA
    version, device generation, topology, or BucketKey). The entry is
    REFUSED and deleted — deserializing it could install an executable
    compiled for different hardware or a different toolchain — and the
    bucket transparently recompiles. ``context`` carries the machine
    fields (``reason``, ``path``, expected vs found); the message names
    the refusing check. A corruption subclass of PYC301 rather than a
    new family: the recovery semantics (never adopt, rebuild from
    source of truth) are the checkpoint discipline's."""

    error_code = "PYC302"


class SnapshotCorruptionError(CheckpointCorruptionError):
    """A compaction snapshot (``serve.stateplane``, ISSUE 20) failed
    verify-before-adopt AND the journal suffix behind it was already
    truncated — the one state-plane failure that cannot self-heal from
    local disk alone. A torn/corrupt snapshot whose journal is still
    intact (the crash landed between snapshot write and truncation) is
    NOT this error: replay simply ignores the bad snapshot, rebuilds
    from the untruncated journal, and the next compaction sweep
    replaces it (``pyconsensus_compactions_total{outcome="refused"}``).
    This class fires only when records the snapshot was supposed to
    cover are gone, so adopting the session locally would lose
    acknowledged rounds; recovery is a shipped copy or an operator
    restoring the snapshot file. ``context`` carries the refusing
    check (``reason``), the snapshot ``path``, and the missing prefix
    length. A corruption subclass of PYC301 like PYC302: same
    never-adopt discipline, narrower blast radius."""

    error_code = "PYC303"


class ServiceOverloadError(ConsensusError, RuntimeError):
    """The serving layer (``pyconsensus_tpu.serve``) shed this request by
    POLICY: the bounded request queue was full, the tenant's token bucket
    was empty, the request's deadline expired before dispatch, or the
    service is draining for shutdown. Deterministic by design — over-rate
    traffic is refused with this stable code at admission, never absorbed
    into unbounded queue growth or a deadline-less hang. ``context``
    carries the shed ``reason`` (``queue_full`` / ``rate_limited`` /
    ``deadline`` / ``draining``) plus tenant/queue detail for structured
    logging and retry policy."""

    error_code = "PYC401"


class WorkerLostError(ConsensusError, RuntimeError):
    """A fleet worker died (SIGKILL, crash, heartbeat loss) while this
    request was queued or in flight on it. The request was ACCEPTED and
    is now provably not running anywhere — it is safe to retry; the
    consistent-hash ring routes the retry to a surviving worker (or, for
    a session, to the standby once takeover completes). ``context``
    carries the dead ``worker`` name and an honest ``retry_after_s``
    (the fleet's expected takeover window)."""

    error_code = "PYC501"


class FailoverInProgressError(ConsensusError, RuntimeError):
    """The request targets a session whose owning worker just died and
    whose durable state (ledger checkpoint + staged-block journal) is
    being replayed onto the standby RIGHT NOW. The session is fenced
    during replay — serving from half-replayed state could return bits
    that differ from the single-box run, the one thing the fleet
    guarantees never happens. ``context.retry_after_s`` is the honest
    remaining takeover-window estimate."""

    error_code = "PYC502"


class PlacementError(ConsensusError, RuntimeError):
    """Consistent-hash placement has no worker for the key: the ring is
    empty (every worker dead or the fleet never started), or a caller
    named a worker the fleet does not know. Unlike PYC501/PYC502 this is
    not transient — retrying without operator action (restart workers)
    cannot succeed, so no ``retry_after_s`` is offered."""

    error_code = "PYC503"


class TransportError(ConsensusError, RuntimeError):
    """A wire-level artifact of the out-of-process transport
    (``serve.transport.wire``) failed validation: truncated/torn frame,
    payload SHA-256 mismatch (a bit flip in transit or on a proxy),
    frame length beyond the bounded-read limit, or foreign magic bytes.
    The frame is REFUSED before any payload byte is decoded — a damaged
    RPC must surface loudly, never as a half-parsed request.

    Deliberately a ``RuntimeError``, NOT an ``OSError``: the transport's
    bounded reconnect retries ``retry_on=(OSError,)``, and a structured
    refusal must never ride that path (identical bytes re-read from a
    broken stream stay broken; an identical fingerprint re-offered
    stays refused — the PYC4xx/5xx double-inheritance precedent).
    Transient SOCKET failures keep their builtin ``OSError`` types and
    DO reconnect, counted under
    ``pyconsensus_transport_reconnects_total``."""

    error_code = "PYC601"


class HandshakeError(TransportError):
    """The versioned connect handshake refused the peer: protocol
    version mismatch, or a runtime-fingerprint field
    (``tune.fingerprint.runtime_fingerprint``: jax/jaxlib version,
    platform, device generation, x64 flag) differs between router and
    worker. A wrong-toolchain worker could serve bits compiled by a
    different world — the refusal happens at connect, before any
    request is routed. ``context`` carries the offending field with
    expected vs found values."""

    error_code = "PYC602"


#: stable code -> class registry (docs/ROBUSTNESS.md table is generated
#: from the same source of truth; tests pin the codes)
ERROR_CODES = {
    cls.error_code: cls
    for cls in (ConsensusError, InputError, NumericsError,
                ConvergenceError, CheckpointCorruptionError,
                AotCacheCorruptionError, SnapshotCorruptionError,
                ServiceOverloadError,
                WorkerLostError, FailoverInProgressError, PlacementError,
                TransportError, HandshakeError)
}
