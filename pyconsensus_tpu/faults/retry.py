"""Jittered-exponential-backoff retry with a deadline (ISSUE 4, part c).

Transient infrastructure faults (a shared filesystem hiccup mid-chunk
write, an NFS ESTALE on a checkpoint read) should cost a bounded delay,
not a crashed sweep. :func:`retry_call` / the :func:`retry` decorator
wrap a callable with capped exponential backoff:

- deterministic jitter: the sleep for attempt *k* is
  ``min(max_delay, base_delay * 2**k) * (0.5 + u/2)`` with ``u`` drawn
  from a PRNG keyed on ``(jitter_seed, label, attempt)`` — reproducible
  in tests, decorrelated across workers that pass distinct seeds (e.g.
  their host id);
- a wall-clock ``deadline``: when the *next* sleep would overrun it, the
  last exception is re-raised instead — a stuck filesystem fails the
  operation in bounded time rather than hanging a host;
- selective: only ``retry_on`` exception classes are retried. The
  structured taxonomy (.errors) is deliberately NOT in the default set —
  a corrupted checkpoint or malformed input does not become valid by
  retrying; recovery for those is re-computation or a clear error, and
  :class:`..plan.SimulatedCrash` (a BaseException) always propagates,
  exactly like the SIGKILL it stands in for.

Every retry increments ``pyconsensus_retries_total{label}``; exhaustion
increments ``pyconsensus_retries_exhausted_total{label}``.
"""

from __future__ import annotations

import functools
import time
import zlib
from typing import Optional, Tuple

import numpy as np

from .. import obs

__all__ = ["retry", "retry_call"]


def _sleep_for(attempt: int, base_delay: float, max_delay: float,
               jitter_seed: int, label: str) -> float:
    u = np.random.default_rng(
        [int(jitter_seed), zlib.crc32(label.encode()), attempt]).random()
    return min(float(max_delay), float(base_delay) * (2.0 ** attempt)) \
        * (0.5 + 0.5 * u)


def retry_call(fn, *args, retries: int = 4, base_delay: float = 0.05,
               max_delay: float = 2.0, deadline: Optional[float] = None,
               retry_on: Tuple = (OSError,), jitter_seed: int = 0,
               label: str = "", on_retry=None, **kwargs):
    """Call ``fn(*args, **kwargs)`` with up to ``retries`` retries on
    ``retry_on`` exceptions (``retries=4`` means at most 5 attempts).
    ``deadline`` bounds the TOTAL wall-clock budget in seconds from the
    first attempt; ``on_retry(attempt, exc)`` is an optional observer
    hook (logging). Raises the last exception on exhaustion."""
    label = label or getattr(fn, "__name__", "call")
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            if attempt >= int(retries):
                obs.counter(
                    "pyconsensus_retries_exhausted_total",
                    "retry_call giving up after exhausting its budget",
                    labels=("label",)).inc(label=label)
                raise
            delay = _sleep_for(attempt, base_delay, max_delay,
                               jitter_seed, label)
            if deadline is not None and (
                    time.monotonic() - start + delay > float(deadline)):
                obs.counter(
                    "pyconsensus_retries_exhausted_total",
                    "retry_call giving up after exhausting its budget",
                    labels=("label",)).inc(label=label)
                raise
            obs.counter(
                "pyconsensus_retries_total",
                "transient-failure retries by operation label",
                labels=("label",)).inc(label=label)
            if on_retry is not None:
                on_retry(attempt, exc)
            time.sleep(delay)
            attempt += 1


def retry(**cfg):
    """Decorator form of :func:`retry_call` — configuration is fixed at
    decoration time::

        @retry(retries=3, retry_on=(OSError,), label="chunk-write")
        def write_chunk(...): ...
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, **cfg, **kwargs)
        return wrapper
    return deco
