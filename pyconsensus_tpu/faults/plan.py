"""Deterministic, seeded fault injection (ISSUE 4 tentpole, part a).

A :class:`FaultPlan` is a set of :class:`FaultRule`\\ s keyed by **named
injection sites** — host-side hook points threaded through the IO layer,
the checkpointed sweep, the ledger, the streaming panel loop, and the
sharded entry (catalog in docs/ROBUSTNESS.md). A rule activates by
``(site, occurrence index)``: the Nth time a site is reached under an
armed plan, deterministically — either at explicit occurrence indices or
with a seeded per-occurrence probability. The PRNG stream is a pure
function of ``(plan seed, site name, occurrence index)``, so replaying
the same plan file over the same workload reproduces the same faults in
the same places regardless of how calls to *other* sites interleave —
the property that makes a chaos run reproducible from its plan alone
(``--fault-plan`` on the CLI).

Zero overhead disarmed: :func:`fire` / :func:`corrupt` test one module
global against ``None`` and return. No plan state, no counters, no PRNG
is touched — the injection sites are free in production, and
consensus-lint CL601 statically guarantees none of them ever lands
inside jit-traced / shard_map code (where the armed-check would bake
into the compiled graph as a constant).

Two hook shapes:

- :func:`fire(site, path=...)` — control-flow faults: raise a
  configured exception (``raise`` kind), simulate a hard kill
  (``crash`` — :class:`SimulatedCrash` derives from ``BaseException``
  so ordinary ``except Exception`` recovery code cannot swallow it,
  matching what a SIGKILL leaves behind), or damage a file in place
  (``torn_write`` / ``truncate`` — the file at ``path`` is cut short,
  silently, exactly like a power loss between write and fsync).
- :func:`corrupt(site, value)` — data faults on host arrays (or dicts
  of arrays): ``nan_storm`` / ``inf_storm`` poison a seeded fraction of
  entries, ``drop_rows`` NaNs whole rows, ``drop_shard`` NaNs one
  contiguous column block (a lost event shard). Returns the value
  unchanged when disarmed or no rule matches.
"""

from __future__ import annotations

import fnmatch
import json
import pathlib
import zlib
from typing import Optional, Sequence

import numpy as np

__all__ = ["FAULT_SITES", "FaultRule", "FaultPlan", "SimulatedCrash",
           "arm", "disarm", "armed", "active_plan", "fire", "corrupt"]

#: The catalog of named injection sites — every :func:`fire` /
#: :func:`corrupt` hook call in the package names exactly one of these,
#: and every entry here is reached by at least one hook call.
#: consensus-lint CL805 enforces both directions against the source, and
#: tests/test_concurrency.py pins docs/ROBUSTNESS.md's site table to
#: this tuple, so plan files, code, and docs cannot drift apart.
FAULT_SITES = (
    "io.read", "io.decode", "io.write", "io.stage",
    "ledger.save", "ledger.load",
    "sweep.chunk.data", "sweep.chunk.write",
    "sweep.chunk.pre_commit", "sweep.chunk.post_commit",
    "streaming.panel", "sharded.reports",
    "oracle.reports", "oracle.raw_result",
    "serve.enqueue", "serve.dispatch", "serve.cache_store",
    "serve.session_append",
    "aot.cache_write", "aot.cache_load",
    "tune.cache_write",
    "fleet.route", "fleet.heartbeat", "fleet.takeover",
    "fleet.ledger_replay",
    "autoscale.decide", "autoscale.spawn", "autoscale.drain",
    "econ.round", "econ.panel", "econ.submit",
    "transport.send", "transport.recv", "transport.connect",
    "shipping.append",
    "state.snapshot", "state.compact", "state.hydrate", "state.migrate",
)


class SimulatedCrash(BaseException):
    """An injected hard kill (``crash`` kind). Derives from
    ``BaseException`` so graceful-recovery code written for *errors*
    (``except Exception``) cannot intercept it — the process state left
    behind is what a real ``kill -9`` at that site would leave, which is
    exactly what crash/resume tests need to exercise."""


#: ``raise`` kind ``error=`` spellings -> exception class. The structured
#: classes come from .errors; ``os_error`` simulates transient
#: infrastructure failures (the retry decorator's domain).
def _error_classes():
    from .errors import (CheckpointCorruptionError, ConsensusError,
                         InputError, NumericsError)

    return {
        "os_error": OSError,
        "input_error": InputError,
        "numerics_error": NumericsError,
        "checkpoint_corruption": CheckpointCorruptionError,
        "consensus_error": ConsensusError,
    }


_FIRE_KINDS = ("raise", "crash", "torn_write", "truncate")
_CORRUPT_KINDS = ("nan_storm", "inf_storm", "drop_rows", "drop_shard",
                  "zero_out")
_KINDS = _FIRE_KINDS + _CORRUPT_KINDS


class FaultRule:
    """One injection rule. ``site`` is an exact site name or an
    ``fnmatch`` pattern (``"sweep.chunk.*"``). Activation: explicit
    ``occurrences`` (0-based indices), or seeded per-occurrence
    ``probability``, or both (union); ``max_fires`` caps total
    activations (default: unlimited for occurrence lists, 1 for pure
    probability rules — a probabilistic rule that can fire forever makes
    replay analysis needlessly noisy). ``args`` parameterizes the kind
    (``fraction``, ``value``, ``rows``, ``shard``, ``n_shards``,
    ``error``, ``message``, ``keep_bytes``)."""

    def __init__(self, site: str, kind: str,
                 occurrences: Optional[Sequence[int]] = None,
                 probability: Optional[float] = None,
                 max_fires: Optional[int] = None,
                 args: Optional[dict] = None) -> None:
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; choose from "
                             f"{_KINDS}")
        if occurrences is None and probability is None:
            occurrences = [0]          # the common "first time" default
        if probability is not None and not 0.0 <= probability <= 1.0:
            raise ValueError("probability must lie in [0, 1]")
        self.site = str(site)
        self.kind = str(kind)
        self.occurrences = (None if occurrences is None
                            else tuple(int(i) for i in occurrences))
        self.probability = None if probability is None else float(probability)
        if max_fires is None:
            max_fires = 1 if self.occurrences is None else 0  # 0 = no cap
        self.max_fires = int(max_fires)
        self.args = dict(args or {})
        self.fires = 0

    def matches(self, site: str) -> bool:
        return site == self.site or fnmatch.fnmatchcase(site, self.site)

    def active(self, occurrence: int, rng_for) -> bool:
        """Whether this rule fires at ``occurrence`` of a matched site.
        ``rng_for(tag)`` supplies the deterministic per-occurrence
        generator (the plan owns the seeding discipline)."""
        if self.max_fires and self.fires >= self.max_fires:
            return False
        if self.occurrences is not None and occurrence in self.occurrences:
            return True
        if self.probability is not None:
            return bool(rng_for("activate").random() < self.probability)
        return False

    def to_dict(self) -> dict:
        out = {"site": self.site, "kind": self.kind}
        if self.occurrences is not None:
            out["occurrences"] = list(self.occurrences)
        if self.probability is not None:
            out["probability"] = self.probability
        if self.max_fires:
            out["max_fires"] = self.max_fires
        if self.args:
            out["args"] = self.args
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        unknown = set(d) - {"site", "kind", "occurrences", "probability",
                            "max_fires", "args"}
        if unknown:
            raise ValueError(f"unknown fault-rule keys {sorted(unknown)}")
        return cls(d["site"], d["kind"],
                   occurrences=d.get("occurrences"),
                   probability=d.get("probability"),
                   max_fires=d.get("max_fires"),
                   args=d.get("args"))


class FaultPlan:
    """A seeded set of rules plus the per-site occurrence bookkeeping.
    One plan instance tracks one chaos run: ``fired`` logs every
    activation ``(site, occurrence, kind)`` in order, so a run can be
    summarized (the CLI prints it) and a replay asserted identical."""

    def __init__(self, seed: int = 0, rules: Sequence = ()) -> None:
        self.seed = int(seed)
        self.rules = [r if isinstance(r, FaultRule) else
                      FaultRule.from_dict(r) for r in rules]
        self._counts: dict = {}
        #: activation log: (site, occurrence, kind) tuples, in fire order
        self.fired: list = []

    # -- deterministic PRNG discipline ----------------------------------

    def _rng(self, site: str, occurrence: int, tag: str):
        """Generator keyed on (seed, site, occurrence, tag): independent
        of call interleaving across sites, stable across platforms
        (crc32 is deterministic), distinct per use within one
        activation (``tag``)."""
        return np.random.default_rng(
            [self.seed, zlib.crc32(site.encode()), occurrence,
             zlib.crc32(tag.encode())])

    def _next(self, site: str):
        """Advance ``site``'s occurrence counter and return the first
        activating rule (or None) with the occurrence index."""
        occ = self._counts.get(site, 0)
        self._counts[site] = occ + 1
        for rule in self.rules:
            if rule.matches(site) and rule.active(
                    occ, lambda tag: self._rng(site, occ, tag)):
                rule.fires += 1
                self.fired.append((site, occ, rule.kind))
                self._record(site, rule.kind)
                return rule, occ
        return None, occ

    @staticmethod
    def _record(site: str, kind: str) -> None:
        from .. import obs

        obs.counter(
            "pyconsensus_faults_injected_total",
            "fault-plan activations by injection site and kind",
            labels=("site", "kind")).inc(site=site, kind=kind)

    # -- the two hook bodies --------------------------------------------

    def fire(self, site: str, path=None) -> None:
        rule, occ = self._next(site)
        if rule is None:
            return
        if rule.kind in ("raise", "crash"):
            self._control(rule, site, occ)
        if rule.kind in ("torn_write", "truncate"):
            if path is None:
                raise ValueError(
                    f"fault rule {rule.kind!r} at {site} needs a file "
                    f"path — this site does not expose one")
            self._tear(pathlib.Path(path), rule, site, occ)
            return
        raise ValueError(f"fault kind {rule.kind!r} is a data fault — "
                         f"site {site} is a fire() (control-flow) site")

    @staticmethod
    def _control(rule: FaultRule, site: str, occ: int) -> None:
        """Shared raise/crash arm of both hooks."""
        if rule.kind == "raise":
            exc = _error_classes()[rule.args.get("error", "os_error")]
            raise exc(rule.args.get(
                "message", f"injected fault at {site} (occurrence {occ})"))
        raise SimulatedCrash(f"injected crash at {site} (occurrence {occ})")

    def _tear(self, path: pathlib.Path, rule: FaultRule, site: str,
              occ: int) -> None:
        """Cut ``path`` short — the torn write a power loss between
        write and fsync leaves. ``keep_bytes`` pins the cut; default:
        a seeded point in the middle half of the file."""
        size = path.stat().st_size
        keep = rule.args.get("keep_bytes")
        if keep is None:
            keep = int(size * (0.25 + 0.5 * self._rng(site, occ,
                                                      "tear").random()))
        with open(path, "r+b") as f:
            f.truncate(max(0, min(int(keep), size)))

    def corrupt(self, site: str, value):
        rule, occ = self._next(site)
        if rule is None:
            return value
        if rule.kind in ("raise", "crash"):
            # control-flow kinds are legal at data sites too
            self._control(rule, site, occ)
        if rule.kind in ("torn_write", "truncate"):
            # loud in BOTH directions: fire() rejects data kinds, and a
            # file kind at a data site must not log a vacuous activation
            raise ValueError(
                f"fault kind {rule.kind!r} is a file fault — site {site} "
                f"is a corrupt() (data) site with no file to tear")
        if isinstance(value, dict):
            # dict payloads (a sweep chunk, a fetched result): poison the
            # FLOAT arrays only — counters/flags ("iterations",
            # "convergence") are bookkeeping, and NaN-ing them would test
            # Python's int() rather than the pipeline's numerics
            return {k: (self._apply(rule, site, occ, v, subkey=k)
                        if np.asarray(v).dtype.kind in "fc" else v)
                    for k, v in value.items()}
        return self._apply(rule, site, occ, value)

    def _apply(self, rule: FaultRule, site: str, occ: int, arr,
               subkey: str = ""):
        arr = np.array(arr, copy=True)     # never mutate the caller's data
        if arr.dtype.kind not in "fc":     # int/bool payloads: poison as f64
            arr = arr.astype(np.float64)
        rng = self._rng(site, occ, f"data:{subkey}")
        if rule.kind in ("nan_storm", "inf_storm", "zero_out"):
            fraction = float(rule.args.get("fraction", 0.05))
            mask = rng.random(arr.shape) < fraction
            if rule.kind == "nan_storm":
                fill = np.nan
            elif rule.kind == "zero_out":
                fill = 0.0
            else:
                fill = float(rule.args.get("value", np.inf))
            arr[mask] = fill
        elif rule.kind == "drop_rows":
            if arr.ndim < 1 or arr.shape[0] == 0:
                return arr
            rows = rule.args.get("rows")
            if rows is None:
                fraction = float(rule.args.get("fraction", 0.1))
                n = max(1, int(round(arr.shape[0] * fraction)))
                rows = rng.choice(arr.shape[0], size=min(n, arr.shape[0]),
                                  replace=False)
            arr[np.asarray(rows, dtype=int)] = np.nan
        elif rule.kind == "drop_shard":
            if arr.ndim < 2 or arr.shape[1] == 0:
                return arr
            n_shards = int(rule.args.get("n_shards", 8))
            shard = rule.args.get("shard")
            if shard is None:
                shard = int(rng.integers(n_shards))
            width = -(-arr.shape[1] // n_shards)
            lo = int(shard) * width
            arr[:, lo:lo + width] = np.nan
        return arr

    # -- serialization ---------------------------------------------------

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "rules": [r.to_dict() for r in self.rules]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        unknown = set(d) - {"seed", "rules"}
        if unknown:
            raise ValueError(f"unknown fault-plan keys {sorted(unknown)}")
        return cls(seed=d.get("seed", 0), rules=d.get("rules", ()))

    def save(self, path) -> pathlib.Path:
        path = pathlib.Path(path)
        path.write_text(json.dumps(self.to_dict(), indent=2) + "\n")
        return path

    @classmethod
    def load(cls, path) -> "FaultPlan":
        return cls.from_dict(json.loads(pathlib.Path(path).read_text()))

    def replay(self) -> "FaultPlan":
        """A fresh plan with the same seed/rules and zeroed bookkeeping —
        arm it over the same workload to reproduce the run."""
        return FaultPlan.from_dict(self.to_dict())


#: the armed plan (module global — the only state the disarmed fast path
#: reads). One plan at a time, process-wide, like obs.REGISTRY.
_ACTIVE: Optional[FaultPlan] = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan`` process-wide. Returns it (for chaining)."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def disarm() -> None:
    global _ACTIVE
    _ACTIVE = None


class armed:
    """``with faults.armed(plan): ...`` — scoped arming for tests."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return arm(self.plan)

    def __exit__(self, *exc) -> None:
        disarm()


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def fire(site: str, path=None) -> None:
    """Control-flow injection hook (see module docstring). No-op (one
    global ``is None`` test) when no plan is armed."""
    if _ACTIVE is None:
        return
    _ACTIVE.fire(site, path=path)


def corrupt(site: str, value):
    """Data injection hook: returns ``value`` (host array or dict of
    arrays) possibly poisoned per the armed plan; the input itself is
    never mutated. No-op passthrough when disarmed."""
    if _ACTIVE is None:
        return value
    return _ACTIVE.corrupt(site, value)
