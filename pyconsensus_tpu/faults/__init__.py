"""pyconsensus_tpu.faults — deterministic fault injection, structured
errors, graceful degradation, and retry (ISSUE 4 tentpole).

Quick use::

    from pyconsensus_tpu import faults

    plan = faults.FaultPlan(seed=7, rules=[
        {"site": "sharded.reports", "kind": "nan_storm",
         "occurrences": [0], "args": {"fraction": 0.02}},
        {"site": "sweep.chunk.pre_commit", "kind": "crash",
         "occurrences": [1]},
    ])
    with faults.armed(plan):
        ...                       # the chaos run
    print(plan.fired)             # [(site, occurrence, kind), ...]
    plan.save("plan.json")        # replay later: --fault-plan plan.json

Rules of engagement:

- **host-side only.** ``fire``/``corrupt`` sites live in host code
  (IO, checkpoint commits, panel staging, front-door entries) — never
  inside jit-traced / shard_map / pallas code, where the armed-plan
  check would bake into the compiled graph. consensus-lint CL601
  rejects traced injection sites statically.
- **zero overhead disarmed.** Both hooks test one module global against
  ``None`` and return; no counters, no PRNG, no allocation.
- **deterministic.** Activation and payloads are pure functions of
  (plan seed, site name, occurrence index) — same plan + same workload
  = same faults, regardless of unrelated call interleaving.
- the **site catalog**, **error-code table**, and **fallback chain**
  live in docs/ROBUSTNESS.md; extend them when adding sites.
"""

from __future__ import annotations

from .degrade import (POWER_METHODS, fallback_steps, quarantine_nonfinite,
                      raise_exhausted, record_fallback, result_nonfinite)
from .errors import (ERROR_CODES, AotCacheCorruptionError,
                     CheckpointCorruptionError, ConsensusError,
                     ConvergenceError, FailoverInProgressError,
                     HandshakeError, InputError, NumericsError,
                     PlacementError, ServiceOverloadError,
                     SnapshotCorruptionError, TransportError,
                     WorkerLostError)
from .plan import (FAULT_SITES, FaultPlan, FaultRule, SimulatedCrash,
                   active_plan, arm, armed, corrupt, disarm, fire)
from .retry import retry, retry_call

__all__ = [
    "FAULT_SITES", "FaultPlan", "FaultRule", "SimulatedCrash",
    "arm", "disarm", "armed", "active_plan", "fire", "corrupt",
    "ConsensusError", "InputError", "NumericsError", "ConvergenceError",
    "CheckpointCorruptionError", "AotCacheCorruptionError",
    "SnapshotCorruptionError", "ServiceOverloadError",
    "WorkerLostError", "FailoverInProgressError", "PlacementError",
    "TransportError", "HandshakeError",
    "ERROR_CODES",
    "retry", "retry_call",
    "quarantine_nonfinite", "result_nonfinite", "record_fallback",
    "fallback_steps", "raise_exhausted", "POWER_METHODS",
]
