"""Graceful degradation: quarantine + the documented fallback chain
(ISSUE 4, part b).

**Row quarantine.** NaN is the mechanism's legal non-participation
marker, but ±Inf in a reports matrix (a poisoned feed, an overflowed
upstream aggregation) used to ride the fill pass into every covariance
contraction and NaN the whole resolution. The front doors
(:class:`..oracle.Oracle`, ``parallel.sharded_consensus``) now route
host matrices through :func:`quarantine_nonfinite`: rows containing a
non-finite non-NaN value are replaced by all-NaN (full
non-participation — the reporter simply isn't heard this round), the
row indices are reported (``quarantined_rows`` result field) and
counted (``pyconsensus_quarantined_rows_total``). The clean-matrix cost
is one ``np.isfinite().all()`` host scan, which REPLACES the
``np.isnan().any()`` scan those doors already paid for ``has_na``.

**Fallback chain.** A power-family PCA that fails to converge (residual
plateau / collapsed loading) or numerically degenerate inputs can leave
non-finite values in the *outputs*. Detection is host-side on the
fetched result (:func:`result_nonfinite` — O(R + E), no extra device
sync) and recovery walks a documented chain, re-resolving with strictly
more conservative numerics at each rung::

    power-fused (Pallas)  ->  eigh-gram (exact XLA)  ->  numpy reference

Each hop emits ``pyconsensus_fallbacks_total{from,to,reason}``. If the
numpy reference also yields non-finite outputs, the failure is genuine:
:class:`..faults.errors.ConvergenceError` (power-family start — the
plateau was the root cause) or :class:`NumericsError` (already-exact
start) is raised rather than returning a poisoned result.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .. import obs
from .errors import ConvergenceError, NumericsError

__all__ = ["quarantine_nonfinite", "result_nonfinite", "record_fallback",
           "fallback_steps", "raise_exhausted", "POWER_METHODS"]

#: pca methods whose failure mode is iterative non-convergence — the
#: chain's entry rungs (and the ConvergenceError classification)
POWER_METHODS = ("power-fused", "power")

#: result keys checked for non-finite escape, in cost order: the O(R)
#: reputation first (a poisoned scorer always shows there), then the
#: O(E) outcome/certainty vectors
_CHECK_KEYS = ("smooth_rep", "this_rep", "outcomes_final", "certainty")


def quarantine_nonfinite(reports: np.ndarray
                         ) -> Tuple[np.ndarray, Optional[np.ndarray], bool]:
    """Replace rows holding ±Inf (any non-finite value that is not the
    legal NaN marker) with all-NaN rows. Returns ``(reports,
    quarantined_row_indices-or-None, has_na)``; the input is only copied
    when a quarantine actually happens. ``has_na`` falls out for free —
    the front doors previously paid an ``np.isnan().any()`` scan for it,
    which this single ``np.isfinite()`` pass replaces, so the
    clean-matrix cost of quarantine is zero extra host passes. Host
    numpy float matrices only — the callers gate on that."""
    finite = np.isfinite(reports)
    if finite.all():
        return reports, None, False
    poisoned = ~finite & ~np.isnan(reports)          # Inf / -Inf cells
    rows = poisoned.any(axis=1)
    if not rows.any():
        return reports, None, True                   # NaN-only: legal
    out = np.array(reports, copy=True)
    out[rows] = np.nan
    idx = np.nonzero(rows)[0]
    obs.counter(
        "pyconsensus_quarantined_rows_total",
        "report rows quarantined (set to full non-participation) for "
        "carrying non-finite non-NaN values").inc(int(idx.size))
    return out, idx, True


def result_nonfinite(raw: dict) -> bool:
    """Whether a fetched (host) flat result dict carries non-finite
    values in its decision outputs. O(R + E) host arithmetic."""
    for key in _CHECK_KEYS:
        v = raw.get(key)
        if v is not None and not np.isfinite(
                np.asarray(v, dtype=np.float64)).all():
            return True
    return False


def record_fallback(frm: str, to: str, reason: str) -> None:
    obs.counter(
        "pyconsensus_fallbacks_total",
        "graceful-degradation fallback hops (docs/ROBUSTNESS.md chain)",
        labels=("from", "to", "reason")).inc(
            **{"from": frm, "to": to, "reason": reason})


def fallback_steps(pca_method: str, backend: str):
    """The ordered ``(from_label, to_label, params_update)`` hops to try
    after a non-finite result. ``params_update`` is a dict of
    ConsensusParams field overrides; the special key ``"backend"``
    switches the whole execution path to the numpy reference."""
    steps = []
    if backend == "jax" and pca_method in POWER_METHODS:
        steps.append((pca_method, "eigh-gram",
                      {"pca_method": "eigh-gram", "fused_resolution": False,
                       "allow_fused": False}))
    if backend == "jax":
        frm = "eigh-gram" if pca_method in POWER_METHODS else pca_method
        steps.append((f"jax:{frm}", "numpy", {"backend": "numpy"}))
    return steps


def raise_exhausted(pca_method: str, algorithm: str) -> None:
    """Every rung failed: classify and raise (never return poison)."""
    if pca_method in POWER_METHODS:
        raise ConvergenceError(
            f"power-family PCA ({pca_method!r}) produced non-finite "
            f"scores and every fallback rung (eigh-gram, numpy "
            f"reference) stayed non-finite — the {algorithm!r} "
            f"resolution has no convergent route for this input",
            pca_method=pca_method, algorithm=algorithm)
    raise NumericsError(
        f"non-finite values in the {algorithm!r} resolution outputs "
        f"survived the whole fallback chain (docs/ROBUSTNESS.md) — "
        f"refusing to return a poisoned result",
        pca_method=pca_method, algorithm=algorithm)
