"""Fault-tolerant, multi-host Monte-Carlo sweep execution.

The reference's simulator is one long Python loop — a crash loses
everything, and there is no way to spread a sweep across machines
(SURVEY.md §5: no failure detection / elastic recovery exists upstream).
:class:`CheckpointedSweep` is the TPU-native framework's answer, built on
the same work-sharding + checkpoint + resume pattern as elastic training
loops:

- the flattened (liar_fraction × variance × trial) grid is split into
  contiguous **chunks** of flat indices; per-trial PRNG keys are a pure
  function of the GLOBAL flat index (``collusion._fold_keys``), so every
  chunk's result is independent of which host computes it, when, or what
  completed before — a resumed/re-sharded sweep is bit-identical to a
  monolithic :meth:`CollusionSimulator.run`;
- each finished chunk is written atomically (tmp file + rename) to a
  shared checkpoint directory; a crashed host loses at most the chunk it
  was computing;
- hosts claim chunks round-robin by rank (``host_id``/``n_hosts`` —
  defaults read ``jax.process_index``/``process_count``, so a
  ``jax.distributed``-initialized multi-host job shards automatically);
  any host (or a fresh process after ALL hosts died) can finish the
  leftovers with ``run(host_id=0, n_hosts=1)``;
- :meth:`gather` merges the chunk files into exactly the result dict
  :meth:`CollusionSimulator.run` returns (per-metric (L, V, T[, ...])
  arrays plus per-cell means and annotations).

>>> sweep = CheckpointedSweep(sim, lf, var, n_trials=1000,
...                           checkpoint_dir="ckpt", seed=0)
>>> sweep.run()                    # this host's share; crash-safe
>>> result = sweep.gather()        # == sim.run(lf, var, 1000, seed=0)
"""

from __future__ import annotations

import json
import os
import pathlib
import tempfile
from typing import Optional, Sequence

import numpy as np

from .. import obs
from .collusion import CollusionSimulator, flat_grid

__all__ = ["CheckpointedSweep"]

_MANIFEST = "sweep.json"


class CheckpointedSweep:
    """Chunked, checkpointed, host-sharded execution of one simulator sweep.

    Parameters
    ----------
    simulator : CollusionSimulator (or subclass, e.g. RoundsSimulator)
        The batched trial runner; its vmapped program is invoked per chunk.
    liar_fractions, variances, n_trials, seed :
        The sweep definition, exactly as :meth:`CollusionSimulator.run`
        takes it.
    checkpoint_dir : path
        Shared directory (shared filesystem for multi-host) for chunk
        files and the manifest.
    trials_per_chunk : int
        Chunk granularity in flat trials (default 1024): the unit of loss
        on a crash and of re-dispatch on resume. Every chunk but the last
        has this exact batch size, so resuming re-uses the chunk-sized
        XLA program from cache.
    """

    def __init__(self, simulator: CollusionSimulator,
                 liar_fractions: Sequence[float],
                 variances: Sequence[float], n_trials: int, seed: int = 0,
                 checkpoint_dir="sweep-ckpt",
                 trials_per_chunk: int = 1024) -> None:
        self.sim = simulator
        self.lf, self.var, self._grid_lf, self._grid_var = flat_grid(
            liar_fractions, variances, n_trials)
        self.n_trials = int(n_trials)
        self.seed = int(seed)
        if int(trials_per_chunk) < 1:
            raise ValueError("trials_per_chunk must be >= 1")
        self.trials_per_chunk = int(trials_per_chunk)
        self.total = len(self._grid_lf)
        self.n_chunks = -(-self.total // self.trials_per_chunk)
        self.dir = pathlib.Path(checkpoint_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._check_manifest()

    def _write_atomic(self, final: pathlib.Path, writer,
                      suffix: str = ".tmp") -> None:
        """All-or-nothing file creation safe against CONCURRENT writers of
        ``final`` (several hosts racing on a shared checkpoint dir, or a
        mop-up process overlapping a restarted host on the same chunk):
        each writer gets its own ``mkstemp``-unique tmp in the target
        directory — pids alone are not unique across hosts — and the
        atomic rename makes last-writer-wins harmless because racers
        write identical content by construction."""
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=suffix)
        try:
            # mkstemp creates 0600 and os.replace preserves it — restore
            # umask-based permissions so a different account (gather /
            # mop-up on a shared filesystem) can read the installed file
            umask = os.umask(0)
            os.umask(umask)
            os.fchmod(fd, 0o666 & ~umask)
            os.close(fd)
            writer(tmp)
            os.replace(tmp, final)
        except BaseException:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            raise

    #: tmp files older than this are orphans from hard-killed writers
    #: (no Python-level except ran); any entry point may reap them
    _TMP_MAX_AGE_S = 3600.0

    def _reap_stale_tmps(self) -> None:
        """Remove orphaned ``*.tmp*`` files left by writers that died
        between mkstemp and the atomic rename (SIGKILL/power loss — the
        exception cleanup never ran, and the next retry gets a fresh
        unique name, so orphans would otherwise accumulate forever under
        a crash loop). Age-gated so a live host's in-flight tmp is never
        touched."""
        import time

        cutoff = time.time() - self._TMP_MAX_AGE_S
        for f in self.dir.glob("tmp*.tmp*"):
            try:
                if f.stat().st_mtime < cutoff:
                    f.unlink()
            except OSError:
                pass                      # already reaped by another host

    # -- manifest: guard against mixing two different sweeps in one dir ------

    def _manifest(self) -> dict:
        # the simulator fingerprint matters as much as the grid: chunks
        # computed by two differently-configured simulators concatenate
        # without shape errors, so a config mismatch must fail HERE, not
        # surface as silently mixed results at gather()
        sim_config = {
            "class": type(self.sim).__name__,
            "n_reporters": self.sim.n_reporters,
            "n_events": self.sim.n_events,
            "collude": self.sim.collude,
            "params": dict(self.sim.params._asdict()),   # JSON-stable form
        }
        if hasattr(self.sim, "n_rounds"):
            sim_config["n_rounds"] = self.sim.n_rounds
        return {
            "liar_fractions": self.lf.tolist(),
            "variances": self.var.tolist(),
            "n_trials": self.n_trials,
            "seed": self.seed,
            "trials_per_chunk": self.trials_per_chunk,
            "simulator": sim_config,
        }

    def _check_manifest(self) -> None:
        path = self.dir / _MANIFEST
        mine = self._manifest()
        if path.exists():
            have = json.loads(path.read_text())
            if have != mine:
                raise ValueError(
                    f"{self.dir} holds a different sweep "
                    f"({have} != {mine}); use a fresh checkpoint_dir")
        else:
            self._write_atomic(
                path, lambda t: pathlib.Path(t).write_text(json.dumps(mine)))

    # -- chunk execution -----------------------------------------------------

    def _chunk_path(self, c: int) -> pathlib.Path:
        return self.dir / f"chunk_{c:06d}.npz"

    def pending(self) -> list:
        """Chunk indices not yet checkpointed (by any host)."""
        return [c for c in range(self.n_chunks)
                if not self._chunk_path(c).exists()]

    def _run_chunk(self, c: int) -> None:
        lo = c * self.trials_per_chunk
        hi = min(lo + self.trials_per_chunk, self.total)
        with obs.span("sweep.chunk", chunk=c, trials=hi - lo):
            # the shared dispatch point: a meshed simulator shards each
            # chunk's trial axis exactly like a monolithic run() would
            host = self.sim._dispatch(self.seed, np.arange(lo, hi),
                                      self._grid_lf[lo:hi],
                                      self._grid_var[lo:hi])
            self._write_atomic(self._chunk_path(c),
                               lambda t: np.savez(t, **host),
                               suffix=".tmp.npz")
        obs.counter(
            "pyconsensus_sweep_chunks_total",
            "checkpointed sweep chunks computed and written by this "
            "process").inc()

    def run(self, host_id: Optional[int] = None,
            n_hosts: Optional[int] = None) -> int:
        """Compute this host's pending chunks (round-robin assignment:
        chunk ``c`` belongs to host ``c % n_hosts``). Already-checkpointed
        chunks — including ones another incarnation of this host wrote
        before crashing — are skipped. Returns the number of chunks this
        call computed."""
        if host_id is None or n_hosts is None:
            import jax

            host_id = jax.process_index() if host_id is None else host_id
            n_hosts = jax.process_count() if n_hosts is None else n_hosts
        if not (0 <= host_id < n_hosts):
            raise ValueError(f"host_id {host_id} not in [0, {n_hosts})")
        self._reap_stale_tmps()
        done = 0
        for c in self.pending():
            if c % n_hosts == host_id:
                self._run_chunk(c)
                done += 1
        return done

    # -- result assembly -----------------------------------------------------

    def gather(self) -> dict:
        """Merge all chunk checkpoints into the monolithic
        :meth:`CollusionSimulator.run` result dict. Raises if any chunk is
        missing (run ``run(host_id=0, n_hosts=1)`` first to mop up after
        lost hosts)."""
        self._reap_stale_tmps()
        missing = self.pending()
        if missing:
            raise ValueError(f"sweep incomplete: {len(missing)} of "
                             f"{self.n_chunks} chunks missing "
                             f"(e.g. {missing[:4]}); call run() to finish")
        parts: list = []
        for c in range(self.n_chunks):
            with np.load(self._chunk_path(c)) as data:
                parts.append({k: data[k] for k in data.files})
        L, V, T = len(self.lf), len(self.var), self.n_trials
        result = {}
        for k in parts[0]:
            arr = np.concatenate([p[k] for p in parts], axis=0)
            result[k] = arr.reshape((L, V, T) + arr.shape[1:])
        result["mean"] = {k: v.mean(axis=2) for k, v in result.items()}
        result["liar_fractions"] = self.lf
        result["variances"] = self.var
        self.sim._annotate(result)
        return result
