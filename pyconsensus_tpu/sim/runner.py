"""Fault-tolerant, multi-host Monte-Carlo sweep execution.

The reference's simulator is one long Python loop — a crash loses
everything, and there is no way to spread a sweep across machines
(SURVEY.md §5: no failure detection / elastic recovery exists upstream).
:class:`CheckpointedSweep` is the TPU-native framework's answer, built on
the same work-sharding + checkpoint + resume pattern as elastic training
loops:

- the flattened (liar_fraction × variance × trial) grid is split into
  contiguous **chunks** of flat indices; per-trial PRNG keys are a pure
  function of the GLOBAL flat index (``collusion._fold_keys``), so every
  chunk's result is independent of which host computes it, when, or what
  completed before — a resumed/re-sharded sweep is bit-identical to a
  monolithic :meth:`CollusionSimulator.run`;
- each finished chunk is written atomically (tmp file + rename) to a
  shared checkpoint directory; a crashed host loses at most the chunk it
  was computing;
- hosts claim chunks round-robin by rank (``host_id``/``n_hosts`` —
  defaults read ``jax.process_index``/``process_count``, so a
  ``jax.distributed``-initialized multi-host job shards automatically);
  any host (or a fresh process after ALL hosts died) can finish the
  leftovers with ``run(host_id=0, n_hosts=1)``;
- :meth:`gather` merges the chunk files into exactly the result dict
  :meth:`CollusionSimulator.run` returns (per-metric (L, V, T[, ...])
  arrays plus per-cell means and annotations).

>>> sweep = CheckpointedSweep(sim, lf, var, n_trials=1000,
...                           checkpoint_dir="ckpt", seed=0)
>>> sweep.run()                    # this host's share; crash-safe
>>> result = sweep.gather()        # == sim.run(lf, var, 1000, seed=0)
"""

from __future__ import annotations

import hashlib
import json
import pathlib
from typing import Optional, Sequence

import numpy as np

from .. import obs
from ..faults import CheckpointCorruptionError, retry_call
from ..faults import plan as _faults
from .collusion import CollusionSimulator, flat_grid

__all__ = ["CheckpointedSweep"]

_MANIFEST = "sweep.json"

#: npz key carrying the chunk's content digest (never a metric array)
_DIGEST_KEY = "__digest__"


def _chunk_digest(host: dict) -> np.ndarray:
    """SHA-256 over the chunk's ARRAYS (sorted key, dtype, shape, raw
    bytes) as a uint8 vector — content-addressed, so it survives any
    npz container re-serialization and catches torn writes, truncated
    members, and silent bit flips alike. Stored inside the chunk file
    under ``__digest__`` and re-derived on every load."""
    h = hashlib.sha256()
    for k in sorted(host):
        if k == _DIGEST_KEY:
            continue
        arr = np.ascontiguousarray(host[k])
        h.update(k.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return np.frombuffer(h.digest(), dtype=np.uint8)


class CheckpointedSweep:
    """Chunked, checkpointed, host-sharded execution of one simulator sweep.

    Parameters
    ----------
    simulator : CollusionSimulator (or subclass, e.g. RoundsSimulator)
        The batched trial runner; its vmapped program is invoked per chunk.
    liar_fractions, variances, n_trials, seed :
        The sweep definition, exactly as :meth:`CollusionSimulator.run`
        takes it.
    checkpoint_dir : path
        Shared directory (shared filesystem for multi-host) for chunk
        files and the manifest.
    trials_per_chunk : int
        Chunk granularity in flat trials (default 1024): the unit of loss
        on a crash and of re-dispatch on resume. Every chunk but the last
        has this exact batch size, so resuming re-uses the chunk-sized
        XLA program from cache.
    """

    def __init__(self, simulator: CollusionSimulator,
                 liar_fractions: Sequence[float],
                 variances: Sequence[float], n_trials: int, seed: int = 0,
                 checkpoint_dir="sweep-ckpt",
                 trials_per_chunk: int = 1024) -> None:
        self.sim = simulator
        self.lf, self.var, self._grid_lf, self._grid_var = flat_grid(
            liar_fractions, variances, n_trials)
        self.n_trials = int(n_trials)
        self.seed = int(seed)
        if int(trials_per_chunk) < 1:
            raise ValueError("trials_per_chunk must be >= 1")
        self.trials_per_chunk = int(trials_per_chunk)
        self.total = len(self._grid_lf)
        self.n_chunks = -(-self.total // self.trials_per_chunk)
        self.dir = pathlib.Path(checkpoint_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._check_manifest()

    def _write_atomic(self, final: pathlib.Path, writer,
                      suffix: str = ".tmp") -> None:
        """All-or-nothing, fsynced file creation (``io.atomic_write``),
        safe against CONCURRENT writers of ``final`` (several hosts
        racing on a shared checkpoint dir, or a mop-up process
        overlapping a restarted host on the same chunk): each writer
        gets its own ``mkstemp``-unique tmp in the checkpoint directory
        — pids alone are not unique across hosts — and the atomic
        rename makes last-writer-wins harmless because racers write
        identical content by construction."""
        from ..io import atomic_write

        atomic_write(final, writer, suffix=suffix, dir=self.dir)

    #: tmp files older than this are orphans from hard-killed writers
    #: (no Python-level except ran); any entry point may reap them
    _TMP_MAX_AGE_S = 3600.0

    def _reap_stale_tmps(self) -> None:
        """Remove orphaned ``*.tmp*`` files left by writers that died
        between mkstemp and the atomic rename (SIGKILL/power loss — the
        exception cleanup never ran, and the next retry gets a fresh
        unique name, so orphans would otherwise accumulate forever under
        a crash loop). Age-gated so a live host's in-flight tmp is never
        touched."""
        import time

        cutoff = time.time() - self._TMP_MAX_AGE_S
        # sorted: glob order is readdir order, which varies with
        # directory history — keep reap order host-independent
        for f in sorted(self.dir.glob("tmp*.tmp*")):
            try:
                if f.stat().st_mtime < cutoff:
                    f.unlink()
            except OSError:
                pass                      # already reaped by another host

    # -- manifest: guard against mixing two different sweeps in one dir ------

    def _manifest(self) -> dict:
        # the simulator fingerprint matters as much as the grid: chunks
        # computed by two differently-configured simulators concatenate
        # without shape errors, so a config mismatch must fail HERE, not
        # surface as silently mixed results at gather()
        sim_config = {
            "class": type(self.sim).__name__,
            "n_reporters": self.sim.n_reporters,
            "n_events": self.sim.n_events,
            "collude": self.sim.collude,
            "params": dict(self.sim.params._asdict()),   # JSON-stable form
        }
        if hasattr(self.sim, "n_rounds"):
            sim_config["n_rounds"] = self.sim.n_rounds
        return {
            "liar_fractions": self.lf.tolist(),
            "variances": self.var.tolist(),
            "n_trials": self.n_trials,
            "seed": self.seed,
            "trials_per_chunk": self.trials_per_chunk,
            "simulator": sim_config,
        }

    def _check_manifest(self) -> None:
        path = self.dir / _MANIFEST
        mine = self._manifest()
        if path.exists():
            have = json.loads(path.read_text())
            if have != mine:
                raise ValueError(
                    f"{self.dir} holds a different sweep "
                    f"({have} != {mine}); use a fresh checkpoint_dir")
        else:
            self._write_atomic(
                path, lambda t: pathlib.Path(t).write_text(json.dumps(mine)))

    # -- chunk execution -----------------------------------------------------

    def _chunk_path(self, c: int) -> pathlib.Path:
        return self.dir / f"chunk_{c:06d}.npz"

    def pending(self) -> list:
        """Chunk indices not yet checkpointed (by any host)."""
        return [c for c in range(self.n_chunks)
                if not self._chunk_path(c).exists()]

    def _run_chunk(self, c: int) -> None:
        lo = c * self.trials_per_chunk
        hi = min(lo + self.trials_per_chunk, self.total)
        with obs.span("sweep.chunk", chunk=c, trials=hi - lo):
            # the shared dispatch point: a meshed simulator shards each
            # chunk's trial axis exactly like a monolithic run() would
            host = self.sim._dispatch(self.seed, np.arange(lo, hi),
                                      self._grid_lf[lo:hi],
                                      self._grid_var[lo:hi])
            host = dict(host)
            host = _faults.corrupt("sweep.chunk.data", host)
            # the digest is computed over whatever is WRITTEN — an
            # injected data corruption upstream of this point is the
            # simulator's problem (and the fuzz suite's), not a torn
            # write; everything between here and the rename is what the
            # checksum guards
            host[_DIGEST_KEY] = _chunk_digest(host)

            def write(tmp):
                np.savez(tmp, **host)
                _faults.fire("sweep.chunk.write", path=tmp)
                _faults.fire("sweep.chunk.pre_commit")
            # transient-OSError retry (shared-filesystem hiccups): the
            # jitter seed folds in the chunk index so concurrent hosts
            # stay decorrelated; SimulatedCrash is a BaseException and
            # always escapes, like the SIGKILL it stands in for
            retry_call(self._write_atomic, self._chunk_path(c), write,
                       suffix=".tmp.npz", retries=3, base_delay=0.05,
                       deadline=30.0, jitter_seed=self.seed + c,
                       label="sweep-chunk-write")
            _faults.fire("sweep.chunk.post_commit")
        obs.counter(
            "pyconsensus_sweep_chunks_total",
            "checkpointed sweep chunks computed and written by this "
            "process").inc()

    def _load_chunk(self, c: int) -> dict:
        """Read + checksum-verify one chunk checkpoint. Raises
        :class:`CheckpointCorruptionError` on a torn/corrupted file or a
        content-digest mismatch (the caller decides between recompute —
        the sweep's choice — and surfacing)."""
        path = self._chunk_path(c)
        try:
            with np.load(path) as data:
                part = {k: data[k] for k in data.files}
        except FileNotFoundError:
            raise
        except Exception as exc:        # BadZipFile / truncated member
            raise CheckpointCorruptionError(
                f"{path}: unreadable sweep chunk ({type(exc).__name__}: "
                f"{exc})", chunk=c, source=str(path)) from exc
        stored = part.pop(_DIGEST_KEY, None)
        if stored is None:
            raise CheckpointCorruptionError(
                f"{path}: sweep chunk has no content digest "
                f"('{_DIGEST_KEY}' missing — pre-digest or torn file)",
                chunk=c, source=str(path), field=_DIGEST_KEY)
        if not np.array_equal(np.asarray(stored, dtype=np.uint8),
                              _chunk_digest(part)):
            raise CheckpointCorruptionError(
                f"{path}: sweep chunk content digest mismatch — the "
                f"file was torn or corrupted after commit", chunk=c,
                source=str(path), field=_DIGEST_KEY)
        return part

    def _scrub(self, chunks=None) -> int:
        """Checksum-verify the given chunks on disk (default: all);
        DELETE corrupt ones so they re-enter ``pending()`` and are
        re-dispatched like never-run chunks (per-trial keys are pure
        functions of the global flat index, so a recomputed chunk is
        bit-identical to the lost one). Returns the number scrubbed.
        Called on every resume entry point — ``run`` scrubs this host's
        round-robin share (a corrupt chunk's owner re-verifies and
        redoes it; N hosts each hashing ALL chunks on a shared
        filesystem would multiply resume I/O by N), ``gather`` verifies
        everything as the final integrity gate."""
        scrubbed = 0
        for c in (range(self.n_chunks) if chunks is None else chunks):
            path = self._chunk_path(c)
            if not path.exists():
                continue
            try:
                self._load_chunk(c)
            except FileNotFoundError:
                continue              # raced: another host's scrub won
            except CheckpointCorruptionError:
                try:
                    path.unlink()
                except FileNotFoundError:
                    pass
                scrubbed += 1
        if scrubbed:
            obs.counter(
                "pyconsensus_chunk_corruptions_total",
                "corrupted/torn sweep chunk checkpoints detected by "
                "checksum and deleted for re-dispatch").inc(scrubbed)
        return scrubbed

    def run(self, host_id: Optional[int] = None,
            n_hosts: Optional[int] = None) -> int:
        """Compute this host's pending chunks (round-robin assignment:
        chunk ``c`` belongs to host ``c % n_hosts``). Already-checkpointed
        chunks — including ones another incarnation of this host wrote
        before crashing — are skipped after a checksum scrub: a chunk
        that exists but fails verification is deleted and recomputed,
        never trusted. Returns the number of chunks this call computed."""
        if host_id is None or n_hosts is None:
            import jax

            host_id = jax.process_index() if host_id is None else host_id
            n_hosts = jax.process_count() if n_hosts is None else n_hosts
        if not (0 <= host_id < n_hosts):
            raise ValueError(f"host_id {host_id} not in [0, {n_hosts})")
        self._reap_stale_tmps()
        self._scrub([c for c in range(self.n_chunks)
                     if c % n_hosts == host_id])
        done = 0
        for c in self.pending():
            if c % n_hosts == host_id:
                self._run_chunk(c)
                done += 1
        return done

    # -- result assembly -----------------------------------------------------

    def gather(self, recompute: bool = True) -> dict:
        """Merge all chunk checkpoints into the monolithic
        :meth:`CollusionSimulator.run` result dict. Every chunk is
        checksum-verified on read; a corrupted or torn chunk is
        transparently recomputed in place (``recompute=True``, the
        default — bit-identical by the global-index key construction) or
        raised as :class:`CheckpointCorruptionError`. Raises if any
        chunk is missing (run ``run(host_id=0, n_hosts=1)`` first to mop
        up after lost hosts)."""
        self._reap_stale_tmps()
        missing = self.pending()
        if missing:
            raise ValueError(f"sweep incomplete: {len(missing)} of "
                             f"{self.n_chunks} chunks missing "
                             f"(e.g. {missing[:4]}); call run() to finish")
        parts: list = []
        for c in range(self.n_chunks):
            try:
                parts.append(self._load_chunk(c))
            except CheckpointCorruptionError:
                if not recompute:
                    raise
                obs.counter(
                    "pyconsensus_chunk_corruptions_total",
                    "corrupted/torn sweep chunk checkpoints detected by "
                    "checksum and deleted for re-dispatch").inc()
                self._chunk_path(c).unlink(missing_ok=True)
                self._run_chunk(c)
                parts.append(self._load_chunk(c))
        L, V, T = len(self.lf), len(self.var), self.n_trials
        result = {}
        for k in parts[0]:
            arr = np.concatenate([p[k] for p in parts], axis=0)
            result[k] = arr.reshape((L, V, T) + arr.shape[1:])
        result["mean"] = {k: v.mean(axis=2) for k, v in result.items()}
        result["liar_fractions"] = self.lf
        result["variances"] = self.var
        self.sim._annotate(result)
        return result
