"""Monte-Carlo collusion simulator (SURVEY.md §2 #13, §3.3; BASELINE.json
config 5).

The reference ran thousands of independent oracle resolutions in a Python
triple loop over (liar_fraction × variance × seed). Here the whole sweep is a
single batched XLA program: report generation is a pure function of
``(key, liar_fraction, variance)``, the full resolution pipeline runs under
``jax.vmap`` over the flattened grid, and only *scalar metrics per trial* ever
leave the device — the (R, E) report matrices exist only inside the fused
graph, so a 10k-trial sweep needs no more HBM than a handful of matrices.

Threat model (mirroring the reference's simulator `[B]`):

- **truth**: each event has a random binary ground truth.
- **honest reporters** report the truth with per-entry flip probability
  ``variance`` (the noise knob).
- **liars** (each reporter independently with probability ``liar_fraction``):
  - ``collude=True``: all liars report the *shared anti-truth* — the
    coordinated attack PCA is supposed to catch;
  - ``collude=False``: each liar reports uniform random noise.

Metrics per trial: fraction of events resolved correctly / captured by the
lie / left ambiguous (0.5), the liars' share of post-resolution reputation,
and convergence of the iterative loop.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import obs
from ..models.pipeline import JIT_ALGORITHMS, ConsensusParams, _iterate_jax
from ..ops import jax_kernels as jk

__all__ = ["CollusionSimulator", "RoundsSimulator", "simulate_grid",
           "generate_reports"]


def _synth_reports(k_truth, k_noise, k_lie, liar, variance, n_reporters: int,
                   n_events: int, collude: bool):
    """Shared threat-model body: given a liar mask, build one round's
    ``(reports, truth)`` — fresh truth, honest noise-flips at probability
    ``variance``, liars reporting the shared anti-truth (collude) or
    uniform noise."""
    dtype = jnp.asarray(0.0).dtype
    truth = jax.random.bernoulli(k_truth, 0.5, (n_events,)).astype(dtype)
    flip = jax.random.bernoulli(k_noise, jnp.clip(variance, 0.0, 0.5),
                                (n_reporters, n_events))
    honest = jnp.abs(truth[None, :] - flip.astype(dtype))
    if collude:
        lie_reports = jnp.broadcast_to(1.0 - truth, (n_reporters, n_events))
    else:
        lie_reports = jax.random.bernoulli(
            k_lie, 0.5, (n_reporters, n_events)).astype(dtype)
    return jnp.where(liar[:, None], lie_reports, honest), truth


def generate_reports(key, liar_fraction, variance, n_reporters: int,
                     n_events: int, collude: bool = True):
    """Pure synthetic-report generator: ``(reports, truth, liar_mask)`` as a
    function of the PRNG key and the two sweep knobs. Public so tests and
    users can replay any trial's exact matrix through :class:`Oracle`."""
    k_truth, k_liar, k_noise, k_lie = jax.random.split(key, 4)
    liar = jax.random.bernoulli(k_liar, liar_fraction, (n_reporters,))
    reports, truth = _synth_reports(k_truth, k_noise, k_lie, liar, variance,
                                    n_reporters, n_events, collude)
    return reports, truth, liar


def _trial_metrics(key, liar_fraction, variance, *, n_reporters: int,
                   n_events: int, collude: bool, p: ConsensusParams):
    """One oracle resolution on synthetic reports; returns scalars only."""
    dtype = jnp.asarray(0.0).dtype
    reports, truth, liar = generate_reports(key, liar_fraction, variance,
                                            n_reporters, n_events, collude)

    # dense binary reports: rescale/interpolate are identities, so the trial
    # goes straight into the iterative scoring loop
    rep0 = jnp.full((n_reporters,), 1.0 / n_reporters, dtype=dtype)
    rep, _, _, converged, iters, _ = _iterate_jax(reports, rep0, p)
    scaled = jnp.zeros((n_events,), dtype=bool)
    _, outcomes_adj = jk.resolve_outcomes(None, reports, rep, scaled,
                                          p.catch_tolerance, any_scaled=False,
                                          has_na=False)
    liar_f = liar.astype(dtype)
    return {
        "correct_rate": jnp.mean((outcomes_adj == truth).astype(dtype)),
        "capture_rate": jnp.mean((outcomes_adj == 1.0 - truth).astype(dtype)),
        "ambiguous_rate": jnp.mean((outcomes_adj == 0.5).astype(dtype)),
        "liar_rep_share": jnp.sum(rep * liar_f),
        "liar_fraction_realized": jnp.mean(liar_f),
        "converged": converged,
        "iterations": iters,
    }


def flat_grid(liar_fractions, variances, n_trials: int):
    """The flattened (liar_fraction × variance × trial) sweep grid in the
    canonical layout (trial-major: flat index ``i = (l*V + v)*T + t``) —
    the single definition shared by :meth:`CollusionSimulator.run` and the
    checkpointed sweep runner, so a chunked/resumed sweep reproduces a
    monolithic one bit-for-bit."""
    lf = np.asarray(liar_fractions, dtype=np.float64)
    var = np.asarray(variances, dtype=np.float64)
    L, V, T = len(lf), len(var), int(n_trials)
    if L < 1 or V < 1 or T < 1:
        raise ValueError("liar_fractions, variances, and n_trials must "
                         "all be non-empty/positive")
    grid_lf = np.repeat(lf, V * T)
    grid_var = np.tile(np.repeat(var, T), L)
    return lf, var, grid_lf, grid_var


def _fold_keys(seed: int, indices):
    """Per-trial PRNG keys: ``fold_in(key(seed), flat_index)`` — a pure
    function of the GLOBAL flat index, so any slice of the grid can be
    computed independently (the checkpointed runner's correctness
    contract)."""
    base = jax.random.key(seed)
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(
        jnp.asarray(indices))


class CollusionSimulator:
    """Batched Monte-Carlo collusion sweeps.

    Parameters
    ----------
    n_reporters, n_events : trial matrix shape (static — one XLA program per
        shape).
    collude : shared-lie attack vs independent random liars.
    algorithm, max_iterations, alpha, catch_tolerance, pca_method,
    power_iters, num_clusters, dbscan_eps, dbscan_min_samples : consensus
        knobs, as on :class:`~pyconsensus_tpu.Oracle`.
        ``pca_method="power"`` is the default here: power iteration is pure
        matmuls, which batch perfectly under vmap on the MXU (batched eigh
        does not). For ``algorithm="dbscan-jit"`` on binary reports, note
        squared row distances count disagreeing events — set ``dbscan_eps``
        to roughly ``sqrt(expected disagreements between honest rows)``
        (e.g. ``sqrt(2 * variance * n_events)``), not the 0.5 default.
    mesh : optional device mesh — the flattened trial axis is sharded over
        EVERY mesh device (SURVEY §7 "vmap × shard composition":
        replicate-and-vmap per chip — trials are independent, so this is
        pure data parallelism with zero collectives; an 8-chip host runs
        8× the trials per wall-second). The grid is padded up to a device
        multiple on device and the padding dropped on the way out.
        Determinism contract: the SAME dispatch topology (mesh × batch
        width) replayed over the same seed is bit-identical — the
        crash/resume guarantee — while a DIFFERENT topology (meshed vs
        single-device, or a different chunk width on a mesh) agrees to
        reduction-order ulps only: GSPMD partitioning at a different
        per-device batch width may re-tile within-trial reductions
        (measured: 1-ulp leaks in 3 of 42 lanes at 1-lane-per-device vs
        a monolithic 42-wide dispatch; full-width meshed dispatch agreed
        bitwise — docs/ROBUSTNESS.md parity ledger #9).
    """

    def __init__(self, n_reporters: int = 20, n_events: int = 10,
                 collude: bool = True, algorithm: str = "sztorc",
                 max_iterations: int = 1, alpha: float = 0.1,
                 catch_tolerance: float = 0.1, pca_method: str = "power",
                 power_iters: int = 64, num_clusters: int = 2,
                 dbscan_eps: float = 0.5, dbscan_min_samples: int = 2,
                 mesh: Optional[Mesh] = None):
        if algorithm not in JIT_ALGORITHMS:
            raise ValueError(
                f"simulator requires a jit-compatible algorithm "
                f"{JIT_ALGORITHMS}, got {algorithm!r}")
        self.n_reporters = int(n_reporters)
        self.n_events = int(n_events)
        self.collude = bool(collude)
        self.params = ConsensusParams(
            algorithm=algorithm, alpha=float(alpha),
            catch_tolerance=float(catch_tolerance),
            max_iterations=int(max_iterations), pca_method=pca_method,
            power_iters=int(power_iters), num_clusters=int(num_clusters),
            dbscan_eps=float(dbscan_eps),
            dbscan_min_samples=int(dbscan_min_samples),
            any_scaled=False, has_na=False)
        self.mesh = mesh
        self._batched = obs.instrument_jit(
            jax.jit(jk.exact_matmuls(jax.vmap(self._trial_fn()))),
            "sim_batched")

    def _trial_fn(self):
        """Subclass hook: the per-trial function ``(key, lf, var) -> metrics``
        that ``__init__`` wraps in one ``jit(vmap(...))``."""
        return functools.partial(_trial_metrics, n_reporters=self.n_reporters,
                                 n_events=self.n_events, collude=self.collude,
                                 p=self.params)

    def _dispatch(self, seed: int, indices, grid_lf, grid_var) -> dict:
        """Run the batched program over the trials at GLOBAL flat
        ``indices`` and return host metric arrays — the one dispatch
        point shared by :meth:`run` and the checkpointed chunk runner,
        so ``mesh=`` applies to both. With a mesh, the trial axis is
        sharded over every mesh device (independent lanes, no
        collectives — XLA partitions the vmapped program per device);
        uneven NamedSharding placement is impossible in JAX, so the
        batch is padded to a device multiple (edge-repeated lanes) and
        the tail dropped on the way out. Lanes at the same flat index
        carry the same per-trial key, so a replay of the SAME topology
        is bit-identical; across topologies agreement is to
        reduction-order ulps (see the class docstring's determinism
        contract)."""
        indices = np.asarray(indices)
        N = indices.shape[0]
        with obs.span("sim.dispatch", trials=int(N),
                      algorithm=self.params.algorithm,
                      meshed=self.mesh is not None):
            n_pad = 0
            if self.mesh is not None:
                n_pad = (-N) % int(self.mesh.devices.size)
                if n_pad:
                    indices = np.pad(indices, (0, n_pad), mode="edge")
                    grid_lf = np.pad(grid_lf, (0, n_pad), mode="edge")
                    grid_var = np.pad(grid_var, (0, n_pad), mode="edge")
            keys = _fold_keys(seed, indices)
            lf_dev, var_dev = jnp.asarray(grid_lf), jnp.asarray(grid_var)
            if self.mesh is not None:
                shard = NamedSharding(
                    self.mesh, PartitionSpec(tuple(self.mesh.axis_names)))
                keys, lf_dev, var_dev = (jax.device_put(a, shard)
                                         for a in (keys, lf_dev, var_dev))
            out = self._batched(keys, lf_dev, var_dev)
            # the host fetch below is the span's completion barrier
            host = {k: np.asarray(v)[:N] for k, v in out.items()}
        obs.counter(
            "pyconsensus_sim_trials_total",
            "Monte-Carlo trials resolved by the batched simulator",
            labels=("algorithm",)).inc(N, algorithm=self.params.algorithm)
        return host

    def run(self, liar_fractions: Sequence[float],
            variances: Sequence[float], n_trials: int, seed: int = 0) -> dict:
        """Sweep the (liar_fraction × variance × seed) grid in one batched
        call. Returns a dict of host arrays shaped (L, V, T) per metric —
        (L, V, T, ...) for metrics with trailing per-trial axes, e.g. the
        per-round trajectories of :class:`RoundsSimulator` — plus ``"mean"``:
        per-cell averages over the trial axis."""
        lf, var, grid_lf, grid_var = flat_grid(liar_fractions, variances,
                                               n_trials)
        L, V, T = len(lf), len(var), int(n_trials)
        host = self._dispatch(seed, np.arange(L * V * T), grid_lf, grid_var)
        result = {k: v.reshape((L, V, T) + v.shape[1:])
                  for k, v in host.items()}
        result["mean"] = {k: v.mean(axis=2) for k, v in result.items()}
        result["liar_fractions"] = lf
        result["variances"] = var
        self._annotate(result)
        return result

    def _annotate(self, result: dict) -> None:
        """Subclass hook: add extra metadata keys to a finished sweep."""


def simulate_grid(liar_fractions=(0.0, 0.1, 0.2, 0.3, 0.4),
                  variances=(0.0, 0.1, 0.2), n_trials: int = 100,
                  seed: int = 0, **kwargs) -> dict:
    """Convenience one-call sweep (the reference's script entry point)."""
    return CollusionSimulator(**kwargs).run(liar_fractions, variances,
                                            n_trials, seed)


def _reports_for_round(key, liar, variance, n_reporters: int, n_events: int,
                       collude: bool):
    """Per-round report generation with a FIXED liar set: fresh truth and
    fresh honest noise every round, the same reporters keep lying — the
    repeated-game setting the reputation mechanism exists for."""
    k_truth, k_noise, k_lie = jax.random.split(key, 3)
    return _synth_reports(k_truth, k_noise, k_lie, liar, variance,
                          n_reporters, n_events, collude)


def _trial_rounds(key, liar_fraction, variance, *, n_rounds: int,
                  n_reporters: int, n_events: int, collude: bool,
                  p: ConsensusParams):
    """One multi-round trial: reputation carries from round to round
    (ReputationLedger semantics, but fully on device as a ``lax.scan``) —
    measures whether sustained colluders get ground down or capture the
    oracle. Returns per-round metric trajectories."""
    dtype = jnp.asarray(0.0).dtype
    k_liar, k_rounds = jax.random.split(key)
    liar = jax.random.bernoulli(k_liar, liar_fraction, (n_reporters,))
    liar_f = liar.astype(dtype)
    scaled = jnp.zeros((n_events,), dtype=bool)
    rep0 = jnp.full((n_reporters,), 1.0 / n_reporters, dtype=dtype)

    def round_step(rep, k):
        reports, truth = _reports_for_round(k, liar, variance, n_reporters,
                                            n_events, collude)
        new_rep, _, _, _, _, _ = _iterate_jax(reports, rep, p)
        _, outcomes_adj = jk.resolve_outcomes(None, reports, new_rep, scaled,
                                              p.catch_tolerance,
                                              any_scaled=False, has_na=False)
        metrics = {
            "correct_rate": jnp.mean((outcomes_adj == truth).astype(dtype)),
            "capture_rate": jnp.mean(
                (outcomes_adj == 1.0 - truth).astype(dtype)),
            "liar_rep_share": jnp.sum(new_rep * liar_f),
        }
        return new_rep, metrics

    keys = jax.random.split(k_rounds, n_rounds)
    _, traj = lax.scan(round_step, rep0, keys)
    return traj


class RoundsSimulator(CollusionSimulator):
    """Multi-round variant of :class:`CollusionSimulator`: each trial is a
    ``lax.scan`` over ``n_rounds`` oracle resolutions with the reputation
    vector carried between rounds (fixed liar set, fresh events each
    round), and the whole (liar_fraction x variance x trial) grid is still
    one vmapped XLA call. The reference has no equivalent — its simulator
    resets reputation every trial; this is the repeated-game experiment
    its README motivates (does sustained collusion get ground down?)."""

    def __init__(self, n_rounds: int = 10, **kwargs):
        if int(n_rounds) < 1:
            raise ValueError("n_rounds must be >= 1")
        self.n_rounds = int(n_rounds)   # before super().__init__ → _trial_fn
        super().__init__(**kwargs)

    def _trial_fn(self):
        return functools.partial(_trial_rounds, n_rounds=self.n_rounds,
                                 n_reporters=self.n_reporters,
                                 n_events=self.n_events,
                                 collude=self.collude, p=self.params)

    def _annotate(self, result: dict) -> None:
        result["n_rounds"] = self.n_rounds
