"""Plotting helpers for collusion-sweep results (SURVEY.md §3.3 — the
reference's sweep ends in "aggregate / plot"; these are the rebuild's
equivalents for :meth:`CollusionSimulator.run` result dicts) and for
the adversarial-economy scoreboard (ISSUE 11:
:func:`plot_cartel_roi_heatmap` / :func:`plot_honest_yield_curves`
over :meth:`~pyconsensus_tpu.econ.MarketEconomy.run` result dicts).

Design rules applied: magnitude grids use a single-hue sequential colormap
(light -> dark, never a rainbow); per-variance curves use a fixed
categorical hue order (never cycled, capped before hues run out); text
stays in neutral ink, color carries only series identity; grid/axes are
recessive. matplotlib is imported lazily so the library works without it.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..io import ensure_parent

__all__ = ["plot_sweep_heatmap", "plot_retention_curves",
           "plot_round_trajectories", "save_sweep_report",
           "plot_cartel_roi_heatmap", "plot_honest_yield_curves"]

#: fixed categorical hue order (validated palette; assigned in order, never
#: cycled — plot_retention_curves raises past the 8-hue budget: facet or
#: subset the sweep instead)
_SERIES = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100",
           "#e87ba4", "#008300", "#4a3aa7", "#e34948"]
_INK = "#0b0b0b"
_INK_2 = "#52514e"
_GRID = "#d8d7d2"

_METRIC_LABELS = {
    "correct_rate": "events resolved to truth",
    "capture_rate": "events captured by the lie",
    "ambiguous_rate": "events left ambiguous (0.5)",
    "liar_rep_share": "reputation held by liars",
}


def _require_mpl():
    try:
        import matplotlib
        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("plotting requires matplotlib "
                          "(pip install matplotlib)") from e


def _mean_grid(result: dict, metric: str) -> np.ndarray:
    """The (L, V) per-cell mean for single-round sweep plots; rejects the
    (L, V, n_rounds) trajectories a RoundsSimulator produces with a pointer
    to the right entry point instead of a garbage render."""
    grid = np.asarray(result["mean"][metric])
    if grid.ndim != 2:
        raise ValueError(f"metric {metric!r} has shape {grid.shape}, not the "
                         "(liar_fractions, variances) grid this plot needs — "
                         "for RoundsSimulator results use "
                         "plot_round_trajectories")
    return grid


def _style_axes(ax):
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(_GRID)
    ax.tick_params(colors=_INK_2, labelsize=9)


def _grid_heatmap(grid, xticks, yticks, xlabel, ylabel, title, ax=None,
                  vmin: float = 0.0, vmax: float = 1.0,
                  annotate: Optional[bool] = None):
    """The shared (grid -> heatmap) core: single-hue sequential ramp
    (Blues, light -> dark), value annotations while the grid stays
    readable, colorbar otherwise. Both the collusion-sweep heatmaps and
    the econ cartel-ROI heatmap render through here."""
    plt = _require_mpl()
    grid = np.asarray(grid, dtype=float)
    if ax is None:
        _, ax = plt.subplots(figsize=(1.2 + 0.6 * len(xticks),
                                      1.0 + 0.45 * len(yticks)), dpi=120)
    im = ax.imshow(grid, cmap="Blues", vmin=vmin, vmax=vmax,
                   aspect="auto", origin="lower")
    ax.set_xticks(range(len(xticks)), [str(x) for x in xticks])
    ax.set_yticks(range(len(yticks)), [str(y) for y in yticks])
    ax.set_xlabel(xlabel, color=_INK, fontsize=10)
    ax.set_ylabel(ylabel, color=_INK, fontsize=10)
    ax.set_title(title, color=_INK, fontsize=11)
    _style_axes(ax)
    if annotate is None:
        annotate = grid.size <= 60
    if annotate:
        # ink flips to white past the dark end of the ramp
        dark_past = vmin + 0.6 * (vmax - vmin)
        for i in range(grid.shape[0]):
            for j in range(grid.shape[1]):
                if not np.isfinite(grid[i, j]):
                    continue
                ax.text(j, i, f"{grid[i, j]:.2f}", ha="center",
                        va="center", fontsize=8,
                        color="#ffffff" if grid[i, j] > dark_past
                        else _INK)
    else:
        ax.figure.colorbar(im, ax=ax, shrink=0.85)
    return ax


def plot_sweep_heatmap(result: dict, metric: str = "capture_rate", ax=None,
                       annotate: Optional[bool] = None):
    """Heatmap of a per-cell mean metric over the (liar_fraction x variance)
    grid. Magnitude -> single-hue sequential (Blues, light -> dark); cells
    are annotated with their values when the grid is small enough to read.
    Returns the matplotlib Axes."""
    if metric not in result["mean"]:
        raise ValueError(f"metric {metric!r} not in result; choose from "
                         f"{sorted(result['mean'])}")
    grid = _mean_grid(result, metric)                  # (L, V)
    lf, var = result["liar_fractions"], result["variances"]
    return _grid_heatmap(
        grid, [f"{v:g}" for v in var], [f"{f:g}" for f in lf],
        "honest-reporter noise (variance)", "liar fraction",
        _METRIC_LABELS.get(metric, metric), ax=ax, annotate=annotate)


def plot_retention_curves(result: dict, metric: str = "liar_rep_share",
                          ax=None):
    """Mean metric vs liar fraction, one line per variance level (fixed
    categorical hue order; >8 levels raise — facet instead). Lines are
    direct-labeled at their right end when there are <= 4, and a legend is
    always present for >= 2. Returns the matplotlib Axes."""
    plt = _require_mpl()
    grid = _mean_grid(result, metric)                  # (L, V)
    lf, var = result["liar_fractions"], result["variances"]
    if len(var) > len(_SERIES):
        raise ValueError(f"{len(var)} variance levels exceed the "
                         f"{len(_SERIES)}-hue categorical budget — facet "
                         "the sweep or subset `variances`")
    if ax is None:
        _, ax = plt.subplots(figsize=(5.2, 3.4), dpi=120)
    # direct end-labels only when every pair of line ends is separated
    # enough to read (colliding labels are worse than legend-only)
    ends = grid[-1, :]
    separable = (len(var) <= 4 and
                 np.min(np.diff(np.sort(ends))) > 0.04 if len(var) > 1
                 else True)
    for k, v in enumerate(var):
        ax.plot(lf, grid[:, k], color=_SERIES[k], lw=2,
                marker="o", ms=4, label=f"variance {v:g}")
        if separable:
            ax.annotate(f" {v:g}", (lf[-1], grid[-1, k]),
                        color=_SERIES[k], fontsize=8, va="center")
    ax.set_xlabel("liar fraction", color=_INK, fontsize=10)
    ax.set_ylabel(_METRIC_LABELS.get(metric, metric), color=_INK, fontsize=10)
    ax.set_ylim(-0.02, 1.02)
    ax.grid(True, color=_GRID, lw=0.5, alpha=0.6)
    ax.set_axisbelow(True)
    _style_axes(ax)
    if len(var) >= 2:
        ax.legend(frameon=False, fontsize=8, labelcolor=_INK_2)
    return ax


def plot_round_trajectories(result: dict, metric: str = "liar_rep_share",
                            variance_index: int = 0, ax=None):
    """Multi-round trajectories from a :class:`RoundsSimulator` result:
    mean metric vs round, one line per liar fraction at one variance level
    (fixed categorical hue order; raises past the hue budget). Answers the
    repeated-game question at a glance — do sustained colluders get ground
    down round over round, or capture the oracle?"""
    plt = _require_mpl()
    if metric not in result["mean"]:
        raise ValueError(f"metric {metric!r} not in result; choose from "
                         f"{sorted(result['mean'])}")
    traj = np.asarray(result["mean"][metric])          # (L, V, n_rounds)
    if traj.ndim != 3:
        raise ValueError(f"metric {metric!r} has no per-round axis — run "
                         "RoundsSimulator (shape (L, V, n_rounds)), got "
                         f"shape {traj.shape}")
    lf, var = result["liar_fractions"], result["variances"]
    if not 0 <= variance_index < len(var):
        raise ValueError(f"variance_index {variance_index} out of range for "
                         f"{len(var)} variance level(s)")
    if len(lf) > len(_SERIES):
        raise ValueError(f"{len(lf)} liar fractions exceed the "
                         f"{len(_SERIES)}-hue categorical budget — facet "
                         "or subset `liar_fractions`")
    rounds = np.arange(1, traj.shape[2] + 1)
    if ax is None:
        _, ax = plt.subplots(figsize=(5.2, 3.4), dpi=120)
    for k, f in enumerate(lf):
        ax.plot(rounds, traj[k, variance_index], color=_SERIES[k], lw=2,
                marker="o", ms=4, label=f"liar fraction {f:g}")
    ax.set_xlabel("round", color=_INK, fontsize=10)
    ax.set_ylabel(_METRIC_LABELS.get(metric, metric), color=_INK, fontsize=10)
    if len(rounds) <= 15:
        ax.set_xticks(rounds)
    else:
        from matplotlib.ticker import MaxNLocator
        ax.xaxis.set_major_locator(MaxNLocator(integer=True))
    ax.set_ylim(-0.02, 1.02)
    ax.set_title(f"variance {var[variance_index]:g}, reputation carried "
                 "across rounds", color=_INK, fontsize=11)
    ax.grid(True, color=_GRID, lw=0.5, alpha=0.6)
    ax.set_axisbelow(True)
    _style_axes(ax)
    if len(lf) >= 2:
        ax.legend(frameon=False, fontsize=8, labelcolor=_INK_2)
    return ax


def plot_cartel_roi_heatmap(econ_result: dict, ax=None,
                            annotate: Optional[bool] = None):
    """Cartel-ROI heatmap over the (strategy x round) grid of an econ
    result dict (:meth:`~pyconsensus_tpu.econ.MarketEconomy.run`):
    each cell is the mean reputation-captured-per-reputation-staked of
    one strategy after that round. Renders through the same sequential
    heatmap core as the collusion-sweep grids; the ramp tops out at the
    observed maximum (at least 1.0), so a cell visibly darker than the
    break-even band is a strategy the mechanism is LOSING to. Returns
    the matplotlib Axes."""
    traj = np.asarray(econ_result["trajectories"]["cartel_roi"],
                      dtype=float)                     # (S, rounds)
    if traj.ndim != 2:
        raise ValueError(f"cartel_roi trajectory has shape {traj.shape}, "
                         "expected (strategies, rounds) — pass a "
                         "MarketEconomy result dict")
    strategies = econ_result["strategies"]
    rounds = econ_result["trajectories"]["round"]
    vmax = max(1.0, float(np.nanmax(traj)) if np.isfinite(traj).any()
               else 1.0)
    return _grid_heatmap(
        traj, [str(r) for r in rounds], strategies, "round",
        "cartel strategy", "cartel ROI (reputation captured / staked)",
        ax=ax, vmin=0.0, vmax=vmax, annotate=annotate)


def plot_honest_yield_curves(econ_result: dict, ax=None):
    """Honest-reporter yield vs round, one line per cartel strategy
    (fixed categorical hue order; raises past the hue budget — subset
    the scenario's strategies instead). The dashed 1.0 reference is
    break-even: curves above it mean honest reporting GAINS share while
    that strategy attacks — the economic-soundness picture at a glance.
    Returns the matplotlib Axes."""
    plt = _require_mpl()
    traj = np.asarray(econ_result["trajectories"]["honest_yield"],
                      dtype=float)                     # (S, rounds)
    if traj.ndim != 2:
        raise ValueError(f"honest_yield trajectory has shape "
                         f"{traj.shape}, expected (strategies, rounds) "
                         "— pass a MarketEconomy result dict")
    strategies = econ_result["strategies"]
    if len(strategies) > len(_SERIES):
        raise ValueError(f"{len(strategies)} strategies exceed the "
                         f"{len(_SERIES)}-hue categorical budget — "
                         "subset the scenario's strategies")
    rounds = np.asarray(econ_result["trajectories"]["round"])
    if ax is None:
        _, ax = plt.subplots(figsize=(5.2, 3.4), dpi=120)
    ax.axhline(1.0, color=_INK_2, lw=1, ls="--")
    for k, s in enumerate(strategies):
        ax.plot(rounds, traj[k], color=_SERIES[k], lw=2, marker="o",
                ms=4, label=f"vs {s}")
    ax.set_xlabel("round", color=_INK, fontsize=10)
    ax.set_ylabel("honest-reporter yield (share / initial share)",
                  color=_INK, fontsize=10)
    if len(rounds) <= 15:
        ax.set_xticks(rounds)
    ax.grid(True, color=_GRID, lw=0.5, alpha=0.6)
    ax.set_axisbelow(True)
    _style_axes(ax)
    if len(strategies) >= 2:
        ax.legend(frameon=False, fontsize=8, labelcolor=_INK_2)
    return ax


def save_sweep_report(result: dict, path, metrics=("correct_rate",
                                                   "capture_rate",
                                                   "liar_rep_share")):
    """Write a one-file PNG report: one heatmap per metric plus the
    retention curves. Returns the path."""
    plt = _require_mpl()
    n = len(metrics) + 1
    fig, axes = plt.subplots(1, n, figsize=(4.6 * n, 3.6), dpi=120)
    for ax, m in zip(axes[:-1], metrics):
        plot_sweep_heatmap(result, metric=m, ax=ax)
    plot_retention_curves(result, ax=axes[-1])
    fig.tight_layout()
    fig.savefig(ensure_parent(path), bbox_inches="tight")
    plt.close(fig)
    return path
