"""Monte-Carlo collusion simulation (BASELINE.json config 5): thousands of
oracle resolutions as one vmap-batched XLA call, plus plotting helpers for
the sweep results."""

from .collusion import (CollusionSimulator, RoundsSimulator, flat_grid,
                        generate_reports, simulate_grid)
from .plots import (plot_cartel_roi_heatmap, plot_honest_yield_curves,
                    plot_retention_curves, plot_round_trajectories,
                    plot_sweep_heatmap, save_sweep_report)
from .runner import CheckpointedSweep

__all__ = ["CollusionSimulator", "RoundsSimulator", "generate_reports",
           "simulate_grid", "flat_grid", "CheckpointedSweep",
           "plot_sweep_heatmap", "plot_retention_curves",
           "plot_round_trajectories", "save_sweep_report",
           "plot_cartel_roi_heatmap", "plot_honest_yield_curves"]
