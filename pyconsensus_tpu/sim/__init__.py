"""Monte-Carlo collusion simulation (BASELINE.json config 5): thousands of
oracle resolutions as one vmap-batched XLA call."""

from .collusion import CollusionSimulator, simulate_grid

__all__ = ["CollusionSimulator", "simulate_grid"]
