"""Monte-Carlo collusion simulation (BASELINE.json config 5): thousands of
oracle resolutions as one vmap-batched XLA call, plus plotting helpers for
the sweep results."""

from .collusion import CollusionSimulator, generate_reports, simulate_grid
from .plots import (plot_retention_curves, plot_sweep_heatmap,
                    save_sweep_report)

__all__ = ["CollusionSimulator", "generate_reports", "simulate_grid",
           "plot_sweep_heatmap", "plot_retention_curves",
           "save_sweep_report"]
