"""Monte-Carlo collusion simulation (BASELINE.json config 5): thousands of
oracle resolutions as one vmap-batched XLA call."""

from .collusion import CollusionSimulator, generate_reports, simulate_grid

__all__ = ["CollusionSimulator", "generate_reports", "simulate_grid"]
