"""Layer 6: bit-determinism & numerics-flow analysis (CL1001-CL1004).

Every contract this codebase ships — catch-snap parity, replication-log
replay, cross-worker takeover, the economy's ``mechanism_digest`` — is a
*bit-identity* claim: identical inputs must reproduce identical bytes.
Layers 1-5 guard traced-code hygiene, host divergence, locks, and
durability order; nothing before this pass statically proves the code
cannot feed *nondeterminism* into the digests those claims rest on. An
unordered ``os.listdir`` sweep feeding a size sum, a ``dict.items()``
fold assembling an npz, a completion-order ``as_completed`` collection
folded into reputation — each replays differently on another host (or
the same host under a different ``PYTHONHASHSEED``), and the replay
tests only catch the interleaving that actually fired.

This pass rides the Layer 3a machinery (:mod:`.dataflow`'s package
table, call-graph fixpoint, and flow-sensitive abstract interpreter)
with its own source/sanitizer/sink model. Taint origins are
category-prefixed strings; the category at the sink selects the rule:

- **order** (CL1001) — unordered iteration: ``dict``/``set``/
  ``frozenset`` iteration (``.items()``/``.keys()``/``.values()``, set
  literals/comprehensions/constructors), ``os.listdir``/``scandir``/
  ``walk``, non-sorted ``glob``/``Path.iterdir``/``rglob``. Python
  dicts iterate in insertion order, but the *insertion* order is
  rarely pinned across processes, and set/str-hash order changes under
  ``PYTHONHASHSEED`` — a digest over either is a per-run number.
- **completion** (CL1002) — completion-order collection:
  ``as_completed``, ``imap_unordered`` — thread/future scheduling
  decides the fold order.
- **hostnd** (CL1003) — host nondeterminism: ``id()``, builtin
  ``hash()`` (str/bytes hashes are salted per process), ``time.*``
  clocks, ``uuid.*``, unseeded host RNG (``random.*``,
  ``numpy.random.*``, ``default_rng()`` with NO seed argument —
  seeded constructions and the economy's ``strategy_rng`` key
  derivation are clean by design).
- **floatacc** (CL1004) — float-accumulation hazard: builtin ``sum()``
  or an ``+=`` fold over an order-/completion-tainted collection.
  Float addition is not associative: the same multiset of summands in
  a different order is a different float, so an unordered accumulation
  reaching reputation/ledger/digest state breaks bit-replay even when
  every element is identical.

**Sinks** — the places where a nondeterministic value becomes a
persisted or compared artifact: digest computation (``hashlib.*``
constructor arguments and ``.update()`` on handles built from them,
``mechanism_digest``), replication-journal and ledger payloads
(``journal_block``/``record_round`` arguments), npz state assembly
(``np.savez``/``savez_compressed``), JSON artifacts
(``json.dump``/``dumps`` WITHOUT ``sort_keys=True``), and operands of
traced entry points (a trace-time constant derived from an unordered
fold bakes per-run bytes into the executable).

**Sanitizers** — ``sorted()`` (strips order/completion taint: a sorted
fold is exactly the fix; host nondeterminism passes through — sorting
a wall-clock reading does not make it reproducible), ``min``/``max``
(order-insensitive reductions), ``strategy_rng``/seeded
``default_rng(seed)`` (keyed RNG is the blessed randomness path), and
``collections.OrderedDict``-by-construction (needs no special case:
its pass-through semantics are already order-clean when its inputs
are).

CL1005 (compiled-artifact determinism) lives in :mod:`.contracts`: the
``stablehlo_pin`` builder compiles registered entries twice in fresh
contexts and pins StableHLO byte equality, and ``check_artifact``
scans post-GSPMD HLO for ops XLA documents as run-to-run
nondeterministic (the scatter-add family) outside a blessed list. The
rule is declared here so ``--list-rules`` and the docs table keep one
Layer 6 inventory.

The runtime mirror is :mod:`.determinism_witness` (DigestWitness) —
see its docstring. ``# consensus-lint: disable=CL100x`` line
directives suppress in place, with the written rationale on the same
comment (house rule).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .dataflow import _Analyzer, _FuncInfo, _Package, _src_line
from .findings import Finding
from .rules import _line_directives, scan_targets

#: rule ID -> (severity, one-line description)
DETERMINISM_RULES = {
    "CL1001": ("error", "unordered iteration (dict/set iteration, "
                        "os.listdir, non-sorted glob/iterdir) reaches a "
                        "digest/journal/ledger/serialization sink — "
                        "iteration order feeds bytes that must replay "
                        "bit-identical; sort first"),
    "CL1002": ("error", "completion-order collection (as_completed / "
                        "imap_unordered) reaches a digest/journal/"
                        "serialization sink — scheduler timing decides "
                        "the fold order; key the fold by sequence "
                        "instead"),
    "CL1003": ("error", "host nondeterminism (id(), salted hash(), "
                        "time.*, uuid.*, unseeded host RNG) reaches a "
                        "digest/journal/serialization sink — the value "
                        "differs per process/run; derive from a seeded "
                        "key (strategy_rng) or drop it from the "
                        "payload"),
    "CL1004": ("error", "float accumulation (sum() / '+=' fold) over an "
                        "order-tainted collection reaches reputation/"
                        "ledger/digest state — float addition is not "
                        "associative, so an unordered fold breaks "
                        "bit-replay; sort the iterate or fold by "
                        "sequence key"),
    "CL1005": ("error", "compiled artifact is not bit-deterministic: "
                        "double-compiled StableHLO bytes differ, or "
                        "post-GSPMD HLO contains an XLA-documented "
                        "run-to-run nondeterministic op (scatter-add "
                        "family) outside the blessed list"),
}

#: rules the STATIC taint pass can emit (CL1005 is the contracts-layer
#: compiled pass; it gates with Layer 2, not with this walk)
STATIC_DETERMINISM_RULES = frozenset(
    r for r in DETERMINISM_RULES if r != "CL1005")

_CATEGORY_RULE = {"order": "CL1001", "completion": "CL1002",
                  "hostnd": "CL1003", "floatacc": "CL1004"}

_CATEGORY_NOUN = {
    "order": "an unordered-iteration value",
    "completion": "a completion-order value",
    "hostnd": "a host-nondeterministic value",
    "floatacc": "an order-dependent float accumulation",
}

#: canonical dotted calls yielding ORDER taint (filesystem enumeration
#: without a pinned order)
_ORDER_CALLS = {
    "os.listdir", "os.scandir", "os.walk",
    "glob.glob", "glob.iglob",
}

#: attribute-call tails yielding ORDER taint on any receiver:
#: Path.iterdir/glob/rglob enumerate in readdir order; dict views
#: iterate in insertion order (unpinned across processes)
_ORDER_TAILS = {"iterdir", "glob", "rglob", "items", "keys", "values"}

#: set construction — str-hash iteration order changes per process
#: under PYTHONHASHSEED
_SET_CTOR_TAILS = {"set", "frozenset"}

#: completion-order collection
_COMPLETION_TAILS = {"as_completed", "imap_unordered"}

#: host-nondeterminism call prefixes (canonical dotted)
_HOSTND_PREFIXES = (
    "time.", "uuid.uuid", "random.", "numpy.random.", "secrets.",
    "os.urandom", "os.getpid",
)

#: bare builtins whose results differ per process
_HOSTND_BUILTINS = {"id", "hash"}

#: sanitizer tails: sorted() pins the order; min/max are
#: order-insensitive reductions; strategy_rng is the economy's seeded
#: key-derivation path (blessed randomness)
_ORDER_SANITIZER_TAILS = {"sorted", "min", "max"}
_RNG_SANITIZER_TAILS = {"strategy_rng"}

#: sink tails: replication-journal / ledger payload construction
_JOURNAL_SINK_TAILS = {"journal_block", "record_round"}

#: sink tails: npz state assembly
_SAVEZ_TAILS = {"savez", "savez_compressed"}

#: container mutators that fold a tainted operand into their receiver
_MUTATOR_TAILS = {"append", "add", "extend", "update", "insert",
                  "setdefault", "appendleft"}


def _category(origin: Optional[str]) -> str:
    return origin.split(":", 1)[0] if origin else ""


class _DetAnalyzer(_Analyzer):
    """The Layer 3a abstract interpreter with the determinism
    source/sanitizer/sink model. State values are category-prefixed
    origin strings (``order: d.items() at path:line``)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        #: names lexically assigned from a hashlib constructor in this
        #: function — their ``.update(x)`` calls are digest sinks
        self._digest_handles: Set[str] = set()

    # ---- expression taint --------------------------------------------

    def eval(self, node, state):
        # set literals / comprehensions iterate in hash order
        if isinstance(node, (ast.Set, ast.SetComp)):
            for child in ast.iter_child_nodes(node):
                org = self.eval(child, state)
                if org and _category(org) != "order":
                    return org
            return (f"order: set literal at "
                    f"{self.mod.path}:{node.lineno}")
        return super().eval(node, state)

    def _origin(self, kind: str, what: str, node: ast.AST) -> str:
        return f"{kind}: {what} at {self.mod.path}:{node.lineno}"

    def _eval_call(self, node: ast.Call, state):
        from .dataflow import _canon

        args = list(node.args) + [kw.value for kw in node.keywords]
        arg_origins = [self.eval(a, state) for a in args]
        tainted_arg = next((o for o in arg_origins if o), None)
        if not isinstance(node.func, (ast.Name, ast.Attribute)):
            tainted_arg = self.eval(node.func, state) or tainted_arg

        dotted = _canon(self.mod, node.func) or ""
        tail = dotted.split(".")[-1] if dotted else ""

        # -- sanitizers -------------------------------------------------
        if tail in _ORDER_SANITIZER_TAILS:
            # sorted()/min()/max() pin or erase the order; host
            # nondeterminism passes through (sorting a uuid does not
            # make it reproducible)
            if tainted_arg and _category(tainted_arg) in ("order",
                                                          "completion",
                                                          "floatacc"):
                return None
            return tainted_arg
        if tail in _RNG_SANITIZER_TAILS:
            return None
        if dotted in ("json.dump", "json.dumps") and any(
                kw.arg == "sort_keys" and isinstance(kw.value, ast.Constant)
                and kw.value.value for kw in node.keywords):
            # canonical JSON: key order is pinned regardless of the
            # input dict's insertion/hash order — the serialization IS
            # the sort
            return None
        if tail == "default_rng":
            # seeded default_rng(seed) is the blessed reproducible RNG;
            # default_rng() with no arguments draws OS entropy
            if args:
                return tainted_arg
            return self._origin("hostnd", "unseeded default_rng()", node)

        # -- sources ----------------------------------------------------
        if dotted in _ORDER_CALLS:
            return self._origin("order", f"{dotted}()", node)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _ORDER_TAILS:
            return self._origin("order", f".{node.func.attr}()", node)
        if tail in _SET_CTOR_TAILS and "." not in dotted:
            org = tainted_arg
            if org and _category(org) != "order":
                return org
            return self._origin("order", f"{tail}(...)", node)
        if tail in _COMPLETION_TAILS:
            return self._origin("completion", f"{tail}()", node)
        if dotted in _HOSTND_BUILTINS:
            return self._origin("hostnd", f"{dotted}()", node)
        for pref in _HOSTND_PREFIXES:
            if dotted == pref.rstrip(".") or dotted.startswith(pref):
                return self._origin("hostnd", f"{dotted}()", node)

        # -- CL1004: unordered float accumulation via builtin sum() ----
        if dotted == "sum" and tainted_arg and \
                _category(tainted_arg) in ("order", "completion"):
            return (f"floatacc: sum() over {tainted_arg}")

        # receiver taint flows through method-call results
        if isinstance(node.func, ast.Attribute):
            tainted_arg = self.eval(node.func.value, state) or tainted_arg

        if self.findings is not None:
            self._check_call_sinks(node, args, arg_origins, state)

        # container-fold propagation: lst.append(v) / d.update(v) / s.add(v)
        # with a tainted operand taints the RECEIVER name — the dominant
        # payload-assembly idiom (append inside an items() loop)
        if tainted_arg and isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_TAILS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id not in self._digest_handles:
            state[node.func.value.id] = tainted_arg

        callee = self.pkg.resolve(self.mod, node.func)
        if callee is not None:
            self._bind_params(callee, node, arg_origins)
            if callee.returns_taint:
                # keep the category prefix at the front so the sink can
                # still classify the wrapped origin
                return (f"{_category(callee.returns_taint)}: "
                        f"{callee.fn.name}() <- {callee.returns_taint}")
            if callee.propagates_params and tainted_arg:
                return tainted_arg
            return None
        return tainted_arg                  # unresolved: pass through

    # ---- sinks --------------------------------------------------------

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        sup = self.directives.get(line, set())
        if "*" in sup or rule in sup:
            return
        self.findings.append(Finding(
            rule=rule, path=self.mod.path, line=line, message=message,
            severity=DETERMINISM_RULES[rule][0],
            snippet=_src_line(self.mod, node).strip()))

    def _sink_hit(self, node: ast.Call, origin: str, sink: str) -> None:
        cat = _category(origin)
        rule = _CATEGORY_RULE.get(cat)
        if rule is None:
            return
        noun = _CATEGORY_NOUN[cat]
        fix = {"order": "sort the iterate before it reaches the sink",
               "completion": "fold by sequence key, not completion "
                             "order",
               "hostnd": "derive from a seeded key or drop it from the "
                         "payload",
               "floatacc": "sort the iterate (or fold by sequence key) "
                           "so the accumulation order is pinned",
               }[cat]
        self._emit(node, rule,
                   f"{sink} in '{self.info.fn.name}' consumes {noun} "
                   f"({origin}) — the bytes cannot replay "
                   f"bit-identically; {fix}")

    def _check_call_sinks(self, node: ast.Call, args, arg_origins,
                          state) -> None:
        from .dataflow import _canon

        dotted = _canon(self.mod, node.func) or ""
        tail = dotted.split(".")[-1] if dotted else ""
        org = next((o for o in arg_origins if o), None)

        if org:
            if dotted.startswith("hashlib."):
                self._sink_hit(node, org,
                               f"digest computation '{tail}(...)'")
                return
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "update" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in self._digest_handles:
                self._sink_hit(node, org, "digest '.update(...)'")
                return
            if tail == "mechanism_digest":
                self._sink_hit(node, org, "'mechanism_digest(...)'")
                return
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _JOURNAL_SINK_TAILS:
                self._sink_hit(node, org,
                               f"replication payload "
                               f"'.{node.func.attr}(...)'")
                return
            if tail in _SAVEZ_TAILS and dotted.startswith("numpy."):
                self._sink_hit(node, org, f"npz assembly '{tail}(...)'")
                return
            if dotted in ("json.dump", "json.dumps"):
                sort_keys = any(
                    kw.arg == "sort_keys"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value
                    for kw in node.keywords)
                if not sort_keys:
                    self._sink_hit(node, org,
                                   f"JSON artifact '{dotted}(...)' "
                                   f"(no sort_keys=True)")
                return
            callee = self.pkg.resolve(self.mod, node.func)
            if callee is not None and callee.fn in callee.mod.traced:
                self._sink_hit(node, org,
                               f"traced-entry operand of "
                               f"'{callee.fn.name}(...)'")

    def _branch_sink(self, node: ast.AST, state) -> None:
        # no branch sink in this layer — only evaluate the test so its
        # side effects (walrus, call-site param binding) still happen
        self.eval(node.test, state)

    # ---- statement execution -----------------------------------------

    def exec_stmt(self, st: ast.stmt, state):
        if isinstance(st, ast.Assign):
            # track digest handles: h = hashlib.sha256(...) makes
            # h.update(x) a sink in this function
            from .dataflow import _canon

            if isinstance(st.value, ast.Call):
                vdotted = _canon(self.mod, st.value.func) or ""
                if vdotted.startswith("hashlib."):
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            self._digest_handles.add(t.id)
        elif isinstance(st, ast.AugAssign):
            # '+=' fold whose operand (or accumulator) carries order
            # taint is an order-dependent accumulation (CL1004 origin)
            vorg = self.eval(st.value, state)
            torg = self.eval(st.target, state)
            org = vorg or torg
            if org and _category(org) in ("order", "completion") and \
                    isinstance(st.op, ast.Add):
                self._assign_target(st.target,
                                    f"floatacc: '+=' fold over {org}",
                                    state)
                return state
            self._assign_target(st.target, org, state)
            return state
        return super().exec_stmt(st, state)


def _det_propagates(pkg: _Package, info: _FuncInfo) -> bool:
    """Param-to-return reachability under the determinism model."""
    probe = _DetAnalyzer(pkg, info, synthetic=True)
    state = {p: "param" for p in info.params}
    try:
        probe.exec_block(info.fn.body, state)
    except RecursionError:                            # pragma: no cover
        return True
    return probe.returned_taint is not None


def analyze_determinism(paths=None, root=None,
                        select: Optional[Set[str]] = None
                        ) -> List[Finding]:
    """Run the Layer 6 determinism taint analysis over ``paths``
    (default: the installed package). Same driver discipline as
    :func:`.dataflow.analyze_paths`: summaries grown to a fixpoint,
    then one findings pass with line-directive suppression; findings
    sorted by (path, line, rule)."""
    files = scan_targets(paths, root)
    pkg = _Package(files)

    for _ in range(8):
        changed = False
        for info in pkg.infos:
            if not info.propagates_params and _det_propagates(pkg, info):
                info.propagates_params = True
                changed = True
            a = _DetAnalyzer(pkg, info)
            a.run()
            changed |= a.changed
        if not changed:
            break

    findings: List[Finding] = []
    directives = {rel: _line_directives(mod.text)
                  for rel, mod in pkg.mods.items()}
    for info in pkg.infos:
        _DetAnalyzer(pkg, info, findings=findings,
                     directives=directives.get(info.mod.path, {})).run()
    if select is not None:
        findings = [f for f in findings if f.rule in select]
    uniq = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line, f.message), f)
    return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule))
