"""Runtime protocol witness — the dynamic mirror of CL901.

:mod:`.protocol` proves a static happens-before order over the
durability events of each replicated operation (journal before ship
before ack, commit before ship before ack, ...); this module checks the
property the proof is *about*: the event orders the serving code
actually executes. A :class:`ProtocolWitness` monkeypatches two layers
while installed:

- **operation boundaries** — ``DurableSession.append``/``resolve`` and
  ``FleetWorkerProcess.append``/``submit_session``/``create_session``
  push a per-thread operation frame; a successful return appends the
  terminal ``ack`` event (the return IS the acknowledgment: the reply
  frame or Future resolution is built from it), an exception records
  the operation ``ok=False`` with no ack;
- **durability events** — ``ReplicationLog.journal_block`` /
  ``commit_round`` and ``LogShipper.ship_file`` record ``journal`` /
  ``commit`` / ``ship`` into the innermost active frame on their
  thread, *after* the call returns (an event that raised never
  happened, exactly as the static walk's success path assumes).

Nested operations fold their events (minus their own ack — an inner
return is not the outer reply) into the enclosing frame, so
``worker.append`` observes the ``journal`` its inner
``session.append`` performed, matching the static walk's
interprocedural inlining. Frames are thread-local: the microbatcher
thread's ``resolve`` can never leak its ``commit`` into an RPC
thread's ``append``.

:meth:`ProtocolWitness.check` then joins observed against static: for
every successfully-acked operation and every static edge ``a -> b`` of
its kind, if both events were observed, every ``a`` must precede every
``b``. Edges whose events did not occur are vacuous — the dedupe
fast-path acks without journaling, an in-process worker never ships —
so the check constrains order, not coverage. On contradiction the full
witness is dumped as JSON and :class:`ProtocolWitnessViolation` (an
``AssertionError``) carries the operation, the violated edge, and the
dump path.

The transport/fleet suites run under the witness via an autouse
fixture, and the CI cross-process chaos smoke wraps its reference
``DurableSession`` ops in one — the same wiring that keeps the lock
witness honest for CL801. Workers in *other processes* are outside any
witness installed here; the in-process ``FleetWorkerProcess`` tests
cover the worker-side orderings.

Overhead: one thread-local list append per durability event; nothing
in the serving path imports this module.
"""

from __future__ import annotations

import contextlib
import functools
import importlib
import json
import pathlib
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["ProtocolWitness", "ProtocolWitnessViolation",
           "static_protocol_graph", "protocol_witnessed"]

#: real constructor bound at import time so the witness's own state
#: lock is never itself a (lock-)witnessed proxy when both witnesses
#: are installed in the same test
_REAL_LOCK = threading.Lock

#: (module, class, method, event kind) — durability events, recorded
#: into the innermost active frame after the call returns
_EVENT_SHIMS: Tuple[Tuple[str, str, str, str], ...] = (
    ("pyconsensus_tpu.serve.failover",
     "ReplicationLog", "journal_block", "journal"),
    ("pyconsensus_tpu.serve.failover",
     "ReplicationLog", "commit_round", "commit"),
    ("pyconsensus_tpu.serve.transport.shipping",
     "LogShipper", "ship_file", "ship"),
)

#: (module, class, method, op kind) — operation boundaries; kinds match
#: :data:`..protocol.PROTOCOL_OPS` so the two sides join by name
_OP_SHIMS: Tuple[Tuple[str, str, str, str], ...] = (
    ("pyconsensus_tpu.serve.failover",
     "DurableSession", "append", "session.append"),
    ("pyconsensus_tpu.serve.failover",
     "DurableSession", "resolve", "session.resolve"),
    ("pyconsensus_tpu.serve.transport.worker",
     "FleetWorkerProcess", "append", "worker.append"),
    ("pyconsensus_tpu.serve.transport.worker",
     "FleetWorkerProcess", "submit_session", "worker.submit_session"),
    ("pyconsensus_tpu.serve.transport.worker",
     "FleetWorkerProcess", "create_session", "worker.create_session"),
)


class ProtocolWitnessViolation(AssertionError):
    """An observed per-operation event order contradicts the static
    happens-before graph. ``op`` is the operation kind, ``edge`` the
    violated ``(before, after)`` pair, ``events`` the observed
    sequence, ``dump_path`` where the full witness JSON landed."""

    def __init__(self, message: str, op: str = "",
                 edge: Optional[Tuple[str, str]] = None,
                 events: Optional[List[str]] = None,
                 dump_path: Optional[str] = None):
        super().__init__(message)
        self.op = op
        self.edge = edge
        self.events = events or []
        self.dump_path = dump_path


class ProtocolWitness:
    """Records the observed durability-event order of every replicated
    operation while installed.

    Use as a context manager (:func:`protocol_witnessed`) or
    install/uninstall explicitly; :meth:`check` validates against the
    static graph, :meth:`dump` persists. :meth:`op` opens an explicit
    operation frame — what the reordered-mock regression test and the
    CI chaos stage use to scope events that don't flow through a
    patched boundary."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        #: completed operation records, in completion order
        self.ops: List[dict] = []
        #: events observed with no operation frame open on their thread
        self.unscoped: Dict[str, int] = {}
        self._installed = False
        self._saved: List[Tuple[type, str, object]] = []

    # -- recording ------------------------------------------------------

    def _frames(self) -> List[dict]:
        frames = getattr(self._tls, "frames", None)
        if frames is None:
            frames = self._tls.frames = []
        return frames

    def _record(self, kind: str) -> None:
        frames = self._frames()
        if frames:
            frames[-1]["events"].append(kind)
            return
        with self._mu:
            self.unscoped[kind] = self.unscoped.get(kind, 0) + 1

    @contextlib.contextmanager
    def op(self, kind: str):
        """Open an operation frame: durability events on this thread
        record into it; clean exit appends the terminal ``ack``."""
        frames = self._frames()
        frame = {"kind": kind, "events": []}
        frames.append(frame)
        ok = False
        try:
            yield frame
            ok = True
        finally:
            frames.pop()
            events = list(frame["events"])
            if frames:
                # fold into the enclosing operation, WITHOUT this op's
                # ack — the inner return is not the outer reply
                frames[-1]["events"].extend(events)
            rec = {"kind": kind, "ok": ok,
                   "events": events + (["ack"] if ok else []),
                   "thread": threading.current_thread().name}
            with self._mu:
                self.ops.append(rec)

    # -- patching -------------------------------------------------------

    def _wrap_event(self, real, kind: str):
        w = self

        @functools.wraps(real)
        def wrapper(*args, **kwargs):
            result = real(*args, **kwargs)
            w._record(kind)
            return result

        return wrapper

    def _wrap_op(self, real, kind: str):
        w = self

        @functools.wraps(real)
        def wrapper(*args, **kwargs):
            with w.op(kind):
                return real(*args, **kwargs)

        return wrapper

    def install(self) -> "ProtocolWitness":
        if self._installed:
            return self
        for shims, wrap in ((_EVENT_SHIMS, self._wrap_event),
                            (_OP_SHIMS, self._wrap_op)):
            for modname, clsname, method, kind in shims:
                cls = getattr(importlib.import_module(modname), clsname)
                real = cls.__dict__[method]
                self._saved.append((cls, method, real))
                setattr(cls, method, wrap(real, kind))
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for cls, method, real in self._saved:
            setattr(cls, method, real)
        self._saved = []
        self._installed = False

    # -- validation -----------------------------------------------------

    def report(self) -> dict:
        """The witness as JSON-ready data (the dump format)."""
        with self._mu:
            return {"ops": [dict(r) for r in self.ops],
                    "unscoped": dict(sorted(self.unscoped.items()))}

    def dump(self, path) -> pathlib.Path:
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.report(), indent=2) + "\n",
                        encoding="utf-8")
        return path

    def check(self, static: Optional[dict] = None,
              dump_path=None) -> dict:
        """Assert every successfully-acked operation's observed event
        sequence is consistent with the static happens-before graph
        (``static``: a :func:`..protocol.happens_before` dict; computed
        fresh when omitted). For an edge ``a -> b`` both of whose
        events occurred, every ``a`` must precede every ``b``;
        operations that raised (no ack) are unconstrained — the static
        order is a promise about what an ack means. Returns the report
        on success; dumps it and raises
        :class:`ProtocolWitnessViolation` on failure."""
        if static is None:
            static = static_protocol_graph()
        specs = static.get("ops", {})
        with self._mu:     # snapshot: other threads may still record
            records = [dict(r) for r in self.ops]
        for rec in records:
            spec = specs.get(rec["kind"])
            if spec is None or not rec["ok"]:
                continue
            ev = rec["events"]
            for a, b in spec.get("edges", []):
                if a not in ev or b not in ev:
                    continue
                last_a = max(i for i, e in enumerate(ev) if e == a)
                first_b = min(i for i, e in enumerate(ev) if e == b)
                if first_b < last_a:
                    dumped = None
                    if dump_path is not None:
                        dumped = str(self.dump(dump_path))
                    raise ProtocolWitnessViolation(
                        f"operation {rec['kind']!r} observed event "
                        f"order {ev} contradicts the static "
                        f"happens-before edge {a!r} -> {b!r} "
                        f"({spec.get('function', '?')})"
                        + (f" (witness dumped to {dumped})"
                           if dumped else ""),
                        op=rec["kind"], edge=(a, b), events=list(ev),
                        dump_path=dumped)
        return self.report()


_STATIC_CACHE: Optional[dict] = None


def static_protocol_graph(refresh: bool = False) -> dict:
    """The static per-operation happens-before graph for the installed
    package (cached — the summary fixpoint costs ~1 s)."""
    global _STATIC_CACHE
    if _STATIC_CACHE is None or refresh:
        from .protocol import happens_before

        _STATIC_CACHE = happens_before()
    return _STATIC_CACHE


@contextlib.contextmanager
def protocol_witnessed(static: Optional[dict] = None, check: bool = True,
                       dump_path=None):
    """Install a fresh :class:`ProtocolWitness` for the block; on clean
    exit, :meth:`~ProtocolWitness.check` it. The witness is always
    uninstalled, even on error."""
    w = ProtocolWitness()
    w.install()
    try:
        yield w
    finally:
        w.uninstall()
    if check:
        w.check(static=static, dump_path=dump_path)
