"""Layer 3a: interprocedural host-divergence taint analysis (CL401-404).

The deadliest bug class in a multi-host SPMD fleet is *host divergence*:
every process must trace, compile, and issue the SAME program — the same
mesh, the same specs, the same collective sequence. A value that differs
between processes (``jax.process_index()``, a wall clock, an environment
variable, an unseeded host RNG) is harmless while it only selects
per-host *data* (which panels to stream, which Monte-Carlo chunks to
compute), but the moment it reaches anything that shapes the *program* —
a Python branch around traced/collective code, a jit static argument, a
``shard_map`` spec, mesh construction, a collective operand (a divergent
trace-time constant bakes a different program into each host's
executable) — the fleet can hang with no error, each host blocked inside
a collective its peers never issued.

PR 1's Layer 1 is intra-file and syntactic; Layer 2 compiles one
process's program. Neither can see a ``process_index()`` read in one
module flow through three call frames into a traced branch in another.
This pass can: it builds a package-wide call graph, runs a small
flow-sensitive abstract interpreter over every function body (gen/kill
def-use taint with joins at control-flow merges, loop bodies iterated
twice), and propagates taint through calls and returns to a fixpoint.

Model:

- **Sources** — calls/reads that may differ between processes:
  ``jax.process_index``/``process_count``, ``jax.local_devices``/
  ``local_device_count``, ``time.*`` clocks, ``os.environ``/``getenv``,
  host RNG (``numpy.random.*``, stdlib ``random.*``), process identity
  (``os.getpid``, ``socket.gethostname``, ``uuid.*``), plus any function
  whose ``def`` line carries a ``# consensus-lint: host-divergent``
  marker (the ``parallel/distributed.py`` slice-topology queries opt in
  this way).
- **Propagation** — assignment, tuple unpacking, arithmetic, subscripts
  (a divergent *index* taints the selection), calls: a resolved callee's
  parameters are tainted at the call site (summaries re-run to
  fixpoint), its call expression is tainted when the callee derives
  taint from a source (``returns_taint``) or passes a tainted parameter
  through to its return (``propagates_params``); an UNRESOLVED call
  with a tainted argument is conservatively tainted.
- **Sanitizers** — ``multihost_utils.broadcast_one_to_all`` /
  ``process_allgather`` / ``sync_global_devices``: gathering or
  broadcasting a per-host value is exactly how divergence is *meant* to
  be resolved, so their results are clean (and feeding them divergent
  operands is the intended use, not a CL404).
- **Sinks** —
  - CL401: a Python ``if``/``while`` test in a function that is traced
    or (transitively) trace-shaping — branches taken differently per
    host issue different programs. A branch one of whose arms is ONLY
    ``raise`` statements is exempt: the surviving hosts all take the
    same arm, and failing fast beats deadlocking — that is the
    validation idiom (``if not 0 <= host_id < n_hosts: raise``).
  - CL402: trace-structural arguments — ``shard_map`` in/out specs,
    ``pallas_call`` grids, jit ``static_argnums``/shardings,
    ``PartitionSpec``/``NamedSharding`` construction.
  - CL403: mesh construction (``Mesh``, ``make_mesh``,
    ``make_hybrid_mesh``, ``create_device_mesh``).
  - CL404: collective operands/parameters (``lax.psum``, ``ppermute``,
    ``all_gather``, …) — a host-divergent trace-time constant compiles
    a different program per host.

Per-host data selection that stays data (round-robin panel/chunk
assignment feeding independent work, guarded by raise-only validation)
produces no findings by construction — ``tests/test_analysis.py``'s
no-trigger corpus pins exactly that.

Known approximations (all conservative or documented): module-level
constants are host context (an env read at import time is an explicit
'read once per process' statement; its uses are not re-tainted);
attribute stores (``self.x = …``) taint only the root name locally;
nested ``def``s see the enclosing function's final taint state for
their free variables.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding
from .rules import (_dotted, _in_comment, _line_directives, _Module,
                    scan_targets)

#: rule ID -> (severity, one-line description)
DATAFLOW_RULES = {
    "CL401": ("error", "host-divergent value reaches a Python branch in "
                       "traced / trace-shaping code (hosts may issue "
                       "different collective sequences)"),
    "CL402": ("error", "host-divergent value reaches a trace-structural "
                       "argument (jit static arg / shard_map specs / "
                       "pallas grid / sharding construction)"),
    "CL403": ("error", "host-divergent value reaches device-mesh "
                       "construction (hosts may build different meshes)"),
    "CL404": ("error", "host-divergent value reaches a collective operand "
                       "or parameter (a divergent trace-time constant "
                       "compiles a different program per host)"),
}

#: canonical dotted-name prefixes whose call results differ per process
_SOURCE_PREFIXES = (
    "jax.process_index", "jax.process_count",
    "jax.local_devices", "jax.local_device_count",
    "time.", "os.environ", "os.getenv", "os.getpid", "os.uname",
    "socket.gethostname", "socket.getfqdn",
    "numpy.random.", "random.",
    "uuid.uuid",
)

#: canonical name tails whose results are host-CONSISTENT by
#: construction: cross-process broadcast/gather is how divergence is
#: legitimately resolved, so these cut taint (and are not CL404 sinks —
#: feeding them per-host values is their purpose)
_SANITIZER_TAILS = (
    "broadcast_one_to_all", "process_allgather", "sync_global_devices",
    "host_local_array_to_global_array", "global_array_to_host_local_array",
)

#: collective calls (CL404 sinks): last dotted component under a jax root
_COLLECTIVE_TAILS = {
    "psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "axis_index", "psum_scatter",
}

#: mesh-construction calls (CL403 sinks)
_MESH_TAILS = {"Mesh", "make_mesh", "make_hybrid_mesh",
               "create_device_mesh", "AbstractMesh"}

#: sharding/spec construction (CL402 sinks)
_SPEC_TAILS = {"PartitionSpec", "NamedSharding", "GridSpec", "BlockSpec"}

#: structural keywords of trace-wrapper calls (CL402 sinks): a divergent
#: value here shapes the traced program itself
_STRUCTURAL_KWARGS = {
    "in_specs", "out_specs", "mesh", "grid", "grid_spec", "static_argnums",
    "static_argnames", "in_shardings", "out_shardings", "donate_argnums",
    "donate_argnames", "axis_name", "axis_size", "device", "backend",
    "devices",
}

#: wrappers whose CALL makes the enclosing function trace-shaping
_TRACE_CALL_TAILS = {
    "jit", "pjit", "vmap", "pmap", "shard_map", "pallas_call", "scan",
    "while_loop", "fori_loop", "cond", "switch", "checkpoint", "remat",
    "grad", "value_and_grad", "lower", "eval_shape", "make_jaxpr",
}


def _src_line(mod: _Module, node: ast.AST) -> str:
    lines = mod.text.splitlines()
    i = getattr(node, "lineno", 0)
    return lines[i - 1] if 0 < i <= len(lines) else ""


def _module_name(rel: str) -> str:
    """``pyconsensus_tpu/parallel/ring.py`` -> dotted module name."""
    p = pathlib.PurePosixPath(rel)
    parts = list(p.with_suffix("").parts)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _FuncInfo:
    """Per-function interprocedural summary (grown monotonically)."""

    def __init__(self, modname: str, mod: _Module, fn: ast.AST):
        self.modname = modname
        self.mod = mod
        self.fn = fn
        self.qual = f"{modname}.{fn.name}"
        args = fn.args
        self.params: List[str] = (
            [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
            + [a.arg for a in args.kwonlyargs])
        #: param name -> origin description, tainted by some call site
        self.tainted_params: Dict[str, str] = {}
        #: body derives taint from a SOURCE and can return it
        self.returns_taint: Optional[str] = None
        #: a tainted parameter can flow through to the return value
        self.propagates_params: bool = False
        #: traced / builds meshes / issues collectives / calls trace
        #: wrappers, directly or transitively — the CL401 relevance bit
        self.trace_shaping: bool = False
        self.marker_divergent: bool = _in_comment(
            _src_line(mod, fn), "consensus-lint: host-divergent")


class _Package:
    """Whole-scan state: module table, function table, import resolution,
    and the enclosing-scope taint snapshots for nested defs."""

    def __init__(self, files: List[Tuple[pathlib.Path, str]]):
        self.mods: Dict[str, _Module] = {}          # rel path -> _Module
        self.modname_of: Dict[str, str] = {}
        self.infos: List[_FuncInfo] = []            # every def, in order
        self.by_qual: Dict[str, _FuncInfo] = {}     # first def wins
        self.by_node: Dict[ast.AST, _FuncInfo] = {}
        #: nested def node -> joined taint state of its enclosing scope
        self.enclosing_state: Dict[ast.AST, Dict[str, str]] = {}
        for f, rel in files:
            try:
                text = f.read_text(encoding="utf-8")
                tree = ast.parse(text, filename=str(f))
            except (OSError, SyntaxError):
                continue
            mod = _Module(rel, text, tree)
            self.mods[rel] = mod
            self.modname_of[rel] = _module_name(rel)
        for rel, mod in self.mods.items():
            modname = self.modname_of[rel]
            for fn in mod.funcs:
                info = _FuncInfo(modname, mod, fn)
                self.infos.append(info)
                self.by_qual.setdefault(info.qual, info)
                self.by_node[fn] = info
        self.scopes: Dict[str, Dict[str, _FuncInfo]] = {}
        self._build_scopes()

    def _build_scopes(self) -> None:
        for rel, mod in self.mods.items():
            modname = self.modname_of[rel]
            scope: Dict[str, _FuncInfo] = {}
            for fn in mod.funcs:
                scope.setdefault(fn.name, self.by_node[fn])
            pkg_parts = modname.split(".")
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ImportFrom):
                    continue
                if node.level:                       # relative import
                    base = pkg_parts[:-node.level] if node.level <= len(
                        pkg_parts) else []
                    target = ".".join(base + (node.module.split(".")
                                              if node.module else []))
                else:
                    target = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    callee = self.by_qual.get(f"{target}.{a.name}")
                    if callee is not None:
                        scope[a.asname or a.name] = callee
            self.scopes[rel] = scope

    def resolve(self, mod: _Module, call_func: ast.AST
                ) -> Optional[_FuncInfo]:
        """Map a call's func expression to a known scanned function."""
        scope = self.scopes.get(mod.path, {})
        if isinstance(call_func, ast.Name):
            return scope.get(call_func.id)
        if isinstance(call_func, ast.Attribute):
            root = _dotted(call_func.value)
            if root in ("self", "cls"):              # same-module method
                return scope.get(call_func.attr)
            dotted = mod.aliases.canon(_dotted(call_func))
            if dotted:
                return self.by_qual.get(dotted)
        return None

    def note_enclosing(self, child: ast.AST, state: Dict[str, str]) -> bool:
        prev = self.enclosing_state.get(child, {})
        nxt = dict(prev)
        for k, v in state.items():
            nxt.setdefault(k, v)
        if nxt != prev:
            self.enclosing_state[child] = nxt
            return True
        return False


# -- taint classification of names/calls -----------------------------------


def _canon(mod: _Module, node: ast.AST) -> str:
    return mod.aliases.canon(_dotted(node)) or ""


def _source_call(mod: _Module, node: ast.Call) -> Optional[str]:
    dotted = _canon(mod, node.func)
    for pref in _SOURCE_PREFIXES:
        if dotted == pref.rstrip(".") or dotted.startswith(pref):
            return dotted
    return None


def _source_read(mod: _Module, node: ast.AST) -> Optional[str]:
    """Non-call sources: the ``os.environ`` mapping itself."""
    if isinstance(node, (ast.Attribute, ast.Name)):
        if _canon(mod, node) == "os.environ":
            return "os.environ"
    return None


def _call_tail(mod: _Module, node: ast.Call) -> str:
    dotted = _canon(mod, node.func)
    return dotted.split(".")[-1] if dotted else ""


def _is_sanitizer(mod: _Module, node: ast.Call) -> bool:
    return _call_tail(mod, node) in _SANITIZER_TAILS


def _is_collective_call(mod: _Module, node: ast.Call) -> bool:
    dotted = _canon(mod, node.func)
    if not dotted:
        return False
    tail = dotted.split(".")[-1]
    return tail in _COLLECTIVE_TAILS and (
        dotted.startswith(("jax.", "lax.")) or "." not in dotted)


def _raise_only(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and all(isinstance(s, ast.Raise) for s in stmts)


# -- the per-function abstract interpreter ---------------------------------


class _Analyzer:
    """Flow-sensitive taint walk of one function body.

    State is ``{name: origin-description}``; statements execute in
    order, branches fork and join, loop bodies run twice (enough for the
    loop-carried flows this package contains)."""

    def __init__(self, pkg: _Package, info: _FuncInfo,
                 findings: Optional[List[Finding]] = None,
                 directives: Optional[Dict[int, Set[str]]] = None,
                 synthetic: bool = False):
        self.pkg = pkg
        self.info = info
        self.mod = info.mod
        self.findings = findings            # None = summary-only pass
        self.directives = directives or {}
        #: the propagates-params probe runs with every param tainted by a
        #: FAKE origin — it must not write that taint into real summaries
        self.synthetic = synthetic
        self.returned_taint: Optional[str] = None
        self.changed = False

    # ---- expression taint -------------------------------------------------

    def eval(self, node: Optional[ast.AST], state: Dict[str, str]
             ) -> Optional[str]:
        """Origin description when ``node``'s value may be
        host-divergent, else None."""
        if node is None:
            return None
        if isinstance(node, ast.Name):
            return state.get(node.id)
        if isinstance(node, ast.Call):
            return self._eval_call(node, state)
        if isinstance(node, ast.Attribute):
            return _source_read(self.mod, node) or self.eval(node.value,
                                                             state)
        if isinstance(node, ast.Subscript):
            return (self.eval(node.value, state)
                    or self.eval(node.slice, state))
        if isinstance(node, ast.NamedExpr):
            org = self.eval(node.value, state)
            self._assign_target(node.target, org, state)
            return org
        if isinstance(node, ast.Lambda):
            # lambdas are the dominant idiom for cond/shard_map arms —
            # walk the body at the definition site (its sinks fire, its
            # captured taint propagates out); the lambda's own params
            # shadow enclosing names
            inner = dict(state)
            a = node.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                inner.pop(p.arg, None)
            return self.eval(node.body, inner)
        if isinstance(node, (ast.Constant, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            return None
        for child in ast.iter_child_nodes(node):
            org = self.eval(child, state)
            if org:
                return org
        return None

    def _eval_call(self, node: ast.Call, state: Dict[str, str]
                   ) -> Optional[str]:
        args = list(node.args) + [kw.value for kw in node.keywords]
        arg_origins = [self.eval(a, state) for a in args]
        tainted_arg = next((o for o in arg_origins if o), None)
        if not isinstance(node.func, (ast.Name, ast.Attribute)):
            # curried call like shard_map(...)(x): evaluate the inner
            # call expression too (its sinks, its taint)
            tainted_arg = self.eval(node.func, state) or tainted_arg
        elif isinstance(node.func, ast.Attribute):
            # method call: the receiver's taint flows through the result
            # (rng.integers(...) with rng = np.random.default_rng(), or
            # the chained np.random.default_rng().integers(...) form)
            tainted_arg = self.eval(node.func.value, state) or tainted_arg

        if _is_sanitizer(self.mod, node):
            return None                     # host-consistent by contract
        src = _source_call(self.mod, node)
        if src:
            self._note_shaping(node)
            return f"{src}() at {self.mod.path}:{node.lineno}"

        if self.findings is not None:
            self._check_call_sinks(node, args, arg_origins, state)
        self._note_shaping(node)

        callee = self.pkg.resolve(self.mod, node.func)
        if callee is not None:
            if callee.marker_divergent:
                return (f"{callee.fn.name}() [marker: host-divergent] "
                        f"at {self.mod.path}:{node.lineno}")
            self._bind_params(callee, node, arg_origins)
            if callee.returns_taint:
                return f"{callee.fn.name}() <- {callee.returns_taint}"
            if callee.propagates_params and tainted_arg:
                return tainted_arg
            return None
        return tainted_arg                  # unresolved: pass through

    def _bind_params(self, callee: _FuncInfo, node: ast.Call,
                     arg_origins) -> None:
        if self.synthetic:
            return
        # method call: the receiver occupies the first parameter slot, so
        # positional arguments shift by one (self.helper(tainted) must
        # taint 'idx', not 'self')
        shift = int(isinstance(node.func, ast.Attribute)
                    and bool(callee.params)
                    and callee.params[0] in ("self", "cls"))
        for pos, (a, org) in enumerate(zip(node.args, arg_origins)):
            if isinstance(a, ast.Starred):
                break
            if org and pos + shift < len(callee.params):
                name = callee.params[pos + shift]
                if name not in callee.tainted_params:
                    callee.tainted_params[name] = org
                    self.changed = True
        for kw, org in zip(node.keywords,
                           arg_origins[len(node.args):]):
            if kw.arg and org and kw.arg in callee.params \
                    and kw.arg not in callee.tainted_params:
                callee.tainted_params[kw.arg] = org
                self.changed = True

    def _note_shaping(self, node: ast.Call) -> None:
        """Mark the enclosing function trace-shaping when this call
        traces, builds meshes/specs, or issues collectives."""
        if self.info.trace_shaping:
            return
        tail = _call_tail(self.mod, node)
        shaping = (tail in _TRACE_CALL_TAILS or tail in _MESH_TAILS
                   or tail in _SPEC_TAILS
                   or _is_collective_call(self.mod, node))
        if not shaping:
            callee = self.pkg.resolve(self.mod, node.func)
            shaping = callee is not None and callee.trace_shaping
        if shaping:
            self.info.trace_shaping = True
            self.changed = True

    # ---- sinks ------------------------------------------------------------

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 0)
        sup = self.directives.get(line, set())
        if "*" in sup or rule in sup:
            return
        self.findings.append(Finding(
            rule=rule, path=self.mod.path, line=line, message=message,
            severity=DATAFLOW_RULES[rule][0],
            snippet=_src_line(self.mod, node).strip()))

    def _check_call_sinks(self, node: ast.Call, args, arg_origins,
                          state: Dict[str, str]) -> None:
        tail = _call_tail(self.mod, node)
        fname = self.info.fn.name
        org = next((o for o in arg_origins if o), None)
        if org:
            if tail in _MESH_TAILS:
                self._emit(node, "CL403",
                           f"mesh construction '{tail}(...)' in "
                           f"'{fname}' consumes a host-divergent value "
                           f"({org}) — hosts may build different meshes "
                           f"and compile different programs")
            elif tail in _SPEC_TAILS:
                self._emit(node, "CL402",
                           f"sharding/spec construction '{tail}(...)' in "
                           f"'{fname}' consumes a host-divergent value "
                           f"({org})")
            elif _is_collective_call(self.mod, node):
                self._emit(node, "CL404",
                           f"collective '{tail}' in '{fname}' consumes a "
                           f"host-divergent value ({org}) — a divergent "
                           f"trace-time constant compiles a different "
                           f"program on each host")
        if tail in _TRACE_CALL_TAILS:
            for kw in node.keywords:
                if kw.arg in _STRUCTURAL_KWARGS:
                    korg = self.eval(kw.value, state)
                    if korg:
                        self._emit(
                            node, "CL402",
                            f"trace-structural argument '{kw.arg}=' of "
                            f"'{tail}' in '{fname}' is host-divergent "
                            f"({korg}) — hosts trace different programs")

    def _branch_sink(self, node: ast.AST, state: Dict[str, str]) -> None:
        # the test is evaluated in EVERY pass — its side effects (walrus
        # assignments, call-site param binding) belong to the summaries
        # too, not just the findings pass
        org = self.eval(node.test, state)
        if self.findings is None or not org:
            return
        # only traced or trace-shaping functions can turn a divergent
        # branch into divergent programs/schedules
        if not (self.info.fn in self.mod.traced or self.info.trace_shaping):
            return
        # fail-fast exemption: when one arm only raises, every SURVIVING
        # host took the same arm — no divergent continuation (and a
        # crashed host is a loud error, not a silent hang)
        body = getattr(node, "body", [])
        orelse = getattr(node, "orelse", [])
        if _raise_only(body) or (orelse and _raise_only(orelse)):
            return
        kind = "if" if isinstance(node, ast.If) else "while"
        self._emit(node, "CL401",
                   f"Python '{kind}' in '{self.info.fn.name}' branches on "
                   f"a host-divergent value ({org}) in traced/"
                   f"trace-shaping code — hosts may issue different "
                   f"collective sequences (fail fast with raise, "
                   f"broadcast the value, or restructure)")

    # ---- statement execution ---------------------------------------------

    def _assign_target(self, target: ast.AST, origin: Optional[str],
                       state: Dict[str, str]) -> None:
        if isinstance(target, ast.Name):
            if origin:
                state[target.id] = origin
            else:
                state.pop(target.id, None)       # kill: clean redefinition
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_target(
                    elt.value if isinstance(elt, ast.Starred) else elt,
                    origin, state)
        elif isinstance(target, (ast.Subscript, ast.Attribute)):
            # container/attribute store: taint the ROOT name (a[i] = bad
            # makes a suspect); never kill on clean stores
            root = target
            while isinstance(root, (ast.Subscript, ast.Attribute)):
                root = root.value
            if isinstance(root, ast.Name) and origin:
                state[root.id] = origin

    def exec_block(self, stmts: Iterable[ast.stmt],
                   state: Dict[str, str]) -> Dict[str, str]:
        for st in stmts:
            state = self.exec_stmt(st, state)
        return state

    def exec_stmt(self, st: ast.stmt, state: Dict[str, str]
                  ) -> Dict[str, str]:
        if isinstance(st, ast.Assign):
            org = self.eval(st.value, state)
            for t in st.targets:
                self._assign_target(t, org, state)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._assign_target(st.target, self.eval(st.value, state),
                                    state)
        elif isinstance(st, ast.AugAssign):
            org = self.eval(st.value, state) or self.eval(st.target, state)
            self._assign_target(st.target, org, state)
        elif isinstance(st, ast.If):
            self._branch_sink(st, state)
            s1 = self.exec_block(st.body, dict(state))
            s2 = self.exec_block(st.orelse, dict(state))
            state = _join(s1, s2)
        elif isinstance(st, ast.While):
            self._branch_sink(st, state)
            once = self.exec_block(st.body, dict(state))
            twice = self.exec_block(st.body, dict(once))
            state = self.exec_block(st.orelse, _join(state,
                                                     _join(once, twice)))
        elif isinstance(st, ast.For):
            org = self.eval(st.iter, state)
            body_state = dict(state)
            self._assign_target(st.target, org, body_state)
            once = self.exec_block(st.body, body_state)
            again = dict(once)
            self._assign_target(st.target, org, again)
            twice = self.exec_block(st.body, again)
            state = self.exec_block(st.orelse, _join(state,
                                                     _join(once, twice)))
        elif isinstance(st, ast.Try):
            merged = _join(state, self.exec_block(st.body, dict(state)))
            for h in st.handlers:
                hstate = dict(merged)
                if h.name:
                    hstate.pop(h.name, None)
                merged = _join(merged, self.exec_block(h.body, hstate))
            merged = self.exec_block(st.orelse, merged)
            state = self.exec_block(st.finalbody, merged)
        elif isinstance(st, ast.With):
            for item in st.items:
                org = self.eval(item.context_expr, state)
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars, org, state)
            state = self.exec_block(st.body, state)
        elif isinstance(st, ast.Return):
            org = self.eval(st.value, state)
            if org and not self.returned_taint:
                self.returned_taint = org
        elif isinstance(st, ast.Expr):
            self.eval(st.value, state)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # snapshot the enclosing taint for the nested def's free vars
            if not self.synthetic:
                self.changed |= self.pkg.note_enclosing(st, state)
        elif isinstance(st, ast.Raise):
            self.eval(st.exc, state)
        elif isinstance(st, ast.Assert):
            self.eval(st.test, state)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    state.pop(t.id, None)
        return state

    # ---- drivers ----------------------------------------------------------

    def initial_state(self) -> Dict[str, str]:
        return _join(dict(self.info.tainted_params),
                     self.pkg.enclosing_state.get(self.info.fn, {}))

    def run(self) -> None:
        state = self.exec_block(self.info.fn.body, self.initial_state())
        del state
        if self.returned_taint and not self.info.returns_taint:
            # param pass-through is the propagates_params bit; only
            # source-derived returns set returns_taint (otherwise every
            # caller of e.g. normalize() would see taint on clean args)
            if self.returned_taint not in set(
                    self.info.tainted_params.values()):
                self.info.returns_taint = self.returned_taint
                self.changed = True


def _join(a: Dict[str, str], b: Dict[str, str]) -> Dict[str, str]:
    out = dict(a)
    for k, v in b.items():
        out.setdefault(k, v)
    return out


def _compute_propagates(pkg: _Package, info: _FuncInfo) -> bool:
    """Does a tainted parameter reach this function's return value?
    One synthetic summary run with every parameter tainted."""
    probe = _Analyzer(pkg, info, synthetic=True)
    state = {p: "param" for p in info.params}
    try:
        probe.exec_block(info.fn.body, state)
    except RecursionError:                            # pragma: no cover
        return True
    return probe.returned_taint is not None


# -- public driver ---------------------------------------------------------


def analyze_paths(paths=None, root=None,
                  select: Optional[Set[str]] = None) -> List[Finding]:
    """Run the Layer 3a taint analysis over ``paths`` (default: the
    installed package). The call graph covers exactly the scanned files —
    linting one file analyzes that file's flows only. Findings are
    sorted by (path, line, rule); ``# consensus-lint: disable=CL40x`` /
    ``# noqa`` line directives suppress in place."""
    files = scan_targets(paths, root)
    pkg = _Package(files)

    # grow summaries (propagates_params / returns_taint / tainted_params
    # / trace_shaping / nested-def scopes) to a fixpoint; findings are
    # discarded in these passes. propagates_params is INSIDE the loop:
    # a pass-through chain whose caller is defined before its callee
    # only converges on the second round (definition order must not
    # decide whether a flow is seen).
    for _ in range(8):
        changed = False
        for info in pkg.infos:
            if not info.propagates_params \
                    and _compute_propagates(pkg, info):
                info.propagates_params = True
                changed = True
            a = _Analyzer(pkg, info)
            a.run()
            changed |= a.changed
        if not changed:
            break

    findings: List[Finding] = []
    directives = {rel: _line_directives(mod.text)
                  for rel, mod in pkg.mods.items()}
    for info in pkg.infos:
        _Analyzer(pkg, info, findings=findings,
                  directives=directives.get(info.mod.path, {})).run()
    if select is not None:
        findings = [f for f in findings if f.rule in select]
    uniq = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line, f.message), f)
    return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule))
