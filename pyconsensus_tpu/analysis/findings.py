"""Finding record + stable fingerprints for the baseline workflow.

A fingerprint deliberately excludes the line NUMBER: baselined findings
must survive unrelated edits above them. It is ``rule:path:crc32(snippet)``
where the snippet is the stripped source line (or a contract's message),
with a ``#n`` ordinal appended for identical repeats so a baseline entry
suppresses exactly one occurrence."""

from __future__ import annotations

import dataclasses
import zlib
from typing import Iterable, List


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str           #: rule ID, e.g. "CL101"
    path: str           #: repo-relative posix path ("<traced>" for contracts)
    line: int           #: 1-based line (0 for whole-artifact findings)
    message: str
    severity: str = "error"      #: "error" | "warning"
    snippet: str = ""            #: stripped source line / contract key

    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path

    def render(self) -> str:
        return (f"{self.location()}: {self.rule} [{self.severity}] "
                f"{self.message}")


def _base_fingerprint(f: Finding) -> str:
    payload = f.snippet or f.message
    return f"{f.rule}:{f.path}:{zlib.crc32(payload.encode('utf-8')):08x}"


def fingerprints(findings: Iterable[Finding]) -> List[str]:
    """Stable fingerprints, ordinal-suffixed for duplicates in input
    order (callers sort by (path, line) first for determinism)."""
    seen: dict = {}
    out = []
    for f in findings:
        base = _base_fingerprint(f)
        n = seen.get(base, 0)
        seen[base] = n + 1
        out.append(base if n == 0 else f"{base}#{n + 1}")
    return out
