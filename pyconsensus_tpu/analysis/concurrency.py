"""Layer 4: host-concurrency analysis (CL801-805).

PRs 5-8 grew ``serve/`` into the most lock-dense code in the tree —
fleet declare locks, session fences, the ``_migrating`` atomic claim,
batcher/queue/cache/admission locks — and every race fixed so far was
found by hand in self-review. Layers 1-3 guard the traced/JAX side and
are blind to host threading; this layer closes that gap statically (its
runtime mirror is :mod:`.witness`, exactly as
``pyconsensus_jit_retraces_total`` mirrors CL304).

Model
-----

**Lock identities** are attribute-resolved, not value-tracked: every
``self.<attr> = threading.Lock()/RLock()/Condition()/Semaphore()``
assignment defines a lock ``Class.<attr>`` (inheritance-aware — a
``DurableSession`` method taking ``self._lock`` holds
``MarketSession._lock``); module-level ``NAME = threading.Lock()``
defines ``module.NAME``; a local ``lock = threading.Lock()`` is a
function-scoped identity. A non-``self`` receiver resolves through a
small type environment (parameter annotations, ``x = ClassName(...)``
assignments, ``self.<attr>`` types recorded from ``__init__``) and
falls back to attribute-name uniqueness (``w.declare_lock`` is a
``FleetWorker`` lock because no other scanned class defines that
attribute); a genuinely ambiguous receiver gets a site-unique identity
— it still counts as "a lock is held" but can never fabricate a
cross-site cycle.

**Held-lock sets** are lexical (``with`` nesting, plus a linear
``.acquire()``/``.release()`` approximation) and interprocedural: each
function's *entry held set* is the intersection of the held sets at
every resolved call site (call sites inside ``__init__`` bodies are
construction-time and excluded — the object is not shared yet), grown
to a fixpoint over the package call graph, which is resolved the same
way :mod:`.dataflow`'s is (module scopes + import aliases + ``self``/
``cls`` methods), extended with the receiver-type environment. Lambda
bodies are walked in their enclosing function (the Layer-3a lesson).

Rules
-----

- **CL801 — lock-order cycles.** Every acquisition of ``B`` while
  ``A`` is held contributes a may-hold-before edge ``A -> B``
  (callee acquisitions propagate through summaries). A cycle in that
  graph is a potential deadlock the moment two threads interleave. A
  ``# consensus-lint: lock-order A < B`` comment documents an intended
  total order; an edge contradicting a declared order is reported even
  without a full cycle.
- **CL802 — blocking under a lock.** ``Future.result``, queue
  get/put/join, ``Event.wait``, ``Condition.wait`` (on a condition
  *other* than one currently held — waiting on the held condition
  releases it, the correct idiom), ``Thread.join``, ``time.sleep``,
  ``jax.block_until_ready``, replication-log/ledger I/O
  (``journal_block``/``commit_round``/``replay_session``/
  ``verify_collect``/``atomic_write``), and fault-site hooks that take
  a ``path=`` (the torn-write file forms — a bare ``fire(site)`` is
  raise-only and exempt) reached while any lock is held. Bounded forms
  (an explicit timeout argument) are exempt: they delay, not deadlock.
- **CL803/CL804 — guarded-by inference.** For every mutable instance
  attribute, the write sites' held-lock sets vote: a lock held at a
  strict majority of (non-construction) write sites is the inferred
  guard, and a ``# guarded-by: _lock`` comment on the attribute's
  ``__init__`` assignment pins it explicitly (``# guarded-by: none``
  opts an attribute out). A write with the guard absent is CL803 when
  nothing is held and CL804 when a *different* lock is held; an
  attribute whose write sites split across locks with no majority is
  one CL804 asking for an annotation. Reads are deliberately not
  flagged (racy reads of monotonic floats/bools are this codebase's
  documented idiom); inference needs >= 2 write sites unless annotated.
- **CL805 — fault-site catalog drift.** Every literal site in a
  ``faults.fire``/``faults.corrupt`` hook call must be in
  ``faults.plan.FAULT_SITES``, and (on a whole-package scan) every
  cataloged site must appear at >= 1 hook call — the code-side half of
  pinning docs/ROBUSTNESS.md's site table, whose doc-side half is
  ``tests/test_concurrency.py``.

``# consensus-lint: disable=CL80x — rationale`` suppresses in place;
the rationale rides in the same comment (the directive parser takes the
first token of each comma-separated piece).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, NamedTuple, Optional, Set, Tuple

from .findings import Finding
from .rules import _dotted, _line_directives, _Module, scan_targets
from .dataflow import _module_name

#: rule ID -> (severity, one-line description)
CONCURRENCY_RULES = {
    "CL801": ("error", "lock-order cycle (potential deadlock) or an "
                       "acquisition contradicting a declared "
                       "'# consensus-lint: lock-order A < B' total order"),
    "CL802": ("error", "blocking call (Future.result / queue op / "
                       "Event.wait / sleep / block_until_ready / "
                       "replication-log I/O / torn-write fault hook) "
                       "reached while a lock is held"),
    "CL803": ("error", "guarded instance attribute written with no lock "
                       "held (its other writes hold a guarding lock)"),
    "CL804": ("error", "instance attribute written under inconsistent "
                       "lock sets (a different lock than its guard, or "
                       "no majority guard at all)"),
    "CL805": ("error", "fault-site drift: a hook call names a site "
                       "missing from faults.plan.FAULT_SITES, or a "
                       "cataloged site has no hook call in the package"),
}

#: threading constructors that create mutual-exclusion lock objects
_LOCK_CONSTRUCTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}

#: dotted calls that block the calling thread outright (CL802)
_BLOCKING_DOTTED = {
    "time.sleep", "concurrent.futures.wait", "futures.wait",
    "concurrent.futures.as_completed", "futures.as_completed",
    "select.select", "jax.block_until_ready",
}

#: method tails that are replication-log / ledger / atomic-file I/O —
#: reaching disk while a lock is held stretches the lock over fsync
#: latency (and a shared-filesystem stall becomes a process-wide stall)
_IO_TAILS = {"journal_block", "commit_round", "replay_session",
             "verify_collect", "atomic_write"}

#: handle kind -> method names that block on it (unbounded forms)
_BLOCKING_METHODS = {
    "queue": {"get", "put", "join"},
    "event": {"wait"},
    "future": {"result", "exception"},
    "thread": {"join"},
}

#: constructor dotted names -> blocking-handle kind (CL701-style handle
#: dataflow, for locals and self attributes alike)
_HANDLE_CONSTRUCTORS = {
    "queue.Queue": "queue", "queue.SimpleQueue": "queue",
    "queue.LifoQueue": "queue", "queue.PriorityQueue": "queue",
    "threading.Event": "event", "threading.Thread": "thread",
    "concurrent.futures.Future": "future", "futures.Future": "future",
    "Future": "future",
}

#: faults-package hook tails whose literal site argument CL805 audits
_HOOK_TAILS = {"fire", "corrupt"}

#: attribute-mutating method names counted as WRITES to ``self.<attr>``
_MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "move_to_end",
    "difference_update", "intersection_update", "appendleft",
}

_LOCK_ORDER_RE = re.compile(
    r"consensus-lint:\s*lock-order\s+([\w.]+)\s*<\s*([\w.]+)")
_GUARDED_BY_RE = re.compile(r"#.*guarded-by:\s*([\w]+)")


class LockId(NamedTuple):
    """One lock identity: display name + defining site. Identity is the
    whole tuple — two classes' ``_lock`` attributes never unify, and the
    (path, line) half is what :mod:`.witness` joins its creation-site
    records against."""

    name: str       #: "FleetWorker.declare_lock" / "tracer._ids_lock"
    path: str       #: repo-relative posix path of the defining line
    line: int

    def render(self) -> str:
        return f"{self.name} ({self.path}:{self.line})"


class _ClassInfo:
    """Per-class table: lock attributes, attribute types, methods,
    base-class names, and ``# guarded-by:`` annotations."""

    def __init__(self, qual: str, name: str, mod: _Module,
                 node: ast.ClassDef):
        self.qual = qual
        self.name = name
        self.mod = mod
        self.node = node
        self.bases: List[str] = [d for d in (_dotted(b) for b in node.bases)
                                 if d]
        self.methods: Dict[str, ast.AST] = {}
        self.lock_attrs: Dict[str, int] = {}      # attr -> def line
        self.attr_types: Dict[str, str] = {}      # attr -> dotted class
        self.attr_kinds: Dict[str, str] = {}      # attr -> handle kind
        self.guards: Dict[str, str] = {}          # attr -> lock attr|"none"


class _FuncInfo:
    """Per-function record grown by the fixpoint passes."""

    def __init__(self, mod: _Module, fn: ast.AST,
                 cls: Optional[_ClassInfo]):
        self.mod = mod
        self.fn = fn
        self.cls = cls
        self.name = fn.name
        self.is_init = fn.name == "__init__"
        #: locks this function may acquire, directly or transitively
        self.acquires: Set[LockId] = set()
        #: entry held set: intersection over resolved call sites
        self.entry: Optional[frozenset] = None    # None = no caller seen


class _Package:
    """Whole-scan state: modules, classes, functions, scope maps."""

    def __init__(self, files: List[Tuple]):
        self.mods: Dict[str, _Module] = {}
        self.modname: Dict[str, str] = {}
        self.classes: Dict[str, _ClassInfo] = {}       # qual -> info
        self.class_scope: Dict[str, Dict[str, str]] = {}  # rel -> name->qual
        self.func_scope: Dict[str, Dict[str, ast.AST]] = {}
        self.infos: Dict[ast.AST, _FuncInfo] = {}
        self.module_locks: Dict[str, Dict[str, LockId]] = {}  # rel->name->id
        #: method name -> [(class qual, node)] for unique-name fallback
        self.method_sites: Dict[str, List[Tuple[str, ast.AST]]] = {}
        #: lock attr name -> [class quals defining it]
        self.lock_attr_owners: Dict[str, List[str]] = {}
        self.order_decls: List[Tuple[str, str, str, int]] = []  # a<b @site
        self._lines: Dict[str, List[str]] = {}    # rel -> splitlines
        for f, rel in files:
            try:
                text = f.read_text(encoding="utf-8")
                tree = ast.parse(text, filename=str(f))
            except (OSError, SyntaxError):
                continue
            mod = _Module(rel, text, tree)
            self.mods[rel] = mod
            self.modname[rel] = _module_name(rel)
        for rel, mod in self.mods.items():
            self._index_module(rel, mod)
        self._build_scopes()
        for rel, mod in self.mods.items():
            self._collect_order_decls(rel, mod)

    # -- indexing -------------------------------------------------------

    def _index_module(self, rel: str, mod: _Module) -> None:
        modname = self.modname[rel]
        self.module_locks[rel] = {}
        lines = mod.text.splitlines()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                dotted = mod.aliases.canon(_dotted(node.value.func)) or ""
                if dotted in _LOCK_CONSTRUCTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            short = modname.split(".")[-1] or modname
                            self.module_locks[rel][t.id] = LockId(
                                f"{short}.{t.id}", rel, node.lineno)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            qual = f"{modname}.{node.name}"
            info = _ClassInfo(qual, node.name, mod, node)
            self.classes.setdefault(qual, info)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods.setdefault(sub.name, sub)
                    self.method_sites.setdefault(sub.name, []).append(
                        (qual, sub))
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    target, value = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign):
                    target, value = sub.target, sub.value
                else:
                    continue
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                attr = target.attr
                if isinstance(value, ast.Call):
                    dotted = mod.aliases.canon(_dotted(value.func)) or ""
                    if dotted in _LOCK_CONSTRUCTORS:
                        info.lock_attrs[attr] = sub.lineno
                        self.lock_attr_owners.setdefault(
                            attr, []).append(qual)
                    elif dotted in _HANDLE_CONSTRUCTORS:
                        info.attr_kinds[attr] = _HANDLE_CONSTRUCTORS[dotted]
                    elif dotted:
                        info.attr_types.setdefault(attr, dotted)
                # a ``# guarded-by: <lock>`` / ``# guarded-by: none``
                # annotation pins intent on ANY self-attribute
                # assignment line, not just constructor calls
                line = lines[sub.lineno - 1] if sub.lineno <= len(lines) \
                    else ""
                m = _GUARDED_BY_RE.search(line)
                if m:
                    info.guards.setdefault(attr, m.group(1))
        # function table: every def, tagged with its enclosing class
        stack: List[Tuple[ast.AST, Optional[_ClassInfo]]] = [
            (mod.tree, None)]
        while stack:
            node, cls = stack.pop()
            for child in ast.iter_child_nodes(node):
                nxt = cls
                if isinstance(child, ast.ClassDef):
                    nxt = self.classes.get(
                        f"{modname}.{child.name}")
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    self.infos[child] = _FuncInfo(mod, child, cls)
                    nxt = None          # nested defs are their own scope
                stack.append((child, nxt))

    def _build_scopes(self) -> None:
        """Per-module name -> class-qual / function maps, resolving
        relative and absolute imports against the scanned set (the
        :mod:`.dataflow` scope discipline)."""
        by_func_qual: Dict[str, ast.AST] = {}
        for fn, info in self.infos.items():
            if info.cls is None:
                by_func_qual.setdefault(
                    f"{self.modname[info.mod.path]}.{fn.name}", fn)
        for rel, mod in self.mods.items():
            modname = self.modname[rel]
            cscope: Dict[str, str] = {}
            fscope: Dict[str, ast.AST] = {}
            for qual, cinfo in self.classes.items():
                if qual.rsplit(".", 1)[0] == modname:
                    cscope.setdefault(cinfo.name, qual)
            for fn, info in self.infos.items():
                if info.mod is mod and info.cls is None:
                    fscope.setdefault(fn.name, fn)
            pkg_parts = modname.split(".")
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ImportFrom):
                    continue
                if node.level:
                    base = pkg_parts[:-node.level] \
                        if node.level <= len(pkg_parts) else []
                    target = ".".join(base + (node.module.split(".")
                                              if node.module else []))
                else:
                    target = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    if f"{target}.{a.name}" in self.classes:
                        cscope.setdefault(local, f"{target}.{a.name}")
                    callee = by_func_qual.get(f"{target}.{a.name}")
                    if callee is not None:
                        fscope.setdefault(local, callee)
            self.class_scope[rel] = cscope
            self.func_scope[rel] = fscope

    def _collect_order_decls(self, rel: str, mod: _Module) -> None:
        for i, line in enumerate(mod.text.splitlines(), 1):
            idx = line.find("#")
            if idx < 0:
                continue
            m = _LOCK_ORDER_RE.search(line[idx:])
            if m:
                self.order_decls.append((m.group(1), m.group(2), rel, i))

    def lines(self, mod: _Module) -> List[str]:
        """Cached splitlines — snippet lookups happen per write site
        and per finding, not once per module."""
        cached = self._lines.get(mod.path)
        if cached is None:
            cached = self._lines[mod.path] = mod.text.splitlines()
        return cached

    # -- resolution helpers ---------------------------------------------

    def resolve_class(self, mod: _Module, dotted: Optional[str]
                      ) -> Optional[_ClassInfo]:
        if not dotted:
            return None
        scope = self.class_scope.get(mod.path, {})
        head = dotted.split(".")[0]
        if dotted in scope:
            return self.classes.get(scope[dotted])
        if head in scope and "." not in dotted:
            return self.classes.get(scope[head])
        canon = mod.aliases.canon(dotted)
        if canon in self.classes:
            return self.classes[canon]
        # suffix match: "failover.DurableSession" etc.
        for qual in self.classes:
            if canon and qual.endswith("." + canon):
                return self.classes[qual]
        return None

    def mro(self, cinfo: _ClassInfo) -> List[_ClassInfo]:
        """The class plus its resolvable bases, depth-first (good enough
        for this package's single-inheritance lattices)."""
        out, seen = [], set()
        stack = [cinfo]
        while stack:
            c = stack.pop(0)
            if c.qual in seen:
                continue
            seen.add(c.qual)
            out.append(c)
            for b in c.bases:
                base = self.resolve_class(c.mod, b)
                if base is not None:
                    stack.append(base)
        return out

    def lock_for_attr(self, cinfo: Optional[_ClassInfo], attr: str
                      ) -> Optional[LockId]:
        """``self.<attr>`` in class ``cinfo`` -> the defining class's
        lock identity (inheritance-aware)."""
        if cinfo is None:
            return None
        for c in self.mro(cinfo):
            if attr in c.lock_attrs:
                return LockId(f"{c.name}.{attr}", c.mod.path,
                              c.lock_attrs[attr])
        return None

    def unique_attr_lock(self, attr: str) -> Optional[LockId]:
        owners = self.lock_attr_owners.get(attr, [])
        if len(owners) == 1:
            c = self.classes[owners[0]]
            return LockId(f"{c.name}.{attr}", c.mod.path,
                          c.lock_attrs[attr])
        return None

    def unique_method(self, name: str) -> Optional[ast.AST]:
        sites = self.method_sites.get(name, [])
        if len(sites) == 1:
            return sites[0][1]
        return None

    def all_lock_ids(self) -> Dict[LockId, None]:
        out: Dict[LockId, None] = {}
        for cinfo in self.classes.values():
            for attr, line in cinfo.lock_attrs.items():
                out[LockId(f"{cinfo.name}.{attr}", cinfo.mod.path,
                           line)] = None
        for table in self.module_locks.values():
            for lid in table.values():
                out[lid] = None
        return out


# -- the per-function walker ------------------------------------------------


class _Access(NamedTuple):
    """One attribute write site (CL803/804 evidence)."""

    cls_qual: str
    attr: str
    path: str
    line: int
    held: frozenset
    in_init: bool
    snippet: str


class _Walk:
    """One lexical pass over a function body: tracks the held-lock list,
    records acquisition edges, call sites, blocking calls, and attribute
    writes. Runs once per fixpoint round (summaries) and once in the
    findings pass."""

    def __init__(self, pkg: _Package, info: _FuncInfo,
                 entry: Iterable[LockId] = ()):
        self.pkg = pkg
        self.info = info
        self.mod = info.mod
        self.entry: Tuple[LockId, ...] = tuple(entry)
        self.local_types: Dict[str, str] = {}     # name -> dotted class
        self.local_locks: Dict[str, LockId] = {}  # name -> local lock id
        self.local_kinds: Dict[str, str] = {}     # name -> handle kind
        #: (edge a->b, site node) in acquisition order
        self.edges: List[Tuple[LockId, LockId, ast.AST]] = []
        #: (node, held-at-site, callee _FuncInfo|None, canon dotted)
        self.calls: List[Tuple[ast.AST, Tuple[LockId, ...],
                               Optional[_FuncInfo], str]] = []
        self.accesses: List[_Access] = []
        self.acquired: Set[LockId] = set()
        self._seed_types()

    def _seed_types(self) -> None:
        args = self.info.fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.annotation is not None:
                cinfo = self.pkg.resolve_class(self.mod,
                                               _dotted(a.annotation))
                if cinfo is not None:
                    self.local_types[a.arg] = cinfo.qual
        if self.info.cls is not None and args.args:
            self.local_types[args.args[0].arg] = self.info.cls.qual

    # -- expression typing ---------------------------------------------

    def _type_of(self, node: ast.AST) -> Optional[_ClassInfo]:
        """The scanned class an expression evaluates to, or None."""
        if isinstance(node, ast.Name):
            qual = self.local_types.get(node.id)
            return self.pkg.classes.get(qual) if qual else None
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            if base is not None:
                for c in self.pkg.mro(base):
                    t = c.attr_types.get(node.attr)
                    if t:
                        return self.pkg.resolve_class(c.mod, t)
            return None
        if isinstance(node, ast.Call):
            return self.pkg.resolve_class(self.mod, _dotted(node.func))
        return None

    def _handle_kind(self, node: ast.AST) -> Optional[str]:
        """Blocking-handle kind of a receiver expression (CL802)."""
        if isinstance(node, ast.Name):
            kind = self.local_kinds.get(node.id)
            if kind:
                return kind
            if node.id in ("future", "fut"):
                return "future"
        if isinstance(node, ast.Attribute):
            if node.attr == "future":
                return "future"
            base = self._type_of(node.value)
            if base is not None:
                for c in self.pkg.mro(base):
                    if node.attr in c.attr_kinds:
                        return c.attr_kinds[node.attr]
        return None

    def _lock_of(self, node: ast.AST) -> Optional[LockId]:
        """Resolve an expression to a lock identity (or None)."""
        if isinstance(node, ast.Name):
            if node.id in self.local_locks:
                return self.local_locks[node.id]
            mod_lock = self.pkg.module_locks.get(self.mod.path, {})
            return mod_lock.get(node.id)
        if isinstance(node, ast.Attribute):
            attr = node.attr
            root = _dotted(node.value)
            if root in ("self", "cls"):
                lid = self.pkg.lock_for_attr(self.info.cls, attr)
                if lid is not None:
                    return lid
                # self receiver but the attr is a lock of some OTHER
                # class only: not this object's lock
                return None
            recv = self._type_of(node.value)
            if recv is not None:
                lid = self.pkg.lock_for_attr(recv, attr)
                if lid is not None:
                    return lid
            if attr in self.pkg.lock_attr_owners:
                lid = self.pkg.unique_attr_lock(attr)
                if lid is not None:
                    return lid
                # ambiguous: a real lock, unknown which — site-unique
                # identity (held-ness without cross-site unification)
                return LockId(f"?.{attr}", self.mod.path, node.lineno)
        return None

    # -- held-set bookkeeping ------------------------------------------

    def _held(self, local: List[LockId]) -> Tuple[LockId, ...]:
        return self.entry + tuple(local)

    def _acquire(self, lid: LockId, node: ast.AST,
                 local: List[LockId]) -> bool:
        held = self._held(local)
        if lid in held:
            return False                 # re-entrant RLock: no edge
        for h in held:
            self.edges.append((h, lid, node))
        self.acquired.add(lid)
        local.append(lid)
        return True

    # -- the walk -------------------------------------------------------

    def run(self) -> None:
        self._block(list(self.info.fn.body), [])

    def _block(self, stmts: List[ast.stmt], local: List[LockId]) -> None:
        # acquire()/release() calls extend/shrink ``local`` linearly
        for st in stmts:
            self._stmt(st, local)

    def _stmt(self, st: ast.stmt, local: List[LockId]) -> None:
        if isinstance(st, ast.With) or isinstance(st, ast.AsyncWith):
            pushed = 0
            for item in st.items:
                lid = self._lock_of(item.context_expr)
                if lid is not None:
                    if self._acquire(lid, item.context_expr, local):
                        pushed += 1
                else:
                    self._expr(item.context_expr, local)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, item.context_expr)
            self._block(st.body, local)
            for _ in range(pushed):
                local.pop()
            return
        if isinstance(st, ast.Assign):
            self._expr(st.value, local)
            for t in st.targets:
                self._bind(t, st.value)
                self._write_target(t, st, local)
            return
        if isinstance(st, ast.AugAssign):
            self._expr(st.value, local)
            self._write_target(st.target, st, local)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._expr(st.value, local)
                self._bind(st.target, st.value)
                self._write_target(st.target, st, local)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return                       # their own scopes
        if isinstance(st, (ast.If, ast.While)):
            # branches share ``local``: a branch-local .acquire() is
            # approximated as held afterwards (conservative for CL802,
            # and a release() in the other branch pops it back off)
            self._expr(st.test, local)
            self._block(st.body, local)
            self._block(st.orelse, local)
            return
        if isinstance(st, ast.For):
            self._expr(st.iter, local)
            self._block(st.body, local)
            self._block(st.orelse, local)
            return
        if isinstance(st, ast.Try):
            self._block(st.body, local)
            for h in st.handlers:
                self._block(h.body, local)
            self._block(st.orelse, local)
            self._block(st.finalbody, local)
            return
        if isinstance(st, ast.Return):
            if st.value is not None:
                self._expr(st.value, local)
            return
        if isinstance(st, ast.Expr):
            self._expr(st.value, local, statement=True)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, local)
            elif isinstance(child, ast.stmt):
                self._stmt(child, local)

    def _bind(self, target: ast.AST, value: ast.AST) -> None:
        """Track local types / lock handles / blocking handles."""
        if not isinstance(target, ast.Name):
            return
        if isinstance(value, ast.Call):
            dotted = self.mod.aliases.canon(_dotted(value.func)) or ""
            if dotted in _LOCK_CONSTRUCTORS:
                self.local_locks[target.id] = LockId(
                    f"{self.info.name}.{target.id}", self.mod.path,
                    value.lineno)
                return
            if dotted in _HANDLE_CONSTRUCTORS:
                self.local_kinds[target.id] = _HANDLE_CONSTRUCTORS[dotted]
                return
            cinfo = self.pkg.resolve_class(self.mod, _dotted(value.func))
            if cinfo is not None:
                self.local_types[target.id] = cinfo.qual
                return
            if dotted.split(".")[-1] in ("submit",):
                self.local_kinds[target.id] = "future"
                return
        self.local_types.pop(target.id, None)
        self.local_kinds.pop(target.id, None)

    def _write_target(self, target: ast.AST, st: ast.stmt,
                      local: List[LockId]) -> None:
        """Record ``self.<attr>`` stores (plain, augmented, and
        subscript stores rooted at ``self.<attr>``)."""
        attr = None
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            attr = target.attr
        elif isinstance(target, ast.Subscript):
            root = target.value
            if isinstance(root, ast.Attribute) \
                    and isinstance(root.value, ast.Name) \
                    and root.value.id == "self":
                attr = root.attr
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._write_target(elt, st, local)
            return
        if attr is None or self.info.cls is None:
            return
        self._record_access(attr, st, local)

    def _record_access(self, attr: str, node: ast.AST,
                       local: List[LockId]) -> None:
        cinfo = self.info.cls
        if attr in cinfo.lock_attrs or attr in cinfo.attr_kinds:
            return                       # the locks themselves
        lines = self.pkg.lines(self.mod)
        ln = getattr(node, "lineno", 0)
        snippet = lines[ln - 1].strip() if 0 < ln <= len(lines) else ""
        self.accesses.append(_Access(
            cinfo.qual, attr, self.mod.path, ln,
            frozenset(self._held(local)),
            self.info.is_init, snippet))

    def _expr(self, node: ast.AST, local: List[LockId],
              statement: bool = False) -> None:
        if isinstance(node, ast.Call):
            self._call(node, local, statement)
            return
        if isinstance(node, ast.Lambda):
            # walked in the enclosing scope: a lambda handed to a
            # callback still runs this module's lock acquisitions
            self._expr(node.body, local)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, local)

    def _call(self, node: ast.Call, local: List[LockId],
              statement: bool) -> None:
        for a in node.args:
            self._expr(a, local)
        for kw in node.keywords:
            self._expr(kw.value, local)
        if not isinstance(node.func, (ast.Name, ast.Attribute)):
            self._expr(node.func, local)
            return
        # explicit acquire()/release() on a resolvable lock
        if isinstance(node.func, ast.Attribute):
            lid = self._lock_of(node.func.value)
            if lid is not None:
                if node.func.attr == "acquire" and statement:
                    self._acquire(lid, node, local)
                    return
                if node.func.attr == "release" and statement \
                        and lid in local:
                    local.remove(lid)
                    return
        callee = self._resolve_callee(node)
        dotted = self.mod.aliases.canon(_dotted(node.func)) or ""
        self.calls.append((node, self._held(local), callee, dotted))

    def _resolve_callee(self, node: ast.Call) -> Optional[_FuncInfo]:
        fn = node.func
        if isinstance(fn, ast.Name):
            target = self.pkg.func_scope.get(self.mod.path, {}).get(fn.id)
            return self.pkg.infos.get(target) if target is not None \
                else None
        if isinstance(fn, ast.Attribute):
            root = _dotted(fn.value)
            if root in ("self", "cls") and self.info.cls is not None:
                for c in self.pkg.mro(self.info.cls):
                    if fn.attr in c.methods:
                        return self.pkg.infos.get(c.methods[fn.attr])
                return None
            recv = self._type_of(fn.value)
            if recv is not None:
                for c in self.pkg.mro(recv):
                    if fn.attr in c.methods:
                        return self.pkg.infos.get(c.methods[fn.attr])
                return None
            target = self.pkg.unique_method(fn.attr)
            if target is not None:
                return self.pkg.infos.get(target)
            # module-function via canonical dotted name
            dotted = self.mod.aliases.canon(_dotted(fn)) or ""
            scope = self.pkg.func_scope.get(self.mod.path, {})
            tail = dotted.split(".")[-1] if dotted else ""
            if tail in scope:
                return self.pkg.infos.get(scope[tail])
        return None


# -- fixpoint drivers -------------------------------------------------------


def _grow_summaries(pkg: _Package) -> Dict[ast.AST, _Walk]:
    """Run the walker over every function, then grow transitive acquire
    sets and entry held sets to a fixpoint. Returns the per-function
    walks (re-used by the findings pass — the walk is deterministic)."""
    walks: Dict[ast.AST, _Walk] = {}
    for fn, info in pkg.infos.items():
        w = _Walk(pkg, info)
        w.run()
        walks[fn] = w
        info.acquires = set(w.acquired)
    # transitive acquisitions
    for _ in range(16):
        changed = False
        for fn, info in pkg.infos.items():
            for _node, _held, callee, _d in walks[fn].calls:
                if callee is not None \
                        and not callee.acquires <= info.acquires:
                    info.acquires |= callee.acquires
                    changed = True
        if not changed:
            break
    # entry held sets: intersection over non-construction call sites
    for info in pkg.infos.values():
        info.entry = None
    for _ in range(8):
        changed = False
        for fn, info in pkg.infos.items():
            caller_entry = info.entry or frozenset()
            if pkg.infos[fn].is_init:
                continue                 # construction-time calls excluded
            for node, held, callee, _d in walks[fn].calls:
                if callee is None:
                    continue
                site_held = frozenset(held) | caller_entry
                prev = callee.entry
                nxt = site_held if prev is None else (prev & site_held)
                if nxt != prev:
                    callee.entry = nxt
                    changed = True
        if not changed:
            break
    for info in pkg.infos.values():
        if info.entry is None:
            info.entry = frozenset()
    return walks


class _Results(NamedTuple):
    pkg: _Package
    #: (a, b) -> first (path, line, snippet) acquisition site
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]]
    findings: List[Finding]


def _snippet(pkg: _Package, mod: _Module, line: int) -> str:
    lines = pkg.lines(mod)
    return lines[line - 1].strip() if 0 < line <= len(lines) else ""


#: positional slot of the timeout parameter per blocking method —
#: ``q.get(block, timeout)`` and ``q.put(item, block, timeout)`` only
#: bound the wait at their timeout slot, so ``q.put(item)`` and
#: ``q.get(True)`` stay unbounded
_TIMEOUT_ARG_INDEX = {
    "wait": 0, "wait_for": 1, "result": 0, "exception": 0, "join": 0,
    "get": 1, "put": 2,
}


def _timeout_bounded(node: ast.Call, meth: str) -> bool:
    """An explicit timeout argument (``timeout=`` or the method's
    positional timeout slot) marks the blocking form bounded — a delay,
    not a deadlock. ``timeout=None`` literals stay unbounded."""
    for kw in node.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    idx = _TIMEOUT_ARG_INDEX.get(meth)
    if idx is None or len(node.args) <= idx:
        return False
    arg = node.args[idx]
    return not (isinstance(arg, ast.Constant) and arg.value is None)


def _analyze(pkg: _Package, select: Optional[Set[str]],
             full_scan: bool) -> _Results:
    walks = _grow_summaries(pkg)
    directives = {rel: _line_directives(mod.text)
                  for rel, mod in pkg.mods.items()}
    findings: List[Finding] = []

    def emit(mod: _Module, line: int, rule: str, message: str) -> None:
        sup = directives.get(mod.path, {}).get(line, set())
        if "*" in sup or rule in sup:
            return
        if select is not None and rule not in select:
            return
        findings.append(Finding(
            rule=rule, path=mod.path, line=line, message=message,
            severity=CONCURRENCY_RULES[rule][0],
            snippet=_snippet(pkg, mod, line)))

    # ---- edge collection (direct + interprocedural) -------------------
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]] = {}

    def add_edge(a: LockId, b: LockId, mod: _Module, line: int) -> None:
        if a == b or a.name.startswith("?.") or b.name.startswith("?."):
            return
        edges.setdefault((a, b), (mod.path, line,
                                  _snippet(pkg, mod, line)))

    accesses: List[_Access] = []
    hook_sites: Dict[str, List[Tuple[str, int]]] = {}
    for fn, info in pkg.infos.items():
        w = walks[fn]
        entry = tuple(info.entry or ())
        for a, b, node in w.edges:
            add_edge(a, b, info.mod, getattr(node, "lineno", 0))
        # entry-held locks order-precede every local acquisition
        for lid in w.acquired:
            for h in entry:
                if h != lid:
                    add_edge(h, lid, info.mod, info.fn.lineno)
        for node, held, callee, dotted in w.calls:
            line = getattr(node, "lineno", 0)
            full_held = tuple(dict.fromkeys(entry + tuple(held)))
            if callee is not None:
                for b in callee.acquires:
                    for h in full_held:
                        if h != b:
                            add_edge(h, b, info.mod, line)
            # ---- CL805: hook-site audit (held or not) ----------------
            tail = dotted.split(".")[-1] if dotted else ""
            parts = dotted.split(".") if dotted else []
            is_hook = tail in _HOOK_TAILS and ("faults" in parts[:3]
                                               or "plan" in parts[:3])
            if is_hook and node.args and isinstance(node.args[0],
                                                    ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
                hook_sites.setdefault(site, []).append(
                    (info.mod.path, line))
                from ..faults.plan import FAULT_SITES
                if site not in FAULT_SITES:
                    emit(info.mod, line, "CL805",
                         f"fault hook names site {site!r} which is not "
                         f"in faults.plan.FAULT_SITES — add it to the "
                         f"catalog (and docs/ROBUSTNESS.md's table) or "
                         f"fix the name")
            # ---- CL802: blocking under a lock ------------------------
            if not full_held:
                continue
            held_s = ", ".join(h.render() for h in full_held)
            if dotted in _BLOCKING_DOTTED:
                emit(info.mod, line, "CL802",
                     f"'{dotted}' blocks while holding {held_s} — every "
                     f"other thread needing that lock stalls for the "
                     f"full wait; block outside the critical section")
                continue
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth in _IO_TAILS:
                    emit(info.mod, line, "CL802",
                         f"replication-log/ledger I/O '.{meth}()' runs "
                         f"while holding {held_s} — the lock is held "
                         f"across fsync/shared-filesystem latency")
                    continue
                if meth == "block_until_ready":
                    emit(info.mod, line, "CL802",
                         f"'.block_until_ready()' synchronizes with the "
                         f"device while holding {held_s}")
                    continue
                recv_lock = w._lock_of(node.func.value)
                if recv_lock is not None and meth in ("wait", "wait_for"):
                    if recv_lock in full_held:
                        continue   # cond.wait releases the held cond
                    if not _timeout_bounded(node, meth):
                        emit(info.mod, line, "CL802",
                             f"'.{meth}()' waits on "
                             f"{recv_lock.render()} while holding "
                             f"{held_s} (the wait only releases its OWN "
                             f"condition)")
                    continue
                kind = w._handle_kind(node.func.value)
                if kind is not None \
                        and meth in _BLOCKING_METHODS.get(kind, ()):
                    if not _timeout_bounded(node, meth):
                        emit(info.mod, line, "CL802",
                             f"blocking '.{meth}()' on a {kind} handle "
                             f"while holding {held_s} — an unbounded "
                             f"wait under a lock is a deadlock waiting "
                             f"for its second thread")
                    continue
            if dotted:
                tail = dotted.split(".")[-1]
                parts = dotted.split(".")
                if tail == "fire" and ("faults" in parts[:3]
                                       or "plan" in parts[:3]):
                    has_path = any(kw.arg == "path"
                                   for kw in node.keywords) \
                        or len(node.args) >= 2
                    if has_path:
                        emit(info.mod, line, "CL802",
                             f"fault hook with a file 'path=' (torn-"
                             f"write form) fires while holding {held_s} "
                             f"— injected file I/O runs under the lock; "
                             f"the raise-only 'fire(site)' form is "
                             f"exempt")
        accesses.extend(
            _Access(a.cls_qual, a.attr, a.path, a.line,
                    a.held | frozenset(entry), a.in_init, a.snippet)
            for a in w.accesses)

    # ---- CL801: cycles + declared-order violations --------------------
    if select is None or "CL801" in select:
        _check_lock_order(pkg, edges, emit)

    # ---- CL803/804: guarded-by inference ------------------------------
    if select is None or select & {"CL803", "CL804"}:
        _check_guarded_by(pkg, accesses, emit)

    # ---- CL805: catalog completeness (whole-package scans only) -------
    if full_scan and (select is None or "CL805" in select):
        from ..faults.plan import FAULT_SITES

        for site in FAULT_SITES:
            if site not in hook_sites:
                findings.append(Finding(
                    rule="CL805", path="faults:catalog", line=0,
                    message=f"cataloged fault site {site!r} has no "
                            f"fire/corrupt hook call anywhere in the "
                            f"scanned package — dead catalog entry or a "
                            f"lost hook (docs/ROBUSTNESS.md site table)",
                    severity="error", snippet=site))

    return _Results(pkg, edges, findings)


def _check_lock_order(pkg: _Package, edges, emit) -> None:
    # declared-order violations: edge (B, A) against a declared A < B
    decl = {}
    for a, b, rel, line in pkg.order_decls:
        decl[(a, b)] = (rel, line)
    by_name = {}
    for (a, b), site in edges.items():
        by_name.setdefault((a.name, b.name), (a, b, site))
    for (a_name, b_name), (rel, dline) in decl.items():
        hit = by_name.get((b_name, a_name))
        if hit is not None:
            a, b, (path, line, _snip) = hit
            emit(pkg.mods[path], line, "CL801",
                 f"acquiring {b.render()} while holding {a.render()} "
                 f"contradicts the declared lock order "
                 f"'{a_name} < {b_name}' ({rel}:{dline})")
    # cycles: Tarjan SCCs over the identity graph
    graph: Dict[LockId, Set[LockId]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on_stack: Set[LockId] = set()
    stack: List[LockId] = []
    sccs: List[List[LockId]] = []
    counter = [0]

    def strongconnect(v: LockId) -> None:
        # iterative Tarjan (the graph is tiny; recursion would be fine,
        # but an explicit stack avoids pathological corpus depth)
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    for scc in sccs:
        members = sorted(scc)
        cyc_edges = [((a, b), edges[(a, b)]) for (a, b) in edges
                     if a in scc and b in scc]
        cyc_edges.sort(key=lambda e: e[1][:2])
        (a0, b0), (path, line, _snip) = cyc_edges[0]
        detail = "; ".join(
            f"{a.name} -> {b.name} at {p}:{ln}"
            for (a, b), (p, ln, _s) in cyc_edges)
        emit(pkg.mods[path], line, "CL801",
             f"lock-order cycle over {{{', '.join(m.name for m in members)}}}"
             f" — two threads interleaving these acquisitions deadlock: "
             f"{detail}. Impose one total order (document it with "
             f"'# consensus-lint: lock-order A < B')")


def _check_guarded_by(pkg: _Package, accesses: List[_Access],
                      emit) -> None:
    by_attr: Dict[Tuple[str, str], List[_Access]] = {}
    for a in accesses:
        if a.in_init:
            continue                     # pre-publication construction
        by_attr.setdefault((a.cls_qual, a.attr), []).append(a)
    for (cls_qual, attr), sites in sorted(by_attr.items()):
        cinfo = pkg.classes.get(cls_qual)
        if cinfo is None:
            continue
        annotated = None
        for c in pkg.mro(cinfo):
            if attr in c.guards:
                annotated = c.guards[attr]
                break
        if annotated == "none":
            continue
        guard: Optional[LockId] = None
        if annotated is not None:
            guard = pkg.lock_for_attr(cinfo, annotated)
            if guard is None:
                emit(cinfo.mod, cinfo.node.lineno, "CL804",
                     f"'# guarded-by: {annotated}' on "
                     f"{cinfo.name}.{attr} names no lock attribute "
                     f"resolvable on {cinfo.name}")
                continue
        else:
            if len(sites) < 2:
                continue                 # not enough evidence to infer
            votes: Dict[LockId, int] = {}
            for a in sites:
                for lid in a.held:
                    votes[lid] = votes.get(lid, 0) + 1
            majority = [lid for lid, n in votes.items()
                        if n * 2 > len(sites)]
            if majority:
                # several locks can clear the strict-majority bar (one
                # nested under another): the guard is the one held at
                # the MOST writes — alphabetical tie-break only between
                # equals, never over a better-supported lock
                guard = sorted(majority,
                               key=lambda lid: (-votes[lid], lid))[0]
            else:
                distinct = {a.held for a in sites}
                if len(distinct) > 1 and any(a.held for a in sites):
                    first = min(sites, key=lambda a: (a.path, a.line))
                    locksets = sorted(
                        "{" + ", ".join(sorted(l.name for l in h)) + "}"
                        for h in distinct)
                    emit(pkg.mods[first.path], first.line, "CL804",
                         f"attribute {cinfo.name}.{attr} is written "
                         f"under inconsistent lock sets "
                         f"({', '.join(locksets)}) with no majority "
                         f"guard — pick one lock and pin it with "
                         f"'# guarded-by: <lock>'")
                continue
        for a in sorted(sites, key=lambda a: (a.path, a.line)):
            if guard in a.held:
                continue
            if not a.held:
                why = ("annotated" if annotated
                       else "held at the majority of writes")
                emit(pkg.mods[a.path], a.line, "CL803",
                     f"write to {cinfo.name}.{attr} with no lock held — "
                     f"its guard is {guard.render()} ({why})")
            else:
                others = ", ".join(sorted(l.name for l in a.held))
                emit(pkg.mods[a.path], a.line, "CL804",
                     f"write to {cinfo.name}.{attr} holds {others} but "
                     f"not its guard {guard.render()} — inconsistent "
                     f"locking reads as protection and is not")


# -- public drivers ---------------------------------------------------------


def analyze_concurrency(paths=None, root=None,
                        select: Optional[Set[str]] = None
                        ) -> List[Finding]:
    """Run Layer 4 over ``paths`` (default: the installed package — a
    full scan, which also enables the CL805 catalog-completeness
    direction). The lock/call graph covers exactly the scanned files.
    Findings are sorted by (path, line, rule)."""
    files = scan_targets(paths, root)
    pkg = _Package(files)
    res = _analyze(pkg, select, full_scan=paths is None)
    uniq = {}
    for f in res.findings:
        uniq.setdefault((f.rule, f.path, f.line, f.message), f)
    return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule))


def lock_order_edges(paths=None, root=None) -> dict:
    """The static lock table + may-hold-before edge set, in the JSON
    shape :mod:`.witness` compares observed acquisition orders against:
    ``{"locks": {"path:line": name}, "edges": [[a_key, b_key], ...]}``
    where a key is the lock's defining ``path:line`` — the same site an
    instrumented lock records at construction time."""
    files = scan_targets(paths, root)
    pkg = _Package(files)
    res = _analyze(pkg, select=set(), full_scan=False)
    locks = {f"{lid.path}:{lid.line}": lid.name
             for lid in pkg.all_lock_ids()}
    edge_keys = sorted({(f"{a.path}:{a.line}", f"{b.path}:{b.line}")
                        for (a, b) in res.edges})
    return {"locks": locks, "edges": [list(e) for e in edge_keys]}
