"""Layer 1: the AST rule engine (JAX/TPU-specific lint).

Traced-context detection is static and conservative. A function is
*traced* when any of these hold:

- decorated with a trace wrapper (``@jax.jit``, ``@jax.vmap``,
  ``@functools.partial(jax.jit, ...)``, ``shard_map``, ``pallas_call``,
  ``checkpoint``/``remat``, ``grad``);
- its NAME is passed to a trace wrapper anywhere in the module
  (``jax.jit(fn)``, ``lax.scan(step, ...)``, ``jax.vmap(body)``), or to
  ``functools.partial`` whose result feeds one;
- it is a ``def`` nested inside a traced function;
- a traced function in the same module calls it by name (transitive
  closure — cross-module calls are Layer 2's job: tracing the real entry
  points catches what this static pass cannot see);
- the module opts in wholesale with a ``consensus-lint: traced-module``
  comment (the ops kernel modules), or the ``def`` line carries a
  ``consensus-lint: traced`` comment marker.

A ``consensus-lint: host`` comment marker on the ``def`` line opts a
function back out; a ``consensus-lint: disable=CL101,CL102`` (or bare
``noqa``) comment on the finding's line suppresses it in place.

Rule IDs are stable and documented in docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding

#: rule ID -> (severity, one-line description)
RULES = {
    "CL101": ("error", "host-device sync call inside a jit-traced context "
                       "(block_until_ready / .item() / np.asarray / "
                       "jax.device_get)"),
    "CL102": ("error", "Python if/while branching on a traced value "
                       "(use lax.cond / jnp.where / lax.while_loop)"),
    "CL103": ("error", "jax.random key passed to more than one sampling "
                       "call without an intervening split"),
    "CL104": ("error", "float64 literal or dtype in a kernel documented "
                       "f32/bf16 (traced context)"),
    "CL105": ("warning", "jnp.where whose branches are both weak Python "
                         "scalars — promotes to the default float dtype "
                         "(f64 on x64 hosts)"),
    "CL201": ("warning", "mutable default argument"),
    "CL202": ("warning", "bare except clause"),
    "CL203": ("warning", "unused module-level import"),
    "CL501": ("error", "obs span/metric emission inside a jit-traced or "
                       "shard_map context (telemetry is host-side only: "
                       "in traced code it runs once per TRACE, and span "
                       "exit is a host sync)"),
    "CL502": ("error", "host wall-clock timer (time.*) or PhaseTimer "
                       "inside a jit-traced context (measures tracing, "
                       "not execution)"),
    "CL601": ("error", "fault-injection hook (faults.fire / faults.corrupt "
                       "/ arming) inside a jit-traced or shard_map context "
                       "(injection sites are host-side only: in traced "
                       "code the armed-plan check bakes into the compiled "
                       "graph as a constant and the fault fires once per "
                       "TRACE, not per run)"),
    "CL701": ("error", "blocking wait / queue operation inside a "
                       "jit-traced context (time.sleep, queue get/put, "
                       "Event.wait, Lock.acquire, Future.result, serve "
                       "RequestQueue ops): it blocks once per TRACE — "
                       "never per execution — and a compiled graph that "
                       "appears to synchronize with other threads "
                       "actually baked the wait's side effects in as "
                       "constants; coordinate on the host, around the "
                       "dispatch"),
}

#: callables that trace their function argument into an XLA graph
_TRACE_WRAPPERS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "scan", "while_loop", "fori_loop", "cond", "switch", "shard_map",
    "pallas_call", "custom_jvp", "custom_vjp", "associative_scan",
}

#: jnp/lax calls that return HOST values (static under trace) — legal in
#: Python control flow inside traced code
_STATIC_SAFE_CALLS = {
    "issubdtype", "result_type", "promote_types", "finfo", "iinfo",
    "dtype", "can_cast", "isdtype", "ndim", "shape",
}

#: jax.random functions that CONSUME a key (reuse is the bug); the rest
#: (split/fold_in/key construction) derive fresh keys
_KEY_DERIVERS = {
    "split", "fold_in", "key", "PRNGKey", "key_data", "wrap_key_data",
    "clone", "key_impl",
}

#: np-rooted converter calls that force a device->host transfer when the
#: operand is traced
_NP_SYNC_CALLS = {"asarray", "array", "asanyarray", "ascontiguousarray"}

#: attribute calls that synchronize with the device regardless of root
_ATTR_SYNC_CALLS = {"item", "block_until_ready", "tolist"}

#: the obs package's emission API (CL501 sources). Kept to EMISSION
#: entry points — registration-only helpers would be equally wrong in
#: traced code, but emission is what actually corrupts measurements.
_OBS_API = {
    "span", "observe", "current_span", "counter", "gauge", "histogram",
    "value", "events", "report", "render_prom", "reset", "write_jsonl",
    "write_prom", "read_jsonl", "span_tree", "instrument_jit",
    "install_compile_monitor",
}

#: metric-object methods (CL501 when the receiver was built from an obs
#: call in the same scope)
_OBS_EMIT_METHODS = {"inc", "set", "observe", "set_attr"}

#: the faults package's injection/arming API (CL601 sources): hook names
#: that must only ever run host-side. Kept in sync with
#: pyconsensus_tpu.faults.__all__'s hook subset.
_FAULTS_API = {
    "fire", "corrupt", "arm", "disarm", "armed", "active_plan",
}

#: host wall-clock reads (CL502): under trace these stamp TRACE time into
#: whatever consumes them, and the jit cache makes later calls not even
#: re-run them
_TIME_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
    "time.monotonic_ns",
}


def _is_obs_dotted(dotted: Optional[str]) -> bool:
    """Whether a canonicalized dotted call path roots in the obs package:
    ``obs.span`` / ``obs.TRACER.span`` (from-import of the module, any
    relative depth strips to 'obs'), ``pyconsensus_tpu.obs.*``, or a name
    imported from the obs module (canon maps it to ``obs.<name>`` /
    ``pyconsensus_tpu.obs.<name>``)."""
    if not dotted:
        return False
    parts = dotted.split(".")
    if "obs" not in parts[:2]:
        return False
    if parts[0] == "obs" or (parts[0] == "pyconsensus_tpu"
                             and parts[1] == "obs"):
        leaf = parts[-1]
        return leaf in _OBS_API or leaf in _OBS_EMIT_METHODS or (
            len(parts) > (2 if parts[0] == "obs" else 3))
    return False


def _dotted(node: ast.AST) -> Optional[str]:
    """``jax.random.bernoulli`` -> that string; None for non-trivial roots."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Aliases:
    """Canonicalize the module's import aliases: jnp -> jax.numpy, ..."""

    def __init__(self, tree: ast.Module):
        self.map: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.map[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def canon(self, dotted: Optional[str]) -> Optional[str]:
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        head = self.map.get(head, head)
        return f"{head}.{rest}" if rest else head


def _line_directives(text: str) -> Dict[int, Set[str]]:
    """{lineno: set of suppressed rule IDs} ('*' = all) from
    ``# consensus-lint: disable=...`` / ``# noqa`` comments. Each
    comma-separated piece contributes its first whitespace token, so a
    suppression can carry its written rationale in the same comment:
    ``# consensus-lint: disable=CL802 — the journal write must commit
    under the session lock``."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if "#" not in line:
            continue
        comment = line[line.index("#"):]
        if "consensus-lint:" in comment and "disable=" in comment:
            ids = comment.split("disable=", 1)[1]
            out[i] = {s.split()[0] for s in ids.replace(";", ",").split(",")
                      if s.strip()}
        elif "# noqa" in comment:
            out[i] = {"*"}
    return out


def _in_comment(line: str, needle: str) -> bool:
    """True when ``needle`` appears inside the line's COMMENT part — a
    mention in a docstring or string literal is not a directive."""
    idx = line.find("#")
    return idx >= 0 and needle in line[idx:]


def _def_markers(text: str) -> Tuple[Set[int], Set[int]]:
    """Line numbers carrying explicit traced / host function markers."""
    traced, host = set(), set()
    for i, line in enumerate(text.splitlines(), 1):
        if (_in_comment(line, "consensus-lint: traced")
                and "traced-module" not in line):
            traced.add(i)
        if _in_comment(line, "consensus-lint: host"):
            host.add(i)
    return traced, host


_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


class _Module:
    """Per-module analysis state: alias map, function table, traced set."""

    def __init__(self, path: str, text: str, tree: ast.Module):
        self.path = path
        self.text = text
        self.tree = tree
        self.aliases = _Aliases(tree)
        self.traced_module = any(
            _in_comment(line, "consensus-lint: traced-module")
            for line in text.splitlines()[:40])
        self.marker_traced, self.marker_host = _def_markers(text)
        self.funcs: List[ast.AST] = [n for n in ast.walk(tree)
                                     if isinstance(n, _FuncNode)]
        self._parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.traced: Set[ast.AST] = set()
        self._compute_traced()

    # -- traced-context closure ------------------------------------------

    def _is_wrapper(self, func_expr: ast.AST) -> bool:
        dotted = self.aliases.canon(_dotted(func_expr))
        return bool(dotted) and dotted.split(".")[-1] in _TRACE_WRAPPERS

    def _traced_root_names(self) -> Set[str]:
        """Function NAMES passed to a trace wrapper (or via partial)."""
        roots: Set[str] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            is_wrap = self._is_wrapper(node.func)
            dotted = self.aliases.canon(_dotted(node.func)) or ""
            is_partial = dotted.split(".")[-1] == "partial"
            if is_partial and node.args:
                # partial(jax.jit, ...) -> remaining Name args are traced;
                # partial(fn, ...) whose result is handed to a wrapper is
                # resolved conservatively: treat the partial'd fn as traced
                # only when SOME wrapper call exists in the module — the
                # cheap over-approximation would flood host code, so
                # instead only partial(<wrapper>, fn) counts here.
                if self._is_wrapper(node.args[0]):
                    is_wrap = True
            if not is_wrap:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Attribute):
                    roots.add(arg.attr)       # self._fn / module.fn
                else:
                    # collect Names recursively: composition like
                    # jax.jit(exact_matmuls(_consensus_core)) traces the
                    # inner function too
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            roots.add(sub.id)
        return roots

    def _calls_in(self, fn: ast.AST) -> Set[str]:
        """Callables a function references: direct calls, self/cls method
        calls, and function NAMES passed as call arguments (wrapper
        composition like ``jax.jit(exact_matmuls(_consensus_core))`` —
        a function handed around inside traced code ends up traced)."""
        names: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                names.add(node.func.id)
            elif isinstance(node.func, ast.Attribute):
                root = _dotted(node.func.value)
                if root in ("self", "cls"):
                    names.add(node.func.attr)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
        return names

    def _compute_traced(self) -> None:
        by_name: Dict[str, List[ast.AST]] = {}
        for fn in self.funcs:
            by_name.setdefault(fn.name, []).append(fn)
        roots = self._traced_root_names()
        for fn in self.funcs:
            if fn.lineno in self.marker_host:
                continue
            if (self.traced_module or fn.name in roots
                    or fn.lineno in self.marker_traced
                    or self._has_trace_decorator(fn)):
                self.traced.add(fn)
        # nested defs + same-module call closure
        changed = True
        while changed:
            changed = False
            for fn in list(self.traced):
                for child in ast.walk(fn):
                    if (isinstance(child, _FuncNode) and child is not fn
                            and child not in self.traced
                            and child.lineno not in self.marker_host):
                        self.traced.add(child)
                        changed = True
                for callee in self._calls_in(fn):
                    for target in by_name.get(callee, []):
                        if (target not in self.traced
                                and target.lineno not in self.marker_host):
                            self.traced.add(target)
                            changed = True

    def _has_trace_decorator(self, fn: ast.AST) -> bool:
        for dec in fn.decorator_list:
            expr = dec.func if isinstance(dec, ast.Call) else dec
            if self._is_wrapper(expr):
                return True
            if isinstance(dec, ast.Call):
                dotted = self.aliases.canon(_dotted(dec.func)) or ""
                if dotted.split(".")[-1] == "partial" and dec.args \
                        and self._is_wrapper(dec.args[0]):
                    return True
        return False

    def enclosing_traced(self, node: ast.AST) -> bool:
        cur = node
        while cur is not None:
            if isinstance(cur, _FuncNode):
                return cur in self.traced
            cur = self._parents.get(cur)
        return False


# -- individual rules -----------------------------------------------------


def _walk_scope(fn: ast.AST):
    """Walk ``fn``'s body WITHOUT descending into nested ``def``s — each
    function is its own rule scope (nested defs are visited by their own
    pass), so findings are never double-reported. Lambdas stay in scope."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FuncNode):
            stack.extend(ast.iter_child_nodes(node))


def _srcline(mod: _Module, node: ast.AST) -> str:
    lines = mod.text.splitlines()
    i = getattr(node, "lineno", 0)
    return lines[i - 1].strip() if 0 < i <= len(lines) else ""


def _mk(mod: _Module, node: ast.AST, rule: str, message: str) -> Finding:
    sev = RULES[rule][0]
    return Finding(rule=rule, path=mod.path,
                   line=getattr(node, "lineno", 0), message=message,
                   severity=sev, snippet=_srcline(mod, node))


def _rule_host_sync(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            parts = dotted.split(".")
            if dotted == "jax.device_get" or (
                    parts[0] == "numpy" and parts[-1] in _NP_SYNC_CALLS):
                yield _mk(mod, node, "CL101",
                          f"'{dotted}' forces a device sync / host "
                          f"round-trip inside traced function "
                          f"'{fn.name}'")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ATTR_SYNC_CALLS
                    and not dotted.startswith(("numpy.", "jax.numpy."))):
                yield _mk(mod, node, "CL101",
                          f"'.{node.func.attr}()' synchronizes with the "
                          f"device inside traced function '{fn.name}'")


def _has_traced_value_call(mod: _Module, expr: ast.AST) -> Optional[str]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            if dotted.startswith(("jax.numpy.", "jax.lax.", "jax.random.")) \
                    and dotted.split(".")[-1] not in _STATIC_SAFE_CALLS:
                return dotted
    return None


def _rule_traced_branch(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        for node in _walk_scope(fn):
            if isinstance(node, (ast.If, ast.While)):
                hit = _has_traced_value_call(mod, node.test)
                if hit:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield _mk(mod, node, "CL102",
                              f"Python '{kind}' on traced value "
                              f"('{hit}') in '{fn.name}' — use lax.cond"
                              f"/jnp.where/lax.while_loop")


def _assigned_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.For):
            targets = [node.target]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


def _rule_key_reuse(mod: _Module) -> Iterable[Finding]:
    # scoped per function (module-level reuse is vanishingly rare here)
    for fn in mod.funcs:
        uses: Dict[str, List[ast.Call]] = {}
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            if not dotted.startswith("jax.random."):
                continue
            name = dotted.split(".")[-1]
            if name in _KEY_DERIVERS:
                continue
            key_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "key"), None)
            if isinstance(key_arg, ast.Name):
                uses.setdefault(key_arg.id, []).append(node)
        if not uses:
            continue
        reassigned = _assigned_names(fn)
        for key_name, sites in uses.items():
            if len(sites) > 1 and key_name not in reassigned:
                for site in sites[1:]:
                    yield _mk(mod, site, "CL103",
                              f"PRNG key '{key_name}' consumed by "
                              f"multiple jax.random draws in '{fn.name}' "
                              f"— split it first")


def _rule_f64_in_kernel(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        for node in _walk_scope(fn):
            if isinstance(node, ast.Attribute):
                dotted = mod.aliases.canon(_dotted(node)) or ""
                if dotted in ("jax.numpy.float64", "numpy.float64",
                              "jax.numpy.complex128"):
                    yield _mk(mod, node, "CL104",
                              f"'{dotted}' inside traced function "
                              f"'{fn.name}' (kernels are f32/bf16)")
            elif (isinstance(node, ast.Constant)
                    and node.value == "float64"):
                yield _mk(mod, node, "CL104",
                          f"dtype string 'float64' inside traced "
                          f"function '{fn.name}'")


def _rule_weak_where(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call) or len(node.args) != 3:
                continue
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            if dotted != "jax.numpy.where":
                continue
            a, b = node.args[1], node.args[2]
            if (isinstance(a, ast.Constant) and isinstance(b, ast.Constant)
                    and isinstance(a.value, float)
                    and isinstance(b.value, float)):
                yield _mk(mod, node, "CL105",
                          f"both branches of jnp.where in '{fn.name}' "
                          f"are weak Python floats — anchor one to an "
                          f"array dtype or the result promotes to the "
                          f"default float (f64 on x64 hosts)")


def _rule_mutable_default(mod: _Module) -> Iterable[Finding]:
    for fn in mod.funcs:
        for default in list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                yield _mk(mod, default, "CL201",
                          f"mutable default argument in '{fn.name}'")


def _rule_bare_except(mod: _Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield _mk(mod, node, "CL202",
                      "bare 'except:' swallows KeyboardInterrupt/"
                      "SystemExit — name the exception")


def _rule_unused_import(mod: _Module) -> Iterable[Finding]:
    if pathlib.PurePath(mod.path).name == "__init__.py":
        return                        # re-export surface
    bound: Dict[str, ast.AST] = {}
    for node in mod.tree.body:        # module level only
        if isinstance(node, ast.Import):
            for a in node.names:
                bound[a.asname or a.name.split(".")[0]] = node
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name != "*":
                    bound[a.asname or a.name] = node
        elif isinstance(node, ast.Try):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Import):
                    for a in sub.names:
                        bound[a.asname or a.name.split(".")[0]] = sub
                elif isinstance(sub, ast.ImportFrom) \
                        and sub.module != "__future__":
                    for a in sub.names:
                        if a.name != "*":
                            bound[a.asname or a.name] = sub
    if not bound:
        return
    used: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            used.add(node.value)      # __all__ strings, doctest refs
    for name, node in bound.items():
        if name not in used:
            yield _mk(mod, node, "CL203",
                      f"import '{name}' is never used")


def _obs_handle_names(mod: _Module, fn: ast.AST) -> Set[str]:
    """Names in ``fn`` assigned from an obs-rooted call — metric handles
    (``residual = obs.histogram(...)``) whose later ``.observe()`` /
    ``.inc()`` is still an obs emission."""
    out: Set[str] = set()
    for node in _walk_scope(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_obs_dotted(mod.aliases.canon(_dotted(node.value.func))):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _rule_obs_in_traced(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        handles = _obs_handle_names(mod, fn)
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            if _is_obs_dotted(dotted):
                yield _mk(mod, node, "CL501",
                          f"'{dotted}' emits telemetry inside traced "
                          f"function '{fn.name}' — spans/metrics run "
                          f"once per trace there and span exit is a "
                          f"host sync; emit from the host caller")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _OBS_EMIT_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in handles):
                yield _mk(mod, node, "CL501",
                          f"'.{node.func.attr}()' on obs metric handle "
                          f"'{node.func.value.id}' inside traced "
                          f"function '{fn.name}' — emit from the host "
                          f"caller")


def _is_faults_dotted(dotted: Optional[str]) -> bool:
    """Whether a canonicalized dotted call path is a faults-package hook:
    ``faults.fire`` / ``_faults.corrupt`` (any from-import of the plan
    module canonicalizes through the alias map), ``pyconsensus_tpu.
    faults.*``, or a hook name imported directly from the package
    (canon maps it to ``...faults.<name>`` / ``...faults.plan.<name>``)."""
    if not dotted:
        return False
    parts = dotted.split(".")
    if "faults" not in parts[:2] and not (
            len(parts) > 2 and parts[1] == "faults"):
        return False
    return parts[-1] in _FAULTS_API


def _rule_faults_in_traced(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            if _is_faults_dotted(dotted):
                yield _mk(mod, node, "CL601",
                          f"'{dotted}' is a fault-injection hook inside "
                          f"traced function '{fn.name}' — the armed-plan "
                          f"check would bake into the compiled graph and "
                          f"the fault would fire once per TRACE; inject "
                          f"from the host caller (docs/ROBUSTNESS.md "
                          f"site catalog)")


#: dotted calls that BLOCK the calling thread (CL701 direct sources)
_BLOCKING_CALLS = {
    "time.sleep", "concurrent.futures.wait",
    "concurrent.futures.as_completed", "futures.wait",
    "futures.as_completed", "select.select",
}

#: constructors whose instances expose blocking methods — a name
#: assigned from one of these becomes a CL701 handle (the
#: _obs_handle_names dataflow pattern)
_BLOCKING_CONSTRUCTORS = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "threading.Event", "threading.Lock",
    "threading.RLock", "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier",
    "concurrent.futures.Future", "futures.Future", "Future",
    "RequestQueue",
}

#: blocking methods on those handles. Deliberately NOT matched on
#: arbitrary receivers: ``.get``/``.join``/``.result`` are common benign
#: names (dict.get, str.join), so only handle-tracked receivers count.
_BLOCKING_METHODS = {
    "get", "put", "get_nowait", "put_nowait", "wait", "acquire",
    "result", "join", "take", "take_matching",
}


def _blocking_handle_names(mod: _Module, fn: ast.AST) -> Set[str]:
    """Names in ``fn`` assigned from a blocking-object constructor."""
    out: Set[str] = set()
    for node in _walk_scope(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            dotted = mod.aliases.canon(_dotted(node.value.func)) or ""
            if (dotted in _BLOCKING_CONSTRUCTORS
                    or dotted.split(".")[-1] in ("Queue", "SimpleQueue",
                                                 "Event", "Condition",
                                                 "Semaphore", "Barrier",
                                                 "RequestQueue", "Future")):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _rule_blocking_in_traced(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        handles = _blocking_handle_names(mod, fn)
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            if dotted in _BLOCKING_CALLS:
                yield _mk(mod, node, "CL701",
                          f"'{dotted}' blocks inside traced function "
                          f"'{fn.name}' — it runs once per TRACE, never "
                          f"per execution; wait on the host, around the "
                          f"dispatch")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BLOCKING_METHODS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in handles):
                yield _mk(mod, node, "CL701",
                          f"blocking '.{node.func.attr}()' on "
                          f"'{node.func.value.id}' (a queue/sync object) "
                          f"inside traced function '{fn.name}' — "
                          f"coordinate on the host, around the dispatch")


def _rule_host_timer_in_traced(mod: _Module) -> Iterable[Finding]:
    for fn in mod.traced:
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.aliases.canon(_dotted(node.func)) or ""
            if dotted in _TIME_CALLS:
                yield _mk(mod, node, "CL502",
                          f"'{dotted}' inside traced function "
                          f"'{fn.name}' stamps TRACE time into the "
                          f"graph (and never re-runs on cached calls) — "
                          f"time on the host, or use obs spans around "
                          f"the dispatch")
            elif dotted.split(".")[-1] == "PhaseTimer":
                yield _mk(mod, node, "CL502",
                          f"PhaseTimer constructed inside traced "
                          f"function '{fn.name}' — phase timing is "
                          f"host-side only")


_ALL_RULES = (
    _rule_host_sync, _rule_traced_branch, _rule_key_reuse,
    _rule_f64_in_kernel, _rule_weak_where, _rule_mutable_default,
    _rule_bare_except, _rule_unused_import, _rule_obs_in_traced,
    _rule_host_timer_in_traced, _rule_faults_in_traced,
    _rule_blocking_in_traced,
)


# -- driver ---------------------------------------------------------------


def lint_file(path, rel_path: Optional[str] = None,
              select: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one source file; returns findings sorted by line."""
    p = pathlib.Path(path)
    text = p.read_text(encoding="utf-8")
    rel = rel_path if rel_path is not None else p.name
    try:
        tree = ast.parse(text, filename=str(p))
    except SyntaxError as e:
        return [Finding(rule="CL000", path=rel, line=e.lineno or 0,
                        message=f"syntax error: {e.msg}", severity="error",
                        snippet="")]
    mod = _Module(rel, text, tree)
    directives = _line_directives(text)
    out: List[Finding] = []
    for rule_fn in _ALL_RULES:
        for f in rule_fn(mod):
            if select and f.rule not in select:
                continue
            suppressed = directives.get(f.line, set())
            if "*" in suppressed or f.rule in suppressed:
                continue
            out.append(f)
    return sorted(out, key=lambda f: (f.line, f.rule))


def default_scan_root() -> pathlib.Path:
    """The package's parent directory — paths are reported relative to it
    (``pyconsensus_tpu/...``), stable across checkouts and installs."""
    return pathlib.Path(__file__).resolve().parents[2]


def default_paths() -> List[pathlib.Path]:
    return [pathlib.Path(__file__).resolve().parents[1]]


def scan_targets(paths=None, root: Optional[pathlib.Path] = None
                 ) -> List[Tuple[pathlib.Path, str]]:
    """Resolve ``paths`` (files or directories, default: the installed
    pyconsensus_tpu package) to ``[(file, repo-relative posix path)]`` —
    the scope a run actually covers, which the baseline updater uses to
    preserve accepted entries OUTSIDE a restricted run's scope."""
    root = root or default_scan_root()
    targets = [pathlib.Path(p) for p in (paths or default_paths())]
    files: List[pathlib.Path] = []
    for t in targets:
        if t.is_dir():
            files.extend(sorted(t.rglob("*.py")))
        elif t.suffix == ".py":
            files.append(t)
    out = []
    for f in files:
        try:
            rel = f.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = f.name
        out.append((f, rel))
    return out


def lint_paths(paths=None, root: Optional[pathlib.Path] = None,
               select: Optional[Set[str]] = None) -> List[Finding]:
    """Lint ``paths`` (files or directories, default: the installed
    pyconsensus_tpu package). Findings are sorted by (path, line)."""
    out: List[Finding] = []
    for f, rel in scan_targets(paths, root):
        out.extend(lint_file(f, rel_path=rel, select=select))
    return sorted(out, key=lambda x: (x.path, x.line, x.rule))
