"""Runtime digest witness — the dynamic mirror of Layer 6 (CL1001-04).

:mod:`.determinism` proves statically that no unordered iteration,
completion-order fold, or host-nondeterministic value reaches a
digest/journal/ledger sink; this module checks the property the proof
is *about*: the digests the running code actually produces must be
**re-derivable from the durable artifacts alone**. A
:class:`DigestWitness` monkeypatches four digest-bearing surfaces
while installed:

- ``ReplicationLog.journal_block`` — records the (session, round,
  block) key and the content digest the journal writer computed, after
  the write returns;
- ``ReputationLedger.record_round`` — records the round number and a
  canonical-JSON digest of the history record the call appended (the
  record is pure derived scalars, so the SAME digest must fall out of
  the on-disk checkpoint on replay);
- ``ReplicationLog.commit_round`` — records the committing ledger's
  full history as a canonical digest list, keyed by log. The commit is
  what links an in-memory ledger to a durable checkpoint, so the
  replay comparison is per-log and exact — no cross-session round
  ambiguity;
- ``econ.scoreboard.mechanism_digest`` — records the digest AND
  recomputes it immediately over the reversed-insertion-order view of
  the same input dict. The function's sorted() fold makes it
  order-invariant by construction; a divergence here means someone
  edited the fold without keeping the invariant, and the witness
  raises at the call site, not at teardown.

:meth:`DigestWitness.check` then replays the durable side: every
journaled block file still on disk is re-read through the log's own
validating reader and its digest compared against the recorded one;
every committed log's ledger checkpoint is re-loaded through
``ReputationLedger._from_state`` and its replayed history digests
compared against the last recorded commit; and every witnessed
``record_round`` whose ledger was committed to a tracked log must
reappear digest-identical in that log's replayed history. The first
diverging op raises :class:`DeterminismWitnessViolation` naming the op
and BOTH digests — the exact two bits a failover postmortem needs.
Files the round commit's garbage collection already unlinked, log dirs
a test removed, and ledgers that never committed to any tracked log
are skipped: the witness constrains agreement, not retention.

The fleet and econ suites run under the witness via an autouse fixture
(the lock/protocol witness wiring precedent), and both CI chaos stages
install one around their kill/takeover loops.

Overhead: one digest + list append per journaled block / recorded
round / commit; nothing in the serving path imports this module.
"""

from __future__ import annotations

import contextlib
import functools
import hashlib
import importlib
import json
import pathlib
import threading
from typing import List, Optional, Tuple

__all__ = ["DigestWitness", "DeterminismWitnessViolation",
           "digest_witnessed"]

#: real constructor bound at import time so the witness's own state
#: lock is never itself a (lock-)witnessed proxy when both witnesses
#: are installed in the same test
_REAL_LOCK = threading.Lock


def _canonical_record_digest(record: dict) -> str:
    """Digest of one ledger history record in canonical JSON — the
    round's derived scalars, independent of dict insertion order."""
    return hashlib.sha256(
        json.dumps(record, sort_keys=True).encode()).hexdigest()


class DeterminismWitnessViolation(AssertionError):
    """A recorded digest does not match its replay from the durable
    artifact (or an order-invariance recompute). ``op`` names the
    diverging operation, ``recorded``/``replayed`` carry both digests,
    ``dump_path`` where the full witness JSON landed."""

    def __init__(self, message: str, op: str = "",
                 recorded: str = "", replayed: str = "",
                 dump_path: Optional[str] = None):
        super().__init__(message)
        self.op = op
        self.recorded = recorded
        self.replayed = replayed
        self.dump_path = dump_path


class DigestWitness:
    """Records the (op, digest) stream of every journaled block,
    recorded round, ledger commit, and mechanism digest while
    installed; :meth:`check` replays each through the durable artifact
    and raises on the first divergence. Use :func:`digest_witnessed`
    for the context-manager form."""

    def __init__(self):
        self._mu = _REAL_LOCK()
        #: [{"op", "key", "digest", ...}, ...] in call order
        self.records: List[dict] = []
        self._installed = False
        self._saved: List[Tuple[object, str, object]] = []

    # -- recording ------------------------------------------------------

    def _record(self, op: str, key: str, digest: str, **extra) -> None:
        with self._mu:
            self.records.append(
                {"op": op, "key": key, "digest": digest, **extra})

    # -- shims ----------------------------------------------------------

    def _wrap_journal_block(self, real):
        w = self

        @functools.wraps(real)
        def wrapper(log, round_idx, block_idx, block, event_bounds=None,
                    append_id=None):
            result = real(log, round_idx, block_idx, block,
                          event_bounds=event_bounds, append_id=append_id)
            from ..serve.failover import _digest
            import numpy as np

            blk = np.ascontiguousarray(block, dtype=np.float64)
            bounds_json = json.dumps(
                None if event_bounds is None
                else list(event_bounds)).encode()
            w._record(
                "journal_block",
                f"{log.name}:round{int(round_idx)}:block{int(block_idx)}",
                _digest(blk, bounds_json),
                root=str(log.dir.parent), name=log.name,
                round=int(round_idx), block=int(block_idx))
            return result

        return wrapper

    def _wrap_record_round(self, real):
        w = self

        @functools.wraps(real)
        def wrapper(ledger, result):
            out = real(ledger, result)
            w._record("record_round", f"round{int(ledger.round)}",
                      _canonical_record_digest(ledger.history[-1]),
                      round=int(ledger.round), ledger_id=id(ledger),
                      hist_index=len(ledger.history) - 1)
            return out

        return wrapper

    def _wrap_commit_round(self, real):
        w = self

        @functools.wraps(real)
        def wrapper(log, ledger):
            result = real(log, ledger)
            digests = [_canonical_record_digest(rec)
                       for rec in ledger.history]
            w._record(
                "commit_round", f"{log.name}:round{int(ledger.round)}",
                hashlib.sha256("".join(digests).encode()).hexdigest(),
                root=str(log.dir.parent), name=log.name,
                digests=digests, ledger_id=id(ledger))
            return result

        return wrapper

    def _wrap_mechanism_digest(self, real):
        w = self

        @functools.wraps(real)
        def wrapper(final_reps):
            digest = real(final_reps)
            # order-invariance recompute AT the call site: the reversed
            # insertion order must produce the identical digest (the
            # sorted() fold inside is the invariant Layer 6 trusts)
            reordered = real(dict(reversed(list(final_reps.items()))))
            if reordered != digest:
                raise DeterminismWitnessViolation(
                    f"mechanism_digest is insertion-order-dependent: "
                    f"{digest} (given order) vs {reordered} (reversed) "
                    f"over the same {len(final_reps)} market(s)",
                    op="mechanism_digest", recorded=digest,
                    replayed=reordered)
            w._record("mechanism_digest",
                      f"{len(final_reps)}markets", digest)
            return digest

        return wrapper

    def install(self) -> "DigestWitness":
        if self._installed:
            return self
        failover = importlib.import_module(
            "pyconsensus_tpu.serve.failover")
        ledger_mod = importlib.import_module("pyconsensus_tpu.ledger")
        scoreboard = importlib.import_module(
            "pyconsensus_tpu.econ.scoreboard")
        targets = (
            (failover.ReplicationLog, "journal_block",
             self._wrap_journal_block),
            (failover.ReplicationLog, "commit_round",
             self._wrap_commit_round),
            (ledger_mod.ReputationLedger, "record_round",
             self._wrap_record_round),
            (scoreboard, "mechanism_digest",
             self._wrap_mechanism_digest),
        )
        for holder, name, wrap in targets:
            real = (holder.__dict__[name] if isinstance(holder, type)
                    else getattr(holder, name))
            self._saved.append((holder, name, real))
            setattr(holder, name, wrap(real))
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for holder, name, real in self._saved:
            setattr(holder, name, real)
        self._saved = []
        self._installed = False

    # -- validation -----------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            return {"records": [dict(r) for r in self.records]}

    def dump(self, path) -> pathlib.Path:
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.report(), indent=2) + "\n",
                        encoding="utf-8")
        return path

    def _raise(self, op: str, recorded: str, replayed: str,
               dump_path) -> None:
        dumped = str(self.dump(dump_path)) if dump_path is not None \
            else None
        raise DeterminismWitnessViolation(
            f"digest divergence at {op}: recorded {recorded} at call "
            f"time, replayed {replayed} from the durable artifact"
            + (f" (witness dumped to {dumped})" if dumped else ""),
            op=op, recorded=recorded, replayed=replayed,
            dump_path=dumped)

    def check(self, dump_path=None) -> dict:
        """Replay every recorded digest through the durable side and
        assert agreement (see the module docstring for exactly what is
        replayed and what is skipped). Returns the report — augmented
        with ``recorded``/``checked``/``skipped`` counts — on success;
        dumps it and raises :class:`DeterminismWitnessViolation` on the
        first divergence."""
        from ..serve.failover import ReplicationLog, _digest
        from ..faults import CheckpointCorruptionError
        from ..ledger import ReputationLedger
        import numpy as np

        with self._mu:
            records = [dict(r) for r in self.records]
        # mechanism_digest entries were verified at the call site; they
        # count as checked without a teardown replay
        checked = sum(1 for r in records if r["op"] == "mechanism_digest")
        skipped = 0

        # the LAST commit per (root, name) is the checkpoint the file
        # currently holds (each save overwrites); its ledger_id links
        # the in-memory record_round stream to that log
        last_commit: dict = {}
        for rec in records:
            if rec["op"] == "commit_round":
                last_commit[(rec["root"], rec["name"])] = rec

        # replayed per-log history digests (None = artifact gone: skip)
        disk_history: dict = {}
        for (root, name), commit in last_commit.items():
            log = ReplicationLog(root, name)
            if not (log.dir.exists() and log.ledger_path.exists()):
                disk_history[(root, name)] = None
                continue
            try:
                led = ReputationLedger._from_state(
                    ReputationLedger._read_state(log.ledger_path),
                    source=str(log.ledger_path))
            except CheckpointCorruptionError:
                # a corruption test tore the checkpoint on purpose; the
                # runtime reader refuses it loudly — that refusal is the
                # tested behavior, not a digest disagreement
                disk_history[(root, name)] = None
                continue
            disk_history[(root, name)] = [
                _canonical_record_digest(rec) for rec in led.history]

        # commit replay: the checkpoint on disk must carry exactly the
        # history the last witnessed commit serialized
        for (root, name), commit in last_commit.items():
            replayed = disk_history[(root, name)]
            if replayed is None:
                skipped += 1
                continue
            checked += 1
            if replayed != commit["digests"]:
                diverge = next(
                    (i for i, (a, b) in enumerate(
                        zip(commit["digests"], replayed)) if a != b),
                    min(len(commit["digests"]), len(replayed)))
                rec_d = (commit["digests"][diverge]
                         if diverge < len(commit["digests"]) else "<absent>")
                rep_d = (replayed[diverge]
                         if diverge < len(replayed) else "<absent>")
                self._raise(
                    f"commit_round[{commit['key']}] history record "
                    f"{diverge}", rec_d, rep_d, dump_path)

        # record_round replay: a witnessed round whose ledger committed
        # to a tracked log must reappear digest-identical in that log's
        # replayed history (ledgers that never committed are skipped)
        log_of_ledger = {c["ledger_id"]: key
                         for key, c in last_commit.items()}
        for rec in records:
            if rec["op"] != "record_round":
                continue
            key = log_of_ledger.get(rec.get("ledger_id"))
            if key is None or disk_history.get(key) is None:
                skipped += 1
                continue
            replayed = disk_history[key]
            idx = int(rec["hist_index"])
            if idx >= len(replayed):
                skipped += 1
                continue    # recorded after the last commit: not durable
            checked += 1
            if rec["digest"] != replayed[idx]:
                self._raise(f"record_round[{rec['key']}]",
                            rec["digest"], replayed[idx], dump_path)

        # journal replay: every journaled block still on disk re-reads
        # to the digest the writer computed
        for rec in records:
            if rec["op"] != "journal_block":
                continue
            log = ReplicationLog(rec["root"], rec["name"])
            if not log.dir.exists():
                skipped += 1
                continue            # test tore the dir down: skip
            path = log._block_path(rec["round"], rec["block"])
            if not path.exists():
                skipped += 1
                continue            # GC'd by a later commit_round
            try:
                _, blk, bounds, _ = log._read_block(path)
            except CheckpointCorruptionError:
                skipped += 1
                continue    # deliberately torn record: the reader's
            # refusal IS the behavior corruption tests pin
            checked += 1
            replayed = _digest(
                np.ascontiguousarray(blk, dtype=np.float64),
                json.dumps(None if bounds is None
                           else list(bounds)).encode())
            if replayed != rec["digest"]:
                self._raise(f"journal_block[{rec['key']}]",
                            rec["digest"], replayed, dump_path)
        report = self.report()
        report.update(recorded=len(records), checked=checked,
                      skipped=skipped)
        return report


@contextlib.contextmanager
def digest_witnessed(check: bool = True, dump_path=None):
    """Install a fresh :class:`DigestWitness` for the block; on clean
    exit, :meth:`~DigestWitness.check` it. The witness is always
    uninstalled, even on error."""
    w = DigestWitness()
    w.install()
    try:
        yield w
    finally:
        w.uninstall()
    if check:
        w.check(dump_path=dump_path)
