"""Layer 5: distributed-protocol analysis (CL901-905).

PR 14/15 made the fleet's fault-tolerance story rest on ORDERING
invariants that lived only in comments and chaos tests: a worker acks
an append only after the journal write *then* the ship complete, a
resolve commits *then* ships, and any failure between durability and
ack fences the session or unlinks the record. ROADMAP items 3 and 4
rewrite exactly that code. This layer makes "acknowledged => durable or
fenced" a lint-enforced property — the same move Layer 4 made for the
lock hierarchy before the fleet went multi-process. Its runtime mirror
is :mod:`.protocol_witness`, exactly as :mod:`.witness` mirrors CL801.

Model
-----

**Protocol events** are call-site classified, interprocedurally:

- *journal* — ``.journal_block(...)``, or any call forwarding an
  ``append_id=`` keyword (the idempotency token travels WITH the
  journaling mutation; a call that threads it is the durability hop
  from the caller's perspective);
- *commit*  — ``.commit_round(...)`` (the ledger checkpoint);
- *ship*    — ``.ship_file(...)`` and anything that transitively calls
  it (``_ship_session``);
- *ack*     — ``Future.set_result(...)``, or a ``send_msg`` whose
  payload literal carries a ``"result"``/``"error"`` key (the RPC
  reply frame), or — for methods registered in a server dispatch table
  (a ``handlers()`` dict or an ``RpcServer({...})`` literal) — a
  ``return`` with a value (returning from a dispatch handler IS the
  ack: the frame goes out the moment the handler returns);
- *fence*   — ``.fence(...)`` or a ``self._fenced = ...`` store;
- *unlink*  — ``.unlink(...)`` (withdrawing a journal record).

journal/commit/ship/fence/unlink summaries grow to a fixpoint over the
package call graph (resolved the :mod:`.concurrency` way); *ack* stays
strictly lexical — an ack belongs to the function that replies, and
propagating it through helpers would blame callers for their callees'
replies.

Rules
-----

- **CL901 — durability ordering.** A flow-sensitive happens-before
  walk (branch-forked may-analysis; loop bodies model one request) over
  every function: an ack event after which a journal/commit/ship event
  is still reachable ON THE SAME PATH is a reply the crash right after
  it can orphan — the finding names both events. A ship observed
  before the journal/commit it must follow is the same reorder one hop
  earlier. And every ``except`` handler of a try whose body performs
  (or follows) durability must re-raise, fence the session, or unlink
  the record — swallowing an exception between durability and ack
  serves on with disks that disagree. Handlers nested inside another
  handler (best-effort cleanup, e.g. the fence call itself) are exempt.
- **CL902 — RPC surface drift.** Three surfaces extracted and diffed
  in all directions: the client method table (string literals fed to
  ``.call``/``._call_data``/``._rpc_future``, plus ``retry_call``-
  wrapped ``.call``), the server dispatch tables, and the
  ``Transport`` handle surface (public methods of every ``WorkerBase``
  subclass, pairwise). A method added to one side can't silently no-op.
- **CL903 — error-taxonomy soundness.** Every class defining an
  ``error_code`` must be in the ``ERROR_CODES`` registry (and vice
  versa), codes must be unique, every registered class must stay
  marshalable as ``cls(message, **context)`` (no extra required
  ``__init__`` params — ``wire.unmarshal_error`` reconstructs with
  exactly that shape), raise sites must use registered classes, and
  ``RETRYABLE_CODES`` must agree with the per-code retry semantics:
  every retryable code is somewhere raised with an honest
  ``retry_after_s=``, and every code raised with one is in the tuple.
- **CL904 — idempotency coverage.** A function that accepts the
  ``append_id`` token must USE it — forward it into a call or test it
  against the dedupe set; accepting and dropping it silently turns a
  retried append into a double fold. On whole-package scans the
  journal side must be matched by the replay side: an
  ``append_id in <dedupe set>`` membership guard and a
  ``.add(append_id)`` seeding call must both exist somewhere, or
  replay cannot recognize the records the journal deduplicates.
- **CL905 — retry-scope.** ``retry_call``/``@retry`` may only retry
  transient ``OSError`` surfaces: a ``retry_on=`` naming a taxonomy
  class (or ``Exception``/``BaseException``) retries a structured
  refusal that cannot become valid; a retry reached after the
  durability point replays a side effect; a retry inside a handler
  that fences is retrying across a fence.

``# consensus-lint: disable=CL90x — rationale`` suppresses in place.
:func:`happens_before` exports the static per-operation event graph
(the shape :mod:`.protocol_witness` validates observed orders against).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, NamedTuple, Optional, Set, Tuple

from .findings import Finding
from .rules import _dotted, _line_directives, _Module, scan_targets
from .concurrency import _FuncInfo, _Package

#: rule ID -> (severity, one-line description)
PROTOCOL_RULES = {
    "CL901": ("error", "durability ordering: an ack/reply/Future "
                       "resolution precedes the journal write or ship "
                       "on some path, a ship precedes its journal/"
                       "commit, or an exception path between durability "
                       "and ack neither re-raises, fences the session, "
                       "nor unlinks the record"),
    "CL902": ("error", "RPC surface drift: client method table, server "
                       "dispatch table, and WorkerBase handle surfaces "
                       "disagree (a method on one side silently no-ops "
                       "on the other)"),
    "CL903": ("error", "error-taxonomy drift: unregistered error_code "
                       "class / dead registry entry / duplicate code / "
                       "non-marshalable __init__ / RETRYABLE_CODES "
                       "inconsistent with retry_after_s raise sites"),
    "CL904": ("error", "idempotency gap: the append_id token is "
                       "accepted but dropped, or the journal side has "
                       "no matching replay dedupe guard/seeding"),
    "CL905": ("error", "retry-scope violation: retry_call/@retry "
                       "retries a taxonomy error or blanket Exception, "
                       "runs after the durability point, or runs "
                       "inside a fencing handler"),
}

#: call tails with a fixed protocol-event meaning (receiver-independent:
#: the names are unique to the replication/transport layer)
_JOURNAL_TAILS = {"journal_block"}
_COMMIT_TAILS = {"commit_round"}
_SHIP_TAILS = {"ship_file"}
_FENCE_TAILS = {"fence"}
_UNLINK_TAILS = {"unlink"}
_ACK_TAILS = {"set_result"}

#: client-side RPC invocation tails whose first string argument names a
#: method (CL902 client table)
_CLIENT_CALL_TAILS = {"call", "_call_data", "_rpc_future"}

#: retry_on= entries that retry everything, not a transient surface
_BLANKET_RETRY = {"Exception", "BaseException", "ConsensusError"}

#: qualname -> witness operation kind (the static half of the
#: happens-before graph :mod:`.protocol_witness` joins against)
PROTOCOL_OPS = {
    "session.append": ("DurableSession", "append"),
    "session.resolve": ("DurableSession", "resolve"),
    "worker.append": ("FleetWorkerProcess", "append"),
    "worker.submit_session": ("FleetWorkerProcess", "submit_session"),
    "worker.create_session": ("FleetWorkerProcess", "create_session"),
}


def _tail(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _scope_walk(node: ast.AST) -> Iterable[ast.AST]:
    """Walk without descending into nested defs/classes (their events
    belong to their own scope); lambda bodies stay in this scope."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            stack.append(child)


def _resolve_callee(pkg: _Package, info: _FuncInfo,
                    node: ast.Call) -> Optional[_FuncInfo]:
    """The scanned function a call lands in, or None — Name via module
    scope, ``self``/``cls``/``super()`` via the MRO, ``ClassName.m`` via
    the class scope, unique-method-name fallback last (the Layer 4
    resolution order)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        target = pkg.func_scope.get(info.mod.path, {}).get(fn.id)
        return pkg.infos.get(target) if target is not None else None
    if not isinstance(fn, ast.Attribute):
        return None
    if isinstance(fn.value, ast.Call) \
            and _dotted(fn.value.func) == "super" \
            and info.cls is not None:
        for c in pkg.mro(info.cls)[1:]:
            if fn.attr in c.methods:
                return pkg.infos.get(c.methods[fn.attr])
        return None
    root = _dotted(fn.value)
    if root in ("self", "cls") and info.cls is not None:
        for c in pkg.mro(info.cls):
            if fn.attr in c.methods:
                return pkg.infos.get(c.methods[fn.attr])
        return None
    cinfo = pkg.resolve_class(info.mod, root) if root else None
    if cinfo is not None:
        for c in pkg.mro(cinfo):
            if fn.attr in c.methods:
                return pkg.infos.get(c.methods[fn.attr])
        return None
    target = pkg.unique_method(fn.attr)
    if target is not None:
        return pkg.infos.get(target)
    return None


def _direct_kinds(node: ast.Call) -> Set[str]:
    """Receiver-independent event classification of one call site."""
    tail = _tail(node)
    kinds: Set[str] = set()
    if tail in _JOURNAL_TAILS:
        kinds.add("journal")
    if tail in _COMMIT_TAILS:
        kinds.add("commit")
    if tail in _SHIP_TAILS:
        kinds.add("ship")
    if tail in _FENCE_TAILS:
        kinds.add("fence")
    if tail in _UNLINK_TAILS:
        kinds.add("unlink")
    if tail in _ACK_TAILS:
        kinds.add("ack")
    if tail == "send_msg" and len(node.args) >= 2 \
            and isinstance(node.args[1], ast.Dict):
        for key in node.args[1].keys:
            if isinstance(key, ast.Constant) \
                    and key.value in ("result", "error"):
                kinds.add("ack")
                break
    for kw in node.keywords:
        if kw.arg == "append_id" and not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is None):
            kinds.add("journal")
            break
    return kinds


#: summary kinds that propagate interprocedurally (ack never does: the
#: ack belongs to the lexical replier)
_SUMMARY_KINDS = ("journal", "commit", "ship", "fence", "unlink")


def _grow_protocol_summaries(pkg: _Package) -> Dict[ast.AST, Set[str]]:
    """Per-function event summaries (journal/commit/ship/fence/unlink)
    grown to a fixpoint through resolvable calls."""
    summaries: Dict[ast.AST, Set[str]] = {}
    calls: Dict[ast.AST, List[Optional[_FuncInfo]]] = {}
    for fn, info in pkg.infos.items():
        direct: Set[str] = set()
        callees: List[Optional[_FuncInfo]] = []
        for node in _scope_walk(fn):
            if isinstance(node, ast.Call):
                direct |= _direct_kinds(node) & set(_SUMMARY_KINDS)
                callees.append(_resolve_callee(pkg, info, node))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and t.attr == "_fenced":
                        direct.add("fence")
        summaries[fn] = direct
        calls[fn] = callees
    for _ in range(16):
        changed = False
        for fn in pkg.infos:
            for callee in calls[fn]:
                if callee is None:
                    continue
                extra = summaries.get(callee.fn, set()) - summaries[fn]
                if extra:
                    summaries[fn] |= extra
                    changed = True
        if not changed:
            break
    return summaries


def _reply_methods(pkg: _Package) -> Tuple[Set[ast.AST], List[dict]]:
    """Dispatch-handler methods + server tables. A server table is a
    dict literal mapping string method names to ``self.<m>`` handlers —
    either returned from a function named ``handlers`` or passed to an
    ``RpcServer(...)`` construction. Returns (handler fn nodes,
    [{method: (mod, key lineno, class qual)} tables])."""
    reply: Set[ast.AST] = set()
    tables: List[dict] = []

    def harvest(d: ast.Dict, info: _FuncInfo) -> None:
        table: dict = {}
        for key, value in zip(d.keys, d.values):
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)):
                continue
            table[key.value] = (info.mod, key.lineno,
                                info.cls.qual if info.cls else "")
            if isinstance(value, ast.Attribute) \
                    and isinstance(value.value, ast.Name) \
                    and value.value.id == "self" and info.cls is not None:
                for c in pkg.mro(info.cls):
                    if value.attr in c.methods:
                        reply.add(c.methods[value.attr])
                        break
        if table:
            tables.append(table)

    for fn, info in pkg.infos.items():
        for node in _scope_walk(fn):
            if isinstance(node, ast.Return) and fn.name == "handlers" \
                    and isinstance(node.value, ast.Dict):
                harvest(node.value, info)
            elif isinstance(node, ast.Call) \
                    and _tail(node) == "RpcServer" and node.args \
                    and isinstance(node.args[0], ast.Dict):
                harvest(node.args[0], info)
    return reply, tables


# -- CL901: the flow-sensitive happens-before walk --------------------------


class _Event(NamedTuple):
    kind: str
    line: int
    label: str


class _PathState:
    """May-have-happened event sets along one path. Forked at branches,
    merged at joins — sets only grow, so the analysis is monotone."""

    __slots__ = ("acks", "durs")

    def __init__(self, acks=None, durs=None):
        self.acks: Dict[int, _Event] = dict(acks or {})
        self.durs: Dict[int, _Event] = dict(durs or {})

    def fork(self) -> "_PathState":
        return _PathState(self.acks, self.durs)


def _merge(*states: Optional[_PathState]) -> Optional[_PathState]:
    live = [s for s in states if s is not None]
    if not live:
        return None
    out = live[0].fork()
    for s in live[1:]:
        out.acks.update(s.acks)
        out.durs.update(s.durs)
    return out


class _FlowWalk:
    """One ordering walk over a function: emits CL901 ordering findings
    and the flow-dependent half of CL905. ``terms`` collect the states
    at value-returning exits of dispatch handlers (the return IS the
    ack) so a ``finally`` that ships after the reply is still seen."""

    def __init__(self, pkg: _Package, info: _FuncInfo,
                 summaries: Dict[ast.AST, Set[str]],
                 reply: Set[ast.AST], emit) -> None:
        self.pkg = pkg
        self.info = info
        self.summaries = summaries
        self.is_reply = info.fn in reply
        self.emit = emit

    # -- event classification ------------------------------------------

    def _kinds(self, node: ast.Call) -> Set[str]:
        kinds = set(_direct_kinds(node))
        if not kinds & {"journal", "commit", "ship"}:
            callee = _resolve_callee(self.pkg, self.info, node)
            if callee is not None:
                kinds |= self.summaries.get(callee.fn, set()) \
                    & {"journal", "commit", "ship"}
        return kinds

    def _label(self, node: ast.AST) -> str:
        lines = self.pkg.lines(self.info.mod)
        ln = getattr(node, "lineno", 0)
        return lines[ln - 1].strip() if 0 < ln <= len(lines) else ""

    # -- the walk -------------------------------------------------------

    def run(self) -> None:
        self._stmts(list(self.info.fn.body), _PathState(),
                    in_handler=False)

    def _stmts(self, stmts: List[ast.stmt], state: Optional[_PathState],
               in_handler: bool
               ) -> Tuple[Optional[_PathState], List[_PathState]]:
        terms: List[_PathState] = []
        for st in stmts:
            if state is None:
                break
            state, t = self._stmt(st, state, in_handler)
            terms.extend(t)
        return state, terms

    def _stmt(self, st: ast.stmt, state: _PathState, in_handler: bool
              ) -> Tuple[Optional[_PathState], List[_PathState]]:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return state, []
        if isinstance(st, ast.Return):
            if st.value is not None:
                self._expr(st.value, state, in_handler)
                if self.is_reply:
                    term = state.fork()
                    term.acks[st.lineno] = _Event(
                        "ack", st.lineno,
                        "dispatch-handler return (the reply frame)")
                    return None, [term]
            return None, []
        if isinstance(st, ast.Raise):
            if st.exc is not None:
                self._expr(st.exc, state, in_handler)
            return None, []
        if isinstance(st, (ast.If, ast.While)):
            self._expr(st.test, state, in_handler)
            o1, t1 = self._stmts(st.body, state.fork(), in_handler)
            o2, t2 = self._stmts(st.orelse, state.fork(), in_handler)
            if isinstance(st, ast.While):
                o1 = _merge(state, o1)
            return _merge(o1, o2), t1 + t2
        if isinstance(st, ast.For):
            self._expr(st.iter, state, in_handler)
            ob, tb = self._stmts(st.body, state.fork(), in_handler)
            oe, te = self._stmts(st.orelse,
                                 (_merge(state, ob) or state).fork(),
                                 in_handler)
            return _merge(state, ob, oe), tb + te
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr, state, in_handler)
            return self._stmts(st.body, state, in_handler)
        if isinstance(st, ast.Try):
            return self._try(st, state, in_handler)
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, state, in_handler)
        return state, []

    def _try(self, st: ast.Try, state: _PathState, in_handler: bool
             ) -> Tuple[Optional[_PathState], List[_PathState]]:
        relevant = (not in_handler) and (
            bool(state.durs) or self._body_has_durability(st.body))
        ob, tb = self._stmts(st.body, state.fork(), in_handler)
        # an exception can fire at any point in the body: the handlers
        # see the union of everything the body may have done
        handler_in = _merge(state, ob, *tb) or state
        outs: List[Optional[_PathState]] = []
        terms: List[_PathState] = list(tb)
        for h in st.handlers:
            if relevant:
                self._check_handler(h)
            oh, th = self._stmts(h.body, handler_in.fork(),
                                 in_handler=True)
            outs.append(oh)
            terms.extend(th)
        oe: Optional[_PathState] = ob
        if st.orelse:
            oe, te = self._stmts(st.orelse,
                                 ob.fork() if ob else handler_in.fork(),
                                 in_handler)
            terms.extend(te)
        out = _merge(oe, *outs)
        if st.finalbody:
            fin_in = _merge(out, *terms) or state
            of, tf = self._stmts(st.finalbody, fin_in.fork(), in_handler)
            terms.extend(tf)
            out = of if out is not None else None
        return out, terms

    def _body_has_durability(self, stmts: List[ast.stmt]) -> bool:
        for st in stmts:
            for node in _scope_walk(st):
                if isinstance(node, ast.Call) \
                        and self._kinds(node) & {"journal", "commit",
                                                 "ship"}:
                    return True
        return False

    def _check_handler(self, h: ast.ExceptHandler) -> None:
        """A handler on the durability path must re-raise, fence, or
        unlink; a handler that fences must not retry (CL905)."""
        reraises = fences = False
        retry_line = 0
        for node in _scope_walk(h):
            if isinstance(node, ast.Raise):
                reraises = True
            elif isinstance(node, ast.Call):
                kinds = _direct_kinds(node)
                callee = _resolve_callee(self.pkg, self.info, node)
                if callee is not None:
                    kinds |= self.summaries.get(callee.fn, set())
                if kinds & {"fence", "unlink"}:
                    fences = True
                if _tail(node) == "retry_call":
                    retry_line = node.lineno
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if any(isinstance(t, ast.Attribute) and t.attr == "_fenced"
                       for t in targets):
                    fences = True
        if not (reraises or fences):
            self.emit(self.info.mod, h.lineno, "CL901",
                      f"exception path between durability and ack in "
                      f"'{self.info.name}' neither re-raises, fences "
                      f"the session, nor unlinks the journal record — "
                      f"swallowing here serves on with memory, local "
                      f"disk, and the standby's disk free to disagree "
                      f"about an acknowledged write")
        if fences and not reraises and retry_line:
            self.emit(self.info.mod, retry_line, "CL905",
                      f"retry_call inside a fencing handler of "
                      f"'{self.info.name}' — the fence declares this "
                      f"session unserveable; retrying across it serves "
                      f"from state the fence just disowned")

    def _expr(self, node: ast.AST, state: _PathState,
              in_handler: bool) -> None:
        if isinstance(node, ast.Call):
            for a in node.args:
                self._expr(a, state, in_handler)
            for kw in node.keywords:
                self._expr(kw.value, state, in_handler)
            self._call(node, state)
            return
        if isinstance(node, ast.Lambda):
            self._expr(node.body, state, in_handler)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, state, in_handler)

    def _call(self, node: ast.Call, state: _PathState) -> None:
        kinds = self._kinds(node)
        line = node.lineno
        label = self._label(node)
        if _tail(node) == "retry_call" \
                and {k for e in state.durs.values()
                     for k in (e.kind,)} & {"journal", "commit"}:
            first = min(state.durs.values(), key=lambda e: e.line)
            self.emit(self.info.mod, line, "CL905",
                      f"retry_call after the durability point "
                      f"('{first.label}' at line {first.line}) in "
                      f"'{self.info.name}' — a retried side effect "
                      f"after the journal write replays a mutation the "
                      f"log already holds")
        for kind in ("journal", "commit", "ship"):
            if kind not in kinds:
                continue
            if state.acks:
                ack = min(state.acks.values(), key=lambda e: e.line)
                self.emit(self.info.mod, line, "CL901",
                          f"ack '{ack.label}' at line {ack.line} "
                          f"precedes the {kind} event '{label}' at "
                          f"line {line} in '{self.info.name}' — a "
                          f"crash between them acknowledges a write "
                          f"that is not durable everywhere a takeover "
                          f"reads")
            if kind in ("journal", "commit") \
                    and any(e.kind == "ship" for e in state.durs.values()):
                ship = min((e for e in state.durs.values()
                            if e.kind == "ship"), key=lambda e: e.line)
                self.emit(self.info.mod, line, "CL901",
                          f"ship '{ship.label}' at line {ship.line} "
                          f"precedes the {kind} event '{label}' at "
                          f"line {line} in '{self.info.name}' — the "
                          f"standby receives a record the local "
                          f"journal does not hold yet")
            state.durs[line] = _Event(kind, line, label)
        if "ack" in kinds:
            state.acks[line] = _Event("ack", line, label)


# -- CL902: surface extraction ----------------------------------------------


def _client_methods(pkg: _Package) -> List[Tuple[_Module, int, str]]:
    out: List[Tuple[_Module, int, str]] = []
    for fn, info in pkg.infos.items():
        for node in _scope_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(node)
            if tail in _CLIENT_CALL_TAILS and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                out.append((info.mod, node.lineno, node.args[0].value))
            elif tail == "retry_call" and len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Attribute) \
                    and node.args[0].attr == "call" \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                out.append((info.mod, node.lineno, node.args[1].value))
    return out


def _check_surfaces(pkg: _Package, tables: List[dict], emit,
                    full_scan: bool) -> None:
    served: Set[str] = set()
    for table in tables:
        served |= set(table)
    clients = _client_methods(pkg)
    if tables:
        for mod, line, method in clients:
            if method not in served:
                emit(mod, line, "CL902",
                     f"client invokes rpc method {method!r} but no "
                     f"scanned server dispatch table serves it — the "
                     f"call can only ever raise 'unknown rpc method'")
    if full_scan and tables and clients:
        used = {m for _, _, m in clients}
        for table in tables:
            for method, (mod, line, cls) in sorted(table.items()):
                if method not in used:
                    emit(mod, line, "CL902",
                         f"server dispatch table entry {method!r} "
                         f"({cls or 'table'}) has no client invocation "
                         f"anywhere in the package — dead surface, or "
                         f"a client-side method lost its wiring")
    # -- handle-surface diff: every WorkerBase subclass must expose the
    # same public method set (the Transport contract in base.py)
    if not full_scan:
        return
    base_methods: Set[str] = set()
    subclasses = []
    for qual, cinfo in sorted(pkg.classes.items()):
        if cinfo.name == "WorkerBase":
            base_methods |= set(cinfo.methods)
        elif any(b.split(".")[-1] == "WorkerBase" for b in cinfo.bases):
            subclasses.append(cinfo)
    if len(subclasses) < 2:
        return
    surfaces = {
        c.qual: {m for m in c.methods
                 if not m.startswith("_") and m not in base_methods}
        for c in subclasses}
    for c in subclasses:
        for m in sorted(surfaces[c.qual]):
            missing = [o.name for o in subclasses
                       if o is not c and m not in surfaces[o.qual]]
            if missing:
                emit(c.mod, c.methods[m].lineno, "CL902",
                     f"handle method '{m}' exists on {c.name} but not "
                     f"on {', '.join(missing)} — the Transport handle "
                     f"surfaces must agree or the fleet behaves "
                     f"differently per transport")


# -- CL903: taxonomy extraction ---------------------------------------------


class _Taxonomy(NamedTuple):
    classes: Dict[str, Tuple[str, _Module, int, ast.ClassDef]]
    registered: Set[str]
    registry_site: Optional[Tuple[_Module, int]]
    retryable: Optional[Tuple[List[str], _Module, int]]


def _collect_taxonomy(pkg: _Package) -> _Taxonomy:
    classes: Dict[str, Tuple[str, _Module, int, ast.ClassDef]] = {}
    registered: Set[str] = set()
    registry_site = None
    retryable = None
    for rel, mod in sorted(pkg.mods.items()):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.targets[0], ast.Name) \
                            and sub.targets[0].id == "error_code" \
                            and isinstance(sub.value, ast.Constant) \
                            and isinstance(sub.value.value, str):
                        classes.setdefault(
                            node.name,
                            (sub.value.value, mod, node.lineno, node))
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name == "ERROR_CODES" \
                        and isinstance(node.value, ast.DictComp):
                    it = node.value.generators[0].iter
                    if isinstance(it, (ast.Tuple, ast.List)):
                        registered |= {e.id for e in it.elts
                                       if isinstance(e, ast.Name)}
                        registry_site = (mod, node.lineno)
                elif name == "RETRYABLE_CODES" \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    codes = [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str)]
                    retryable = (codes, mod, node.lineno)
    return _Taxonomy(classes, registered, registry_site, retryable)


def _check_taxonomy(pkg: _Package, tax: _Taxonomy, emit,
                    full_scan: bool) -> None:
    if tax.registry_site is None:
        return
    reg_mod, reg_line = tax.registry_site
    by_code: Dict[str, str] = {}
    for name, (code, mod, line, node) in sorted(tax.classes.items()):
        if name not in tax.registered:
            emit(mod, line, "CL903",
                 f"taxonomy class {name} defines error_code {code!r} "
                 f"but is not in the ERROR_CODES registry — its errors "
                 f"cross the wire as the generic remote-failure shape, "
                 f"code and context lost")
        prior = by_code.get(code)
        if prior is not None:
            emit(mod, line, "CL903",
                 f"error_code {code!r} is claimed by both {prior} and "
                 f"{name} — the registry maps each code to ONE class; "
                 f"a duplicate silently shadows on unmarshal")
        by_code.setdefault(code, name)
        # marshalability: wire.unmarshal_error reconstructs with
        # cls(message, **context) — extra required params break it
        for sub in node.body:
            if not (isinstance(sub, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
                    and sub.name == "__init__"):
                continue
            a = sub.args
            required = (a.posonlyargs + a.args)[1:]
            n_defaults = len(a.defaults)
            bad = [p.arg for i, p in enumerate(required)
                   if i < len(required) - n_defaults]
            kwonly_bad = [p.arg for p, d in zip(a.kwonlyargs, a.kw_defaults)
                          if d is None]
            if bad or kwonly_bad or a.kwarg is None:
                emit(mod, sub.lineno, "CL903",
                     f"{name}.__init__ is not marshalable as "
                     f"cls(message, **context): required params "
                     f"{bad + kwonly_bad or '(no **context)'} — "
                     f"unmarshal_error cannot rebuild it client-side "
                     f"with code and context intact")
    for name in sorted(tax.registered - set(tax.classes)):
        emit(reg_mod, reg_line, "CL903",
             f"ERROR_CODES registers {name} but no scanned class of "
             f"that name defines an error_code — dead registry entry")
    # raise sites must use registered classes
    hint_codes: Dict[str, List[Tuple[_Module, int]]] = {}
    for fn, info in pkg.infos.items():
        for node in _scope_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(node)
            entry = tax.classes.get(tail)
            if entry is None:
                continue
            if tail not in tax.registered:
                emit(info.mod, node.lineno, "CL903",
                     f"raise site constructs unregistered taxonomy "
                     f"class {tail} — its {entry[0]!r} code does not "
                     f"survive the wire")
            if any(kw.arg == "retry_after_s" for kw in node.keywords):
                hint_codes.setdefault(entry[0], []).append(
                    (info.mod, node.lineno))
    if tax.retryable is None:
        return
    codes, rmod, rline = tax.retryable
    known = {code for code, *_ in tax.classes.values()}
    for code in codes:
        if code not in known:
            emit(rmod, rline, "CL903",
                 f"RETRYABLE_CODES lists {code!r} but no scanned "
                 f"taxonomy class carries that code")
        elif full_scan and code not in hint_codes:
            emit(rmod, rline, "CL903",
                 f"RETRYABLE_CODES lists {code!r} but no raise site in "
                 f"the package offers a retry_after_s hint for it — "
                 f"clients are told to retry with no honest window")
    for code, sites in sorted(hint_codes.items()):
        if code not in codes:
            mod, line = sites[0]
            emit(mod, line, "CL903",
                 f"{code} is raised with a retry_after_s hint here but "
                 f"is not in RETRYABLE_CODES — the client-side retry "
                 f"policy will drop a retry the server priced")


# -- CL904: idempotency-token threading -------------------------------------


def _check_idempotency(pkg: _Package, emit, full_scan: bool) -> None:
    journal_with_token = False
    has_guard = has_seed = False
    for fn, info in pkg.infos.items():
        params = fn.args
        names = {a.arg for a in (params.posonlyargs + params.args
                                 + params.kwonlyargs)}
        takes_token = "append_id" in names
        uses_token = False
        for node in _scope_walk(fn):
            if isinstance(node, ast.Call):
                passes = any(
                    kw.arg == "append_id" or (
                        kw.arg is None
                        and isinstance(kw.value, ast.Name)
                        and kw.value.id == "append_id")
                    for kw in node.keywords) or any(
                    isinstance(a, ast.Name) and a.id == "append_id"
                    for a in node.args)
                if passes:
                    uses_token = True
                if _tail(node) in _JOURNAL_TAILS and any(
                        kw.arg == "append_id" for kw in node.keywords):
                    journal_with_token = True
                if _tail(node) == "add" and any(
                        isinstance(a, ast.Name) and a.id == "append_id"
                        for a in node.args):
                    has_seed = True
            elif isinstance(node, ast.Dict):
                # forwarding the token inside a wire params literal
                # ({"append_id": append_id}) threads it too
                if any(isinstance(v, ast.Name) and v.id == "append_id"
                       for v in node.values):
                    uses_token = True
            elif isinstance(node, ast.Compare):
                if isinstance(node.left, ast.Name) \
                        and node.left.id == "append_id" \
                        and any(isinstance(op, ast.In)
                                for op in node.ops):
                    has_guard = True
                    uses_token = True
        if takes_token and not uses_token:
            emit(info.mod, fn.lineno, "CL904",
                 f"'{fn.name}' accepts the append_id idempotency token "
                 f"and drops it — the journal record it leads to can "
                 f"never be deduplicated, so a retried append folds "
                 f"twice")
    if full_scan and journal_with_token:
        if not has_guard:
            emit(None, 0, "CL904",
                 "the journal threads append_id but no scanned code "
                 "membership-tests it against a dedupe set — a retried "
                 "append is journaled (and folded) twice",
                 path="protocol:idempotency")
        if not has_seed:
            emit(None, 0, "CL904",
                 "the journal threads append_id but no scanned code "
                 "seeds a dedupe set from it (.add(append_id)) — "
                 "replay on the standby cannot recognize already-"
                 "applied records", path="protocol:idempotency")


# -- CL905: retry_on inspection (flow-independent half) ---------------------


def _check_retry_scope(pkg: _Package, tax: _Taxonomy, emit) -> None:
    for fn, info in pkg.infos.items():
        for node in _scope_walk(fn):
            if not isinstance(node, ast.Call) \
                    or _tail(node) not in ("retry_call", "retry"):
                continue
            for kw in node.keywords:
                if kw.arg != "retry_on":
                    continue
                elts = kw.value.elts \
                    if isinstance(kw.value, (ast.Tuple, ast.List)) \
                    else [kw.value]
                for e in elts:
                    name = (_dotted(e) or "").split(".")[-1]
                    if name in tax.classes or name in _BLANKET_RETRY:
                        emit(info.mod, node.lineno, "CL905",
                             f"retry_on includes {name} — a structured "
                             f"refusal does not become valid by "
                             f"retrying; only transient OSError "
                             f"surfaces ride the bounded-retry path")


# -- drivers ----------------------------------------------------------------


def _analyze(pkg: _Package, select: Optional[Set[str]],
             full_scan: bool) -> List[Finding]:
    directives = {rel: _line_directives(mod.text)
                  for rel, mod in pkg.mods.items()}
    findings: List[Finding] = []

    def emit(mod: Optional[_Module], line: int, rule: str,
             message: str, path: Optional[str] = None) -> None:
        if select is not None and rule not in select:
            return
        if mod is not None:
            sup = directives.get(mod.path, {}).get(line, set())
            if "*" in sup or rule in sup:
                return
            lines = pkg.lines(mod)
            snippet = lines[line - 1].strip() \
                if 0 < line <= len(lines) else ""
            rel = mod.path
        else:
            snippet, rel = "", path or "protocol:package"
        findings.append(Finding(
            rule=rule, path=rel, line=line, message=message,
            severity=PROTOCOL_RULES[rule][0], snippet=snippet))

    summaries = _grow_protocol_summaries(pkg)
    reply, tables = _reply_methods(pkg)
    tax = _collect_taxonomy(pkg)
    if select is None or select & {"CL901", "CL905"}:
        for fn, info in pkg.infos.items():
            _FlowWalk(pkg, info, summaries, reply, emit).run()
    if select is None or "CL902" in select:
        _check_surfaces(pkg, tables, emit, full_scan)
    if select is None or "CL903" in select:
        _check_taxonomy(pkg, tax, emit, full_scan)
    if select is None or "CL904" in select:
        _check_idempotency(pkg, emit, full_scan)
    if select is None or "CL905" in select:
        _check_retry_scope(pkg, tax, emit)
    return findings


def analyze_protocol(paths=None, root=None,
                     select: Optional[Set[str]] = None) -> List[Finding]:
    """Run Layer 5 over ``paths`` (default: the installed package — a
    full scan, which also enables the whole-surface CL902 direction,
    the RETRYABLE coverage direction of CL903, and the package-level
    CL904 dedupe checks). Findings sorted by (path, line, rule)."""
    files = scan_targets(paths, root)
    pkg = _Package(files)
    findings = _analyze(pkg, select, full_scan=paths is None)
    uniq = {}
    for f in findings:
        uniq.setdefault((f.rule, f.path, f.line, f.message), f)
    return sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule))


# -- the static happens-before export ---------------------------------------


def _success_events(pkg: _Package, info: _FuncInfo,
                    summaries: Dict[ast.AST, Set[str]],
                    visited: Set[ast.AST], out: List[str]) -> None:
    """Direct journal/commit/ship events on the success path of
    ``info``, in program order, with resolvable calls inlined (handlers
    skipped — the success path is the one that acks)."""
    if info.fn in visited:
        return
    visited.add(info.fn)

    def walk_expr(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            for a in node.args:
                walk_expr(a)
            for kw in node.keywords:
                walk_expr(kw.value)
            kinds = _direct_kinds(node) & {"journal", "commit", "ship"}
            if kinds:
                out.extend(sorted(kinds))
                return
            callee = _resolve_callee(pkg, info, node)
            if callee is not None and summaries.get(callee.fn, set()) \
                    & {"journal", "commit", "ship"}:
                _success_events(pkg, callee, summaries, visited, out)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                walk_expr(child)

    def walk_stmts(stmts: List[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, ast.Try):
                walk_stmts(st.body)
                walk_stmts(st.orelse)
                walk_stmts(st.finalbody)
                continue
            if isinstance(st, (ast.If, ast.While, ast.For)):
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        walk_expr(child)
                walk_stmts(st.body)
                walk_stmts(st.orelse)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    walk_expr(item.context_expr)
                walk_stmts(st.body)
                continue
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    walk_expr(child)

    walk_stmts(list(info.fn.body))


def happens_before(paths=None, root=None) -> dict:
    """The static per-operation happens-before graph, in the JSON shape
    :mod:`.protocol_witness` validates observed event orders against:
    ``{"ops": {kind: {"order": [...], "edges": [[a, b], ...],
    "function": "path:Class.method"}}}``. An edge ``[a, b]`` asserts
    that within one operation every ``a`` completes before any ``b``;
    the terminal ``ack`` is the operation's successful return."""
    files = scan_targets(paths, root)
    pkg = _Package(files)
    summaries = _grow_protocol_summaries(pkg)
    ops: Dict[str, dict] = {}
    by_name = {c.name: c for c in pkg.classes.values()}
    for kind, (cls_name, method) in sorted(PROTOCOL_OPS.items()):
        cinfo = by_name.get(cls_name)
        if cinfo is None or method not in cinfo.methods:
            continue
        info = pkg.infos.get(cinfo.methods[method])
        if info is None:
            continue
        seq: List[str] = []
        _success_events(pkg, info, summaries, set(), seq)
        seq.append("ack")
        order: List[str] = []
        for k in seq:
            if k not in order:
                order.append(k)
        edges = [[a, b] for i, a in enumerate(order)
                 for b in order[i + 1:]]
        ops[kind] = {"order": order, "edges": edges,
                     "function": f"{cinfo.mod.path}:{cls_name}.{method}"}
    return {"ops": ops}
