"""Layer 3b: collective-schedule extraction + deadlock detection (CL41x).

Layer 2 inspects post-GSPMD HLO — the right artifact for *budgets*, but
a single process's compiled program cannot show the *structural* hangs:
a ``lax.cond`` whose branches issue different collective sequences (the
branch not taken compiles fine; the fleet hangs the first time predicates
diverge), a hand-written ``ppermute`` whose permutation is not a
bijection on its mesh axis (some device waits for a message nobody
sends — the ring modules build perms in Python, one typo hangs
silently), or a collective naming an axis no enclosing ``shard_map``
binds. These live in the *jaxpr*, before partitioning, where the
branch/loop structure is still explicit — so this layer traces the real
entry points with ``jax.make_jaxpr`` (cheap: abstract evaluation, no
compile) and walks the jaxpr tree:

- **CL411** — every ``lax.cond``/``switch``: all branches must issue the
  IDENTICAL collective sequence (op kind + axes, in order). Under SPMD a
  replicated predicate makes an imbalance latent, not safe: the first
  divergent predicate (a NaN on one host, CL401's divergent values)
  deadlocks the fleet inside the longer branch.
- **CL412** — every ``ppermute``: the permutation must be a bijection on
  the full axis index set (each index exactly once as source and as
  destination, all in range). jax accepts partial perms (missing
  receivers get zeros), but in a hand-written ring a non-total perm is
  a dropped hop — and duplicate sources/destinations hang outright.
- **CL413** — every collective's axis names must be bound by an
  enclosing ``shard_map`` (or the target's declared axis environment).

``while_loop`` bodies are walked recursively: the body is one fixed
jaxpr, so its per-iteration collective sequence is structurally
identical by construction once nested conds are balanced (checked) and
the predicate is replicated (divergent predicates are Layer 3a's CL401
and shard_map's vma check); the cond jaxpr is walked too.

Targets (``SCHEDULES``) are the real hand-written-collective entry
points: the ring primitives (``ring_allreduce`` under ``shard_map``,
``ring_gram``, ``ring_matvec``, ``ring_first_pc``), the fused
shard_map executable (binary and NA variants), the streaming panel
kernel (collective-free; walked so a regression that introduces an
unbound or malformed collective is caught), and the GSPMD light
pipeline (its jaxpr carries no explicit collectives — GSPMD inserts
them post-partitioning, which Layer 2 budgets — but its ``cond``
structure is balance-checked here).

A target that fails to trace reports **CL410** (same contract as Layer
2's CL300: the trace failure IS the signal).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from .contracts import N_DEV
from .findings import Finding

SCHEDULE_RULES = {
    "CL410": ("error", "schedule target failed to trace"),
    "CL411": ("error", "lax.cond/switch branches issue different "
                       "collective sequences (deadlock on divergent "
                       "predicates)"),
    "CL412": ("error", "ppermute permutation is not a bijection on its "
                       "mesh axis (some device hangs waiting for a "
                       "message nobody sends)"),
    "CL413": ("error", "collective uses an axis name not bound by an "
                       "enclosing shard_map / declared axis environment"),
}

#: jaxpr primitives that move data across a named mesh axis
_COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "psum2",
    "all_gather_invariant",
}
#: primitives that only QUERY the axis (no communication): axis-binding
#: checked, but not part of the schedule (imbalance across branches is
#: harmless)
_AXIS_QUERY_PRIMS = {"axis_index", "axis_size"}


def _axis_names(params: dict) -> Tuple[str, ...]:
    """Normalize a collective eqn's axis parameter (``axes=('event',)``
    for psum/pmax, ``axis_name='event'`` or a tuple for
    ppermute/all_gather) to a tuple of names."""
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _sub_jaxprs(params: dict):
    """Every (key, jaxpr) nested in an eqn's params — covers cond
    branches, while cond/body, scan/pjit/shard_map/custom_* bodies —
    without depending on any one primitive's param spelling."""
    import jax.core as core

    ClosedJaxpr = core.ClosedJaxpr
    Jaxpr = core.Jaxpr
    for key, val in params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, ClosedJaxpr):
                yield key, v.jaxpr
            elif isinstance(v, Jaxpr):
                yield key, v


def _mesh_axis_sizes(params: dict) -> Dict[str, int]:
    """Axis name -> size from a shard_map eqn's mesh param (shaped like
    ``Mesh``/``AbstractMesh``: a ``.shape`` mapping)."""
    mesh = params.get("mesh")
    shape = getattr(mesh, "shape", None)
    if shape is None:
        return {}
    try:
        return {str(k): int(v) for k, v in dict(shape).items()}
    except (TypeError, ValueError):                 # pragma: no cover
        return {}


def _check_perm(perm, size: Optional[int]) -> Optional[str]:
    """Why ``perm`` is not a bijection on a ``size``-element axis
    (None = fine)."""
    try:
        pairs = [(int(s), int(d)) for s, d in perm]
    except (TypeError, ValueError):
        return f"malformed perm {perm!r}"
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    if len(set(srcs)) != len(srcs):
        return f"duplicate source indices in perm {pairs}"
    if len(set(dsts)) != len(dsts):
        return (f"duplicate destination indices in perm {pairs} — two "
                f"messages race into one device, one is never received")
    if size is not None:
        bad = [i for i in srcs + dsts if not 0 <= i < size]
        if bad:
            return (f"perm indices {sorted(set(bad))} out of range for "
                    f"axis of size {size}")
        if pairs and len(pairs) != size:
            missing = sorted(set(range(size)) - set(srcs))
            return (f"perm covers {len(pairs)} of {size} axis indices "
                    f"(e.g. missing sources {missing[:4]}) — a dropped "
                    f"ring hop: the uncovered devices receive zeros "
                    f"instead of data")
    return None


def extract_schedule(jaxpr, bound_axes: Dict[str, int],
                     findings: List[str], where: str = ""
                     ) -> List[Tuple[str, Tuple[str, ...]]]:
    """Walk ``jaxpr`` in execution order; return its collective sequence
    ``[(prim, axes), ...]`` and append violation messages to
    ``findings``. ``bound_axes`` maps available axis names to sizes."""
    seq: List[Tuple[str, Tuple[str, ...]]] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        params = eqn.params
        if name in _COLLECTIVE_PRIMS or name in _AXIS_QUERY_PRIMS:
            axes = _axis_names(params)
            for ax in axes:
                if ax not in bound_axes:
                    findings.append(
                        f"CL413:{where}'{name}' names axis '{ax}' which "
                        f"no enclosing shard_map binds (bound: "
                        f"{sorted(bound_axes) or 'none'})")
            if name == "ppermute":
                for ax in axes or (None,):
                    why = _check_perm(params.get("perm", ()),
                                      bound_axes.get(ax))
                    if why:
                        findings.append(f"CL412:{where}ppermute on axis "
                                        f"{ax!r}: {why}")
            if name in _COLLECTIVE_PRIMS:
                seq.append((name, axes))
            continue
        if name in ("cond", "switch"):
            branches = [j for k, j in _sub_jaxprs(params)
                        if k == "branches"]
            branch_seqs = [extract_schedule(b, bound_axes, findings,
                                            f"{where}cond>")
                          for b in branches]
            if branch_seqs and any(s != branch_seqs[0]
                                   for s in branch_seqs[1:]):
                pretty = [" -> ".join(f"{p}{list(a)}" for p, a in s)
                          or "(none)" for s in branch_seqs]
                findings.append(
                    f"CL411:{where}lax.cond branches issue different "
                    f"collective sequences: " + " VS ".join(pretty))
            if branch_seqs:
                seq.extend(branch_seqs[0])
            continue
        if name == "shard_map":
            inner_axes = dict(bound_axes)
            inner_axes.update(_mesh_axis_sizes(params))
            for _, sub in _sub_jaxprs(params):
                seq.extend(extract_schedule(sub, inner_axes, findings,
                                            f"{where}shard_map>"))
            continue
        # generic recursion: while (cond_jaxpr + body_jaxpr), scan, pjit,
        # remat, custom_jvp/vjp, closed_call, ... — walk every nested
        # jaxpr once, in param order
        for _, sub in _sub_jaxprs(params):
            seq.extend(extract_schedule(sub, bound_axes, findings,
                                        f"{where}{name}>"))
    return seq


# -- targets ---------------------------------------------------------------
# Meshes are sized by contracts.N_DEV — the device count
# ensure_cpu_devices actually provisions before this layer runs.


def _mesh8():
    from ..parallel import make_mesh

    return make_mesh(batch=1, event=N_DEV)


def _t_ring_allreduce():
    """ring_allreduce under shard_map, standalone (the primitive the
    other ring entry points compose)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring import ring_allreduce, shard_map

    mesh = _mesh8()
    f = shard_map(lambda x: ring_allreduce(x, "event"), mesh,
                  P(None, "event"), P())
    return jax.make_jaxpr(f)(jnp.ones((6, 2 * N_DEV))), {}


def _t_ring_gram():
    import jax
    import jax.numpy as jnp

    from ..parallel.ring import ring_gram

    mesh = _mesh8()
    return jax.make_jaxpr(
        lambda a: ring_gram(a, mesh))(jnp.ones((6, 4 * N_DEV))), {}


def _t_ring_matvec():
    import jax
    import jax.numpy as jnp

    from ..parallel.ring import ring_matvec

    mesh = _mesh8()
    E = 4 * N_DEV
    return jax.make_jaxpr(
        lambda a, v: ring_matvec(a, v, mesh))(
            jnp.ones((6, E)), jnp.ones((E,))), {}


def _t_ring_first_pc():
    import jax
    import jax.numpy as jnp

    from ..parallel.ring import ring_first_pc

    mesh = _mesh8()
    R, E = 6, 4 * N_DEV
    return jax.make_jaxpr(
        lambda x, rep: ring_first_pc(x, rep, mesh))(
            jnp.ones((R, E)), jnp.full((R,), 1.0 / R)), {}


def _fused_jaxpr(has_na: bool):
    import jax
    import jax.numpy as jnp

    from ..models.pipeline import ConsensusParams
    from ..parallel.fused_sharded import _build, _seed_placed

    mesh = _mesh8()
    R, E = 8, 32 * N_DEV
    p = ConsensusParams(algorithm="sztorc", pca_method="power",
                        has_na=has_na, any_scaled=False, median_block=0,
                        fused_resolution=True)
    dt = jnp.asarray(0.0).dtype
    seed, base_unit = _seed_placed(mesh, E, 0, dt.name)
    fn = _build(mesh, p, True, E, False)
    return jax.make_jaxpr(fn)(
        jnp.ones((R, E), dt), jnp.full((R,), 1.0 / R, dt), seed,
        base_unit), {}


def _t_fused_sharded():
    return _fused_jaxpr(has_na=False)


def _t_fused_sharded_na():
    return _fused_jaxpr(has_na=True)


def _t_streaming_panel():
    import jax
    import jax.numpy as jnp

    from ..parallel.streaming import _pass1_panel

    R, E = 6, 64
    dt = jnp.asarray(0.0).dtype
    return jax.make_jaxpr(
        lambda *a: _pass1_panel(*a, tolerance=0.1, with_s=True))(
            jnp.ones((R, E), dt), jnp.full((R,), 1.0 / R, dt),
            jnp.full((R,), 1.0 / R, dt), jnp.zeros((E,), bool),
            jnp.zeros((E,), dt), jnp.ones((E,), dt),
            jnp.ones((E,), bool)), {}


def _t_pipeline_light():
    """The GSPMD light pipeline: no explicit collectives in its jaxpr
    (Layer 2 budgets the post-partitioning ones), but every lax.cond in
    the traced pipeline gets branch-balance checked."""
    import jax
    import jax.numpy as jnp

    from ..models.pipeline import ConsensusParams, consensus_light_jit

    R, E = 8, 64
    p = ConsensusParams(algorithm="sztorc", pca_method="power",
                        has_na=True, any_scaled=False)
    dt = jnp.asarray(0.0).dtype
    return jax.make_jaxpr(
        lambda *a: consensus_light_jit(*a, p))(
            jnp.ones((R, E), dt), jnp.full((R,), 1.0 / R, dt),
            jnp.zeros((E,), bool), jnp.zeros((E,), dt),
            jnp.ones((E,), dt)), {}


#: name -> builder returning ``(closed_jaxpr, extra_axis_env)`` —
#: ``extra_axis_env`` maps axis names the target assumes pre-bound
#: (empty for real entry points: shard_map binds everything)
SCHEDULES: Dict[str, Callable] = {
    "ring-allreduce": _t_ring_allreduce,
    "ring-gram": _t_ring_gram,
    "ring-matvec": _t_ring_matvec,
    "ring-first-pc": _t_ring_first_pc,
    "fused-sharded": _t_fused_sharded,
    "fused-sharded-na": _t_fused_sharded_na,
    "streaming-pass1": _t_streaming_panel,
    "pipeline-light": _t_pipeline_light,
}


def check_schedule(name: str, jaxpr, axis_env: Optional[Dict[str, int]]
                   = None) -> List[Finding]:
    """Walk one target's jaxpr; findings carry ``schedule:<name>`` paths
    (baselined like contract findings). Pure given a jaxpr — unit
    testable on crafted functions."""
    core_jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    msgs: List[str] = []
    extract_schedule(core_jaxpr, dict(axis_env or {}), msgs)
    out = []
    for m in msgs:
        rule, _, detail = m.partition(":")
        out.append(Finding(
            rule=rule, path=f"schedule:{name}", line=0, message=detail,
            severity=SCHEDULE_RULES[rule][0], snippet=f"{name}:{rule}"))
    return out


def run_schedules(names: Optional[List[str]] = None) -> List[Finding]:
    """Trace every declared schedule target and check it. Returns
    findings (empty = every schedule is deadlock-clean)."""
    out: List[Finding] = []
    for name, builder in SCHEDULES.items():
        if names and name not in names:
            continue
        try:
            jaxpr, axis_env = builder()
        except Exception as e:            # noqa - reported, not raised
            out.append(Finding(
                rule="CL410", path=f"schedule:{name}", line=0,
                message=f"schedule target failed to trace: "
                        f"{type(e).__name__}: {str(e)[:300]}",
                severity="error", snippet=f"{name}:trace"))
            continue
        out.extend(check_schedule(name, jaxpr, axis_env))
    return out
