"""Baseline workflow: land the lint pass green, fail CI on NEW findings.

The checked-in ``baseline.json`` records the fingerprint of every
accepted finding plus a human rationale (mandatory — a baseline entry is
a documented decision, not a mute button). ``match_baseline`` splits a
run's findings into (new, baselined, stale): *new* findings fail the run;
*stale* entries (baselined fingerprints that no longer occur) fail it too
under ``--strict`` so the file can never rot."""

from __future__ import annotations

import json
import pathlib
from typing import Iterable, List, Tuple

from .findings import Finding, fingerprints

_DEFAULT_BASELINE = pathlib.Path(__file__).with_name("baseline.json")


def default_baseline_path() -> pathlib.Path:
    return _DEFAULT_BASELINE


def load_baseline(path=None) -> dict:
    p = pathlib.Path(path) if path else _DEFAULT_BASELINE
    if not p.exists():
        return {"version": 1, "findings": []}
    return json.loads(p.read_text())


def save_baseline(findings: Iterable[Finding], path=None,
                  reason: str = "baselined by --update-baseline",
                  preserve=None) -> dict:
    """Write the baseline for the given findings, preserving the reasons
    of entries whose fingerprint already exists.

    ``preserve``: optional predicate over EXISTING entries — those for
    which it returns True are kept even when this run did not reproduce
    them. The CLI uses it so a path-restricted or contracts-off
    ``--update-baseline`` run cannot silently delete accepted findings
    that were simply outside its scope."""
    p = pathlib.Path(path) if path else _DEFAULT_BASELINE
    old_entries = load_baseline(p).get("findings", [])
    old = {e["fingerprint"]: e for e in old_entries}
    findings = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    entries = []
    seen = set()
    for f, fp in zip(findings, fingerprints(findings)):
        entries.append({
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
            "reason": old.get(fp, {}).get("reason", reason),
        })
        seen.add(fp)
    if preserve is not None:
        for e in old_entries:
            if e["fingerprint"] not in seen and preserve(e):
                entries.append(e)
    entries.sort(key=lambda e: (e["path"], e["fingerprint"]))
    doc = {"version": 1, "findings": entries}
    p.write_text(json.dumps(doc, indent=2, sort_keys=False) + "\n")
    return doc


def match_baseline(findings: List[Finding], baseline: dict
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Split ``findings`` into (new, baselined) and return the stale
    baseline fingerprints (entries that matched nothing this run)."""
    findings = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    fps = fingerprints(findings)
    known = {e["fingerprint"] for e in baseline.get("findings", [])}
    new, matched = [], []
    hit = set()
    for f, fp in zip(findings, fps):
        if fp in known:
            matched.append(f)
            hit.add(fp)
        else:
            new.append(f)
    stale = sorted(known - hit)
    return new, matched, stale
