"""Layer 2: traced contracts over the compiled entry points.

Each contract (declared in ``contracts.json``) names a *builder* — a
function that lowers one real jitted/sharded entry point to optimized
(post-GSPMD-partitioning) HLO on the CPU mesh — and a set of assertions
over that artifact:

- **collective inventory** (rule CL301): every collective instruction's
  operand element count is bounded; generalizes
  tests/test_hlo_collectives.py's helpers into reusable infrastructure
  (that test now consumes this module). Budget expressions are evaluated
  with ``R``/``E``/``n_dev`` bound to the contract's shape.
- **no f64 ops** (CL302): no ``f64[``/``c128[`` shapes in the HLO. Checked
  only when ``jax_enable_x64`` is OFF — under x64 (the pytest
  environment) every array is legitimately f64, so the check is SKIPPED
  there (silently: a skip notice would itself be a non-baselined
  finding). The authoritative f64 gate is the fresh-process CI run,
  where x64 is off.
- **no host callbacks** (CL303): no python-callback custom-calls,
  infeed/outfeed, or host sends — a host round-trip inside a traced path
  stalls the device pipeline.
- **retrace budget** (CL304): calling an entry point twice with identical
  (shape, dtype, params) must not grow the jit cache — a retrace on a
  steady-state serving path is a silent multi-second stall.
- **run-to-run determinism** (CL1005, Layer 6's compiled-artifact half):
  no scatter-family op (arrival-order combining) outside a contract's
  blessed list, and the ``stablehlo_pin`` dynamic builder traces an
  entry point twice through fresh jit wrappers and pins the StableHLO
  modules to byte equality — two workers must compile the SAME program
  from the same source (the fleet's bit-identity contract).

A builder that raises reports CL300 (contract-trace-failure): the entry
point could not even be traced — e.g. a host sync seeded into a jitted
path raises ``TracerArrayConversionError`` here, which is exactly the
signal wanted.

Run under ``JAX_PLATFORMS=cpu`` with 8 virtual devices
(``ensure_cpu_devices`` arranges both when nothing has initialized a
backend yet — the CLI calls it; pytest's conftest already does the same).
"""

from __future__ import annotations

import json
import pathlib
import re
from typing import Callable, Dict, List, Optional

import numpy as np

from .findings import Finding

CONTRACT_RULES = {
    "CL300": ("error", "entry point failed to trace/compile"),
    "CL301": ("error", "collective inventory violates the declared budget"),
    "CL302": ("error", "f64/c128 op in compiled HLO"),
    "CL303": ("error", "host callback / infeed / outfeed in compiled HLO"),
    "CL304": ("error", "jit cache grew on an identical re-call "
                       "(retrace budget exceeded)"),
    "CL305": ("error", "bf16/i8-operand compare in compiled HLO "
                       "(Mosaic rejects the lowered cmpf/cmpi — "
                       "BENCH_r02's compile-failure class)"),
    "CL306": ("error", "donated input buffers not aliased in compiled "
                       "HLO (the padded-bucket donation contract: XLA "
                       "must re-use the donated pad storage for "
                       "outputs, or every dispatch allocates fresh "
                       "buffers)"),
}

_DEFAULT_CONTRACTS = pathlib.Path(__file__).with_name("contracts.json")

# -- HLO text analysis (the reusable core of tests/test_hlo_collectives) --

COLLECTIVE_RE = re.compile(
    r"= ([^=]*?) (all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\(")
_DIMS_RE = re.compile(r"\[([0-9,]*)\]")

_HOST_CALLBACK_RE = re.compile(
    r"custom-call.*(callback|xla_python|py_func)|"
    r"\b(infeed|outfeed|send-to-host|recv-from-host)\b")

_F64_RE = re.compile(r"\b(f64|c128)\[")

# XLA-documented run-to-run nondeterministic op families (Layer 6 /
# CL1005): scatter with duplicate indices combines in hardware-arrival
# order, and select-and-scatter ties break nondeterministically on some
# backends. `reduce-scatter` is a collective, NOT this family — the
# leading space in the pattern keeps it out.
_NONDET_OP_RE = re.compile(r"= [^=]*? (select-and-scatter|scatter)\(")


# dtype token = letters, a digit, then optional alphanumerics: matches
# f32/bf16/u32/c128 AND fp8 names (f8e4m3fn), but NOT annotation tokens
# like `devices=[8]` that carry no digit before the bracket
_TYPED_DIMS_RE = re.compile(r"\b(pred|[a-z]+[0-9][a-z0-9]*)\[([0-9,]*)\]")


def collective_inventory(hlo_text: str) -> List[tuple]:
    """``[(op_kind, dtypes, elems), ...]`` for every collective
    instruction in compiled HLO — one entry per instruction,
    tuple-shaped outputs summed (the tuple is one fused collective's
    payload) with the union of their dtypes. ``dtypes`` is a frozenset
    of HLO type names (``f32``, ``u32``, …), letting budgets distinguish
    DATA partials from PRNG-bit/index assemblies."""
    out: List[tuple] = []
    for line in hlo_text.splitlines():
        m = COLLECTIVE_RE.search(line.strip())
        if m:
            shape, op = m.group(1), m.group(2)
            elems, dtypes = 0, set()
            for dt, dims in _TYPED_DIMS_RE.findall(shape):
                dtypes.add(dt)
                elems += (int(np.prod([int(d) for d in dims.split(",")]))
                          if dims else 1)
            out.append((op, frozenset(dtypes), elems))
    return out


def collective_sizes(hlo_text: str) -> Dict[str, List[int]]:
    """{op_kind: [operand element counts]} — the dtype-blind view
    (tests/test_hlo_collectives.py's original helper, kept as API)."""
    out: Dict[str, List[int]] = {}
    for op, _, elems in collective_inventory(hlo_text):
        out.setdefault(op, []).append(elems)
    return out


def _is_float_payload(dtypes) -> bool:
    return any(dt.startswith(("f", "bf", "c")) for dt in dtypes)


def f64_ops(hlo_text: str) -> List[str]:
    """HLO lines computing in f64/c128 (ignores metadata-only mentions)."""
    return [ln.strip() for ln in hlo_text.splitlines()
            if _F64_RE.search(ln.split("metadata=")[0])]


def host_callbacks(hlo_text: str) -> List[str]:
    """HLO lines that re-enter the host mid-graph."""
    return [ln.strip() for ln in hlo_text.splitlines()
            if _HOST_CALLBACK_RE.search(ln)]


def nondeterministic_ops(hlo_text: str, blessed=()) -> List[str]:
    """HLO lines carrying an op from the run-to-run nondeterministic
    family (scatter / select-and-scatter — CL1005's compiled-artifact
    half). ``blessed`` names op kinds an individual contract has
    audited as safe (e.g. a scatter whose indices are provably unique);
    anything else in the family is a finding. Ignores metadata-only
    mentions, like :func:`f64_ops`."""
    out: List[str] = []
    for ln in hlo_text.splitlines():
        m = _NONDET_OP_RE.search(ln.split("metadata=")[0])
        if m and m.group(1) not in blessed:
            out.append(ln.strip())
    return out


#: compare instruction whose OPERAND region names a dtype Mosaic rejects
#: in kernel comparisons (bf16 cmpf — BENCH_r02's crash — and s8/u8
#: cmpi, probed round 4). Compiled HLO text carries operand types inline
#: (`pred[...] compare(bf16[...] %a, bf16[...] %b), direction=LT`), so a
#: line check suffices; jaxpr-level tests/test_mosaic_compat.py is the
#: structural guard, this is its post-lowering mirror inside the lint
#: gate.
_ILLEGAL_CMP_RE = re.compile(r"compare\([^)]*\b(bf16|s8|u8)\[")


def input_output_aliases(hlo_text: str) -> List[tuple]:
    """``[(output_index, param_number), ...]`` parsed from the compiled
    module's ``input_output_alias={ {out}: (param, {}, may-alias), … }``
    header attribute — the artifact donation leaves behind when XLA
    actually re-uses a donated input buffer for an output. An HLO
    module with no alias table (nothing donated, or nothing usable)
    parses as the empty list."""
    out: List[tuple] = []
    for line in hlo_text.splitlines():
        if "input_output_alias={" not in line:
            continue
        seg = line.split("input_output_alias={", 1)[1]
        # the table nests braces ({0}: (2, {}, may-alias)); walk to the
        # matching close instead of trusting a regex across the header
        depth, end = 1, 0
        for i, ch in enumerate(seg):
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        table = seg[:end]
        for m in re.finditer(r"\{\s*([0-9]*)[0-9, ]*\}:\s*\(([0-9]+)",
                             table):
            out.append((int(m.group(1) or 0), int(m.group(2))))
        break
    return out


def bf16_compare_ops(hlo_text: str) -> List[str]:
    """HLO compare instructions on bf16/i8 operands — the lowered form
    Mosaic refuses to compile in Pallas kernels ("Target does not
    support this comparison"). Ignores metadata-only mentions, like
    :func:`f64_ops`."""
    return [ln.strip() for ln in hlo_text.splitlines()
            if _ILLEGAL_CMP_RE.search(ln.split("metadata=")[0])]


def check_collective_budget(inventory: List[tuple], budget: dict,
                            env: dict) -> List[str]:
    """Violation messages for one compiled artifact against a declared
    budget. ``inventory`` is :func:`collective_inventory`'s output.
    Budget fields (expressions may use R, E, n_dev):

    - ``forbid_collectives``: no collective of any kind may appear;
    - ``require_all_reduce``: the path must actually be sharded;
    - ``all_reduce_max``: per-all-reduce operand element bound for
      FLOAT-payload all-reduces (the data partials the scaling claim is
      about), except…
    - ``large_all_reduces`` / ``large_all_reduce_max``: …this many may
      exceed it up to the large bound (the Gram path's one R x R
      reduction);
    - ``other_max``: bound for every other collective kind AND for
      integer-only all-reduces (PRNG-bit / index assemblies — GSPMD
      sometimes expresses an all-gather as a sum-all-reduce of u32
      bits, same bytes on the wire);
    - ``max_collectives``: bound on the TOTAL collective instruction
      count in the artifact — the psum-count half of a collective
      budget (the element bounds above are the bytes half): a new
      reduction sneaking into a per-sweep loop body shows up here even
      when its payload is small;
    - ``matrix_backstop``: absolute bound for anything (defaults to
      ``R * E // (2 * n_dev)`` — half a matrix shard).

    Expressions may also use ``B`` (the contract's declared batch-lane
    capacity, default 1) — mesh-batched entry points carry
    ``B / n_batch`` lanes of each (R,) partial per psum.
    """
    def ev(expr):
        ns = dict(env, max=max, min=min)
        return int(eval(str(expr), {"__builtins__": {}}, ns))

    out: List[str] = []
    if budget.get("forbid_collectives"):
        if inventory:
            counts: Dict[str, int] = {}
            for op, _, _ in inventory:
                counts[op] = counts.get(op, 0) + 1
            out.append(f"expected a collective-free program, found {counts}")
        return out
    float_ars = [n for op, dt, n in inventory
                 if op == "all-reduce" and _is_float_payload(dt)]
    all_ars = [n for op, _, n in inventory if op == "all-reduce"]
    if budget.get("require_all_reduce", True) and not all_ars:
        out.append("no all-reduce at all: path is not actually sharded")
    if "all_reduce_max" in budget and float_ars:
        bound = ev(budget["all_reduce_max"])
        n_large = int(budget.get("large_all_reduces", 0))
        large_bound = ev(budget.get("large_all_reduce_max", 0))
        big = sorted((n for n in float_ars if n > bound), reverse=True)
        if len(big) > n_large:
            out.append(
                f"{len(big)} float all-reduce(s) exceed {bound} elements "
                f"(largest {big[0]}; {n_large} large ones allowed) — "
                f"per-sweep collectives should carry only (R,) partials")
        for n in big[:n_large]:
            if n > large_bound:
                out.append(f"large all-reduce of {n} elements exceeds "
                           f"the {large_bound} bound")
    if "other_max" in budget:
        bound = ev(budget["other_max"])
        for op, dt, n in inventory:
            if op == "all-reduce" and _is_float_payload(dt):
                continue
            if n > bound:
                out.append(f"{op} ({'/'.join(sorted(dt))}) moving {n} "
                           f"elements (> {bound}): a sharded operand is "
                           f"being re-assembled")
    if "max_collectives" in budget:
        bound = ev(budget["max_collectives"])
        if len(inventory) > bound:
            counts: Dict[str, int] = {}
            for op, _, _ in inventory:
                counts[op] = counts.get(op, 0) + 1
            out.append(f"{len(inventory)} collective instructions exceed "
                       f"the declared count budget {bound} ({counts}) — "
                       f"a reduction crept into the traced path")
    backstop = ev(budget.get(
        "matrix_backstop", "R * E // (2 * n_dev) if n_dev > 1 else R * E"))
    if backstop > 0:
        for op, dt, n in inventory:
            if n >= backstop:
                out.append(f"{op} moving {n} elements — matrix-sized "
                           f"collective (backstop {backstop})")
    return out


# -- environment ----------------------------------------------------------

N_DEV = 8


def ensure_cpu_devices(n: int = N_DEV) -> None:
    """Force the CPU platform with ``n`` virtual devices — must run before
    jax initializes a backend (the CLI path). Safe no-op when a suitable
    backend already exists (pytest's conftest)."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}").strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


# -- builders -------------------------------------------------------------
# Each returns compiled HLO text, or a list[Finding] for dynamic checks.


def _shape(spec: dict):
    sh = spec.get("shape", {})
    return int(sh.get("R", 32)), int(sh.get("E", 2048))


def _params(spec: dict, **overrides):
    from ..models.pipeline import ConsensusParams

    kw = dict(spec.get("params", {}))
    kw.update(overrides)
    return ConsensusParams(**kw)


def _acc_dtype():
    import jax.numpy as jnp

    return jnp.asarray(0.0).dtype


def _builder_pipeline_sharded(spec: dict) -> str:
    """consensus_light_jit on the event-sharded mesh, params resolved
    through the REAL front-end logic (resolve_params /
    effective_median_block), inputs as ShapeDtypeStructs — nothing
    (R, E)-sized is materialized."""
    import jax

    from ..models.pipeline import consensus_light_jit
    from ..parallel import make_mesh, resolve_params
    from ..parallel.mesh import event_sharding, replicated

    R, E = _shape(spec)
    mesh_spec = spec.get("mesh", {"batch": 1, "event": N_DEV})
    mesh = make_mesh(**mesh_spec)
    n_scaled = int(spec.get("shape", {}).get("n_scaled", 0))
    p = _params(spec, any_scaled=n_scaled > 0, n_scaled=n_scaled)
    p = resolve_params(p, R, E, mesh)
    dt = _acc_dtype()
    e_sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("event"))
    args = (
        jax.ShapeDtypeStruct((R, E), dt, sharding=event_sharding(mesh)),
        jax.ShapeDtypeStruct((R,), dt, sharding=replicated(mesh)),
        jax.ShapeDtypeStruct((E,), bool, sharding=e_sh),
        jax.ShapeDtypeStruct((E,), dt, sharding=e_sh),
        jax.ShapeDtypeStruct((E,), dt, sharding=e_sh),
    )
    return consensus_light_jit.lower(*args, p).compile().as_text()


def _builder_pipeline_single(spec: dict) -> str:
    """Single-device light pipeline: the serving fast path must stay
    collective- and callback-free."""
    import jax

    from ..models.pipeline import consensus_light_jit

    R, E = _shape(spec)
    p = _params(spec)
    dt = _acc_dtype()
    args = (jax.ShapeDtypeStruct((R, E), dt),
            jax.ShapeDtypeStruct((R,), dt),
            jax.ShapeDtypeStruct((E,), bool),
            jax.ShapeDtypeStruct((E,), dt),
            jax.ShapeDtypeStruct((E,), dt))
    return consensus_light_jit.lower(*args, p).compile().as_text()


def _builder_fused_sharded(spec: dict) -> str:
    """The shard_map fused-kernel executable (parallel.fused_sharded) —
    explicit psum collectives around the Pallas storage kernels
    (interpret mode off-TPU, so the kernels lower to plain XLA ops)."""
    import jax

    from ..parallel import make_mesh
    from ..parallel.fused_sharded import _build, _seed_placed
    from ..parallel.mesh import event_sharding, replicated

    R, E = _shape(spec)
    mesh = make_mesh(**spec.get("mesh", {"batch": 1, "event": N_DEV}))
    p = _params(spec, fused_resolution=True)
    dt = _acc_dtype()
    interpret = jax.default_backend() != "tpu"
    seed, base_unit = _seed_placed(mesh, E, 0, dt.name)
    fn = _build(mesh, p, interpret, E, False)
    args = (jax.ShapeDtypeStruct((R, E), dt, sharding=event_sharding(mesh)),
            jax.ShapeDtypeStruct((R,), dt, sharding=replicated(mesh)))
    return fn.lower(*args, seed, base_unit).compile().as_text()


def _builder_pallas_resolve(spec: dict) -> str:
    """The revived fused-resolution tier (ISSUE 7): the single-device
    light pipeline with ``fused_resolution=True`` — the graph the
    Oracle's TPU fused gate and the serve ``bucket_pallas`` class run.
    Off-TPU the Pallas kernels lower through the interpreter to plain
    XLA ops (the ``fused_sharded`` builder's precedent), which is
    exactly the surface the ``forbid_bf16_compares`` assertion needs:
    every kernel comparison appears in the compiled module, and one on
    bf16/i8 operands is the BENCH_r02 Mosaic rejection waiting to
    happen on hardware."""
    import jax

    from ..models.pipeline import consensus_light_jit

    R, E = _shape(spec)
    p = _params(spec, fused_resolution=True)
    dt = _acc_dtype()
    args = (jax.ShapeDtypeStruct((R, E), dt),
            jax.ShapeDtypeStruct((R,), dt),
            jax.ShapeDtypeStruct((E,), bool),
            jax.ShapeDtypeStruct((E,), dt),
            jax.ShapeDtypeStruct((E,), dt))
    return consensus_light_jit.lower(*args, p).compile().as_text()


def _builder_collusion_vmap(spec: dict) -> str:
    """The Monte-Carlo simulator's batched trial program: pure data
    parallelism — zero collectives, everything on device."""
    import jax.numpy as jnp

    from ..sim.collusion import CollusionSimulator, _fold_keys

    R, E = _shape(spec)
    n = int(spec.get("shape", {}).get("n_trials", 8))
    sim = CollusionSimulator(n_reporters=R, n_events=E,
                             **spec.get("simulator", {}))
    keys = _fold_keys(0, np.arange(n))
    lf = jnp.full((n,), 0.2, _acc_dtype())
    var = jnp.full((n,), 0.1, _acc_dtype())
    return sim._batched.lower(keys, lf, var).compile().as_text()


def _builder_streaming_panel(spec: dict) -> str:
    """The out-of-core path's per-panel accumulation kernel
    (streaming._pass1_panel): one panel in, R x R sufficient statistics
    out — no collectives on a single device, no host re-entry."""
    import jax

    from ..parallel.streaming import _pass1_panel

    R, E = _shape(spec)
    dt = _acc_dtype()
    args = (jax.ShapeDtypeStruct((R, E), dt),      # panel
            jax.ShapeDtypeStruct((R,), dt),        # fill_rep
            jax.ShapeDtypeStruct((R,), dt),        # weight_rep
            jax.ShapeDtypeStruct((E,), bool),      # scaled
            jax.ShapeDtypeStruct((E,), dt),        # mins
            jax.ShapeDtypeStruct((E,), dt),        # maxs
            jax.ShapeDtypeStruct((E,), bool))      # valid
    return _pass1_panel.lower(*args, tolerance=0.1,
                              with_s=True).compile().as_text()


def _builder_kmeans_single(spec: dict) -> str:
    """models.clustering's jit-compatible k-means conformity scorer."""
    import functools

    import jax

    from ..models import clustering as cl

    R, E = _shape(spec)
    dt = _acc_dtype()
    fn = jax.jit(functools.partial(cl.kmeans_conformity_jax,
                                   num_clusters=2))
    return fn.lower(jax.ShapeDtypeStruct((R, E), dt),
                    jax.ShapeDtypeStruct((R,), dt)).compile().as_text()


def _builder_sztorc_scores(spec: dict) -> str:
    """models.sztorc's power-method scorer, jitted standalone."""
    import jax

    from ..models.sztorc import sztorc_scores_jax

    R, E = _shape(spec)
    dt = _acc_dtype()
    fn = jax.jit(lambda reports, rep: sztorc_scores_jax(
        reports, rep, pca_method="power"))
    return fn.lower(jax.ShapeDtypeStruct((R, E), dt),
                    jax.ShapeDtypeStruct((R,), dt)).compile().as_text()


def _builder_retrace_pipeline(spec: dict) -> List[Finding]:
    """Dynamic check: two identical consensus_light_jit calls must share
    one cache entry (budget = allowed growth across BOTH calls; identical
    re-calls growing the cache means params/shape hashing broke)."""
    import jax.numpy as jnp

    from ..models.pipeline import consensus_light_jit

    R, E = _shape(spec)
    budget = int(spec.get("retrace_budget", 1))
    p = _params(spec)
    dt = _acc_dtype()
    rng = np.random.default_rng(0)
    args = (jnp.asarray(rng.choice([0.0, 1.0], size=(R, E)), dt),
            jnp.full((R,), 1.0 / R, dt), jnp.zeros((E,), bool),
            jnp.zeros((E,), dt), jnp.ones((E,), dt))
    before = consensus_light_jit._cache_size()
    consensus_light_jit(*args, p)
    mid = consensus_light_jit._cache_size()
    consensus_light_jit(*args, p)
    after = consensus_light_jit._cache_size()
    findings = []
    if after - mid > 0:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"identical re-call retraced: cache grew "
                    f"{mid} -> {after}", severity="error",
            snippet=f"{spec['name']}:recall"))
    if after - before > budget:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"two calls grew the jit cache by "
                    f"{after - before} (> budget {budget})",
            severity="error", snippet=f"{spec['name']}:budget"))
    return findings


def _serve_bucket_args(spec: dict):
    """Shared arg-shapes for the serve-bucket builders."""
    import jax

    R, E = _shape(spec)
    dt = _acc_dtype()
    return (jax.ShapeDtypeStruct((R, E), dt),       # padded reports
            jax.ShapeDtypeStruct((R,), dt),         # reputation
            jax.ShapeDtypeStruct((E,), bool),       # scaled
            jax.ShapeDtypeStruct((E,), dt),         # mins
            jax.ShapeDtypeStruct((E,), dt),         # maxs
            jax.ShapeDtypeStruct((R,), bool),       # row_valid
            jax.ShapeDtypeStruct((E,), bool),       # col_valid
            jax.ShapeDtypeStruct((E,), dt))         # power seed


def _builder_serve_bucket(spec: dict) -> str:
    """The serving layer's padded bucket entry point
    (serve.kernels.padded_consensus) — the hot path every bucketed
    dispatch rides; must stay collective- and callback-free.
    ``"donate": true`` in the spec builds the serving cache's DONATED
    form (ISSUE 13) so the CL306 aliasing assertion sees the artifact
    dispatch actually runs."""
    from ..serve.kernels import make_bucket_executable

    fn = make_bucket_executable(_params(spec),
                                donate=bool(spec.get("donate")))
    return fn.lower(*_serve_bucket_args(spec),
                    _params(spec)).compile().as_text()


def _builder_retrace_serve_bucket(spec: dict) -> List[Finding]:
    """Dynamic check: two identical bucket dispatches share one cache
    entry — the runtime mirror is the serve cache warmup contract
    (steady-state ``serve_bucket`` retraces == warmed bucket count)."""
    import jax.numpy as jnp

    from ..serve.kernels import bucket_inputs, make_bucket_executable

    R, E = _shape(spec)
    budget = int(spec.get("retrace_budget", 1))
    p = _params(spec)
    rng = np.random.default_rng(0)
    reports = rng.choice([0.0, 1.0], size=(R, E))
    reports[0, 0] = np.nan
    args = [jnp.asarray(a) for a in bucket_inputs(
        reports, np.full(R, 1.0 / R), np.zeros(E, bool), np.zeros(E),
        np.ones(E), R, E, has_na=True)]
    fn = make_bucket_executable(p)
    before = fn._cache_size()
    fn(*args, p)
    mid = fn._cache_size()
    fn(*args, p)
    after = fn._cache_size()
    findings = []
    if after - mid > 0:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"identical bucket re-dispatch retraced: cache grew "
                    f"{mid} -> {after}", severity="error",
            snippet=f"{spec['name']}:recall"))
    if after - before > budget:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"two dispatches grew the jit cache by "
                    f"{after - before} (> budget {budget})",
            severity="error", snippet=f"{spec['name']}:budget"))
    return findings


def _first_divergence(a: str, b: str) -> str:
    """First line where two artifacts differ (for the CL1005 message)."""
    for i, (la, lb) in enumerate(zip(a.splitlines(), b.splitlines())):
        if la != lb:
            return (f"line {i + 1}: {la.strip()[:80]!r} vs "
                    f"{lb.strip()[:80]!r}")
    return (f"length {len(a)} vs {len(b)} bytes "
            f"(common prefix identical)")


def _builder_stablehlo_pin(spec: dict) -> List[Finding]:
    """Dynamic check (Layer 6 / CL1005): trace the entry point TWICE
    through fresh jit wrappers and pin the StableHLO modules to byte
    equality. A divergence means trace-time Python fed
    order/iteration/id()-dependent structure into the graph — the exact
    class of bug that makes two workers compile different programs from
    the same source and break the fleet's bit-identity contract.
    ``spec["entry"]`` picks the registered entry point."""
    entry = spec.get("entry", "serve_bucket")
    texts = []
    for _ in range(2):
        if entry == "serve_bucket":
            from ..serve.kernels import make_bucket_executable
            fn = make_bucket_executable(_params(spec),
                                        donate=bool(spec.get("donate")))
            texts.append(fn.lower(*_serve_bucket_args(spec),
                                  _params(spec)).as_text())
        elif entry == "serve_bucket_incremental":
            from ..serve.incremental import make_incremental_executable
            fn = make_incremental_executable(_params(spec))
            texts.append(fn.lower(*_incremental_avals(spec),
                                  _params(spec)).as_text())
        else:
            return [Finding(
                rule="CL300", path=f"contract:{spec['name']}", line=0,
                message=f"stablehlo_pin: unknown entry {entry!r}",
                severity="error", snippet=f"{spec['name']}:entry")]
    if texts[0] != texts[1]:
        return [Finding(
            rule="CL1005", path=f"contract:{spec['name']}", line=0,
            message=f"entry {entry!r} lowered to DIFFERENT StableHLO "
                    f"on two fresh traces ({_first_divergence(*texts)})"
                    f" — trace-time Python is feeding nondeterministic "
                    f"structure into the graph",
            severity="error", snippet=f"{spec['name']}:stablehlo")]
    return []


def _serve_mesh_setup(spec: dict):
    """Shared (mesh, params, batch capacity) for the sharded serve-bucket
    builders."""
    from ..parallel import make_mesh

    mesh = make_mesh(**spec.get("mesh", {"batch": 2, "event": 4}))
    B = int(spec.get("shape", {}).get("B", 8))
    return mesh, _params(spec), B


def _builder_serve_bucket_sharded(spec: dict) -> str:
    """The mesh-sharded serving bucket entry point
    (serve.sharded.make_sharded_bucket_executable): co-batched lanes
    over the mesh's batch axis, events over its event axis — every
    psum must carry only (B/n_batch, R) partials or O(1) scalars, and
    the total psum count per dispatch is pinned."""
    import jax

    from ..serve.sharded import make_sharded_bucket_executable

    R, E = _shape(spec)
    mesh, p, B = _serve_mesh_setup(spec)
    dt = _acc_dtype()
    fn = make_sharded_bucket_executable(p, mesh, batched=B > 1,
                                        donate=bool(spec.get("donate")))
    lead = (B,) if B > 1 else ()
    args = (jax.ShapeDtypeStruct(lead + (R, E), dt),
            jax.ShapeDtypeStruct(lead + (R,), dt),
            jax.ShapeDtypeStruct(lead + (E,), bool),
            jax.ShapeDtypeStruct(lead + (E,), dt),
            jax.ShapeDtypeStruct(lead + (E,), dt),
            jax.ShapeDtypeStruct(lead + (R,), bool),
            jax.ShapeDtypeStruct(lead + (E,), bool),
            jax.ShapeDtypeStruct(lead + (E,), dt))
    return fn.lower(*args, p).compile().as_text()


def _builder_retrace_serve_bucket_sharded(spec: dict) -> List[Finding]:
    """Dynamic check: two identical sharded bucket dispatches share one
    jit cache entry — the runtime mirror is the multi-device serve
    smoke's warmed-bucket retrace pin."""
    import jax.numpy as jnp

    from ..serve.kernels import bucket_inputs
    from ..serve.sharded import make_sharded_bucket_executable

    R, E = _shape(spec)
    mesh, p, B = _serve_mesh_setup(spec)
    budget = int(spec.get("retrace_budget", 1))
    rng = np.random.default_rng(0)
    reports = rng.choice([0.0, 1.0], size=(R, E))
    reports[0, 0] = np.nan
    lane = bucket_inputs(reports, np.full(R, 1.0 / R), np.zeros(E, bool),
                         np.zeros(E), np.ones(E), R, E, has_na=True)
    args = [jnp.broadcast_to(jnp.asarray(a), (B,) + np.shape(a))
            for a in lane]
    fn = make_sharded_bucket_executable(p, mesh, batched=B > 1)
    before = fn._cache_size()
    fn(*args, p)
    mid = fn._cache_size()
    fn(*args, p)
    after = fn._cache_size()
    findings = []
    if after - mid > 0:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"identical sharded bucket re-dispatch retraced: "
                    f"cache grew {mid} -> {after}", severity="error",
            snippet=f"{spec['name']}:recall"))
    if after - before > budget:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"two dispatches grew the jit cache by "
                    f"{after - before} (> budget {budget})",
            severity="error", snippet=f"{spec['name']}:budget"))
    return findings


def _incremental_avals(spec: dict):
    """Shared arg avals for the incremental-bucket builders: three R×R
    sufficient statistics, the round reputation, and the warm start."""
    import jax

    R, _ = _shape(spec)
    dt = _acc_dtype()
    return (jax.ShapeDtypeStruct((R, R), dt),     # G
            jax.ShapeDtypeStruct((R, R), dt),     # M
            jax.ShapeDtypeStruct((R, R), dt),     # S
            jax.ShapeDtypeStruct((R,), dt),       # reputation
            jax.ShapeDtypeStruct((R,), dt))       # warm_u


def _builder_serve_bucket_incremental(spec: dict) -> str:
    """The ``bucket_incremental`` marginal-resolve entry point
    (serve.incremental.make_incremental_executable): warm-started power
    iteration + dirfix/row-reward/smooth over R×R session statistics —
    the hot path of every warm session resolve; must stay collective-,
    callback-, f64- and bf16-compare-free."""
    from ..serve.incremental import make_incremental_executable

    fn = make_incremental_executable(_params(spec))
    return fn.lower(*_incremental_avals(spec),
                    _params(spec)).compile().as_text()


def _builder_retrace_serve_bucket_incremental(spec: dict) -> List[Finding]:
    """Dynamic check: two identical incremental dispatches share one jit
    cache entry — the runtime mirror is the steady-state
    ``serve_bucket_incremental`` retrace pin (one compile per warmed
    (roster, params), then flat across every marginal resolve)."""
    import jax.numpy as jnp

    from ..serve.incremental import make_incremental_executable

    R, _ = _shape(spec)
    budget = int(spec.get("retrace_budget", 1))
    p = _params(spec)
    dt = _acc_dtype()
    rng = np.random.default_rng(0)
    A = rng.standard_normal((R, R))
    args = [jnp.asarray(a, dt) for a in
            (A @ A.T, rng.standard_normal((R, R)), A.T @ A,
             np.full((R,), 1.0 / R), rng.standard_normal(R))]
    fn = make_incremental_executable(p)
    before = fn._cache_size()
    fn(*args, p)
    mid = fn._cache_size()
    fn(*args, p)
    after = fn._cache_size()
    findings = []
    if after - mid > 0:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"identical incremental re-dispatch retraced: "
                    f"cache grew {mid} -> {after}", severity="error",
            snippet=f"{spec['name']}:recall"))
    if after - before > budget:
        findings.append(Finding(
            rule="CL304", path=f"contract:{spec['name']}", line=0,
            message=f"two dispatches grew the jit cache by "
                    f"{after - before} (> budget {budget})",
            severity="error", snippet=f"{spec['name']}:budget"))
    return findings


BUILDERS: Dict[str, Callable] = {
    "pipeline_sharded": _builder_pipeline_sharded,
    "pipeline_single": _builder_pipeline_single,
    "fused_sharded": _builder_fused_sharded,
    "pallas_resolve": _builder_pallas_resolve,
    "collusion_vmap": _builder_collusion_vmap,
    "streaming_panel": _builder_streaming_panel,
    "kmeans_single": _builder_kmeans_single,
    "sztorc_scores": _builder_sztorc_scores,
    "retrace_pipeline": _builder_retrace_pipeline,
    "serve_bucket": _builder_serve_bucket,
    "retrace_serve_bucket": _builder_retrace_serve_bucket,
    "serve_bucket_sharded": _builder_serve_bucket_sharded,
    "retrace_serve_bucket_sharded": _builder_retrace_serve_bucket_sharded,
    "serve_bucket_incremental": _builder_serve_bucket_incremental,
    "retrace_serve_bucket_incremental":
        _builder_retrace_serve_bucket_incremental,
    "stablehlo_pin": _builder_stablehlo_pin,
}


# -- driver ---------------------------------------------------------------


def load_contracts(path=None) -> List[dict]:
    p = pathlib.Path(path) if path else _DEFAULT_CONTRACTS
    return json.loads(p.read_text())["contracts"]


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def check_artifact(name: str, hlo_text: str, spec: dict) -> List[Finding]:
    """All text-level checks for one compiled artifact (pure — unit
    testable on crafted HLO strings)."""
    R, E = _shape(spec)
    mesh_spec = spec.get("mesh") or {}
    env = {"R": R, "E": E,
           "B": int(spec.get("shape", {}).get("B", 1)),
           "n_dev": int(mesh_spec.get("batch", 1))
           * int(mesh_spec.get("event", 1)) if mesh_spec else 1}
    path = f"contract:{name}"
    out: List[Finding] = []
    if "budget" in spec:
        inventory = collective_inventory(hlo_text)
        for msg in check_collective_budget(inventory, spec["budget"], env):
            out.append(Finding(rule="CL301", path=path, line=0,
                               message=msg, severity="error",
                               snippet=f"{name}:collectives"))
    if spec.get("forbid_f64", True) and not _x64_enabled():
        bad = f64_ops(hlo_text)
        if bad:
            out.append(Finding(
                rule="CL302", path=path, line=0,
                message=f"{len(bad)} f64/c128 op(s) in compiled HLO "
                        f"(first: {bad[0][:120]})", severity="error",
                snippet=f"{name}:f64"))
    if spec.get("forbid_host_callbacks", True):
        bad = host_callbacks(hlo_text)
        if bad:
            out.append(Finding(
                rule="CL303", path=path, line=0,
                message=f"{len(bad)} host re-entry op(s) in compiled HLO "
                        f"(first: {bad[0][:120]})", severity="error",
                snippet=f"{name}:callback"))
    if spec.get("forbid_bf16_compares"):
        bad = bf16_compare_ops(hlo_text)
        if bad:
            out.append(Finding(
                rule="CL305", path=path, line=0,
                message=f"{len(bad)} bf16/i8-operand compare(s) in "
                        f"compiled HLO — Mosaic rejects the lowered "
                        f"form (first: {bad[0][:120]})",
                severity="error", snippet=f"{name}:bf16cmp"))
    if spec.get("forbid_nondeterministic_ops", True):
        bad = nondeterministic_ops(
            hlo_text, blessed=tuple(
                spec.get("blessed_nondeterministic_ops", ())))
        if bad:
            out.append(Finding(
                rule="CL1005", path=path, line=0,
                message=f"{len(bad)} run-to-run nondeterministic op(s) "
                        f"in compiled HLO — scatter-family combines in "
                        f"arrival order (first: {bad[0][:120]})",
                severity="error", snippet=f"{name}:nondet"))
    if "min_donated_aliases" in spec:
        aliases = input_output_aliases(hlo_text)
        want = int(spec["min_donated_aliases"])
        if len(aliases) < want:
            out.append(Finding(
                rule="CL306", path=path, line=0,
                message=f"compiled module aliases only {len(aliases)} "
                        f"donated input buffer(s) to outputs (contract "
                        f"requires >= {want}) — donated pad storage is "
                        f"not being re-used",
                severity="error", snippet=f"{name}:alias"))
    return out


def run_contracts(names: Optional[List[str]] = None,
                  contracts_path=None) -> List[Finding]:
    """Compile every declared contract's entry point and check it.
    Returns findings (empty = all contracts hold)."""
    out: List[Finding] = []
    for spec in load_contracts(contracts_path):
        name = spec["name"]
        if names and name not in names:
            continue
        builder = BUILDERS.get(spec["builder"])
        if builder is None:
            out.append(Finding(
                rule="CL300", path=f"contract:{name}", line=0,
                message=f"unknown builder {spec['builder']!r}",
                severity="error", snippet=f"{name}:builder"))
            continue
        try:
            artifact = builder(spec)
        except Exception as e:                    # noqa - reported, not raised
            out.append(Finding(
                rule="CL300", path=f"contract:{name}", line=0,
                message=f"entry point failed to trace/compile: "
                        f"{type(e).__name__}: {str(e)[:300]}",
                severity="error", snippet=f"{name}:trace"))
            continue
        if isinstance(artifact, list):            # dynamic check findings
            out.extend(artifact)
        else:
            out.extend(check_artifact(name, artifact, spec))
    return out
