"""consensus-lint — JAX/TPU-aware static analysis for pyconsensus_tpu.

Three layers (docs/STATIC_ANALYSIS.md):

- **Layer 1 (AST lint, :mod:`.rules`)**: a rule engine over the package's
  own source with JAX/TPU-specific rules — host-device syncs inside
  jit-traced code, Python control flow on traced values, PRNG key reuse,
  f64 literals in f32/bf16 kernels, weak-scalar dtype promotion — plus a
  few generic hygiene rules (mutable defaults, bare except, unused
  imports).
- **Layer 2 (traced contracts, :mod:`.contracts`)**: the jitted/sharded
  entry points are lowered to optimized HLO on the 8-virtual-device CPU
  mesh and checked against declared contracts (``contracts.json``): exact
  collective inventories (generalizing tests/test_hlo_collectives.py into
  reusable infrastructure), no f64 ops, no host callbacks, and a
  retrace-count budget via jit cache stats.
- **Layer 3 (whole-program deadlock analysis)**: :mod:`.dataflow` is an
  interprocedural host-divergence taint pass (CL401-404) — package-wide
  call graph + flow-sensitive def-use chains from divergent sources
  (``process_index``, clocks, env, host RNG) to program-shaping sinks
  (traced branches, jit static args, shard_map specs, mesh construction,
  collective operands); :mod:`.schedule` (CL410-413) walks the jaxprs of
  the hand-written-collective entry points and verifies cond-branch
  collective balance, ``ppermute`` bijectivity per mesh axis, and axis
  binding under ``shard_map``.
- **Layer 4 (host concurrency, :mod:`.concurrency`)**: an
  interprocedural lock-order graph over the whole package with
  attribute-resolved lock identities — cycle detection and declared
  total-order enforcement (CL801), blocking-call and
  replication-log-I/O detection under held locks (CL802), guarded-by
  inference for mutable instance attributes with a ``# guarded-by:``
  annotation convention (CL803/CL804), and fault-site catalog drift
  (CL805). :mod:`.witness` is the runtime mirror: an instrumented-lock
  recorder the fleet/serve tests and the CI chaos smoke run under,
  asserting the *observed* acquisition order stays acyclic and
  consistent with the static graph.

Findings carry rule IDs, file:line and severity; a checked-in baseline
(``baseline.json``, :mod:`.baseline`) lets the tree stay green while CI
fails on *new* violations. CLI: ``python -m pyconsensus_tpu.analysis`` or
the ``consensus-lint`` console script.
"""

from .baseline import load_baseline, match_baseline, save_baseline
from .concurrency import (CONCURRENCY_RULES, analyze_concurrency,
                          lock_order_edges)
from .dataflow import DATAFLOW_RULES, analyze_paths
from .findings import Finding, fingerprints
from .rules import RULES, lint_file, lint_paths
from .contracts import (collective_sizes, f64_ops, host_callbacks,
                        load_contracts, run_contracts)
from .schedule import (SCHEDULE_RULES, check_schedule, extract_schedule,
                       run_schedules)
from .witness import (LockWitness, WitnessViolation, load_witness,
                      static_lock_graph, witnessed)

__all__ = [
    "Finding", "fingerprints", "RULES", "lint_file", "lint_paths",
    "DATAFLOW_RULES", "analyze_paths",
    "SCHEDULE_RULES", "check_schedule", "extract_schedule",
    "run_schedules",
    "CONCURRENCY_RULES", "analyze_concurrency", "lock_order_edges",
    "LockWitness", "WitnessViolation", "load_witness",
    "static_lock_graph", "witnessed",
    "collective_sizes", "f64_ops", "host_callbacks", "load_contracts",
    "run_contracts", "load_baseline", "save_baseline", "match_baseline",
]
