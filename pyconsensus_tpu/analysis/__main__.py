"""``python -m pyconsensus_tpu.analysis`` — the consensus-lint CLI."""

from .cli import main

main()
