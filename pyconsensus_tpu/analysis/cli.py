"""consensus-lint CLI.

Usage (also ``python -m pyconsensus_tpu.analysis``):

    consensus-lint                      # Layers 1 + 3a over the package
    consensus-lint --strict             # + traced contracts (Layer 2) and
                                        #   collective schedules (Layer 3b);
                                        #   the CI gate
    consensus-lint path/to/file.py      # explicit targets
    consensus-lint --update-baseline    # accept the current tree
    consensus-lint --list-rules

Exit codes: 0 = no non-baselined findings (and, under --strict, no stale
baseline entries); 1 = new findings; 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from .baseline import (default_baseline_path, load_baseline, match_baseline,
                       save_baseline)
from .concurrency import CONCURRENCY_RULES
from .dataflow import DATAFLOW_RULES
from .determinism import DETERMINISM_RULES, STATIC_DETERMINISM_RULES
from .findings import Finding, fingerprints
from .protocol import PROTOCOL_RULES
from .rules import RULES, lint_paths


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="consensus-lint",
        description="JAX/TPU-aware static analysis for pyconsensus_tpu "
                    "(AST rules + traced HLO contracts)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "installed pyconsensus_tpu package)")
    ap.add_argument("--strict", action="store_true",
                    help="run the traced contracts and collective "
                         "schedules too and fail on stale baseline "
                         "entries (the CI gate)")
    ap.add_argument("--contracts", action="store_true",
                    help="run Layer 2 traced contracts + Layer 3b "
                         "collective schedules (implied by --strict)")
    ap.add_argument("--no-contracts", action="store_true",
                    help="skip the traced layers (2 and 3b) even under "
                         "--strict")
    ap.add_argument("--contract", action="append", default=None,
                    metavar="NAME", help="run only this contract "
                                         "(repeatable)")
    ap.add_argument("--no-dataflow", action="store_true",
                    help="skip the Layer 3a interprocedural "
                         "host-divergence taint analysis")
    ap.add_argument("--no-concurrency", action="store_true",
                    help="skip the Layer 4 host-concurrency analysis "
                         "(lock-order cycles, blocking-under-lock, "
                         "guarded-by inference, fault-site drift)")
    ap.add_argument("--no-protocol", action="store_true",
                    help="skip the Layer 5 distributed-protocol "
                         "analysis (durability ordering, RPC surface "
                         "drift, error taxonomy, idempotency, "
                         "retry scope)")
    ap.add_argument("--no-determinism", action="store_true",
                    help="skip the Layer 6 bit-determinism analysis "
                         "(order/completion/host-nondeterminism taint "
                         "into digests/journals/artifacts, float-fold "
                         "hazards, and the CL1005 compiled-artifact "
                         "checks inside the traced layer)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help=f"baseline file (default: "
                         f"{default_baseline_path()})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to accept the current tree "
                         "(keeps existing reasons)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--select", default=None, metavar="CL101,CL203",
                    help="comma-separated rule subset for Layer 1")
    ap.add_argument("--list-rules", action="store_true")
    return ap


def _list_rules() -> str:
    from .contracts import CONTRACT_RULES
    from .dataflow import DATAFLOW_RULES
    from .schedule import SCHEDULE_RULES

    lines = ["Layer 1 (AST rules):"]
    for rid, (sev, desc) in sorted(RULES.items()):
        lines.append(f"  {rid} [{sev:7s}] {desc}")
    lines.append("Layer 2 (traced contracts):")
    for rid, (sev, desc) in sorted(CONTRACT_RULES.items()):
        lines.append(f"  {rid} [{sev:7s}] {desc}")
    lines.append("Layer 3a (interprocedural host-divergence taint):")
    for rid, (sev, desc) in sorted(DATAFLOW_RULES.items()):
        lines.append(f"  {rid} [{sev:7s}] {desc}")
    lines.append("Layer 3b (collective schedules):")
    for rid, (sev, desc) in sorted(SCHEDULE_RULES.items()):
        lines.append(f"  {rid} [{sev:7s}] {desc}")
    lines.append("Layer 4 (host concurrency):")
    for rid, (sev, desc) in sorted(CONCURRENCY_RULES.items()):
        lines.append(f"  {rid} [{sev:7s}] {desc}")
    lines.append("Layer 5 (distributed protocol):")
    for rid, (sev, desc) in sorted(PROTOCOL_RULES.items()):
        lines.append(f"  {rid} [{sev:7s}] {desc}")
    lines.append("Layer 6 (bit determinism):")
    for rid, (sev, desc) in sorted(DETERMINISM_RULES.items()):
        lines.append(f"  {rid} [{sev:7s}] {desc}")
    return "\n".join(lines)


_SARIF_LEVEL = {"error": "error", "warning": "warning"}


def _all_rule_meta() -> dict:
    """Every layer's {rule: (severity, description)} in one table."""
    from .contracts import CONTRACT_RULES
    from .schedule import SCHEDULE_RULES

    meta: dict = {}
    for table in (RULES, CONTRACT_RULES, DATAFLOW_RULES, SCHEDULE_RULES,
                  CONCURRENCY_RULES, PROTOCOL_RULES, DETERMINISM_RULES):
        meta.update(table)
    return meta


def _sarif_payload(rows) -> dict:
    """SARIF 2.1.0 view of the finding rows (``--format sarif``): rule
    metadata for every rule a result references, one result per finding
    with its location, the stable fingerprint as a partialFingerprint,
    and the pragma/baseline state mapped onto SARIF's ``baselineState``
    vocabulary — the shape code-scanning UIs ingest directly. Exit
    codes are the JSON format's, unchanged."""
    meta = _all_rule_meta()
    rule_ids = sorted({r["rule"] for r in rows})
    index = {rid: i for i, rid in enumerate(rule_ids)}
    rules = []
    for rid in rule_ids:
        sev, desc = meta.get(rid, ("warning", rid))
        rules.append({
            "id": rid,
            "shortDescription": {"text": desc},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL.get(sev, "note")},
        })
    results = []
    for r in rows:
        results.append({
            "ruleId": r["rule"],
            "ruleIndex": index[r["rule"]],
            "level": _SARIF_LEVEL.get(r["severity"], "note"),
            "message": {"text": r["message"]},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": r["path"]},
                    "region": {"startLine": max(int(r["line"]), 1)},
                }}],
            "partialFingerprints": {"consensusLint/v1": r["fingerprint"]},
            "baselineState": ("unchanged" if r["state"] == "baselined"
                              else "new"),
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "consensus-lint",
                "informationUri": "docs/STATIC_ANALYSIS.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def run(argv: Optional[List[str]] = None, stdout=None) -> int:
    out = stdout or sys.stdout
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        print(_list_rules(), file=out)
        return 0

    t0 = time.monotonic()
    select = (set(s.strip() for s in args.select.split(",") if s.strip())
              if args.select else None)
    findings: List[Finding] = lint_paths(args.paths or None, select=select)

    # skip the interprocedural fixpoint entirely when --select excludes
    # every CL40x rule (it would only discard its own findings)
    if not args.no_dataflow and (select is None
                                 or select & DATAFLOW_RULES.keys()):
        from .dataflow import analyze_paths

        findings.extend(analyze_paths(args.paths or None, select=select))

    # Layer 4 mirrors Layer 3a: its lock/call-graph fixpoint only runs
    # when at least one CL80x rule is in scope
    if not args.no_concurrency and (select is None
                                    or select & CONCURRENCY_RULES.keys()):
        from .concurrency import analyze_concurrency

        findings.extend(analyze_concurrency(args.paths or None,
                                            select=select))

    # Layer 5 runs on every lint (it is pure AST work, no tracing):
    # the durability-order walk is exactly the guard ROADMAP items 3-4
    # churn against, so it must not hide behind --strict
    if not args.no_protocol and (select is None
                                 or select & PROTOCOL_RULES.keys()):
        from .protocol import analyze_protocol

        findings.extend(analyze_protocol(args.paths or None,
                                         select=select))

    # Layer 6 rides every lint like Layer 5 (pure AST + the shared
    # dataflow fixpoint): bit-determinism regressions are exactly what
    # the replay/shipping digest contract churns against. CL1005 is the
    # layer's compiled-artifact half and rides the traced gate below.
    if not args.no_determinism and (select is None
                                    or select & STATIC_DETERMINISM_RULES):
        from .determinism import analyze_determinism

        findings.extend(analyze_determinism(args.paths or None,
                                            select=select))

    run_contracts_layer = (args.strict or args.contracts
                           or args.contract) and not args.no_contracts
    if run_contracts_layer:
        from .contracts import ensure_cpu_devices, run_contracts
        from .schedule import run_schedules

        ensure_cpu_devices()
        # --no-determinism also silences Layer 6's compiled-artifact
        # half (CL1005 scatter scan + StableHLO pins) — one opt-out
        # covers the whole layer
        findings.extend(
            f for f in run_contracts(names=args.contract)
            if not (args.no_determinism and f.rule == "CL1005"))
        # Layer 3b rides the traced gate: the schedule targets need jax
        # + the virtual device mesh, same environment as the contracts.
        # --contract NAME runs are contract-focused; schedules are
        # skipped there so their findings stay out of scope
        run_schedules_layer = not args.contract
        if run_schedules_layer:
            findings.extend(run_schedules())
    else:
        run_schedules_layer = False

    if args.update_baseline:
        # preserve accepted entries this run could not have reproduced:
        # contract findings when Layer 2 did not run, and Layer-1 findings
        # in files outside a path-/rule-restricted scope — otherwise a
        # partial update would silently delete accepted decisions and the
        # next full --strict run would fail on them as "new"
        from .rules import scan_targets

        scanned = {rel for _, rel in scan_targets(args.paths or None)}

        def preserve(entry):
            if entry["path"].startswith("contract:"):
                return not run_contracts_layer or (
                    args.no_determinism and entry["rule"] == "CL1005")
            if entry["path"].startswith("schedule:"):
                return not run_schedules_layer
            if entry["rule"] in DATAFLOW_RULES and args.no_dataflow:
                return True
            if entry["rule"] in CONCURRENCY_RULES and args.no_concurrency:
                return True
            if entry["rule"] in PROTOCOL_RULES and args.no_protocol:
                return True
            if (entry["rule"] in STATIC_DETERMINISM_RULES
                    and args.no_determinism):
                return True
            if entry["path"] not in scanned:
                return True
            return bool(select) and entry["rule"] not in select

        doc = save_baseline(findings, path=args.baseline, preserve=preserve)
        print(f"baseline updated: {len(doc['findings'])} finding(s) "
              f"accepted -> {args.baseline or default_baseline_path()}",
              file=out)
        return 0

    baseline = ({"version": 1, "findings": []} if args.no_baseline
                else load_baseline(args.baseline))
    new, matched, stale = match_baseline(findings, baseline)
    if stale:
        # scope the stale check like the updater's preserve(): an entry a
        # path-/rule-restricted or contracts-off run could not have
        # reproduced is out of scope, not stale — only a run that COULD
        # observe it and didn't may fail on it
        from .rules import scan_targets

        scanned = {rel for _, rel in scan_targets(args.paths or None)}
        by_fp = {e["fingerprint"]: e for e in baseline.get("findings", [])}

        def in_scope(fp):
            e = by_fp.get(fp)
            if e is None:
                return True
            if e["path"].startswith("contract:"):
                return run_contracts_layer and not (
                    args.no_determinism and e["rule"] == "CL1005")
            if e["path"].startswith("schedule:"):
                return run_schedules_layer
            if e["rule"] in DATAFLOW_RULES and args.no_dataflow:
                return False
            if e["rule"] in CONCURRENCY_RULES and args.no_concurrency:
                return False
            if e["rule"] in PROTOCOL_RULES and args.no_protocol:
                return False
            if (e["rule"] in STATIC_DETERMINISM_RULES
                    and args.no_determinism):
                return False
            return e["path"] in scanned and (
                not select or e["rule"] in select)

        stale = [fp for fp in stale if in_scope(fp)]

    if args.format in ("json", "sarif"):
        # stable finding schema (ISSUE 16 satellite): one "findings"
        # list covering new AND baselined entries, each row carrying its
        # pragma/baseline state, so CI stages and bots consume a keyed
        # record instead of scraping render() text. The legacy "new"/
        # "baselined"/"stale_baseline" keys stay — exit codes and
        # existing consumers are unchanged; "schema" gates evolution.
        # --format sarif re-maps the SAME rows onto SARIF 2.1.0.
        def _row(f: Finding, fp: str, state: str) -> dict:
            return {"rule": f.rule, "path": f.path, "line": f.line,
                    "severity": f.severity, "message": f.message,
                    "snippet": f.snippet, "fingerprint": fp,
                    "state": state}

        rows = sorted(
            [_row(f, fp, "new")
             for f, fp in zip(new, fingerprints(new))]
            + [_row(f, fp, "baselined")
               for f, fp in zip(matched, fingerprints(matched))],
            key=lambda r: (r["path"], r["line"], r["rule"]))
        if args.format == "sarif":
            # results are explicitly sorted and the SARIF envelope is a
            # fixed literal schema
            print(json.dumps(_sarif_payload(rows), indent=2),  # consensus-lint: disable=CL1001
                  file=out)
        else:
            payload = {
                "schema": 1,
                "findings": rows,
                "new": [vars(f) | {"fingerprint": fp}
                        for f, fp in zip(new, fingerprints(new))],
                "baselined": len(matched),
                "stale_baseline": stale,
                "elapsed_s": round(time.monotonic() - t0, 2),
            }
            # findings rows are explicitly sorted above and the payload
            # keys are a fixed literal schema — insertion order IS the
            # documented order
            print(json.dumps(payload, indent=2), file=out)  # consensus-lint: disable=CL1001
    else:
        for f in new:
            print(f.render(), file=out)
        if stale and args.strict:
            for fp in stale:
                print(f"stale baseline entry (fix landed? remove it): {fp}",
                      file=out)
        n_err = sum(1 for f in new if f.severity == "error")
        n_warn = len(new) - n_err
        print(f"consensus-lint: {n_err} error(s), {n_warn} warning(s) "
              f"({len(matched)} baselined"
              + (f", {len(stale)} stale baseline entr"
                 + ("y" if len(stale) == 1 else "ies") if stale else "")
              + f") in {time.monotonic() - t0:.1f}s", file=out)

    if new:
        return 1
    if stale and args.strict:
        return 1
    return 0


def main() -> None:
    sys.exit(run())


if __name__ == "__main__":
    main()
