"""Runtime lock witness — the dynamic mirror of CL801.

:mod:`.concurrency` proves the static may-hold-before graph acyclic;
this module checks the property the proof is *about*: the acquisition
orders threads actually execute. A :class:`LockWitness` monkeypatches
the ``threading`` lock constructors while installed; any lock whose
construction site lies inside the pyconsensus_tpu package is replaced
with a recording proxy (everything else — stdlib ``queue`` mutexes, jax
internals — is left untouched, keyed by the constructor's caller
frame). Each successful acquisition of ``B`` while the acquiring thread
holds ``A`` records the observed edge ``A -> B``, keyed by the locks'
**creation sites** (``path:line``) — exactly the identity
:func:`..concurrency.lock_order_edges` emits for the static graph, so
the two sides join on the ``self._lock = threading.Lock()`` line itself.

:meth:`LockWitness.check` then asserts

1. the observed edge relation is acyclic (two threads interleaving a
   cyclic order deadlock — observing the cycle means the schedule that
   hangs exists, even if this run got lucky), and
2. the union of observed and static edges is acyclic — an observed
   ``B -> A`` whose reverse the static graph knows about means runtime
   behavior contradicts the documented order, the exact drift CL801's
   pragma-declared total orders are meant to pin.

On violation the full witness (lock table, edges, the offending cycle)
is dumped as JSON for offline diff against ``lock_order_edges()``, and
:class:`WitnessViolation` (an ``AssertionError``) carries the dump
path. The fleet/serve test suites run under the witness via an autouse
fixture, and the CI fleet chaos smoke installs it around the
kill-a-worker stage — the same wiring that keeps
``pyconsensus_jit_retraces_total`` honest for CL304.

Overhead: one dict-membership probe per nested acquisition (the global
mutex is only taken the first time an edge is seen), zero for locks
constructed outside the package. The witness is test/CI machinery;
nothing in the serving path imports it.
"""

from __future__ import annotations

import contextlib
import json
import pathlib
import sys
import threading
from typing import Dict, List, Optional, Tuple

from .rules import default_scan_root

__all__ = ["LockWitness", "WitnessViolation", "static_lock_graph",
           "load_witness", "witnessed"]

#: the constructors patched while a witness is installed — the same set
#: :mod:`.concurrency` treats as lock definitions (_LOCK_CONSTRUCTORS)
_PATCHED = ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore")

#: unpatched originals, bound at import time so a witness's own state
#: lock (and proxies' inner locks) can never be witnessed recursively
_REAL = {name: getattr(threading, name) for name in _PATCHED}

_PKG_DIR = str(pathlib.Path(__file__).resolve().parents[1])


class WitnessViolation(AssertionError):
    """The observed acquisition order is cyclic, or contradicts the
    static may-hold-before graph. ``cycle`` is the offending lock-key
    sequence; ``dump_path`` is where the full witness JSON landed."""

    def __init__(self, message: str, cycle: Optional[List[str]] = None,
                 dump_path: Optional[str] = None):
        super().__init__(message)
        self.cycle = cycle or []
        self.dump_path = dump_path


def _rel(filename: str) -> str:
    """Repo-relative posix path, with :func:`scan_targets`'s fallback
    (bare filename) so runtime keys match static keys byte-for-byte."""
    p = pathlib.Path(filename)
    try:
        return p.resolve().relative_to(default_scan_root()).as_posix()
    except (ValueError, OSError):
        return p.name


class _WitnessedLock:
    """Recording proxy over a real Lock/RLock/Semaphore. Forwards the
    full lock protocol (including the ``_acquire_restore`` family
    ``threading.Condition`` needs when handed an RLock)."""

    def __init__(self, witness: "LockWitness", key: str, inner):
        self._w = witness
        self._key = key
        self._inner = inner

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._w._on_acquire(self._key)
        return got

    def release(self):
        self._inner.release()
        self._w._on_release(self._key)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition-compatibility when a witnessed lock backs a Condition:
    # wait() parks through these, so held-state must track them too.
    # threading.Condition binds these names when the lock HAS them and
    # falls back to acquire/release shims otherwise — a proxy over a
    # plain Lock must provide the same shims itself, or advertising
    # the names would crash the stdlib-supported Condition(Lock()) form
    # only while the witness is installed.
    def _acquire_restore(self, state):
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._w._on_acquire(self._key)

    def _release_save(self):
        inner = self._inner
        if hasattr(inner, "_release_save"):
            state = inner._release_save()
        else:
            inner.release()
            state = None
        self._w._on_release(self._key)
        return state

    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # the stdlib's own plain-lock heuristic
        if inner.acquire(blocking=False):
            inner.release()
            return False
        return True

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<witnessed {self._inner!r} @ {self._key}>"


class _WitnessedCondition(_WitnessedLock):
    """A Condition proxy: ``wait()`` releases the condition's own lock
    while parked, so the held stack must drop the key for the duration
    (otherwise every lock taken by *other* code during the wait would
    fabricate an edge from a lock this thread no longer holds)."""

    def wait(self, timeout=None):
        self._w._on_release(self._key)
        try:
            return self._inner.wait(timeout)
        finally:
            self._w._on_acquire(self._key)

    def wait_for(self, predicate, timeout=None):
        self._w._on_release(self._key)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._w._on_acquire(self._key)


class LockWitness:
    """Records actual lock-acquisition order per thread while installed.

    Use as a context manager (:func:`witnessed`) or install/uninstall
    explicitly; :meth:`check` validates, :meth:`dump` persists."""

    def __init__(self):
        self._mu = _REAL["Lock"]()
        self._tls = threading.local()
        #: creation-site key -> key (the static lock table supplies
        #: display names at check time; the witness only knows sites)
        self.locks: Dict[str, str] = {}
        #: (a_key, b_key) -> first-observation record
        self.edges: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._installed = False
        self._saved: Dict[str, object] = {}

    # -- recording ------------------------------------------------------

    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _on_acquire(self, key: str) -> None:
        held = self._held()
        for h in held:
            if h == key:
                continue
            pair = (h, key)
            if pair in self.edges:        # GIL-atomic probe: fast path
                continue
            with self._mu:
                self.edges.setdefault(pair, {
                    "thread": threading.current_thread().name})
        held.append(key)

    def _on_release(self, key: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == key:
                del held[i]
                return
        # released by a thread that never recorded the acquire (handoff
        # idiom, or acquired before install) — nothing to unwind

    # -- construction-site patching ------------------------------------

    def _make_ctor(self, kind: str):
        real = _REAL[kind]
        proxy_cls = (_WitnessedCondition if kind == "Condition"
                     else _WitnessedLock)

        def ctor(*args, **kwargs):
            inner = real(*args, **kwargs)
            frame = sys._getframe(1)
            filename = frame.f_code.co_filename
            if not filename.startswith(_PKG_DIR):
                return inner              # not ours: zero overhead
            key = f"{_rel(filename)}:{frame.f_lineno}"
            with self._mu:
                self.locks.setdefault(key, key)
            return proxy_cls(self, key, inner)

        return ctor

    def install(self) -> "LockWitness":
        if self._installed:
            return self
        self._saved = {k: getattr(threading, k) for k in _PATCHED}
        for kind in _PATCHED:
            setattr(threading, kind, self._make_ctor(kind))
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for kind, orig in self._saved.items():
            setattr(threading, kind, orig)
        self._installed = False

    # -- validation -----------------------------------------------------

    def report(self) -> dict:
        """The witness as JSON-ready data (the dump format)."""
        with self._mu:
            return {
                "locks": dict(sorted(self.locks.items())),
                "edges": [{"from": a, "to": b, **info}
                          for (a, b), info in sorted(self.edges.items())],
            }

    def dump(self, path) -> pathlib.Path:
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.report(), indent=2) + "\n",
                        encoding="utf-8")
        return path

    def check(self, static: Optional[dict] = None,
              dump_path=None) -> dict:
        """Assert the observed order is acyclic and (when ``static`` —
        a :func:`..concurrency.lock_order_edges` dict — is given)
        stays acyclic when unioned with the static may-hold-before
        edges. Returns the report on success; dumps it and raises
        :class:`WitnessViolation` on failure."""
        names = dict(static.get("locks", {})) if static else {}

        def render(key: str) -> str:
            return f"{names[key]} ({key})" if key in names else key

        with self._mu:     # snapshot: a draining thread may still record
            observed = sorted(self.edges)
        cycle = _find_cycle(observed)
        kind = "observed lock-acquisition order is cyclic"
        if cycle is None and static is not None:
            # only a union cycle that observation CONTRIBUTED to is the
            # witness's business: an observed edge (a, b) whose reverse
            # path b ->* a exists through the combined graph. A cycle
            # purely among static edges is CL801's finding, not runtime
            # drift — the witness must not blame behavior that never
            # happened.
            combined = sorted(set(observed)
                              | {(a, b) for a, b in static["edges"]})
            adj: Dict[str, List[str]] = {}
            for a, b in combined:
                adj.setdefault(a, []).append(b)
            for a, b in observed:
                back = _find_path(adj, b, a)
                if back is not None:
                    cycle = [a] + back
                    kind = ("observed acquisition order contradicts the "
                            "static may-hold-before graph")
                    break
        if cycle is None:
            return self.report()
        dumped = None
        if dump_path is not None:
            dumped = str(self.dump(dump_path))
        chain = " -> ".join(render(k) for k in cycle)
        raise WitnessViolation(
            f"{kind}: {chain}"
            + (f" (witness dumped to {dumped})" if dumped else ""),
            cycle=cycle, dump_path=dumped)


def _find_cycle(edges) -> Optional[List[str]]:
    """First cycle in the edge list as ``[a, b, ..., a]``, or None."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {v: WHITE for v in graph}
    for root in sorted(graph):
        if color[root] != WHITE:
            continue
        path: List[str] = []
        stack: List[Tuple[str, int]] = [(root, 0)]
        while stack:
            node, idx = stack[-1]
            if idx == 0:
                color[node] = GRAY
                path.append(node)
            succ = graph[node]
            if idx < len(succ):
                stack[-1] = (node, idx + 1)
                nxt = succ[idx]
                if color[nxt] == GRAY:
                    return path[path.index(nxt):] + [nxt]
                if color[nxt] == WHITE:
                    stack.append((nxt, 0))
            else:
                color[node] = BLACK
                path.pop()
                stack.pop()
    return None


def _find_path(adj: Dict[str, List[str]], src: str,
               dst: str) -> Optional[List[str]]:
    """Shortest ``[src, ..., dst]`` node path through ``adj``, or
    None. BFS with parent pointers; the graphs here are tiny."""
    if src == dst:
        return [src]
    parent = {src: None}
    frontier = [src]
    while frontier:
        nxt = []
        for v in frontier:
            for w in adj.get(v, ()):
                if w in parent:
                    continue
                parent[w] = v
                if w == dst:
                    path = [w]
                    while parent[path[-1]] is not None:
                        path.append(parent[path[-1]])
                    return path[::-1]
                nxt.append(w)
        frontier = nxt
    return None


def load_witness(path) -> dict:
    """Round-trip a dumped witness back to its report dict."""
    return json.loads(pathlib.Path(path).read_text(encoding="utf-8"))


_STATIC_CACHE: Optional[dict] = None


def static_lock_graph(refresh: bool = False) -> dict:
    """The static lock table + may-hold-before edges for the installed
    package (cached — the interprocedural pass costs ~1 s)."""
    global _STATIC_CACHE
    if _STATIC_CACHE is None or refresh:
        from .concurrency import lock_order_edges

        _STATIC_CACHE = lock_order_edges()
    return _STATIC_CACHE


@contextlib.contextmanager
def witnessed(static: Optional[dict] = None, check: bool = True,
              dump_path=None):
    """Install a fresh :class:`LockWitness` for the block; on clean
    exit, :meth:`~LockWitness.check` it (against ``static`` when
    given). The witness is always uninstalled, even on error."""
    w = LockWitness()
    w.install()
    try:
        yield w
    finally:
        w.uninstall()
    if check:
        w.check(static=static, dump_path=dump_path)
