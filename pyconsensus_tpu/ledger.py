"""Multi-round reputation ledger: checkpoint/resume across oracle rounds.

The reference is stateless per call — "reputation carried across *rounds* by
the caller" (SURVEY.md §5, checkpoint/resume row). This module is that
caller, made first-class: a :class:`ReputationLedger` feeds each round's
``smooth_rep`` into the next resolution, records per-round metrics, and
serializes its full state — to a single ``.npz`` file or an orbax
checkpoint directory (``save(..., format="orbax")``) — so a long-running
oracle (e.g. a Truthcoin-style voting period sequence) can stop and
resume anywhere.

>>> ledger = ReputationLedger(n_reporters=50)
>>> result = ledger.resolve(reports_round_1)       # uniform prior
>>> result = ledger.resolve(reports_round_2)       # carries reputation
>>> ledger.save("state.npz")
>>> resumed = ReputationLedger.load("state.npz")
"""

from __future__ import annotations

import json
import pathlib

import numpy as np

from .io import ensure_parent
from .oracle import Oracle

__all__ = ["ReputationLedger"]

_FORMAT_VERSION = 1


def _json_scalar(obj):
    """JSON fallback for numpy scalars in oracle kwargs (e.g. a
    ``max_iterations`` read out of a config array as ``np.int64``) — without
    this, ``save()`` would crash exactly when a long run needs it."""
    if isinstance(obj, np.generic):
        return obj.item()
    raise TypeError(f"oracle_kwargs value {obj!r} is not JSON-serializable")


class ReputationLedger:
    """Carries the reputation vector (and resolution history) across rounds.

    Parameters
    ----------
    n_reporters : int
        Fixed reporter-set size (reputation dimension).
    reputation : (R,) array or None
        Starting reputation; uniform if None. Normalized on entry.
    oracle_kwargs : dict
        Default :class:`Oracle` knobs applied to every round (individual
        ``resolve`` calls may override).
    """

    def __init__(self, n_reporters: int, reputation=None,
                 **oracle_kwargs) -> None:
        self.n_reporters = int(n_reporters)
        if reputation is None:
            rep = np.full(self.n_reporters, 1.0 / self.n_reporters)
        else:
            rep = np.asarray(reputation, dtype=np.float64)
            if rep.shape != (self.n_reporters,):
                raise ValueError(f"reputation shape {rep.shape} does not "
                                 f"match {self.n_reporters} reporters")
            # mirror Oracle's validation so bad state fails here, at the
            # construction/load site, not rounds later inside resolve()
            if np.isnan(rep).any():
                raise ValueError("reputation must not contain NaN")
            if (rep < 0).any():
                raise ValueError("reputation must be non-negative")
            total = rep.sum()
            if total <= 0:
                raise ValueError("reputation must have positive mass")
            rep = rep / total
        self.reputation = rep
        self.oracle_kwargs = dict(oracle_kwargs)
        self.round = 0
        #: per-round scalars: certainty / participation / convergence
        self.history: list[dict] = []

    # -- rounds --------------------------------------------------------------

    def resolve(self, reports, event_bounds=None, **overrides) -> dict:
        """Run one oracle round with the ledger's current reputation, feed
        the resulting ``smooth_rep`` forward, and return the round's full
        result dict."""
        kwargs = {**self.oracle_kwargs, **overrides}
        oracle = Oracle(reports=reports, event_bounds=event_bounds,
                        reputation=self.reputation, **kwargs)
        result = oracle.consensus()
        self.reputation = np.asarray(result["agents"]["smooth_rep"],
                                     dtype=np.float64)
        self.round += 1
        self.history.append({
            "round": self.round,
            "certainty": float(result["certainty"]),
            "participation": float(result["participation"]),
            "convergence": bool(result["convergence"]),
            "iterations": int(result["iterations"]),
        })
        return result

    # -- checkpoint / resume -------------------------------------------------

    def _state_tree(self) -> dict:
        return {
            "format_version": np.int64(_FORMAT_VERSION),
            "reputation": self.reputation,
            "round": np.int64(self.round),
            "history": np.frombuffer(
                json.dumps(self.history).encode(), dtype=np.uint8),
            "oracle_kwargs": np.frombuffer(
                json.dumps(self.oracle_kwargs,
                           default=_json_scalar).encode(), dtype=np.uint8),
        }

    def save(self, path, format: str = "npz") -> None:
        """Serialize full ledger state to ``path``.

        ``format="npz"`` (default): a single ``.npz`` file (the suffix is
        appended if missing, matching what np.savez writes so
        ``load(path)`` round-trips either spelling). ``format="orbax"``:
        an orbax checkpoint DIRECTORY (SURVEY.md §5's "orbax if sweeps get
        huge" — atomic writes, async-friendly, the idiomatic choice when
        the ledger lives next to other orbax-managed state).
        """
        if format == "orbax":
            import orbax.checkpoint as ocp

            # force=True: re-checkpointing to a fixed path every round is
            # the module's core use case — match npz overwrite semantics
            ocp.PyTreeCheckpointer().save(
                pathlib.Path(path).resolve(), self._state_tree(), force=True)
            return
        if format != "npz":
            raise ValueError(f"unknown checkpoint format {format!r}; "
                             "choose 'npz' or 'orbax'")
        path = pathlib.Path(path)
        if path.suffix != ".npz":
            path = path.with_name(path.name + ".npz")
        np.savez(ensure_parent(path), **self._state_tree())

    @classmethod
    def _from_state(cls, data) -> "ReputationLedger":
        version = int(data["format_version"])
        if version > _FORMAT_VERSION:
            raise ValueError(f"checkpoint format {version} is newer "
                             f"than supported {_FORMAT_VERSION}")
        rep = np.asarray(data["reputation"], dtype=np.float64)
        kwargs = json.loads(bytes(data["oracle_kwargs"]).decode())
        ledger = cls(n_reporters=rep.shape[0], reputation=rep, **kwargs)
        ledger.reputation = rep          # verbatim — no re-normalization,
        ledger.round = int(data["round"])  # resume is bit-exact
        ledger.history = json.loads(bytes(data["history"]).decode())
        return ledger

    @classmethod
    def load(cls, path) -> "ReputationLedger":
        """Restore a ledger exactly as :meth:`save` left it. The format is
        auto-detected: an orbax checkpoint is a directory, an npz a file."""
        path = pathlib.Path(path)
        if path.is_dir():
            import orbax.checkpoint as ocp

            data = ocp.PyTreeCheckpointer().restore(path.resolve())
            return cls._from_state(data)
        if not path.exists() and path.suffix != ".npz":
            path = path.with_name(path.name + ".npz")
        with np.load(path) as data:
            return cls._from_state(data)
