"""Multi-round reputation ledger: checkpoint/resume across oracle rounds.

The reference is stateless per call — "reputation carried across *rounds* by
the caller" (SURVEY.md §5, checkpoint/resume row). This module is that
caller, made first-class: a :class:`ReputationLedger` feeds each round's
``smooth_rep`` into the next resolution, records per-round metrics, and
serializes its full state — to a single ``.npz`` file or an orbax
checkpoint directory (``save(..., format="orbax")``) — so a long-running
oracle (e.g. a Truthcoin-style voting period sequence) can stop and
resume anywhere.

>>> ledger = ReputationLedger(n_reporters=50)
>>> result = ledger.resolve(reports_round_1)       # uniform prior
>>> result = ledger.resolve(reports_round_2)       # carries reputation
>>> ledger.save("state.npz")
>>> resumed = ReputationLedger.load("state.npz")
"""

from __future__ import annotations

import json
import pathlib

import numpy as np

from .faults import CheckpointCorruptionError
from .faults import plan as _faults
from .io import atomic_write
from .oracle import Oracle

__all__ = ["ReputationLedger"]

_FORMAT_VERSION = 1

#: required checkpoint fields -> validator run on restore. Every restored
#: value passes its validator or ``load`` raises a
#: :class:`CheckpointCorruptionError` NAMING the field — a bad
#: checkpoint must fail at the load site, not rounds later inside
#: ``resolve`` (ISSUE 4 satellite).
_REQUIRED_FIELDS = ("format_version", "reputation", "round", "history",
                    "oracle_kwargs")


def _json_scalar(obj):
    """JSON fallback for numpy scalars in oracle kwargs (e.g. a
    ``max_iterations`` read out of a config array as ``np.int64``) — without
    this, ``save()`` would crash exactly when a long run needs it."""
    if isinstance(obj, np.generic):
        return obj.item()
    raise TypeError(f"oracle_kwargs value {obj!r} is not JSON-serializable")


class ReputationLedger:
    """Carries the reputation vector (and resolution history) across rounds.

    Parameters
    ----------
    n_reporters : int
        Fixed reporter-set size (reputation dimension).
    reputation : (R,) array or None
        Starting reputation; uniform if None. Normalized on entry.
    oracle_kwargs : dict
        Default :class:`Oracle` knobs applied to every round (individual
        ``resolve`` calls may override).
    """

    def __init__(self, n_reporters: int, reputation=None,
                 **oracle_kwargs) -> None:
        self.n_reporters = int(n_reporters)
        if reputation is None:
            rep = np.full(self.n_reporters, 1.0 / self.n_reporters)
        else:
            rep = np.asarray(reputation, dtype=np.float64)
            if rep.shape != (self.n_reporters,):
                raise ValueError(f"reputation shape {rep.shape} does not "
                                 f"match {self.n_reporters} reporters")
            # mirror Oracle's validation so bad state fails here, at the
            # construction/load site, not rounds later inside resolve()
            if np.isnan(rep).any():
                raise ValueError("reputation must not contain NaN")
            if (rep < 0).any():
                raise ValueError("reputation must be non-negative")
            total = rep.sum()
            if total <= 0:
                raise ValueError("reputation must have positive mass")
            rep = rep / total
        # Owner-confined, deliberately lock-free: a ledger is either
        # used single-threaded (sweep/CLI) or owned by exactly one
        # MarketSession, whose _lock serializes every resolve/record.
        self.reputation = rep          # guarded-by: none
        self.oracle_kwargs = dict(oracle_kwargs)
        self.round = 0                 # guarded-by: none
        #: per-round scalars: certainty / participation / convergence
        self.history: list[dict] = []
        #: auxiliary numeric state checkpointed ATOMICALLY with the
        #: round commit (optional ``aux__*`` npz fields; absent in older
        #: checkpoints, which load with an empty dict). The serve
        #: layer's incremental sessions carry their warm eigenstate
        #: here so replication-log replay restores the exact bits the
        #: never-killed session would hold (docs/SERVING.md,
        #: ``bucket_incremental``).
        self.aux: dict = {}            # guarded-by: none

    # -- rounds --------------------------------------------------------------

    def resolve(self, reports, event_bounds=None, **overrides) -> dict:
        """Run one oracle round with the ledger's current reputation, feed
        the resulting ``smooth_rep`` forward, and return the round's full
        result dict."""
        kwargs = {**self.oracle_kwargs, **overrides}
        oracle = Oracle(reports=reports, event_bounds=event_bounds,
                        reputation=self.reputation, **kwargs)
        result = oracle.consensus()
        self.reputation = np.asarray(result["agents"]["smooth_rep"],
                                     dtype=np.float64)
        self.round += 1
        self.history.append({
            "round": self.round,
            "certainty": float(result["certainty"]),
            "participation": float(result["participation"]),
            "convergence": bool(result["convergence"]),
            "iterations": int(result["iterations"]),
        })
        return result

    def record_round(self, result: dict) -> dict:
        """Carry the reputation of a round RESOLVED ELSEWHERE — the
        serving layer's market sessions resolve through the bucketed or
        streaming paths and feed the ledger here, so checkpoint/resume
        and per-round history work identically to :meth:`resolve`.
        Accepts either the nested ``Oracle.consensus()`` dict or a flat
        light result dict; returns ``result`` for chaining."""
        if "agents" in result:             # nested Oracle.consensus dict
            agents = result["agents"]
            certainty = result["certainty"]          # scalar there
            participation = result["participation"]
        else:                              # flat light result dict
            agents = result
            certainty = result["avg_certainty"]
            participation = 1.0 - float(result["percent_na"])
        rep = np.asarray(agents["smooth_rep"], dtype=np.float64)
        if rep.shape != (self.n_reporters,):
            raise ValueError(
                f"round reputation shape {rep.shape} does not match the "
                f"ledger's {self.n_reporters} reporters")
        self.reputation = rep
        self.round += 1
        self.history.append({
            "round": self.round,
            "certainty": float(certainty),
            "participation": float(participation),
            "convergence": bool(result["convergence"]),
            "iterations": int(result["iterations"]),
        })
        return result

    # -- checkpoint / resume -------------------------------------------------

    def _state_tree(self) -> dict:
        state = {
            "format_version": np.int64(_FORMAT_VERSION),
            "reputation": self.reputation,
            "round": np.int64(self.round),
            "history": np.frombuffer(
                json.dumps(self.history).encode(), dtype=np.uint8),
            "oracle_kwargs": np.frombuffer(
                json.dumps(self.oracle_kwargs,
                           default=_json_scalar).encode(), dtype=np.uint8),
        }
        # sorted: npz members are written in dict order, so the aux
        # insertion order would otherwise decide the checkpoint's BYTES
        # — two workers carrying identical state must serialize
        # identical files (the replay/shipping digest contract)
        for key, value in sorted(self.aux.items()):
            state[f"aux__{key}"] = np.asarray(value)
        return state

    def save(self, path, format: str = "npz") -> None:
        """Serialize full ledger state to ``path``.

        ``format="npz"`` (default): a single ``.npz`` file (the suffix is
        appended if missing, matching what np.savez writes so
        ``load(path)`` round-trips either spelling). ``format="orbax"``:
        an orbax checkpoint DIRECTORY (SURVEY.md §5's "orbax if sweeps get
        huge" — atomic writes, async-friendly, the idiomatic choice when
        the ledger lives next to other orbax-managed state).
        """
        if format == "orbax":
            import orbax.checkpoint as ocp

            # force=True: re-checkpointing to a fixed path every round is
            # the module's core use case — match npz overwrite semantics
            ocp.PyTreeCheckpointer().save(
                pathlib.Path(path).resolve(), self._state_tree(), force=True)
            return
        if format != "npz":
            raise ValueError(f"unknown checkpoint format {format!r}; "
                             "choose 'npz' or 'orbax'")
        path = pathlib.Path(path)
        if path.suffix != ".npz":
            path = path.with_name(path.name + ".npz")
        state = self._state_tree()

        def write(tmp):
            np.savez(tmp, **state)
            _faults.fire("ledger.save", path=tmp)
        # atomic + fsynced (io.atomic_write): a crash mid-save leaves the
        # PREVIOUS checkpoint intact — overwriting a good checkpoint in
        # place was the one way a long run could lose its only copy
        atomic_write(path, write, suffix=".tmp.npz")

    @classmethod
    def _validate_state(cls, data, source) -> dict:
        """Field-presence / shape / dtype / finiteness validation of a
        restored state tree. Returns the decoded pieces; raises
        :class:`CheckpointCorruptionError` naming the offending field."""
        def bad(field, why, **ctx):
            return CheckpointCorruptionError(
                f"{source}: checkpoint field '{field}' {why}",
                field=field, source=str(source), **ctx)

        keys = set(getattr(data, "files", None) or data.keys())
        for field in _REQUIRED_FIELDS:
            if field not in keys:
                raise bad(field, "is missing")
        try:
            version = int(np.asarray(data["format_version"]).item())
        except (TypeError, ValueError) as exc:
            raise bad("format_version", f"is not an integer ({exc})")
        if version > _FORMAT_VERSION:
            raise bad("format_version",
                      f"({version}) is newer than supported "
                      f"{_FORMAT_VERSION}", version=version)
        rep = np.asarray(data["reputation"])
        if rep.ndim != 1 or rep.shape[0] < 1:
            raise bad("reputation",
                      f"must be a non-empty 1-D vector, got shape "
                      f"{rep.shape}", shape=tuple(rep.shape))
        if rep.dtype.kind not in "fiu":
            raise bad("reputation", f"has non-numeric dtype {rep.dtype}")
        rep = rep.astype(np.float64)
        if not np.isfinite(rep).all():
            raise bad("reputation", "contains non-finite values")
        if (rep < 0).any():
            raise bad("reputation", "contains negative mass")
        if rep.sum() <= 0:
            raise bad("reputation", "has no positive mass")
        try:
            rnd = int(np.asarray(data["round"]).item())
        except (TypeError, ValueError) as exc:
            raise bad("round", f"is not an integer scalar ({exc})")
        if rnd < 0:
            raise bad("round", f"is negative ({rnd})", value=rnd)
        decoded = {}
        for field, expect in (("history", list), ("oracle_kwargs", dict)):
            try:
                decoded[field] = json.loads(bytes(
                    np.asarray(data[field], dtype=np.uint8)).decode())
            except (TypeError, ValueError) as exc:
                raise bad(field, f"does not decode as JSON ({exc})")
            if not isinstance(decoded[field], expect):
                raise bad(field, f"decodes to "
                          f"{type(decoded[field]).__name__}, expected "
                          f"{expect.__name__}")
        aux = {}
        for key in keys:
            if not key.startswith("aux__"):
                continue
            arr = np.asarray(data[key])
            if arr.dtype.kind not in "fiu":
                raise bad(key, f"has non-numeric dtype {arr.dtype}")
            if not np.isfinite(arr.astype(np.float64)).all():
                raise bad(key, "contains non-finite values")
            aux[key[len("aux__"):]] = arr
        return {"reputation": rep, "round": rnd, "aux": aux, **decoded}

    @classmethod
    def _from_state(cls, state, source="checkpoint") -> "ReputationLedger":
        """Build a ledger from an ALREADY-validated state dict (see
        :meth:`_validate_state`). A rebuild failure (e.g. a foreign
        kwarg in ``oracle_kwargs``) is still a checkpoint problem and
        surfaces under the taxonomy."""
        rep = state["reputation"]
        try:
            ledger = cls(n_reporters=rep.shape[0], reputation=rep,
                         **state["oracle_kwargs"])
        except Exception as exc:
            raise CheckpointCorruptionError(
                f"{source}: checkpoint does not rebuild "
                f"({type(exc).__name__}: {exc})",
                source=str(source)) from exc
        ledger.reputation = rep          # verbatim — no re-normalization,
        ledger.round = state["round"]    # resume is bit-exact
        ledger.history = state["history"]
        ledger.aux = {k: np.asarray(v)
                      for k, v in state.get("aux", {}).items()}
        return ledger

    @classmethod
    def _read_state(cls, path) -> dict:
        """Open a checkpoint (orbax dir / npz file, with the ``.npz``
        suffix fallback) and run the full field validation. The ONE
        reader behind both :meth:`load` and :meth:`verify` — the
        takeover preflight must accept and reject exactly the files the
        load that follows it would, so they cannot be allowed to
        drift."""
        path = pathlib.Path(path)
        if path.is_dir():
            import orbax.checkpoint as ocp

            try:
                data = ocp.PyTreeCheckpointer().restore(path.resolve())
                return cls._validate_state(data, source=path)
            except CheckpointCorruptionError:
                raise
            except Exception as exc:
                # a truncated orbax directory / TensorStore error —
                # same taxonomy as the npz branch below
                raise CheckpointCorruptionError(
                    f"{path}: unreadable checkpoint "
                    f"({type(exc).__name__}: {exc})",
                    source=str(path)) from exc
        if not path.exists() and path.suffix != ".npz":
            path = path.with_name(path.name + ".npz")
        try:
            with np.load(path) as data:
                return cls._validate_state(data, source=path)
        except (FileNotFoundError, CheckpointCorruptionError):
            raise
        except Exception as exc:
            # a torn final record truncates the npz central directory /
            # last member — zipfile.BadZipFile or a short-read
            # ValueError, the classic power-loss / SIGKILL-mid-write
            # artifact, surfaced under the taxonomy
            raise CheckpointCorruptionError(
                f"{path}: unreadable checkpoint ({type(exc).__name__}: "
                f"{exc})", source=str(path)) from exc

    @classmethod
    def verify(cls, path) -> dict:
        """Dry-run integrity check of a checkpoint: run the FULL load
        validation (field presence / shape / dtype / finiteness / JSON
        decode, torn-npz detection included) WITHOUT constructing a
        ledger or mutating anything — the file is opened read-only and
        no ``ReputationLedger`` state exists afterward. Returns a
        summary ``{"n_reporters", "round", "rounds_recorded"}`` on
        success; raises :class:`CheckpointCorruptionError` naming the
        offending field or file otherwise.

        This is the hot-standby takeover PREFLIGHT (ISSUE 8): a standby
        about to adopt a dead worker's sessions verifies every ledger it
        would replay first, so it never builds serving state from a
        corrupt log — a torn final record (the classic power-loss /
        SIGKILL-mid-write artifact) fails HERE, before any session
        exists to serve wrong bits."""
        state = cls._read_state(path)
        return {"n_reporters": int(state["reputation"].shape[0]),
                "round": int(state["round"]),
                "rounds_recorded": len(state["history"])}

    @classmethod
    def load(cls, path) -> "ReputationLedger":
        """Restore a ledger exactly as :meth:`save` left it. The format is
        auto-detected: an orbax checkpoint is a directory, an npz a file.
        A torn/unreadable file or a failed field validation raises
        :class:`CheckpointCorruptionError` naming the problem — never a
        parser traceback or, worse, an error rounds later inside
        ``resolve``. :meth:`verify` runs the same validation as a
        no-construction dry run (the takeover preflight)."""
        path = pathlib.Path(path)
        _faults.fire("ledger.load", path=path)
        return cls._from_state(cls._read_state(path), source=path)
