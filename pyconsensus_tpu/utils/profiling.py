"""Lightweight observability compatibility layer.

Since ISSUE 3 the real observability subsystem is
:mod:`pyconsensus_tpu.obs` (span tracer + metrics registry + sinks);
:class:`PhaseTimer` survives as a thin shim over it so pre-existing
callers (tools/profile_phases.py and friends) keep their accumulating
totals()/means()/report() surface while their phases ALSO show up as
spans in the process-wide tracer and as
``pyconsensus_phase_seconds{phase=...}`` in the metrics registry.

``trace`` (the jax.profiler wrapper) is unchanged.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Iterator, List, Optional

import jax

from .. import obs

__all__ = ["PhaseTimer", "trace"]


class PhaseTimer:
    """Accumulating named-phase wall-clock timer (compatibility shim over
    :mod:`pyconsensus_tpu.obs` — each ``phase`` opens a tracer span).

    >>> timer = PhaseTimer()
    >>> with timer.phase("pca"):
    ...     ...
    >>> timer.totals()
    {'pca': 0.0123}

    ``block=True`` (default) calls ``block_until_ready`` on EVERY value
    the body stores via :meth:`observe` — ``_pending`` is a list, so a
    phase that observes twice waits on both (the original single-slot
    implementation overwrote the first value, attributing its device time
    to whatever phase blocked next).
    """

    def __init__(self) -> None:
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._pending: List = []
        self._span = None

    def observe(self, value):
        """Mark a jax value whose completion the current phase should wait
        on before stopping the clock. Accumulates — every observed value
        is blocked on at phase exit. Outside any phase the slot holds the
        LAST value only (the original single-slot behavior — nothing will
        ever drain it, so accumulating there would pin every observed
        device buffer for the timer's lifetime)."""
        if self._span is not None:
            self._pending.append(value)
            self._span.observe(value)
        else:
            self._pending = [value]
        return value

    @contextlib.contextmanager
    def phase(self, name: str, block: bool = True) -> Iterator[None]:
        outer_span, outer_pending = self._span, self._pending
        self._pending = []
        sp = None
        try:
            with obs.span(name, timer="PhaseTimer") as sp:
                self._span = sp
                try:
                    yield
                finally:
                    if not block:
                        # the span must not block either: drop the
                        # observed values so dispatch stays asynchronous
                        sp._pending = []
                    self._span = outer_span
                    self._pending = outer_pending
        finally:
            # span exit blocked on every observed value (observe() feeds
            # the span) before stamping duration_s; reuse it so shim
            # totals and tracer spans can never disagree. Accumulate even
            # when the body raised — the original implementation did (a
            # sweep tolerating one failing phase keeps its totals).
            if sp is not None and sp.duration_s is not None:
                self._totals[name] = (self._totals.get(name, 0.0)
                                      + sp.duration_s)
                self._counts[name] = self._counts.get(name, 0) + 1

    def totals(self) -> Dict[str, float]:
        return dict(self._totals)

    def means(self) -> Dict[str, float]:
        return {k: v / self._counts[k] for k, v in self._totals.items()}

    def report(self) -> str:
        lines = [f"  {name:24s} {total * 1e3:10.3f} ms "
                 f"({self._counts[name]} call(s))"
                 for name, total in sorted(self._totals.items(),
                                           key=lambda kv: -kv[1])]
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` wrapper that no-ops when ``log_dir`` is None,
    so callers can thread a ``--trace`` flag straight through."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
