"""Lightweight observability: wall-clock phase timers and a jax.profiler
wrapper (SURVEY.md §5 — the reference had only ``verbose`` prints; the
rebuild adds structured timing and real TPU traces)."""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

import jax

__all__ = ["PhaseTimer", "trace"]


class PhaseTimer:
    """Accumulating named-phase wall-clock timer.

    >>> timer = PhaseTimer()
    >>> with timer.phase("pca"):
    ...     ...
    >>> timer.totals()
    {'pca': 0.0123}

    ``block=True`` (default) calls ``block_until_ready`` on the value the
    body stores via :meth:`observe`, so asynchronous dispatch doesn't
    attribute device time to the wrong phase.
    """

    def __init__(self) -> None:
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._pending = None

    def observe(self, value):
        """Mark a jax value whose completion the current phase should wait
        on before stopping the clock."""
        self._pending = value
        return value

    @contextlib.contextmanager
    def phase(self, name: str, block: bool = True) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            if block and self._pending is not None:
                jax.block_until_ready(self._pending)
                self._pending = None
            elapsed = time.perf_counter() - start
            self._totals[name] = self._totals.get(name, 0.0) + elapsed
            self._counts[name] = self._counts.get(name, 0) + 1

    def totals(self) -> Dict[str, float]:
        return dict(self._totals)

    def means(self) -> Dict[str, float]:
        return {k: v / self._counts[k] for k, v in self._totals.items()}

    def report(self) -> str:
        lines = [f"  {name:24s} {total * 1e3:10.3f} ms "
                 f"({self._counts[name]} call(s))"
                 for name, total in sorted(self._totals.items(),
                                           key=lambda kv: -kv[1])]
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` wrapper that no-ops when ``log_dir`` is None,
    so callers can thread a ``--trace`` flag straight through."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
