"""Observability helpers: phase timers and profiler hooks (the rebuild's
answer to SURVEY.md §5 "tracing/profiling: absent in reference")."""

from .profiling import PhaseTimer, trace

__all__ = ["PhaseTimer", "trace"]
