"""NumPy reference kernels for the Truthcoin/Sztorc oracle consensus pipeline.

This module is the *correctness anchor* of the framework: every kernel here is a
small, pure function on plain ``numpy`` arrays, mirroring the semantics of the
reference library (IanMadlenya/pyconsensus, a fork of AugurProject/pyconsensus).
The JAX backend (``pyconsensus_tpu.ops.jax_kernels``) must agree with these
kernels — bit-identically on catch-snapped binary outcomes, and to float
tolerance on reputation vectors.

Semantics provenance: the reference mount ``/root/reference`` was empty at
survey and build time, so no ``file:line`` citations into it are possible.
Every kernel below cites the corresponding section of ``SURVEY.md`` (the
reconstructed blueprint, anchored in BASELINE.json's authoritative symbol
list: ``interpolate``, ``weighted_cov``, ``weighted_prin_comp``, ``catch``,
``smooth``, ``row_reward_weighted``, ``event_bounds``).

Conventions
-----------
- ``reports``: float64 array, shape (R, E). Rows = reporters, columns =
  events. ``NaN`` marks a non-report. Binary events take values in
  {0, 0.5, 1}; scaled events are raw reals rescaled into [0, 1] via
  ``event_bounds``.
- ``reputation``: float64 array, shape (R,), non-negative, sums to 1.
- ``scaled``: bool array, shape (E,). True where the event is scaled
  (continuous, resolved by weighted median) rather than binary/categorical
  (resolved by weighted mean + catch-snap).
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "normalize",
    "canon_sign",
    "catch",
    "rescale",
    "unscale_outcomes",
    "interpolate",
    "weighted_cov",
    "weighted_prin_comp",
    "weighted_median",
    "direction_fixed_scores",
    "row_reward_weighted",
    "smooth",
    "resolve_outcomes",
    "certainty_and_bonuses",
]


def normalize(v: np.ndarray) -> np.ndarray:
    """Rescale ``v`` to sum to 1 (SURVEY.md §2 #6, the R ``GetWeight`` rule).

    Plain ``v / sum(v)``; a vector with negative entries and a negative sum
    normalizes back to a non-negative weighting (which is why a global sign
    flip of adjusted scores is a no-op through ``row_reward_weighted``). A
    zero-sum vector is returned unchanged — callers guard degenerate cases
    explicitly (see ``row_reward_weighted``).
    """
    v = np.asarray(v, dtype=np.float64)
    total = v.sum()
    if total == 0.0:
        return v.copy()
    return v / total


def canon_sign(v: np.ndarray) -> np.ndarray:
    """Canonicalize an eigenvector's arbitrary sign: flip so the
    largest-|value| entry is positive (first-argmax tie-break, mirrored in the
    jax kernel). Used on *reported* loadings so both backends expose the same
    vector; scores go through the direction fix instead."""
    v = np.asarray(v, dtype=np.float64)
    s = np.sign(v[np.argmax(np.abs(v))])
    return v * (1.0 if s == 0.0 else s)


#: Catch-snap boundary tie band (same decision pattern as
#: MEDIAN_TIE_ATOL / DIRFIX_TIE_ATOL below): a value within this
#: distance of a snap boundary ``0.5 ± tolerance`` resolves to the
#: AMBIGUOUS 0.5 bucket instead of letting the last ulp decide.
#: Rationale (docs/ROBUSTNESS.md parity ledger #1-7): rational report
#: data under uniform reputation lands weighted means EXACTLY on the
#: boundary (e.g. 12 ones over 20 present reporters = 0.6 = 0.5 + the
#: default 0.1 tolerance), and two exact computations of the same mean
#: through different reduction orders (a (R, E) column reduce vs the
#: same column inside a (R, E/n) shard block) straddle the boundary by
#: one ulp — flipping the snapped fill between 0.5 and 1.0 and feeding
#: a MATERIALLY different filled matrix to the scorer. The band makes
#: the decision reduction-order-stable: a knife-edge value fails to
#: resolve (0.5) on every path rather than resolving by noise on some.
#: 1e-9 sits ~7 orders above f64 ulp noise on O(1) means yet far below
#: any data-driven margin (a mean 1e-9 inside the snap region requires
#: a reporter weight that small); f32 paths floor the band at 32*eps
#: (see the jax kernel), the same dtype rule as the median tie.
CATCH_TIE_ATOL = 1e-9


def catch(x, tolerance: float):
    """Snap a consensus value toward {0, 0.5, 1} (SURVEY.md §2 #6).

    ``x < 0.5 - tolerance -> 0``; ``x > 0.5 + tolerance -> 1``; else
    ``0.5``. Boundary decisions are banded by :data:`CATCH_TIE_ATOL`
    (shared with the jax and Pallas mirrors) so reduction-order ulp
    noise cannot flip a knife-edge snap. Works elementwise on arrays.
    """
    x = np.asarray(x, dtype=np.float64)
    return np.where(x < 0.5 - tolerance - CATCH_TIE_ATOL, 0.0,
                    np.where(x > 0.5 + tolerance + CATCH_TIE_ATOL, 1.0, 0.5))


def rescale(reports: np.ndarray, scaled: np.ndarray, mins: np.ndarray,
            maxs: np.ndarray) -> np.ndarray:
    """Map scaled-event columns into [0, 1]: ``(x - min) / (max - min)``
    (SURVEY.md §2 #1). Binary columns pass through. NaNs stay NaN."""
    reports = np.asarray(reports, dtype=np.float64)
    span = np.where(scaled, maxs - mins, 1.0)
    span = np.where(span == 0.0, 1.0, span)
    out = np.where(scaled[None, :], (reports - np.where(scaled, mins, 0.0)[None, :]) / span[None, :], reports)
    return out


def unscale_outcomes(outcomes: np.ndarray, scaled: np.ndarray, mins: np.ndarray,
                     maxs: np.ndarray) -> np.ndarray:
    """Inverse of :func:`rescale` on per-event outcomes: scaled events map back
    through ``x * (max - min) + min`` (SURVEY.md §2 #8, outcomes_final)."""
    return np.where(scaled, outcomes * (maxs - mins) + mins, outcomes)


def interpolate(reports: np.ndarray, reputation: np.ndarray, scaled: np.ndarray,
                tolerance: float) -> np.ndarray:
    """Fill NaN entries with the reputation-weighted column mean over the
    reporters who did report (SURVEY.md §3.4):

        fill[j] = sum_k rep[k] * reports[k, j] / sum_k rep[k]   over non-NaN k

    Binary columns snap the fill through :func:`catch`; scaled columns keep the
    raw weighted mean. A column with no reports at all fills with 0.5.
    Returns ``reports_filled`` (dense, no NaN).
    """
    reports = np.asarray(reports, dtype=np.float64)
    rep = np.asarray(reputation, dtype=np.float64)
    present = ~np.isnan(reports)                       # (R, E)
    active_rep = present * rep[:, None]                # (R, E)
    denom = active_rep.sum(axis=0)                     # (E,)
    numer = (np.where(present, reports, 0.0) * rep[:, None]).sum(axis=0)
    with np.errstate(invalid="ignore", divide="ignore"):
        fill = np.where(denom > 0.0, numer / denom, 0.5)
    fill = np.where(scaled, fill, catch(fill, tolerance))
    return np.where(present, reports, fill[None, :])


def weighted_cov(reports_filled: np.ndarray, reputation: np.ndarray):
    """Reputation-weighted covariance of the filled reports (SURVEY.md §3.5).

    mu = rep^T X (weighted column means); D = X - mu; then

        cov = D^T diag(rep) D / (1 - sum(rep^2))

    Returns ``(cov, deviations)`` where ``deviations`` is the centered matrix D
    (R, E) and ``cov`` is (E, E). The ``1 - sum(rep^2)`` denominator is the
    unbiased weighted normalization.
    """
    X = np.asarray(reports_filled, dtype=np.float64)
    rep = np.asarray(reputation, dtype=np.float64)
    mu = rep @ X                                       # (E,)
    dev = X - mu[None, :]                              # (R, E)
    denom = 1.0 - float(np.sum(rep ** 2))
    if denom == 0.0:
        denom = 1.0  # single-reporter degenerate case
    cov = (dev * rep[:, None]).T @ dev / denom         # (E, E)
    return cov, dev


def weighted_prin_comp(reports_filled: np.ndarray, reputation: np.ndarray):
    """First principal component of the weighted covariance (SURVEY.md §2 #4).

    Returns ``(loading, scores)``: ``loading`` is the E-vector first
    eigenvector of the weighted covariance; ``scores = deviations @ loading``
    is the per-reporter projection. Sign is arbitrary (fixed downstream by
    :func:`direction_fixed_scores`).
    """
    cov, dev = weighted_cov(reports_filled, reputation)
    eigvals, eigvecs = np.linalg.eigh(cov)
    loading = eigvecs[:, -1]                           # largest eigenvalue
    scores = dev @ loading
    return loading, scores


def weighted_prin_comps(reports_filled: np.ndarray, reputation: np.ndarray,
                        n_components: int):
    """Top-``n_components`` principal components, with explained-variance
    fractions. Used by the ``fixed-variance`` algorithm variant
    (SURVEY.md §2 #10). Returns ``(loadings (E, k), scores (R, k),
    explained (k,))`` ordered by descending eigenvalue."""
    cov, dev = weighted_cov(reports_filled, reputation)
    eigvals, eigvecs = np.linalg.eigh(cov)
    order = np.argsort(eigvals)[::-1][:n_components]
    loadings = eigvecs[:, order]
    eig = np.clip(eigvals[order], 0.0, None)
    total = eigvals.clip(0.0, None).sum()
    explained = eig / total if total > 0 else np.zeros_like(eig)
    scores = dev @ loadings
    return loadings, scores, explained


#: absolute tolerance for the weighted median's "cumulative weight hits
#: 0.5 exactly" midpoint rule. The reference's ``weightedstats`` compares
#: exactly (``==``), but exact float equality here is backend-fragile:
#: the normalized cumulative sum is computed by different reduction
#: orders on numpy vs XLA, so a true tie (e.g. four reporters at weight
#: 1/4 + two at 1/8... summing to exactly 0.5 in one order) can land one
#: ulp off 0.5 in the other — and the two backends would then disagree
#: on an OUTCOME. The epsilon is sized to the reduction noise it must
#: absorb (R * eps_f64 * 0.5 ~ 1e-12 at R = 10k) and far below any
#: data-driven near-tie: a cumulative weight 1e-9 from 0.5 without being
#:  a tie requires a reporter weight that small, whose report cannot
#: move the median anyway. This REPLACES round-3's accidental
#: ``np.isclose`` rtol=1e-5 (a semantics choice made by a default
#: tolerance — VERDICT r3 weak item 2); verify against the real
#: ``weightedstats`` comparison on first reference contact (SURVEY §8).
MEDIAN_TIE_ATOL = 1e-9

#: Direction-fix tie band (same decision pattern as MEDIAN_TIE_ATOL and
#: ``models.clustering.DBSCAN_D2_ATOL``): ``set1`` wins when
#: ``d1 - d2 <= DIRFIX_TIE_ATOL * (d1 + d2)`` instead of the bare
#: ``d1 - d2 <= 0``. Rationale: on symmetric report matrices the two
#: candidate orientations are EXACTLY equidistant from the current
#: consensus (the lattice concentrates ``ref_ind`` on 0), and backends
#: computing the distances through different-but-exact algebra (eigh-cov
#: vs eigh-gram vs the fused projected form) land on opposite sides of 0
#: by one ulp — flipping the orientation WHOLESALE (round-4 fuzz seed
#: 1989: smooth_rep reversed 0.58, outcomes 0.85 vs 0.10). A 1e-9
#: relative band is ~7 orders above f64 ulp noise while only rebinding
#: decisions that are semantically arbitrary. f32 runs can still compute
#: a true tie ~1e-7 off zero (outside the band) — that residual falls
#: under the documented f32 envelope (docs/PERFORMANCE.md), while the
#: x64 parity suite is exact. All six decision sites (numpy, jax
#: single/multi/fused, shard_map mesh, streaming) share this rule.
DIRFIX_TIE_ATOL = 1e-9


def weighted_median(values: np.ndarray, weights: np.ndarray) -> float:
    """Weighted median by sorted cumulative weight (SURVEY.md §2 #8).

    Sort values; find the first value where the cumulative normalized weight
    reaches 0.5. If the cumulative weight hits 0.5 exactly at a sample
    (to :data:`MEDIAN_TIE_ATOL` — see its sizing note), return the
    midpoint of that value and the next (the standard lower/upper-median
    midpoint rule, matching the ``weightedstats`` dependency of the
    reference). Implemented identically (same comparisons, same midpoint rule)
    in the JAX backend so backend outcomes agree bit-identically.
    """
    values = np.asarray(values, dtype=np.float64)
    weights = np.asarray(weights, dtype=np.float64)
    total = weights.sum()
    if total <= 0.0:
        return 0.5
    order = np.argsort(values, kind="stable")
    v = values[order]
    w = weights[order] / total
    cw = np.cumsum(w)
    # first index where the cumulative weight reaches 0.5 — less the tie
    # tolerance, so a true tie that lands one ulp BELOW 0.5 still selects
    # the tie index (and then midpoints) instead of skipping past it
    idx = int(np.searchsorted(cw, 0.5 - MEDIAN_TIE_ATOL))
    if idx >= len(v):
        idx = len(v) - 1
    if abs(cw[idx] - 0.5) <= MEDIAN_TIE_ATOL and idx + 1 < len(v):
        return 0.5 * (v[idx] + v[idx + 1])
    return float(v[idx])


def direction_fixed_scores(scores: np.ndarray, reports_filled: np.ndarray,
                           reputation: np.ndarray) -> np.ndarray:
    """Resolve PCA sign ambiguity (the ``nonconformity`` step, SURVEY.md §2 #5).

    Candidate orientations ``set1 = scores + |min(scores)|`` and
    ``set2 = scores - max(scores)`` imply two outcome vectors; whichever lies
    closer (squared distance) to the current reputation-weighted outcomes
    ``old = rep^T X`` wins. Ties — banded by :data:`DIRFIX_TIE_ATOL`,
    see its sizing note — go to ``set1``.

    The chosen orientation is returned in its NON-NEGATIVE form: when
    ``set2`` (entrywise <= 0) wins, ``-set2 = max(scores) - scores`` is
    returned instead. Through ``row_reward_weighted``'s normalize a global
    sign flip is an exact no-op for a single component, and the
    non-negative convention keeps multi-component blends (fixed-variance)
    on the reputation simplex — a mixed-sign blend of raw set1/set2
    vectors can otherwise produce negative reputation entries.
    """
    # canonicalize the eigensolver's arbitrary sign BEFORE building the
    # candidates: when the two orientations are exactly equidistant (the
    # DIRFIX_TIE_ATOL band), "pick set1" is not sign-invariant — set1
    # built from -scores is the OTHER orientation — so without this a
    # tie's winner depends on which sign the backend's eigensolver
    # happened to return (round-4 fuzz seed 1989: numpy eigh-cov and the
    # jax Gram path returned opposite signs on a symmetric matrix and
    # resolved opposite outcomes). Away from the band the winner is
    # sign-invariant, so this changes nothing.
    s = canon_sign(np.asarray(scores, dtype=np.float64))
    set1 = s + np.abs(np.min(s))
    set2 = s - np.max(s)
    old = reputation @ reports_filled
    new1 = normalize(set1) @ reports_filled
    new2 = normalize(set2) @ reports_filled
    d1 = np.sum((new1 - old) ** 2)
    d2 = np.sum((new2 - old) ** 2)
    return set1 if d1 - d2 <= DIRFIX_TIE_ATOL * (d1 + d2) else -set2


def row_reward_weighted(adj_scores: np.ndarray, reputation: np.ndarray) -> np.ndarray:
    """Convert direction-fixed scores into the new reputation weighting
    (SURVEY.md §2 #6, symbol ``row_reward_weighted`` from BASELINE.json):

        normalize(adj_scores * rep / mean(rep))

    If all adjusted scores are zero (no disagreement direction — e.g. a
    unanimous reports matrix), reputation is returned unchanged.
    """
    rep = np.asarray(reputation, dtype=np.float64)
    adj = np.asarray(adj_scores, dtype=np.float64)
    if np.max(np.abs(adj)) == 0.0:
        return rep.copy()
    return normalize(adj * (rep / np.mean(rep)))


def smooth(this_rep: np.ndarray, old_rep: np.ndarray, alpha: float) -> np.ndarray:
    """Blend new reputation with prior: ``alpha*this + (1-alpha)*old``
    (SURVEY.md §2 #6, the ``smooth`` step)."""
    return alpha * np.asarray(this_rep, dtype=np.float64) + (1.0 - alpha) * np.asarray(old_rep, dtype=np.float64)


def resolve_outcomes(reports: np.ndarray, reports_filled: np.ndarray,
                     smooth_rep: np.ndarray, scaled: np.ndarray,
                     tolerance: float):
    """Per-event outcome resolution (SURVEY.md §2 #8).

    For each event, reputation is restricted to the reporters who actually
    reported (non-NaN in the *original* matrix) and renormalized; binary
    events resolve by weighted mean, scaled events by weighted median. Returns
    ``(outcomes_raw, outcomes_adjusted)`` where adjusted = catch-snapped for
    binary events, raw for scaled.
    """
    reports = np.asarray(reports, dtype=np.float64)
    R, E = reports.shape
    present = ~np.isnan(reports)
    outcomes_raw = np.empty(E, dtype=np.float64)
    for j in range(E):
        mask = present[:, j]
        w = smooth_rep * mask
        tw = w.sum()
        if tw <= 0.0:
            # nobody reported: fall back to the filled column under full rep
            w = smooth_rep
            col = reports_filled[:, j]
            outcomes_raw[j] = float(w @ col / w.sum())
            continue
        col = reports_filled[:, j]
        if scaled[j]:
            outcomes_raw[j] = weighted_median(col[mask], w[mask])
        else:
            outcomes_raw[j] = float((w @ col) / tw)
    outcomes_adjusted = np.where(scaled, outcomes_raw, catch(outcomes_raw, tolerance))
    return outcomes_raw, outcomes_adjusted


def certainty_and_bonuses(reports: np.ndarray, reports_filled: np.ndarray,
                          smooth_rep: np.ndarray, outcomes_adjusted: np.ndarray,
                          scaled: np.ndarray, tolerance: float):
    """Certainty, participation accounting and bonuses (SURVEY.md §2 #9).

    - ``certainty[j]``: total smoothed reputation sitting on the winning
      outcome — reporters whose filled report equals the adjusted outcome
      (binary), or lies within ``tolerance`` of it (scaled).
    - ``consensus_reward = normalize(certainty)``.
    - ``participation_columns = 1 - smooth_rep^T NA``;
      ``participation_rows = 1 - NA consensus_reward``;
      ``percent_na = 1 - mean(participation_columns)``.
    - ``reporter_bonus`` blends NA-participation weight with smoothed rep by
      ``percent_na``; ``author_bonus`` does the same on the column side.

    Returns a dict of all of the above.
    """
    reports = np.asarray(reports, dtype=np.float64)
    na_mat = np.isnan(reports).astype(np.float64)
    agree = np.where(
        scaled[None, :],
        np.abs(reports_filled - outcomes_adjusted[None, :]) <= tolerance,
        reports_filled == outcomes_adjusted[None, :],
    )
    certainty = (agree * smooth_rep[:, None]).sum(axis=0)          # (E,)
    consensus_reward = normalize(certainty)
    avg_certainty = float(np.mean(certainty))

    participation_columns = 1.0 - smooth_rep @ na_mat              # (E,)
    participation_rows = 1.0 - na_mat @ consensus_reward           # (R,)
    percent_na = 1.0 - float(np.mean(participation_columns))

    na_bonus_rows = normalize(participation_rows)
    reporter_bonus = na_bonus_rows * percent_na + smooth_rep * (1.0 - percent_na)
    na_bonus_cols = normalize(participation_columns)
    author_bonus = na_bonus_cols * percent_na + consensus_reward * (1.0 - percent_na)

    return {
        "certainty": certainty,
        "consensus_reward": consensus_reward,
        "avg_certainty": avg_certainty,
        "participation_columns": participation_columns,
        "participation_rows": participation_rows,
        "percent_na": percent_na,
        "na_bonus_rows": na_bonus_rows,
        "reporter_bonus": reporter_bonus,
        "na_bonus_cols": na_bonus_cols,
        "author_bonus": author_bonus,
    }
