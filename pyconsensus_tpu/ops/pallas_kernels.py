"""Pallas TPU kernels for the consensus hot loop.

The single hottest operation in the framework (SURVEY.md §3.5, §7 "hard
parts") is the implicit-covariance application inside power iteration:

    y = D^T (rep * (D v)) / denom,      D = X - mu   (R x E, centered)

XLA computes this as two matvecs — ``t = D @ v`` then ``D.T @ (rep*t)`` —
each a full HBM sweep of the (R, E) matrix, because dot operands must be
materialized and the matrix exceeds VMEM by orders of magnitude. At the
north-star scale (10k x 100k, 4 GB f32) that is 8 GB of HBM traffic per
iteration, and the op is purely bandwidth-bound.

:func:`apply_weighted_cov` halves that: a grid over *row panels* keeps each
panel resident in VMEM and uses it for **both** contractions —

    per panel i:   t_i = (X_i - mu) v          (panel read from HBM once)
                   y  += (X_i - mu)^T (rep_i * t_i)

TPU Pallas grid steps run sequentially on a core, so the (1, E) output block
accumulates across steps (constant index map keeps it in VMEM). Centering
happens in-register — the centered matrix D is never materialized at all,
which also lets the caller keep ``X`` in bfloat16 (half the traffic again)
while all arithmetic accumulates in f32.

Padding contract: rows beyond R must be zero-filled and carry zero
reputation if the caller pads R up to the panel size — padded rows then
contribute exactly 0 to ``y`` (t on a zero row is finite, and rep=0 zeroes
the second contraction). :func:`_pad_rows` does this.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["apply_weighted_cov", "power_iteration_fused"]

#: target VMEM footprint of one row panel (bytes); actual VMEM use is a few
#: times this (double-buffered input + in-register f32 upcast)
_PANEL_BYTES = 4 * 1024 * 1024


def _panel_rows(n_events: int, itemsize: int) -> int:
    """Rows per panel: ~_PANEL_BYTES big, multiple of 8 sublanes, >= 8."""
    rows = max(1, _PANEL_BYTES // max(1, n_events * itemsize))
    return max(8, (rows // 8) * 8)


def _apply_cov_kernel(x_ref, mu_ref, rep_ref, v_ref, y_ref):
    """One row panel: both contractions off a single HBM read of the panel."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        y_ref[:] = jnp.zeros_like(y_ref)

    xc = x_ref[:].astype(jnp.float32) - mu_ref[:]          # (T, E) centered
    t = jnp.sum(xc * v_ref[:], axis=1, keepdims=True)      # (T, 1) = D_i v
    w = rep_ref[:] * t                                     # (T, 1)
    y_ref[:] += jnp.sum(xc * w, axis=0, keepdims=True)     # (1, E) partial


def _pad_rows(x, rep, tile_r: int):
    """Zero-pad rows (and reputation) up to a multiple of the panel size —
    see the padding contract in the module docstring."""
    R = x.shape[0]
    pad = (-R) % tile_r
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        rep = jnp.pad(rep, (0, pad))
    return x, rep


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply_weighted_cov(x, mu, rep, v, interpret: bool = False):
    """``(X - mu)^T (rep * ((X - mu) v))`` in ONE HBM sweep of ``X``.

    x : (R, E) filled reports, f32 or bf16 (row count padded internally).
    mu : (E,) f32 weighted column means.  rep : (R,) f32.  v : (E,) f32.
    Returns (E,) f32. Caller divides by the unbiased-weight denominator.
    ``interpret=True`` runs the Pallas interpreter (CPU tests).
    """
    R, E = x.shape
    tile_r = _panel_rows(E, x.dtype.itemsize)
    x, rep = _pad_rows(x, rep, tile_r)
    Rp = x.shape[0]
    f32 = jnp.float32
    grid = (Rp // tile_r,)
    y = pl.pallas_call(
        _apply_cov_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, E), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, E), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, E), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, E), f32),
        cost_estimate=pl.CostEstimate(
            flops=4 * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, mu.astype(f32).reshape(1, E), rep.astype(f32).reshape(-1, 1),
      v.astype(f32).reshape(1, E))
    return y.reshape(E)


def power_iteration_fused(x, mu, denom, rep, n_iters: int, tol: float,
                          interpret: bool = False):
    """First principal component via power iteration with the fused
    one-HBM-pass covariance application. Runs the shared convergence driver
    (``jax_kernels._power_loop`` — same start vector, normalization, and
    early-exit rule as the XLA matvec path) but never materializes the
    centered matrix and reads ``x`` once — not twice — per step.

    x : (R, E) filled reports (f32 or bf16 — bf16 halves the HBM traffic).
    mu, denom : weighted column means and the ``1 - sum(rep^2)`` scalar.
    Returns the (E,) f32 loading (unit norm, sign arbitrary).
    """
    from .jax_kernels import _power_loop

    E = x.shape[1]
    f32 = jnp.float32
    # pad once, outside the convergence loop — apply_weighted_cov's own pad
    # then no-ops, instead of copying the matrix on every sweep when R is
    # not a panel multiple
    tile_r = _panel_rows(E, x.dtype.itemsize)
    x, rep = _pad_rows(x, rep.astype(f32), tile_r)

    def apply_cov(v):
        return apply_weighted_cov(x, mu, rep, v, interpret=interpret) / denom

    return _power_loop(apply_cov, E, f32, n_iters, tol)
