"""Pallas TPU kernels for the consensus hot loop.

The single hottest operation in the framework (SURVEY.md §3.5, §7 "hard
parts") is the implicit-covariance application inside power iteration:

    y = D^T (rep * (D v)) / denom,      D = X - mu   (R x E, centered)

XLA computes this as two matvecs — ``t = D @ v`` then ``D.T @ (rep*t)`` —
each a full HBM sweep of the (R, E) matrix, because dot operands must be
materialized and the matrix exceeds VMEM by orders of magnitude. At the
north-star scale (10k x 100k, 4 GB f32) that is 8 GB of HBM traffic per
iteration, and the op is purely bandwidth-bound.

:func:`apply_weighted_cov` halves that: a grid over *row panels* keeps each
panel resident in VMEM and uses it for **both** contractions —

    per panel i:   t_i = (X_i - mu) v          (panel read from HBM once)
                   y  += (X_i - mu)^T (rep_i * t_i)

TPU Pallas grid steps run sequentially on a core, so the (1, E) output block
accumulates across steps (constant index map keeps it in VMEM). Centering
happens in-register — the centered matrix D is never materialized at all,
which also lets the caller keep ``X`` in bfloat16 (half the traffic again)
while all arithmetic accumulates in f32.

Padding contract: rows beyond R must be zero-filled and carry zero
reputation if the caller pads R up to the panel size — padded rows then
contribute exactly 0 to ``y`` (t on a zero row is finite, and rep=0 zeroes
the second contraction). :func:`_pad_rows` does this.
"""

# consensus-lint: traced-module — every function here is device
# kernel code compiled into jitted callers; host-sync calls and
# f64 literals are lint errors throughout (docs/STATIC_ANALYSIS.md)


from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["apply_weighted_cov", "apply_weighted_cov_block",
           "power_iteration_fused",
           "scores_dirfix_pass", "resolve_certainty_fused",
           "storage_matvec", "storage_rows_matmat", "storage_matmat",
           "matmat_kernels_fit", "matmat_tile_rows",
           "cov_block_kernel_fits",
           "set_tune_provider", "cov_tile_fits", "cov_tile_candidates",
           "resolve_block_fits", "resolve_block_candidates"]

#: target VMEM footprint of one row panel (bytes); actual VMEM use is a few
#: times this (double-buffered input + in-register f32 upcast)
_PANEL_BYTES = 4 * 1024 * 1024


def _panel_rows(n_events: int, itemsize: int,
                panel_bytes: int = _PANEL_BYTES) -> int:
    """Rows per panel: ~panel_bytes big, multiple of 8 sublanes, >= 8.
    (8 is below the native sublane tile of sub-32-bit dtypes — (16, 128)
    for bf16, (32, 128) for int8 — but Mosaic masks sub-tile blocks, and
    8-row bf16 panels are measured-good on v5e; 16-row panels at E=100k
    blow the scoped-VMEM limit via the in-register f32 upcast.)

    Sized against the VMEM footprint, not the logical bytes: VMEM tiles
    pad the lane (event) axis up to 128, so a narrow matrix costs
    ``roundup(E, 128)`` lanes per row. Without this, E=4 sized panels at
    262144 rows whose "4 MB" window was physically 128 MB — a measured
    VMEM OOM on v5e driving pca_method='power-fused' at toy shapes."""
    lanes = -(-n_events // 128) * 128
    rows = max(1, panel_bytes // max(1, lanes * itemsize))
    return max(8, (rows // 8) * 8)


#: scoped-VMEM budget the fit models target (the hardware limit is 16 MB;
#: leave headroom for Mosaic's own stack)
_VMEM_BUDGET = 14 * 1024 * 1024


# -- autotuned block shapes (pyconsensus_tpu.tune) -------------------------
#
# The block shapes above were hand-measured on v5e and are the
# deterministic fallback. The autotuner (ISSUE 7 tentpole b) can install a
# PROVIDER here that overrides them per (TPU generation, dtype, shape
# class): ``matmat_tile_rows`` consults it for the storage/cov row-panel
# size and ``resolve_certainty_fused`` for the resolution column-block
# width. Provider calls happen at TRACE time (host code building static
# grid/BlockSpec shapes); a provider must be deterministic per process —
# the tune runtime guarantees that by resolving its cache file once at
# install time. Every value a provider returns is re-validated against
# the legality helpers below before use, so a stale or corrupt cache
# entry can degrade performance but never produce an illegal kernel.

_TUNE_PROVIDER = None
_TUNE_AUTOLOAD = True


def set_tune_provider(provider):
    """Install (or clear, with None) the block-shape provider —
    ``provider(kind, **ctx) -> int | None`` with kinds ``"cov_tile_rows"``
    (ctx: n_events, itemsize, nan_fill) and ``"resolve_block_cols"``
    (ctx: n_reporters, itemsize). Returns the previous provider.
    Explicitly installing a provider (even None) disables the lazy
    default-cache autoload."""
    global _TUNE_PROVIDER, _TUNE_AUTOLOAD
    prev = _TUNE_PROVIDER
    _TUNE_PROVIDER = provider
    _TUNE_AUTOLOAD = False
    return prev


def _tuned(kind: str, **ctx):
    """The provider's override for ``kind`` at ``ctx`` (None = use the
    built-in measured-good heuristic). First call lazily installs the
    tune runtime's default provider (persisted-cache lookup; a no-op
    provider when no cache file exists) unless one was set explicitly.

    Hardened like the autoload: a provider that raises, or returns
    anything but a positive integral number (a hand-edited cache file
    can put ANY JSON value behind ``"value"``), yields None — tuning is
    never load-bearing, so a bad cache entry must degrade to the
    heuristic, never crash a kernel build."""
    global _TUNE_PROVIDER, _TUNE_AUTOLOAD
    if _TUNE_PROVIDER is None and _TUNE_AUTOLOAD:
        _TUNE_AUTOLOAD = False
        try:
            from ..tune import default_provider

            _TUNE_PROVIDER = default_provider()
        except Exception:      # noqa: BLE001 — tuning is never load-bearing
            _TUNE_PROVIDER = None
    if _TUNE_PROVIDER is None:
        return None
    try:
        v = _TUNE_PROVIDER(kind, **ctx)
    except Exception:          # noqa: BLE001 — same rule as the autoload
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if isinstance(v, float) and not v.is_integer():
        return None
    v = int(v)
    return v if v > 0 else None


def cov_tile_fits(tile_rows: int, n_events: int, itemsize: int) -> bool:
    """Whether a ``tile_rows``-row panel of the storage sweep kernels
    (matvec/matmat class) fits scoped VMEM: double-buffered storage block
    + the decode image (bf16 on compact storage, f32 otherwise) + the
    shared aux/accumulator vectors. This is the legality bound the
    autotuner sweeps under — deliberately the small-k model; the
    k-heavy block kernels re-check their own fit predicates
    (``cov_block_kernel_fits`` / ``matmat_kernels_fit``), which consult
    the tuned tile through :func:`matmat_tile_rows` and therefore stay
    consistent with whatever the provider installs."""
    lanes = -(-n_events // 128) * 128
    elem = 4 if itemsize == 4 else 2
    est = tile_rows * lanes * (2 * itemsize + elem) + 8 * lanes * 4
    return est <= _VMEM_BUDGET


def cov_tile_candidates(n_events: int, itemsize: int,
                        nan_fill: bool) -> list:
    """The legal row-panel sizes the autotuner may sweep for the storage
    sweep kernels at this (E, itemsize): a geometric multiple-of-8
    ladder from the minimum sub-tile panel up to the scoped-VMEM bound
    (a full multiple-of-8 scan would be ~100 configs at small E — sweep
    cost with no resolution benefit). The built-in heuristic
    (:func:`matmat_tile_rows`'s fallback value) joins the ladder ONLY
    when it passes :func:`cov_tile_fits` itself — the sweep must never
    propose a config outside its own legality model, and the
    hand-measured heuristic can exceed this (deliberately conservative)
    model at compact dense storage; in that case it simply stays what
    the kernels fall back to when no winner is installed. An empty list
    means no panel fits at all (the caller's shape belongs to the XLA
    path)."""
    ladder = (8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512, 768,
              1024)
    out = [t for t in ladder if cov_tile_fits(t, n_events, itemsize)]
    fallback = _panel_rows(n_events, itemsize,
                           _PANEL_BYTES // 2 if nan_fill else _PANEL_BYTES)
    if fallback not in out and cov_tile_fits(fallback, n_events, itemsize):
        out.append(fallback)
        out.sort()
    return out


def resolve_block_fits(n_reporters: int, block_cols: int,
                       itemsize: int) -> bool:
    """Whether the fused resolution kernel fits scoped VMEM at a
    ``block_cols``-wide column block for this (padded) R: double-buffered
    (R, C) block + (R, 1) f32 outputs + chunk-loop temps. Block widths
    must be multiples of 128 (the Pallas TPU lane-tiling rule)."""
    if block_cols % 128 or block_cols < 128:
        return False
    chunk = min(_pick_chunk(n_reporters) or 8, 1024)
    est = (n_reporters * block_cols * itemsize * 2 + n_reporters * 4 * 4
           + 6 * chunk * block_cols * 4 + 8 * block_cols * 4)
    return est <= _VMEM_BUDGET


def resolve_block_candidates(n_reporters: int, itemsize: int) -> list:
    """Legal column-block widths for :func:`resolve_certainty_fused` at
    this (padded) R, ascending — the autotuner's sweep space (the
    hand-measured heuristic picks from {256, 128})."""
    return [c for c in (128, 256, 384, 512, 768, 1024)
            if resolve_block_fits(n_reporters, c, itemsize)]


def fused_pca_fits(n_events: int, itemsize: int) -> bool:
    """Whether the E-wide row-panel kernels (apply_weighted_cov,
    scores_dirfix_pass) fit scoped VMEM at the minimum 8-row panel:
    double-buffered block + f32 upcast/temps + the (E,) f32 vectors.
    Measured failure: E=200k f32 blows the 16 MB limit by ~2 MB."""
    est = 8 * n_events * itemsize * 2 + 8 * n_events * 4 + 4 * n_events * 4
    return est <= _VMEM_BUDGET


def _resolve_block_cols(n_reporters: int, itemsize: int):
    """Largest column-block width of the measured-good {256, 128} ladder
    the fused resolution kernel can hold in scoped VMEM for this R; None
    when even the narrowest legal block does not fit. The VMEM estimate
    itself lives in ONE place — :func:`resolve_block_fits`, which the
    autotuner's sweep space uses too, so the heuristic and the sweep can
    never budget against different models. (Pallas TPU lowering requires
    the block width be a multiple of 128 or the whole array, so 128 is
    the floor.)"""
    for C in (256, 128):
        if resolve_block_fits(n_reporters, C, itemsize):
            return C
    return None


def resolve_kernel_fits(n_reporters: int, itemsize: int) -> bool:
    """Whether resolve_certainty_fused has a workable column-block width.
    Measured failure: R=20k f32 at C=128 blows the 16 MB limit by ~3.5 MB
    (C=64 fits)."""
    return _resolve_block_cols(n_reporters, itemsize) is not None


def _compensated_split(v):
    """Split an f32 vector into (head, residual) bf16 halves such that
    ``head + residual`` carries ~16 mantissa bits — the operand form of
    every compensated MXU dot in this module.

    The head passes through ``lax.optimization_barrier`` because XLA's
    simplifier on the TPU backend otherwise folds the convert chain
    ``bf16(v - f32(bf16(v)))`` to an all-zero vector under jit (verified
    on v5e 2026-07-31: eager gives the true residual, jit gives 0.0
    everywhere) — which silently turned every "compensated" dot built
    inside a jitted wrapper into a plain bf16-head dot (~2^-9 relative
    error instead of ~2^-17). The barrier hides the head's provenance
    from the simplifier; reconstruction error returns to ~2^-18
    (measured)."""
    vh = jax.lax.optimization_barrier(v.astype(jnp.bfloat16))
    vl = (v - vh.astype(jnp.float32)).astype(jnp.bfloat16)
    return vh, vl



def _is_compact(x) -> bool:
    """Whether the storage rides the MXU compact path (bf16 / int8
    sentinel) vs the exact-f32 VPU path."""
    return (x.dtype == jnp.bfloat16
            or jnp.issubdtype(x.dtype, jnp.integer))


def _vector_aux(v, fill, compact: bool):
    """The (2-or-3, E) aux operand of the separable storage kernels
    (storage_matvec — NOT apply_weighted_cov, whose VPU form reads the
    plain f32 vector; round 4): compensated bf16 halves of the
    f32 vector (+ bf16 fill row) on the compact path; ``[v, 0, (fill)]``
    f32 rows on the exact-f32 path. ONE implementation so a precision or
    layout fix (e.g. the _compensated_split jit-annihilation guard)
    cannot be applied to one kernel and silently missed in another."""
    E = v.shape[0]
    f32 = jnp.float32
    if compact:
        vh, vl = _compensated_split(v)
        rows = [vh.reshape(1, E), vl.reshape(1, E)]
        if fill is not None:
            rows.append(fill.astype(jnp.bfloat16).reshape(1, E))
    else:
        rows = [v.reshape(1, E), jnp.zeros((1, E), f32)]
        if fill is not None:
            rows.append(fill.astype(f32).reshape(1, E))
    return jnp.concatenate(rows)


def _matrix_aux(V, fill, compact: bool):
    """The ``(2k(+1), E)`` aux operand of the BLOCK storage kernels
    (storage_matmat and apply_weighted_cov_block): compensated bf16
    head/residual rows of ``V^T`` (+ bf16 fill row) on the compact path;
    ``[V^T; zeros; (fill)]`` f32 rows on the exact-f32 path. The k-column
    sibling of :func:`_vector_aux`, and one implementation for the same
    reason — a precision or layout fix must not be applied to one block
    kernel and silently missed in the other."""
    E = V.shape[0]
    f32 = jnp.float32
    Vt = V.astype(f32).T                                   # (k, E)
    if compact:
        Vh, Vl = _compensated_split(Vt)
        rows = [Vh, Vl]
        if fill is not None:
            rows.append(fill.astype(jnp.bfloat16).reshape(1, E))
    else:
        rows = [Vt, jnp.zeros_like(Vt)]
        if fill is not None:
            rows.append(fill.astype(f32).reshape(1, E))
    return jnp.concatenate(rows)


def _decode_block(x_ref):
    """Upcast one storage block to f32 and return ``(values, absent)``.

    Two storage encodings share every kernel (the decode branch is
    resolved at trace time from the ref dtype):

    - float (f32/bf16): values are the values; absence is NaN;
    - int8 sentinel: ``stored = round(2 * value)`` in {0, 1, 2} with
      ``-1`` marking absence — exact for binary/categorical reports
      ({0, 0.5, 1}), half the HBM bytes of bf16. ``x * 0.5`` decodes
      exactly in f32; zero-padded rows decode to value 0.0, non-absent,
      preserving the zero-rep padding contract.

    Comparison legality (the round-3 BENCH_r02 crash class, extended
    round 4 by an on-chip probe): Mosaic rejects BOTH bf16 ``cmpf`` and
    i8 ``cmpi`` ("Target does not support this comparison"); i32 ``cmpi``
    and f32 ``cmpf`` are the legal forms. The int8 sentinel test
    compares on the f32 value image this function materializes anyway —
    a second (i32) image purely for the compare would be added work."""
    if jnp.issubdtype(x_ref.dtype, jnp.integer):
        xp = x_ref[:].astype(jnp.float32)
        return xp * 0.5, xp < 0.0
    xp = x_ref[:].astype(jnp.float32)
    return xp, jnp.isnan(xp)


def _absent_only(x_view):
    """Just the absence mask of a storage block — for passes that never
    touch the values (the resolve kernel's row-NA accumulation): int8
    skips the float convert (i32-upcast integer compare — i8 cmpi is
    Mosaic-rejected); float storage pays only the isnan upcast."""
    if jnp.issubdtype(x_view.dtype, jnp.integer):
        return x_view[:].astype(jnp.int32) < 0
    return jnp.isnan(x_view[:].astype(jnp.float32))


def _decode_filled_bf16(x_ref, fill_row, *, nan_fill):
    """One storage block -> the FILLED panel in bf16 (exact: storage
    values and catch-snapped fills live on lattices bf16 represents
    exactly; continuous scaled-column fills round to bf16, which only
    perturbs the approximation-tolerant loading — scaled outcomes come
    from the exact gather median downstream).

    (Used by the separable matvec/matmat storage kernels — the power
    sweep's apply_weighted_cov decodes through :func:`_decode_block`
    instead. Decode cost was round 4's FIRST regression hypothesis and
    was ruled out — the real cost was the MXU-dot kernel form, see
    docs/PERFORMANCE.md r4 — but the one-convert form below is kept: it
    is no slower and carries no comparison at all.) The int8 path
    converts the raw lattice STRAIGHT to bf16 (exact on {-1, 0, 1, 2};
    halving is bf16-exact) and separates the sentinel by min/max
    arithmetic; the bf16 path passes values through untouched. The one
    remaining f32 operand is bf16 isnan's upcast: Mosaic rejects bf16
    ``arith.cmpf`` outright ("Target does not support this comparison" —
    BENCH_r02's compile failure was this kernel's old ``bf16 < 0``) and
    i8 ``cmpi`` likewise (on-chip probe); i32 ``cmpi`` and f32 ``cmpf``
    are the legal forms."""
    bf16 = jnp.bfloat16
    if jnp.issubdtype(x_ref.dtype, jnp.integer):
        # ONE i8->bf16 convert and NO comparison at all: the sentinel -1
        # decodes to -0.5, so min/max arithmetic separates it —
        # max(val, 0) zeroes the sentinel lane, and -2*min(val, 0) is an
        # exact {0, 1} mask that injects the fill. All values exact on
        # the bf16 lattice (probed legal on v5e; both compare forms cost
        # a second full-width convert: i8 cmpi is Mosaic-rejected and
        # i32/f32 compares need their own upcast image).
        val = x_ref[:].astype(bf16) * bf16(0.5)
        if nan_fill:
            mask = jnp.minimum(val, bf16(0)) * bf16(-2)
            return jnp.maximum(val, bf16(0)) + mask * fill_row
        return val
    if x_ref.dtype == bf16:
        val = x_ref[:]
        absent = jnp.isnan(x_ref[:].astype(jnp.float32))
    else:
        val32, absent = _decode_block(x_ref)
        val = val32.astype(bf16)
    if nan_fill:
        return jnp.where(absent, fill_row, val)
    return val


def _cov_panel_contribution(x_ref, mu_ref, rep_ref, v, *, nan_fill):
    """One row panel's ``D_i^T (rep_i * (D_i v))`` contribution, centered
    in-register on the VPU. ``nan_fill=True`` reads sentinel-threaded
    storage: absent entries are NaN (float) / -1 (int8) in ``x`` and
    ``mu_ref`` row 1 carries ``fill - mu`` (the centered per-column fill
    value), so the filled matrix is reconstructed in-register and never
    exists in HBM.

    This is deliberately the VPU elementwise form, NOT an MXU dot
    (round-4 forensics, docs/PERFORMANCE.md): the power sweep's
    contractions have tiny non-MXU-shaped minor dims (N=1..2 against
    8-row panels), and the "compensated bf16 MXU dots" rewrite that
    replaced this form late in round 2 measured **7.6 ms/sweep vs this
    form's 4.4** at the north-star shape on v5e — the entire r2→r3
    headline regression. The f32 chain is also exact per-product (no
    compensation machinery needed), and every comparison runs on the f32
    value image (Mosaic rejects bf16 ``cmpf`` / i8 ``cmpi``)."""
    val, absent = _decode_block(x_ref)
    if nan_fill:
        xc = jnp.where(absent, mu_ref[1:2, :], val - mu_ref[0:1, :])
    else:
        xc = val - mu_ref[0:1, :]                          # (T, E) centered
    t = jnp.sum(xc * v, axis=1, keepdims=True)             # (T, 1) = D_i v
    return jnp.sum(xc * (rep_ref[:] * t), axis=0, keepdims=True)


def _apply_cov_kernel(x_ref, mu_ref, rep_ref, v_ref, y_ref, *, nan_fill):
    """One row panel: both contractions off a single HBM read of the
    panel (see :func:`_cov_panel_contribution`)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        y_ref[:] = jnp.zeros_like(y_ref)

    y_ref[:] += _cov_panel_contribution(x_ref, mu_ref, rep_ref, v_ref[:],
                                        nan_fill=nan_fill)


def _pad_rows(x, rep, tile_r: int):
    """Zero-pad rows (and reputation) up to a multiple of the panel size —
    see the padding contract in the module docstring."""
    R = x.shape[0]
    pad = (-R) % tile_r
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        rep = jnp.pad(rep, (0, pad))
    return x, rep


def _prep_cov_inputs(x, mu, rep, fill):
    """Shared input prep for the covariance-application kernel: panel
    sizing (halved budget under NaN threading), row padding, and the
    stacked ``[mu; fill - mu]`` operand. Returns
    ``(x, rep, tile_r, mu2)``."""
    E = x.shape[1]
    nan_fill = fill is not None
    tile_r = matmat_tile_rows(E, x.dtype.itemsize, nan_fill)
    x, rep = _pad_rows(x, rep.astype(jnp.float32), tile_r)
    mu = mu.astype(jnp.float32).reshape(1, E)
    if nan_fill:
        # row 0: mu; row 1: fill - mu (the centered value of an absent entry)
        mu2 = jnp.concatenate([mu, fill.astype(jnp.float32).reshape(1, E)
                               - mu])
    else:
        mu2 = mu
    return x, rep, tile_r, mu2


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply_weighted_cov(x, mu, rep, v, fill=None, interpret: bool = False):
    """``(X - mu)^T (rep * ((X - mu) v))`` in ONE HBM sweep of ``X``,
    centered in-register on the VPU (see :func:`_cov_panel_contribution`
    for why this is NOT an MXU-dot kernel).

    x : (R, E) filled reports, f32 or bf16 (row count padded internally) —
        or, with ``fill`` given, sentinel-threaded storage (absent entries
        NaN / int8 -1) whose filled values are reconstructed in-register
        from the (E,) per-column fill vector, so the filled matrix never
        exists in HBM.
    mu : (E,) f32 weighted column means.  rep : (R,) f32.  v : (E,) f32.
    Returns (E,) f32. Caller divides by the unbiased-weight denominator.
    ``interpret=True`` runs the Pallas interpreter (CPU tests).
    """
    R, E = x.shape
    nan_fill = fill is not None
    x, rep, tile_r, mu2 = _prep_cov_inputs(x, mu, rep, fill)
    Rp = x.shape[0]
    f32 = jnp.float32
    grid = (Rp // tile_r,)
    y = pl.pallas_call(
        functools.partial(_apply_cov_kernel, nan_fill=nan_fill),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mu2.shape[0], E), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, E), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, E), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, E), f32),
        cost_estimate=pl.CostEstimate(
            flops=4 * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, mu2, rep.astype(f32).reshape(-1, 1), v.astype(f32).reshape(1, E))
    return y.reshape(E)


def _matvec_kernel(x_ref, aux_ref, t_ref, *, nan_fill):
    """One row panel of the UNCENTERED storage matvec ``t = filled @ v``
    (the separable first half of the covariance application — the
    event-sharded path must ``psum`` the (R,) result across shards before
    the second contraction can run, so the one-pass fusion of
    ``_apply_cov_kernel`` is structurally unavailable there). Same
    compensated-operand exactness scheme: ``aux_ref`` rows 0..1 carry the
    bf16 head/residual of ``v`` (row 2 the fill row under ``nan_fill``);
    f32 storage takes the exact VPU chain."""
    f32 = jnp.float32
    if not (x_ref.dtype == jnp.bfloat16
            or jnp.issubdtype(x_ref.dtype, jnp.integer)):
        val, absent = _decode_block(x_ref)
        v_full = aux_ref[0:1, :] + aux_ref[1:2, :]
        filled = jnp.where(absent, aux_ref[2:3, :], val) if nan_fill else val
        t_ref[:] = jnp.sum(filled * v_full, axis=1, keepdims=True)
        return
    fill_row = aux_ref[2:3, :] if nan_fill else None
    filled = _decode_filled_bf16(x_ref, fill_row, nan_fill=nan_fill)
    t2 = jax.lax.dot_general(filled, aux_ref[0:2, :],
                             (((1,), (1,)), ((), ())),
                             precision=jax.lax.Precision.DEFAULT,
                             preferred_element_type=f32)       # (T, 2)
    t_ref[:] = t2[:, 0:1] + t2[:, 1:2]


@functools.partial(jax.jit, static_argnames=("interpret",))
def storage_matvec(x, v, fill=None, interpret: bool = False):
    """``filled(x) @ v`` in one HBM sweep of the storage matrix, decode
    in-register (see :func:`_decode_block` for the encodings). Returns the
    UNCENTERED (R,) f32 product — callers on the event-sharded path
    ``psum`` it (plus their own ``mu·v`` partial) across shards and
    finish the centering globally."""
    R, E = x.shape
    nan_fill = fill is not None
    tile_r = matmat_tile_rows(E, x.dtype.itemsize, nan_fill)
    x, _ = _pad_rows(x, jnp.zeros((R,), jnp.float32), tile_r)
    Rp = x.shape[0]
    f32 = jnp.float32
    bf16 = jnp.bfloat16
    v = v.astype(f32)
    compact = _is_compact(x)
    aux = _vector_aux(v, fill if nan_fill else None, compact)
    t = pl.pallas_call(
        functools.partial(_matvec_kernel, nan_fill=nan_fill),
        grid=(Rp // tile_r,),
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((aux.shape[0], E), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Rp, 1), f32),
        cost_estimate=pl.CostEstimate(
            flops=2 * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, aux)
    return t.reshape(Rp)[:R]


def _fill_stats_tile_rows(n_events: int, itemsize: int) -> int:
    """Row-panel size for :func:`fill_stats_pass` — its OWN budget model,
    not ``matmat_tile_rows``: this kernel holds two full-width f32
    temporaries per row (decode image, masked weights) on top of the
    double-buffered storage block, so the matmat sizing overflows scoped
    VMEM (measured: 18.28M at the matmat-sized 16-row panel with the
    original 3-temp select form, E=100k int8 — first on-chip contact)."""
    lanes = -(-n_events // 128) * 128
    per_row = lanes * (2 * itemsize + 8)        # 2x block + 2 f32 images
    rows = max(1, (_VMEM_BUDGET - 2 * lanes * 4) // per_row)
    return max(8, (rows // 8) * 8)


def fill_stats_kernel_fits(n_events: int, itemsize: int) -> bool:
    """Whether the minimum 8-row fill-stats panel fits scoped VMEM (the
    caller falls back to the XLA reduction form when it does not)."""
    lanes = -(-n_events // 128) * 128
    est = 8 * lanes * (2 * itemsize + 8) + 2 * lanes * 4
    return est <= _VMEM_BUDGET


def _fill_stats_kernel(x_ref, rep_ref, acc_ref):
    """One row panel of the per-column present-weight statistics: row 0
    accumulates ``tw[e] = sum_i rep_i [present]``, row 1
    ``numer[e] = sum_i rep_i * value``. Zero-padded rows decode to value
    0.0 / present with zero reputation — exact no-ops in both sums (the
    module's padding contract).

    int8 takes the select-free min/max decode (the _decode_filled_bf16
    trick, in f32): the sentinel -1 decodes to -0.5, so
    ``1 + 2*min(val, 0)`` is an exact {0, 1} presence mask and
    ``max(val, 0)`` the zeroed value — two f32 temps, no compares (the
    original 3-temp select form also cost an extra 2 MB of scoped VMEM
    at the 16-row panel)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if jnp.issubdtype(x_ref.dtype, jnp.integer):
        val = x_ref[:].astype(jnp.float32) * 0.5       # absent -> -0.5
        w = (1.0 + 2.0 * jnp.minimum(val, 0.0)) * rep_ref[:]
        val = jnp.maximum(val, 0.0)
    else:
        val, absent = _decode_block(x_ref)             # (T, E) f32
        w = jnp.where(absent, 0.0, rep_ref[:])
        val = jnp.where(absent, 0.0, val)
    acc_ref[0:1, :] += jnp.sum(w, axis=0, keepdims=True)
    acc_ref[1:2, :] += jnp.sum(val * w, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fill_stats_pass(x, rep, interpret: bool = False):
    """Per-column NA-fill statistics off sentinel storage in ONE HBM
    sweep: ``(tw, numer)``, both (E,) f32, where ``tw`` is the present
    reputation mass and ``numer`` the present rep-weighted value sum —
    the inputs of the interpolate fill vector and the first-iteration
    means (models.pipeline._fill_stats).

    Round-5 kernel (VERDICT r4 item 3): the XLA reduction form of this
    pass measured 12.7 ms in-context at 10000x100000 int8 (~79 GB/s —
    an order under the chip's HBM bandwidth, whatever fusion XLA picked),
    while the storage sweeps around it ran near roofline; this kernel is
    the same one-read panel-accumulate shape as :func:`storage_matvec`.
    """
    R, E = x.shape
    tile_r = _fill_stats_tile_rows(E, x.dtype.itemsize)
    x, rep = _pad_rows(x, rep.astype(jnp.float32), tile_r)
    Rp = x.shape[0]
    f32 = jnp.float32
    out = pl.pallas_call(
        _fill_stats_kernel,
        grid=(Rp // tile_r,),
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((2, E), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2, E), f32),
        cost_estimate=pl.CostEstimate(
            flops=3 * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, rep.reshape(-1, 1))
    return out[0], out[1]


def _matmat_kernel(x_ref, aux_ref, t_ref, *, nan_fill, k):
    """One row panel of the UNCENTERED storage matmat ``T = filled @ V``
    for a thin (E, k) block of column vectors — the multi-component
    analogue of :func:`_matvec_kernel` (orthogonal iteration's first
    sweep streams k directions at once; k is the component count, <= ~8).
    ``aux_ref`` carries the compensated bf16 halves as 2k rows
    [V^T_head; V^T_residual] (+ the fill row under ``nan_fill``) on the
    compact path, or [V^T; zeros; (fill)] f32 rows on the exact-f32
    path."""
    f32 = jnp.float32
    if not (x_ref.dtype == jnp.bfloat16
            or jnp.issubdtype(x_ref.dtype, jnp.integer)):
        val, absent = _decode_block(x_ref)
        filled = (jnp.where(absent, aux_ref[2 * k:2 * k + 1, :], val)
                  if nan_fill else val)
        # one full-block store (a per-column t_ref[:, c:c+1] loop is a
        # width-1 lane-sliced store Mosaic has rejected patterns like
        # before — see _rows_matmat_kernel's layout note)
        cols = [jnp.sum(filled * (aux_ref[c:c + 1, :]
                                  + aux_ref[k + c:k + c + 1, :]),
                        axis=1, keepdims=True) for c in range(k)]
        t_ref[:] = jnp.concatenate(cols, axis=1)
        return
    fill_row = aux_ref[2 * k:2 * k + 1, :] if nan_fill else None
    filled = _decode_filled_bf16(x_ref, fill_row, nan_fill=nan_fill)
    t2 = jax.lax.dot_general(filled, aux_ref[0:2 * k, :],
                             (((1,), (1,)), ((), ())),
                             precision=jax.lax.Precision.DEFAULT,
                             preferred_element_type=f32)       # (T, 2k)
    t_ref[:] = t2[:, :k] + t2[:, k:]


def matmat_tile_rows(n_events: int, itemsize: int, nan_fill: bool) -> int:
    """The row-panel size the matmat storage kernels
    (:func:`storage_matmat` / :func:`storage_rows_matmat`) will tile with
    — exposed so sweep LOOPS can pad the matrix ONCE up front (the
    kernels' internal ``_pad_rows`` then no-ops) instead of paying a full
    (R, E) HBM pad copy on every sweep when R is not a panel multiple
    (the hoist ``power_iteration_fused`` applies; measured ~25-35%
    end-to-end on ica at panel-indivisible R, 2026-08-01).

    Consults the autotune provider first (``pyconsensus_tpu.tune``):
    a persisted per-(generation, dtype, shape-class) winner overrides
    the hand-measured heuristic, re-validated against
    :func:`cov_tile_fits` so a stale cache entry can never produce an
    illegal kernel."""
    t = _tuned("cov_tile_rows", n_events=n_events, itemsize=itemsize,
               nan_fill=nan_fill)
    if t and t % 8 == 0 and cov_tile_fits(int(t), n_events, itemsize):
        return int(t)
    return _panel_rows(n_events, itemsize,
                       _PANEL_BYTES // 2 if nan_fill else _PANEL_BYTES)


@functools.partial(jax.jit, static_argnames=("interpret",))
def storage_matmat(x, V, fill=None, interpret: bool = False):
    """``filled(x) @ V`` for a thin (E, k) block in one HBM sweep of the
    storage matrix, decode in-register (:func:`_decode_block`). Returns
    the UNCENTERED (R, k) f32 product; centering is the caller's
    (``T - 1 (mu @ V)``). The k <= ~8 component-block sibling of
    :func:`storage_matvec`."""
    R, E = x.shape
    k = V.shape[1]
    nan_fill = fill is not None
    tile_r = matmat_tile_rows(E, x.dtype.itemsize, nan_fill)
    x, _ = _pad_rows(x, jnp.zeros((R,), jnp.float32), tile_r)
    Rp = x.shape[0]
    f32 = jnp.float32
    aux = _matrix_aux(V, fill if nan_fill else None, _is_compact(x))
    t = pl.pallas_call(
        functools.partial(_matmat_kernel, nan_fill=nan_fill, k=k),
        grid=(Rp // tile_r,),
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((aux.shape[0], E), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_r, k), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Rp, k), f32),
        cost_estimate=pl.CostEstimate(
            flops=2 * k * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, aux)
    return t[:R]


def _cov_block_kernel(x_ref, aux_ref, muv_ref, rep_ref, y_ref, s_ref,
                      *t_refs, nan_fill, k, emit_t):
    """One row panel of the BLOCK covariance application — both
    contractions of ``(X - 1 mu^T)^T (rep * ((X - 1 mu^T) V))`` off a
    single HBM read of the panel, the k-column sibling of
    :func:`_apply_cov_kernel` (which stays VPU for its N=1 shapes; k >= 2
    makes the stacked MXU dots win, like the dirfix kernel's).

    Algebra identical to the separable two-sweep form the orth-iter used
    before (storage_matmat then storage_rows_matmat): raw ``t = X V``
    per panel (compensated aux operand), centered in-register with the
    precomputed ``mu . V`` row, then the second contraction against the
    SAME resident panel with an in-kernel compensated split of
    ``rep * t`` — the caller finishes ``- mu (x) sum(rep * t)`` exactly
    like the separable caller did. ``s_ref`` accumulates that (1, k)
    column-sum. Under ``emit_t`` a third output ref stores the centered
    per-row projections — requested ONLY for the final Rayleigh-Ritz
    application, where the caller rotates them into the component
    scores, eliminating the whole separate scores sweep (the loop's
    sweeps skip the output entirely: a Pallas output cannot be
    dead-code-eliminated by XLA, so an always-on t would pay an
    (Rp, k) HBM write per sweep for nothing). The in-kernel split is
    plain arithmetic Mosaic compiles as written (the XLA-simplifier
    annihilation that motivated ``_compensated_split``'s barrier is an
    HLO-pass hazard; the orth-iter-vs-eigh parity test would see the
    2^-9 head-only error if a Mosaic fold ever appeared)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        y_ref[:] = jnp.zeros_like(y_ref)
        s_ref[:] = jnp.zeros_like(s_ref)

    f32 = jnp.float32
    bf16 = jnp.bfloat16
    if not (x_ref.dtype == bf16
            or jnp.issubdtype(x_ref.dtype, jnp.integer)):
        # exact-f32 VPU path (parity mode; big-E f32 is gated out by
        # cov_block_kernel_fits before it can reach here)
        val, absent = _decode_block(x_ref)
        filled = (jnp.where(absent, aux_ref[2 * k:2 * k + 1, :], val)
                  if nan_fill else val)
        cols = [jnp.sum(filled * (aux_ref[c:c + 1, :]
                                  + aux_ref[k + c:k + c + 1, :]),
                        axis=1, keepdims=True) for c in range(k)]
        tc = jnp.concatenate(cols, axis=1) - muv_ref[:]    # (T, k)
        if emit_t:
            t_refs[0][:] = tc
        rt = rep_ref[:] * tc
        s_ref[:] += jnp.sum(rt, axis=0, keepdims=True)
        rows = [jnp.sum(filled * rt[:, c:c + 1], axis=0, keepdims=True)
                for c in range(k)]
        y_ref[:] += jnp.concatenate(rows, axis=0)
        return
    fill_row = aux_ref[2 * k:2 * k + 1, :] if nan_fill else None
    filled = _decode_filled_bf16(x_ref, fill_row, nan_fill=nan_fill)
    t2 = jax.lax.dot_general(filled, aux_ref[0:2 * k, :],
                             (((1,), (1,)), ((), ())),
                             precision=jax.lax.Precision.DEFAULT,
                             preferred_element_type=f32)   # (T, 2k)
    tc = t2[:, :k] + t2[:, k:] - muv_ref[:]                # (T, k) f32
    if emit_t:
        t_refs[0][:] = tc
    rt = rep_ref[:] * tc
    s_ref[:] += jnp.sum(rt, axis=0, keepdims=True)
    h = rt.astype(bf16)
    low = (rt - h.astype(f32)).astype(bf16)
    w = jnp.concatenate([h, low], axis=1)                  # (T, 2k) bf16
    part = jax.lax.dot_general(w, filled, (((0,), (0,)), ((), ())),
                               precision=jax.lax.Precision.DEFAULT,
                               preferred_element_type=f32)  # (2k, E)
    y_ref[:] += part[:k, :] + part[k:, :]


def cov_block_kernel_fits(n_events: int, n_components: int,
                          itemsize: int) -> bool:
    """Whether :func:`apply_weighted_cov_block` fits scoped VMEM at its
    tile: double-buffered storage panel + the bf16 decode image + the
    (k, E) f32 accumulator + the (2k+1, E) compensated aux rows + the
    per-panel (T, 2k) working operands + the emit_t (T, k) output window
    (modeled double-buffered, and at its worst case: the final
    Rayleigh-Ritz application requests it). f32 storage carries an f32
    decode image and f32 aux instead — at north-star width that is what
    pushes it over, so f32 big-E takes the separable two-sweep form."""
    k = n_components
    lanes = -(-n_events // 128) * 128
    tile = matmat_tile_rows(n_events, itemsize, True)
    elem = 4 if itemsize == 4 else 2                  # decode/aux width
    est = (tile * lanes * itemsize * 2                # double-buffered panel
           + tile * lanes * elem                      # decoded filled image
           + k * lanes * 4                            # y accumulator
           + (2 * k + 1) * lanes * elem               # aux rows
           + 2 * lanes * 4                            # mu/fill working rows
           + tile * 2 * k * 8                         # t/rt/w panels
           + tile * k * 4 * 2)                        # emit_t output window
    return est <= _VMEM_BUDGET


@functools.partial(jax.jit, static_argnames=("interpret", "emit_t"))
def apply_weighted_cov_block(x, mu, rep, V, fill=None,
                             interpret: bool = False, emit_t: bool = False):
    """``(X - 1 mu^T)^T (rep * ((X - 1 mu^T) V))`` for a thin (E, k)
    block in ONE HBM sweep of the storage matrix — halves the orth-iter
    sweep traffic versus the separable storage_matmat +
    storage_rows_matmat pair (single-device only: the event-sharded path
    needs a psum between the two contractions, exactly like the
    single-vector :func:`apply_weighted_cov`'s note). Returns
    ``(y (E, k), t)`` f32 — the covariance application (caller divides
    by the unbiased-weight denominator) and, under ``emit_t``, the
    CENTERED per-row projections ``(X - 1 mu^T) V`` of the same call,
    sliced back to the input row count (``t`` is None otherwise — the
    orth-iter loop's sweeps must not pay the per-sweep (Rp, k) HBM
    write, which XLA cannot dead-code-eliminate from a pallas_call; the
    final Rayleigh-Ritz application requests it and rotates it into the
    component scores, eliminating the separate scores sweep). Callers
    must check :func:`cov_block_kernel_fits` first."""
    R, E = x.shape
    k = V.shape[1]
    nan_fill = fill is not None
    tile_r = matmat_tile_rows(E, x.dtype.itemsize, nan_fill)
    x, rep = _pad_rows(x, rep.astype(jnp.float32), tile_r)
    Rp = x.shape[0]
    f32 = jnp.float32
    aux = _matrix_aux(V, fill if nan_fill else None, _is_compact(x))
    muv = (mu.astype(f32) @ V.astype(f32)).reshape(1, k)
    out_specs = [
        pl.BlockSpec((k, E), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((k, E), f32),
        jax.ShapeDtypeStruct((1, k), f32),
    ]
    if emit_t:
        out_specs.append(pl.BlockSpec((tile_r, k), lambda i: (i, 0),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((Rp, k), f32))
    out = pl.pallas_call(
        functools.partial(_cov_block_kernel, nan_fill=nan_fill, k=k,
                          emit_t=emit_t),
        grid=(Rp // tile_r,),
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((aux.shape[0], E), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        cost_estimate=pl.CostEstimate(
            flops=4 * k * Rp * E,
            bytes_accessed=(Rp * E * x.dtype.itemsize
                            + (Rp * k * 4 if emit_t else 0)),
            transcendentals=0),
        interpret=interpret,
    )(x, aux, muv, rep.reshape(-1, 1))
    y, s = out[0], out[1]
    y = y - s.reshape(k, 1) * mu.astype(f32)[None, :]  # - mu (x) sum(rep*t)
    return y.T, (out[2][:R] if emit_t else None)


def matmat_kernels_fit(n_events: int, n_components: int,
                       itemsize: int) -> bool:
    """Whether the multi-component storage sweeps (storage_matmat +
    storage_rows_matmat with a (k+1)-row stack) fit scoped VMEM at the
    minimum 8-row panel: double-buffered block + f32 upcast + the
    (2k+1, E) aux rows + the (k+1, E) f32 accumulator. The k-row
    accumulators are what distinguishes this from :func:`fused_pca_fits`."""
    k = n_components
    lanes = -(-n_events // 128) * 128
    est = (8 * lanes * itemsize * 2          # double-buffered panel
           + 8 * lanes * 4                   # in-register f32 upcast
           + (2 * k + 1) * lanes * 2         # compensated aux rows (bf16)
           + (k + 1) * lanes * 4             # rows_matmat accumulator
           + 2 * lanes * 4)                  # fill/mu working vectors
    return est <= _VMEM_BUDGET


def _rows_matmat_kernel(x_ref, w_ref, fill_ref, acc_ref, *, nan_fill,
                        n_rows):
    """One row panel of ``W @ filled(x)`` for a few (k <= ~8) row vectors:
    the separable second half of the sharded covariance application (and
    the direction-fix contractions — W = [t, rep, ones] gives q/o/c per
    event shard in one pass). ``w_ref`` carries the operand TRANSPOSED —
    a (tile_r, 2k) block of [W_head; W_residual]^T on the compact path
    (each product against the lattice-exact filled panel is then exact;
    only the ~2^-17 second-order residual is lost), or (tile_r, k) f32 on
    the f32 path (exact VPU chains — the parity mode must not round
    continuous values). The transposed layout is a Mosaic lowering
    requirement, not a preference: a (2k, tile_r) block has a last dim
    that is neither 128-divisible nor the full array width, which the
    TPU lowering rejects outright (first hit on real hardware round 4 —
    interpret-mode tests cannot see it); (tile_r, 2k) satisfies the
    (8, 128)-or-full rule because tile_r is a multiple of 8 and 2k IS
    the full width."""
    i = pl.program_id(0)
    f32 = jnp.float32

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if not (x_ref.dtype == jnp.bfloat16
            or jnp.issubdtype(x_ref.dtype, jnp.integer)):
        val, absent = _decode_block(x_ref)
        filled = (jnp.where(absent, fill_ref[0:1, :], val) if nan_fill
                  else val)
        for r in range(n_rows):
            acc_ref[r:r + 1, :] += jnp.sum(
                w_ref[:, r:r + 1] * filled, axis=0, keepdims=True)
        return
    fill_row = fill_ref[0:1, :] if nan_fill else None
    filled = _decode_filled_bf16(x_ref, fill_row, nan_fill=nan_fill)
    part = jax.lax.dot_general(w_ref[:], filled,
                               (((0,), (0,)), ((), ())),
                               precision=jax.lax.Precision.DEFAULT,
                               preferred_element_type=f32)   # (2k, E)
    acc_ref[:] += part[:n_rows, :] + part[n_rows:, :]


@functools.partial(jax.jit, static_argnames=("interpret",))
def storage_rows_matmat(x, W, fill=None, interpret: bool = False):
    """``W @ filled(x)`` for a small stack of row vectors (W: (k, R) f32,
    k <= ~8) in ONE HBM sweep of the storage matrix. Per-event-column
    results are local to an event shard, so the sharded path needs no
    collective here. Returns (k, E) f32. Centering is the caller's:
    ``(W @ filled) - (W @ 1) mu^T`` with local ``mu``."""
    R, E = x.shape
    k = W.shape[0]
    nan_fill = fill is not None
    tile_r = matmat_tile_rows(E, x.dtype.itemsize, nan_fill)
    x, _ = _pad_rows(x, jnp.zeros((R,), jnp.float32), tile_r)
    Rp = x.shape[0]
    f32 = jnp.float32
    bf16 = jnp.bfloat16
    W = W.astype(f32)
    if W.shape[1] != Rp:                     # zero-pad the padded rows
        W = jnp.pad(W, ((0, 0), (0, Rp - W.shape[1])))
    compact = _is_compact(x)
    if compact:
        Wh, Wl = _compensated_split(W)
        Wop = jnp.concatenate([Wh, Wl]).T               # (Rp, 2k)
    else:
        Wop = W.T                                       # (Rp, k)
    fill_arr = (fill.astype(bf16 if compact else f32).reshape(1, E)
                if nan_fill else jnp.zeros((1, E), bf16 if compact else f32))
    acc = pl.pallas_call(
        functools.partial(_rows_matmat_kernel, nan_fill=nan_fill, n_rows=k),
        grid=(Rp // tile_r,),
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, Wop.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, E), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, E), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, E), f32),
        cost_estimate=pl.CostEstimate(
            flops=2 * k * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, Wop, fill_arr)
    return acc


def _scores_dirfix_kernel(x_ref, rep_ref, lf_ref, t_ref, acc_ref, *,
                          nan_fill):
    """One row panel: the raw projection t = X_i @ loading plus all three
    direction-fix contractions (t^T X, column sums, rep^T X) off a single
    HBM read. t_i is row-local, so t_i^T X_i accumulates exactly like the
    two-pass form. ``nan_fill=True`` reconstructs filled values in-register
    from ``lf_ref`` row 1 (the per-column fill vector).

    Both contractions ride the MXU (``dot_general``, f32 operands — Mosaic
    cannot lower the mixed bf16xf32 form) — the first VPU-reduction version
    of this kernel was ~3.5x slower than the HBM read it covers."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    f32 = jnp.float32
    val, absent = _decode_block(x_ref)                     # (T, E)
    xp = jnp.where(absent, lf_ref[1:2, :], val) if nan_fill else val
    t = jax.lax.dot_general(xp, lf_ref[0:1, :],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=f32)    # (T, 1)
    t_ref[:] = t
    ones = jnp.ones_like(t)
    w3 = jnp.concatenate([t, rep_ref[:], ones], axis=1)    # (T, 3) f32
    acc_ref[:] += jax.lax.dot_general(
        w3, xp, (((0,), (0,)), ((), ())),
        preferred_element_type=f32)                        # (3, E): q, o, c


@functools.partial(jax.jit, static_argnames=("interpret",))
def scores_dirfix_pass(x, rep, loading, fill=None, interpret: bool = False):
    """The post-PCA contractions of the sztorc scoring step in ONE HBM sweep.

    XLA needs two sweeps of the (R, E) matrix after power iteration: one for
    ``scores = X @ loading`` and one for the stacked direction-fix
    projections (jax_kernels.direction_fixed_scores). But every
    direction-fix projection decomposes over those same rows:

        set1^T X = scores^T X + a1 * colsum(X),   scores^T X row-local in t

    so a single row-panel pass yields everything the direction fix needs:

    Returns ``(t (R,), q (E,), c (E,), o (E,))`` — raw projection
    ``t = X @ loading``, ``q = t^T X``, column sums ``c = 1^T X``, and
    ``o = rep^T X`` — all f32. The caller finishes the (O(R) + O(E))
    direction-fix arithmetic (jax_kernels.sztorc_scores_power_fused).

    x : (R, E) filled reports, f32 or bf16 — or NaN-threaded storage when
    the (E,) ``fill`` vector is given. rep : (R,). loading : (E,).
    """
    R, E = x.shape
    # halved panel budget: 16-row panels at E=100k blow the 16 MB scoped
    # VMEM limit (observed on v5e), 8-row panels fit comfortably
    tile_r = _panel_rows(E, x.dtype.itemsize, _PANEL_BYTES // 2)
    x, rep = _pad_rows(x, rep.astype(jnp.float32), tile_r)
    Rp = x.shape[0]
    f32 = jnp.float32
    grid = (Rp // tile_r,)
    loading = loading.astype(f32).reshape(1, E)
    if fill is not None:
        lf = jnp.concatenate([loading, fill.astype(f32).reshape(1, E)])
    else:
        lf = loading
    t, acc = pl.pallas_call(
        functools.partial(_scores_dirfix_kernel, nan_fill=fill is not None),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_r, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((lf.shape[0], E), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_r, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, E), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, 1), f32),
            jax.ShapeDtypeStruct((3, E), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=8 * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, rep.reshape(-1, 1), lf)
    return t.reshape(Rp)[:R], acc[0], acc[2], acc[1]


def _resolve_certainty_kernel(x_ref, rep_ref, fv_ref, raw_ref, out_ref,
                              cert_ref, pcol_ref, prow_ref, narow_ref, *,
                              tolerance, atol, chunk, n_chunks, n_events):
    """One column panel, one HBM read, the whole back half of the pipeline.

    The panel's full column must be resident before outcomes exist (they are
    column reductions) and outcomes must exist before agreement/certainty,
    which in turn must exist before the per-row NA participation partials —
    so the kernel loops over row chunks of the resident block three times
    (VMEM traversals; HBM is only touched once):

      1. column stats: present-weight totals, present-weighted sums,
         full-reputation filled means, per-row NA counts, NA participation
         columns -> outcomes (weighted mean, catch-snapped);
      2. certainty: reputation mass on the agreeing reporters;
      3. row partials: na @ certainty, which needs this panel's finished
         certainty.

    ``fv_ref``: row 0 = per-column fill value, row 1 = full reputation total
    (broadcast). Columns beyond ``n_events`` (the ragged last block) are
    masked out of every row-indexed accumulation and their column outputs
    are sliced off by the caller.
    """
    jc = pl.program_id(0)

    @pl.when(jc == 0)
    def _():
        prow_ref[:] = jnp.zeros_like(prow_ref)
        narow_ref[:] = jnp.zeros_like(narow_ref)

    f32 = jnp.float32
    C = out_ref.shape[1]
    # ragged-E guard: garbage columns of the physically padded last block
    # must not leak into row-indexed accumulations
    col_ok = (jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
              + jc * C) < n_events
    fill = fv_ref[0:1, :]
    zero = jnp.zeros((1, C), f32)
    # All reductions ride the MXU (dot_general against the chunk's
    # reputation column / a ones vector) — VPU sum() chains measured ~2x
    # the HBM read this kernel covers. Exactness: Mosaic's DEFAULT dot
    # precision rounds f32 operands to bf16, which left these weighted
    # means bf16-quantized (~1e-3 off, measured on v5e) — and this
    # kernel's outputs ARE the outcome/certainty contract. Per-dot
    # Precision.HIGHEST fixes that but its 6-pass decomposition measured
    # ~9 res/s off the headline rate. Instead ``compensated_dot`` runs TWO
    # DEFAULT passes — bf16(v) and the f32 residual — each of whose
    # products is EXACT, because every matrix operand below holds
    # bf16-exact values ({0, 0.5, 1} reports/fills, 0/1 masks) and bf16
    # products against them need <=17 mantissa bits. Only the vector
    # operand (reputation / certainty) is continuous, and its
    # second-order residual (~2^-17 relative) is the only loss. A
    # fancier one-pass (chunk,3)-stacked variant was tried and measured
    # WORSE precision — the stacked shape flips the backend onto a
    # lower-precision path — so the two plain dots stay.
    dn_col = (((0,), (0,)), ((), ()))       # (chunk,1)^T x (chunk,C) -> (1,C)
    dn_row = (((1,), (0,)), ((), ()))       # (chunk,C) x (C,1) -> (chunk,1)

    def compensated_dot(v, m, dn):
        h = v.astype(jnp.bfloat16).astype(f32)
        return (jax.lax.dot_general(h, m, dn, preferred_element_type=f32)
                + jax.lax.dot_general(v - h, m, dn,
                                      preferred_element_type=f32))

    def col_dot(v, m):
        return compensated_dot(v, m, dn_col)

    # The four column stats need only TWO dot subjects — rep.pres and
    # rep.xz — because the rest derive exactly:  pcol = sum(rep) - tw,
    # fmn = numer + fill * pcol  (xf = xz + na*fill elementwise). So the
    # exact compensated kernel issues the same number of MXU passes the
    # quantized 4-dot version did. ``tw`` is the directly-computed one
    # (not derived) because the all-NaN-column fallback tests ``tw > 0``
    # and the direct products are exact zeros there; pcol faces no such
    # zero test (it only feeds ``1 - pcol``).
    def stats_body(i, acc):
        numer, tw = acc
        sl = pl.ds(i * chunk, chunk)
        xs, na = _decode_block(x_ref.at[sl, :])
        rs = rep_ref[sl, :]                            # (chunk, 1)
        naf = (na & col_ok).astype(f32)
        pres = 1.0 - na.astype(f32)
        xz = jnp.where(na, 0.0, xs)
        # 0/1 x 1.0 products are exact in any precision
        narow_ref[sl, :] += jax.lax.dot_general(
            naf, jnp.ones((C, 1), f32), dn_row, preferred_element_type=f32)
        return (numer + col_dot(rs, xz),
                tw + col_dot(rs, pres))

    numer, tw = jax.lax.fori_loop(
        0, n_chunks, stats_body, (zero, zero))
    rep_total = jnp.sum(rep_ref[:])
    # clamp: rep_total is a VPU sum while tw accumulates per-chunk
    # compensated MXU dots (different accumulation orders), so fully
    # present columns can land an ulp either side of pcol==0 — without the
    # clamp participation_columns = 1 - pcol can exceed 1 and percent_na
    # go marginally negative on NA-free data
    pcol = jnp.clip(rep_total - tw, 0.0, rep_total)
    fmn = numer + fill * pcol
    pcol_ref[:] = pcol
    ft = fv_ref[1:2, :]
    full_mean = fmn / jnp.where(ft == 0.0, 1.0, ft)
    means = jnp.where(tw > 0.0,
                      numer / jnp.where(tw > 0.0, tw, 1.0), full_mean)
    # the inner where's branches must anchor to f32: two weak Python
    # scalars promote to the DEFAULT float dtype, which under an x64
    # host (the CPU interpret test environment) is f64 — a dtype this
    # kernel's output refs reject (consensus-lint CL104's bug class).
    # Boundary band: ``atol`` is jax_kernels.catch_tie_atol(f32) — the
    # ONE dtype-floored band shared by the numpy/XLA/Pallas catch
    # kernels, threaded in by resolve_certainty_fused so a band change
    # cannot be applied to one kernel family and missed here (knife-edge
    # means must snap identically across every path).
    out = jnp.where(means < 0.5 - tolerance - atol, 0.0,
                    jnp.where(means > 0.5 + tolerance + atol, 1.0,
                              jnp.asarray(0.5, f32)))
    raw_ref[:] = means
    out_ref[:] = out

    def cert_body(i, cert):
        sl = pl.ds(i * chunk, chunk)
        xs, na = _decode_block(x_ref.at[sl, :])
        rs = rep_ref[sl, :]
        xf = jnp.where(na, fill, xs)
        return cert + col_dot(rs, (xf == out).astype(f32))

    cert = jax.lax.fori_loop(0, n_chunks, cert_body, zero)
    cert_ref[:] = cert
    cert_col = cert.reshape(C, 1)

    def row_body(i, _):
        sl = pl.ds(i * chunk, chunk)
        # absence only — no value decode (int8: raw integer compare;
        # float: isnan on the f32 upcast, since Mosaic rejects bf16 cmpf)
        naf = (_absent_only(x_ref.at[sl, :]) & col_ok).astype(f32)
        # deliberately NOT compensated: certainty's bf16 rounding (~2^-8
        # relative) enters prow scaled by the NA fraction, so the
        # participation_rows error is ~1e-4 absolute at 2% NA — not worth
        # an extra MXU pass per chunk (the means/certainty dots above ARE
        # exact; they are the result contract)
        prow_ref[sl, :] += jax.lax.dot_general(
            naf, cert_col, dn_row, preferred_element_type=f32)
        return 0

    jax.lax.fori_loop(0, n_chunks, row_body, 0)


def _pick_chunk(R: int, cap: int = 1024):
    """Largest row-chunk <= cap that divides R and is a multiple of 8
    sublanes; None when R has no such divisor (caller falls back to XLA)."""
    for c in range(min(cap, R), 7, -1):
        if R % c == 0 and c % 8 == 0:
            return c
    return None


@functools.partial(jax.jit,
                   static_argnames=("tolerance", "block_cols", "interpret"))
def resolve_certainty_fused(x, rep, fill, full_total, tolerance: float,
                            block_cols: int = 0, interpret: bool = False):
    """Outcome resolution + certainty/participation accounting in ONE HBM
    sweep (binary events; jax_kernels.resolve_outcomes +
    certainty_and_bonuses semantics on NaN-threaded storage).

    x : (R, E) reports in any supported storage encoding — f32/bf16 with
        NaN marking absence, or int8 sentinel storage
        (``stored = round(2 * value)`` in {0, 1, 2}, ``-1`` = absent;
        see :func:`_decode_block`). When R has
        no 8-multiple divisor <= 1024 (_pick_chunk — e.g. a prime reporter
        count) the matrix is zero-padded to the next multiple of 8: padded
        rows are non-NaN with zero reputation, so they contribute exactly
        nothing to any column accumulation, and their row outputs are
        sliced off. The pad costs one extra HBM copy of the matrix — far
        cheaper than the multi-pass XLA fallback it replaces.
    rep : (R,) final (smooth) reputation. fill : (E,) per-column fill values
    (computed from the INITIAL reputation — interpolate semantics).
    full_total : () sum of ``rep`` (the XLA path's zero-guarded total).

    Returns ``(outcomes_raw, outcomes_adjusted, certainty, pcol, prow,
    na_count_rows)`` where ``pcol = rep^T [is-NaN]`` (so
    ``participation_columns = 1 - pcol``) and ``prow = [is-NaN] @ certainty``
    (the caller normalizes by total certainty for ``participation_rows``).
    """
    R, E = x.shape
    f32 = jnp.float32
    x, rep = _pad_rows(x, rep, 8)        # no-op when R is a multiple of 8
    Rp = x.shape[0]
    chunk = _pick_chunk(Rp)              # always found: 8 divides Rp
    n_chunks = Rp // chunk
    if not block_cols:          # 0 = auto: widest block that fits VMEM
        if interpret:
            block_cols = 128    # the interpreter has no VMEM limit
        else:
            # autotuned width first (pyconsensus_tpu.tune), re-validated
            # against the VMEM fit so a stale cache entry cannot compile
            # an illegal kernel; the hand-measured heuristic otherwise
            tuned = _tuned("resolve_block_cols", n_reporters=Rp,
                           itemsize=x.dtype.itemsize)
            if tuned and resolve_block_fits(Rp, int(tuned),
                                            x.dtype.itemsize):
                block_cols = int(tuned)
            else:
                block_cols = _resolve_block_cols(Rp, x.dtype.itemsize)
            if block_cols is None:
                raise ValueError(f"R={R} (padded to {Rp}) does not fit the "
                                 "fused resolution kernel's VMEM budget; "
                                 "use the XLA path")
    C = min(block_cols, E)
    n_blocks = pl.cdiv(E, C)
    fv = jnp.concatenate([
        fill.astype(f32).reshape(1, E),
        jnp.broadcast_to(jnp.asarray(full_total, f32), (1, E)),
    ])
    col_spec = pl.BlockSpec((1, C), lambda j: (0, j), memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((Rp, 1), lambda j: (0, 0),
                            memory_space=pltpu.VMEM)
    # the dtype-floored catch boundary band (jax_kernels.catch_tie_atol)
    # — computed HERE, at the same f32 the kernel's means carry, so the
    # numpy/XLA/Pallas catch families share one band definition (lazy
    # import: jax_kernels lazily imports this module's kernels back)
    from .jax_kernels import catch_tie_atol

    raw, out, cert, pcol, prow, narow = pl.pallas_call(
        functools.partial(_resolve_certainty_kernel,
                          tolerance=float(tolerance),
                          atol=catch_tie_atol(f32), chunk=chunk,
                          n_chunks=n_chunks, n_events=E),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((Rp, C), lambda j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((Rp, 1), lambda j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, C), lambda j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=[col_spec, col_spec, col_spec, col_spec,
                   row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((1, E), f32),
            jax.ShapeDtypeStruct((1, E), f32),
            jax.ShapeDtypeStruct((1, E), f32),
            jax.ShapeDtypeStruct((1, E), f32),
            jax.ShapeDtypeStruct((Rp, 1), f32),
            jax.ShapeDtypeStruct((Rp, 1), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=10 * Rp * E, bytes_accessed=Rp * E * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x, rep.astype(f32).reshape(-1, 1), fv)
    return (raw.reshape(E), out.reshape(E), cert.reshape(E), pcol.reshape(E),
            prow.reshape(Rp)[:R], narow.reshape(Rp)[:R])


def power_iteration_fused(x, mu, denom, rep, n_iters: int, tol: float,
                          fill=None, interpret: bool = False, v_init=None):
    """First principal component via power iteration with the fused
    one-HBM-pass covariance application. Runs the shared convergence driver
    (``jax_kernels._power_loop`` — same start vector, normalization, and
    early-exit rule as the XLA matvec path) but never materializes the
    centered matrix and reads ``x`` once — not twice — per step.

    x : (R, E) filled reports (f32 or bf16 — bf16 halves the HBM traffic),
        or NaN-threaded storage when the (E,) ``fill`` vector is given.
    mu, denom : weighted column means and the ``1 - sum(rep^2)`` scalar.
    Returns the (E,) f32 loading (unit norm, sign arbitrary).
    """
    from .jax_kernels import _power_loop

    E = x.shape[1]
    f32 = jnp.float32
    # pad once, outside the convergence loop — apply_weighted_cov's own pad
    # then no-ops, instead of copying the matrix on every sweep when R is
    # not a panel multiple
    tile_r = matmat_tile_rows(E, x.dtype.itemsize, fill is not None)
    x, rep = _pad_rows(x, rep.astype(f32), tile_r)

    def apply_cov(v):
        return apply_weighted_cov(x, mu, rep, v, fill=fill,
                                  interpret=interpret) / denom

    return _power_loop(apply_cov, E, f32, n_iters, tol, v_init=v_init)[0]
