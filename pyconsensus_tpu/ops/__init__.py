"""Kernel layer: numpy reference kernels (correctness anchor) and their
jit-compatible JAX mirrors. See module docstrings for semantics provenance."""

from . import jax_kernels, numpy_kernels

__all__ = ["numpy_kernels", "jax_kernels"]
