"""JAX/TPU kernels for the oracle consensus pipeline.

Every function here is a pure, jit-compatible mirror of the reference
semantics defined in ``pyconsensus_tpu.ops.numpy_kernels`` (the correctness
anchor; see its module docstring for provenance — SURVEY.md §2-3, symbols
anchored in BASELINE.json). Design rules, per SURVEY.md §7 M0:

- **No masked arrays.** Missing reports are ``NaN`` in the input matrix; every
  kernel derives an explicit ``present`` mask with ``jnp.isnan`` and works
  through ``jnp.where``. Shapes are static; nothing here branches on values in
  Python.
- **No E×E covariance at scale.** :func:`weighted_prin_comp` dispatches between
  an explicit ``E×E`` eigendecomposition (small E, exact-parity path), the
  ``R×R`` Gram trick (rank <= R-1, SURVEY.md §7 "hard parts" route b), and
  matrix-free power iteration (route a) — the latter two only ever contract
  over the event axis, so they shard cleanly over an event-partitioned mesh
  with ``psum``-style reductions inserted by XLA.
- All comparisons and tie-breaks replicate the numpy kernels exactly, so
  catch-snapped binary outcomes agree bit-identically across backends.
"""

from __future__ import annotations

# consensus-lint: traced-module — every function here is device
# kernel code compiled into jitted callers; host-sync calls and
# f64 literals are lint errors throughout (docs/STATIC_ANALYSIS.md)


from typing import Optional


import jax
import jax.numpy as jnp
from jax import lax

from . import numpy_kernels as nk

__all__ = [
    "normalize",
    "canon_sign",
    "catch",
    "rescale",
    "unscale_outcomes",
    "interpolate",
    "interpolate_masked",
    "weighted_cov",
    "weighted_prin_comp",
    "weighted_prin_comps",
    "weighted_median_cols",
    "direction_fixed_scores",
    "row_reward_weighted",
    "smooth",
    "resolve_outcomes",
    "certainty_and_bonuses",
]


def exact_matmuls(fn):
    """Trace ``fn`` under ``jax.default_matmul_precision("highest")``.

    TPU's DEFAULT matmul precision multiplies f32 operands in bf16 on the
    MXU. Measured on v5e: the resolution kernel's reputation-weighted
    column means came back bf16-quantized (~1e-3 relative error vs the
    interpreter), which silently degrades every cross-backend value
    contract (reputation/certainty parity is tested at 5e-6) and can flip
    a catch-snap within 1e-3 of a boundary. Every contraction in this
    pipeline is matvec-shaped and HBM-bandwidth-bound — the 3-pass exact
    f32 MXU mode costs arithmetic the bandwidth already hides — so the
    pipeline drivers opt into exactness wholesale. Explicitly-lowered
    bf16 OPERANDS (``matvec_dtype``/``storage_dtype``) still stream at
    half width: highest precision multiplies the stored bf16 values
    exactly, which is precisely the "low-precision storage, exact
    accumulation" contract."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)
    return wrapped


def normalize(v: jnp.ndarray) -> jnp.ndarray:
    """``v / sum(v)`` with the zero-sum vector returned unchanged
    (numpy_kernels.normalize)."""
    total = jnp.sum(v)
    safe = jnp.where(total == 0.0, 1.0, total)
    return jnp.where(total == 0.0, v, v / safe)


def canon_sign_factor(v: jnp.ndarray) -> jnp.ndarray:
    """The scalar +-1 factor canon_sign would multiply by (first-argmax
    tie-break, zero sign -> +1) — shared by every direction-fix decision
    site so the tie-break convention cannot drift between them; exposed
    separately because the fused forms must apply the same factor to
    quantities LINEAR in the scores (qs) as well."""
    s = jnp.sign(v[jnp.argmax(jnp.abs(v))])
    return jnp.where(s == 0.0, 1.0, s)


def canon_sign(v: jnp.ndarray) -> jnp.ndarray:
    """JAX mirror of numpy_kernels.canon_sign (identical tie-break)."""
    return v * canon_sign_factor(v)


def catch_tie_atol(dtype) -> float:
    """The catch-snap boundary band for ``dtype`` arithmetic:
    ``numpy_kernels.CATCH_TIE_ATOL`` floored at ``32 * eps`` (the
    weighted-median tie's dtype rule) — under f32 a knife-edge mean
    lands up to ~ulp(1.0) = 1.2e-7 off, so the f64-sized band would
    collapse to exact equality there."""
    return max(nk.CATCH_TIE_ATOL, 32.0 * float(jnp.finfo(dtype).eps))


def catch(x: jnp.ndarray, tolerance) -> jnp.ndarray:
    """Snap toward {0, 0.5, 1} (numpy_kernels.catch, including its
    :data:`~numpy_kernels.CATCH_TIE_ATOL` boundary band — a value within
    the band of ``0.5 ± tolerance`` resolves to the ambiguous 0.5 on
    every path instead of by reduction-order ulp noise). The 0.5 branch
    is anchored to ``x.dtype``: an all-weak-scalar ``jnp.where`` promotes
    to the DEFAULT float dtype, which silently widened f32 inputs to f64
    on x64 hosts (consensus-lint CL104's bug class)."""
    atol = catch_tie_atol(x.dtype)
    return jnp.where(x < 0.5 - tolerance - atol, 0.0,
                     jnp.where(x > 0.5 + tolerance + atol, 1.0,
                               jnp.asarray(0.5, x.dtype)))


def row_any(mask, dtype):
    """``mask.any(axis=1)`` for a big (R, E) bool matrix, as an MXU matvec.

    Row-axis bool reductions lower pathologically on TPU (~360 ms at
    10k x 100k, measured — 35x the matrix read time); counting via a
    matmul against ones is one bandwidth-bound pass."""
    return jnp.matmul(mask.astype(dtype),
                      jnp.ones((mask.shape[1],), dtype=dtype)) > 0.0


def rescale(reports, scaled, mins, maxs):
    """Scaled columns -> [0, 1]; binary pass through; NaN stays NaN."""
    span = jnp.where(scaled, maxs - mins, 1.0)
    span = jnp.where(span == 0.0, 1.0, span)
    shifted = (reports - jnp.where(scaled, mins, 0.0)[None, :]) / span[None, :]
    return jnp.where(scaled[None, :], shifted, reports)


def unscale_outcomes(outcomes, scaled, mins, maxs):
    """Scaled outcomes map back through ``x * (max - min) + min``."""
    return jnp.where(scaled, outcomes * (maxs - mins) + mins, outcomes)


def interpolate_masked(reports, reputation, scaled, tolerance):
    """Reputation-weighted column-mean fill of NaN entries; binary fills are
    catch-snapped (numpy_kernels.interpolate). One fused pass: XLA folds the
    mask/where/reduce chain into a single HBM sweep of the (R, E) matrix.

    Returns ``(filled, present)`` — the bool participation mask is a
    by-product of the fill and every downstream phase that needs NA
    accounting (outcome resolution, certainty/bonuses, ``na_row``) consumes
    it instead of re-deriving ``isnan`` from the raw f32 matrix: after this
    kernel the original reports never need to be read again."""
    present = ~jnp.isnan(reports)
    zeroed = jnp.where(present, reports, 0.0)
    active_rep = jnp.where(present, reputation[:, None], 0.0)
    denom = jnp.sum(active_rep, axis=0)
    numer = jnp.sum(zeroed * reputation[:, None], axis=0)
    fill = jnp.where(denom > 0.0, numer / jnp.where(denom > 0.0, denom, 1.0), 0.5)
    fill = jnp.where(scaled, fill, catch(fill, tolerance))
    return jnp.where(present, zeroed, fill[None, :]), present


def interpolate(reports, reputation, scaled, tolerance):
    """:func:`interpolate_masked` without the mask (reference-shaped API)."""
    return interpolate_masked(reports, reputation, scaled, tolerance)[0]


def weighted_cov(reports_filled, reputation):
    """(cov (E,E), deviations (R,E)) — only used on small E; the scaled path
    goes through the Gram trick / power iteration below
    (numpy_kernels.weighted_cov)."""
    mu = reputation @ reports_filled
    dev = reports_filled - mu[None, :]
    denom = 1.0 - jnp.sum(reputation ** 2)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    cov = (dev * reputation[:, None]).T @ dev / denom
    return cov, dev


def _mu_denom(reports_filled, reputation):
    """Weighted column means + the ``1 - sum(rep^2)`` unbiased-weight
    denominator (zero-guarded) — the single definition of the weighting
    convention shared by every PCA strategy."""
    mu = reputation @ reports_filled
    denom = 1.0 - jnp.sum(reputation ** 2)
    return mu, jnp.where(denom == 0.0, 1.0, denom)


def _center(reports_filled, reputation):
    mu, denom = _mu_denom(reports_filled, reputation)
    return reports_filled - mu[None, :], denom


def _first_pc_eigh_cov(dev, denom, reputation):
    cov = (dev * reputation[:, None]).T @ dev / denom
    _, eigvecs = jnp.linalg.eigh(cov)
    loading = eigvecs[:, -1]
    return loading, dev @ loading


def _first_pc_eigh_gram(dev, denom, reputation):
    """Gram trick (SURVEY.md §7 route b): with A = diag(sqrt(rep)) D, the
    nonzero spectrum of C = A^T A / denom equals that of G = A A^T / denom
    (R×R). Eigenvector map-back: v = A^T u / ||A^T u||. Never forms E×E."""
    sqrt_rep = jnp.sqrt(jnp.clip(reputation, 0.0, None))
    A = dev * sqrt_rep[:, None]                       # (R, E)
    G = (A @ A.T) / denom                             # (R, R) — contracts over E
    _, eigvecs = jnp.linalg.eigh(G)
    u = eigvecs[:, -1]
    v = A.T @ u                                       # (E,)
    norm = jnp.linalg.norm(v)
    loading = v / jnp.where(norm == 0.0, 1.0, norm)
    return loading, dev @ loading


def _power_seed(E: int, dtype):
    """Deterministic dense start vector for power iteration: a fixed-key
    standard-normal draw (NOT the ones vector). The ones vector is EXACTLY
    orthogonal to the dominant eigenvector whenever that eigenvector's
    entries sum to zero — which the canonical Truthcoin 6×4 matrix
    produces (an antisymmetric top loading): power iteration then starts
    with zero v1 component and must wait for rounding noise to leak one
    in. In f64 the 128-sweep budget recovers; in f32 on the real chip the
    alignment early-exit fires while the iterate still sits on the
    runner-up eigenvector (measured on v5e: outcomes [1, .5, .5, 0]
    vs numpy's [1, 1, 0, 0]). A fixed-key normal vector is deterministic
    across runs/backends and has measure-zero probability of orthogonality
    to any data-derived direction."""
    return jax.random.normal(jax.random.key(0), (E,), dtype)


def _power_loop(apply_cov, E: int, dtype, n_iters: int, tol: float,
                v_init=None, base=None):
    """Shared power-iteration driver (used by the XLA matvec path below and
    the fused Pallas path in ``pallas_kernels``): deterministic start — one
    implicit-covariance application to the fixed-key :func:`_power_seed`
    vector — then a
    ``lax.while_loop`` that stops once successive (normalized) iterates
    align to ``|<v_k, v_{k-1}>| >= 1 - max(tol, 8*eps(dtype))``. With a
    strong first-eigenvalue gap (the coordinated-collusion signal PCA
    exists to detect) this converges in a handful of steps, and each
    avoided step is a full HBM sweep of the (R, E) matrix at north-star
    scale. The machine-epsilon floor means ``tol=0`` stops once per-step
    improvement falls below float noise — the loading then differs from an
    exhaustive run only by O(eps / eigengap); ``tol < 0`` disables the
    early exit entirely (exactly ``n_iters`` sweeps — the testing
    baseline). The
    dynamic trip count is jit/vmap/GSPMD-compatible (vmapped lanes run
    until all converge). Returns ``(loading, n_sweeps)`` — the unit-norm
    loading (sign arbitrary) and the number of in-loop covariance
    applications executed (the start application is not counted; exposed
    so tests can pin the warm-start sweep savings).

    ``v_init`` (optional) warm-starts the iteration: the iterative Sztorc
    loop feeds each outer iteration the previous iteration's loading —
    reputation moves a little per redistribution step, so the dominant
    eigenvector barely moves and the early exit fires after one or two
    sweeps instead of a cold handful. A zero/None ``v_init`` falls back to
    the cold-start seed, bitwise identical to the cold start (so outer
    iteration 1, whose scan carry is zeros, is unchanged).

    The warm seed is BLENDED with the cold-start seed rather than used
    pure. A pure stale eigenvector is an exact fixed point of
    ``apply_cov``, so if the top two eigenvalues crossed between outer
    iterations (e.g. redistribution demoting one of two near-tied
    collusion clusters) a pure warm start could pass the self-consistency
    exit while sitting on the now-SECOND eigenvector. Mixing in the dense
    seed direction restores the cold start's reachability assumption
    (<seed, v1> != 0): any decisively dominant new direction contaminates
    the iterate geometrically and the exit cannot fire until it has won;
    in the genuinely near-tied regime the early exit may still stop
    between the two, where the directions are statistically
    interchangeable (and where the exact eigh is itself unstable). Cost:
    at most a sweep or two over the pure warm start when nothing
    crossed.

    ``base`` (optional) substitutes an explicit start vector for the
    fixed-key :func:`_power_seed` draw. The serving layer's padded
    bucket kernel passes the TRUE-width seed zero-extended to the bucket
    width — threefry counters are not prefix-stable across draw lengths,
    so a bucket-width draw would start a DIFFERENT trajectory than the
    direct resolution the padded results must match bit-for-bit (the
    ``fused_sharded._seed_placed`` precedent)."""
    no_exit = tol < 0
    tol = max(float(tol), 8.0 * float(jnp.finfo(dtype).eps))

    base = _power_seed(E, dtype) if base is None else base.astype(dtype)
    base_unit = base / jnp.linalg.norm(base)
    if v_init is None:
        seed = base
    else:
        v_init = v_init.astype(dtype)
        n_i = jnp.linalg.norm(v_init)
        blended = (v_init / jnp.where(n_i > 0.0, n_i, 1.0)
                   + 0.25 * base_unit)
        seed = jnp.where(n_i > 0.0, blended, base)
    v0 = apply_cov(seed)
    n0 = jnp.linalg.norm(v0)
    v0 = jnp.where(n0 == 0.0, base_unit,
                   v0 / jnp.where(n0 == 0.0, 1.0, n0))

    def cond(state):
        i, _, done = state
        return (i < n_iters) & ~done

    def body(state):
        i, v, _ = state
        w = apply_cov(v)
        n = jnp.linalg.norm(w)
        w = jnp.where(n == 0.0, v, w / jnp.where(n == 0.0, 1.0, n))
        if no_exit:
            done = jnp.asarray(False)
        else:
            done = jnp.abs(jnp.vdot(w, v)) >= 1.0 - tol
        return i + 1, w, done

    i, loading, _ = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), v0, jnp.asarray(False)))
    return loading, i


def _first_pc_power(reports_filled, mu, denom, reputation,
                    n_iters: int = 128, tol: float = 0.0, matvec_dtype=None,
                    v_init=None):
    """Matrix-free power iteration (SURVEY.md §7 route a): each step is two
    sharded matvecs, O(R*E), no E×E or R×R matrix. Convergence/early-exit
    semantics in :func:`_power_loop`.

    Centering is matrix-free too: with D = X - 1 mu^T,

        D v            = X v - (mu . v) 1
        D^T (rep ⊙ t)  = X^T (rep ⊙ t) - mu * sum(rep ⊙ t)

    so the centered matrix is never materialized — the matvecs stream the
    *raw* filled reports, saving a full (R, E) write + read at north-star
    scale, and ``matvec_dtype`` (e.g. ``jnp.bfloat16``) can keep the one
    low-precision copy as the only large buffer for the bandwidth-bound
    sweeps (f32 accumulation via ``preferred_element_type``; outcomes are
    catch-snapped, so the loading noise stays far below the snap tolerance
    — the parity-critical f64 path leaves it None).

    The iterates, norms, and early-exit test run in the *reputation* dtype
    (the accumulation precision), never the matrix storage dtype — a bf16
    matrix (via ``matvec_dtype`` or a pipeline ``storage_dtype``) only
    lowers the precision of the streamed operand, not of the convergence
    arithmetic.
    """
    out_dtype = reputation.dtype
    mm = (reports_filled if matvec_dtype is None
          else reports_filled.astype(matvec_dtype))
    rep = reputation

    def apply_cov(v):
        t = jnp.matmul(mm, v.astype(mm.dtype),
                       preferred_element_type=out_dtype) - mu @ v   # (R,)
        rt = rep * t
        y = (jnp.matmul(mm.T, rt.astype(mm.dtype),
                        preferred_element_type=out_dtype)
             - mu * jnp.sum(rt))                                    # (E,)
        return y / denom

    loading, _ = _power_loop(apply_cov, reports_filled.shape[1], out_dtype,
                             n_iters, tol, v_init=v_init)
    scores = (jnp.matmul(reports_filled,
                         loading.astype(reports_filled.dtype),
                         preferred_element_type=out_dtype) - mu @ loading)
    return loading, scores


def resolve_pca_method(R: int, E: int, method: str) -> str:
    """Resolve ``"auto"`` by static shape (E<=1024 explicit cov eigh, else
    R<=4096 Gram eigh, else power iteration — Pallas-fused on TPU when the
    E-wide kernel fits scoped VMEM), and downgrade a ``"power-fused"``
    request that cannot run: off-TPU beyond toy sizes (the Pallas
    *interpreter* would be pathological) or past the VMEM budget (the
    compile fails outright) — the XLA matvec path computes the same
    loading."""
    from .pallas_kernels import fused_pca_fits

    # conservative f32 itemsize: the matrix may be f32 even when a bf16
    # matvec dtype is configured
    fits = fused_pca_fits(E, 4)
    if method == "auto":
        if E <= 1024:
            return "eigh-cov"
        if R <= 4096:
            return "eigh-gram"
        if jax.default_backend() == "tpu" and fits:
            return "power-fused"
        return "power"
    if method == "power-fused":
        if jax.default_backend() != "tpu" and R * E > (1 << 20):
            return "power"
        if not fits:
            return "power"
    return method


def weighted_prin_comp(reports_filled, reputation, method: str = "auto",
                       power_iters: int = 128, power_tol: float = 0.0,
                       matvec_dtype: str = "", v_init=None):
    """First principal component of the reputation-weighted covariance
    (numpy_kernels.weighted_prin_comp). ``method``:

    - ``"eigh-cov"``  — explicit E×E eigh (parity path, small E);
    - ``"eigh-gram"`` — R×R Gram-trick eigh (exact, E-shardable);
    - ``"power"``     — matrix-free power iteration (fully scalable), with
      ``power_tol`` early exit and optional low-precision ``matvec_dtype``
      (e.g. ``"bfloat16"``) for the bandwidth-bound sweeps;
    - ``"power-fused"`` — power iteration through the Pallas row-panel
      kernel (pallas_kernels.apply_weighted_cov): one HBM sweep per step
      instead of two, centered matrix never materialized. Single-device
      TPU path (runs interpreted elsewhere — tests only);
    - ``"auto"``      — picks by static shape: E<=1024 cov, else R<=4096 gram,
      else power.

    Returns ``(loading (E,), scores (R,))``; sign fixed downstream.
    """
    R, E = reports_filled.shape
    method = resolve_pca_method(R, E, method)
    if method == "power-fused":
        from .pallas_kernels import power_iteration_fused

        acc = reputation.dtype
        mu, denom = _mu_denom(reports_filled, reputation)
        xmm = (reports_filled.astype(jnp.dtype(matvec_dtype))
               if matvec_dtype else reports_filled)
        loading = power_iteration_fused(
            xmm, mu, denom, reputation, power_iters, power_tol,
            interpret=jax.default_backend() != "tpu",
            v_init=v_init).astype(acc)
        # scores = (X - mu) @ loading without materializing the centered
        # matrix: X @ loading is one sweep; mu . loading is a scalar
        scores = (jnp.matmul(reports_filled,
                             loading.astype(reports_filled.dtype),
                             preferred_element_type=acc) - mu @ loading)
        return loading, scores
    if method == "power":
        mu, denom = _mu_denom(reports_filled, reputation)
        return _first_pc_power(reports_filled, mu, denom, reputation,
                               power_iters, tol=power_tol,
                               matvec_dtype=(jnp.dtype(matvec_dtype)
                                             if matvec_dtype else None),
                               v_init=v_init)
    dev, denom = _center(reports_filled, reputation)
    if method == "eigh-cov":
        return _first_pc_eigh_cov(dev, denom, reputation)
    if method == "eigh-gram":
        return _first_pc_eigh_gram(dev, denom, reputation)
    raise ValueError(f"unknown PCA method: {method!r}")


#: reporter count above which multi-component extraction abandons the exact
#: R×R Gram eigh for matrix-free orthogonal iteration. Measured: at R=10k
#: XLA's QDWH eigh on the (R, R) Gram allocates dozens of ~300 MB
#: temporaries and, with the explicitly-centered (R, E) dev matrix also
#: resident, exhausts a v5e's 16 GB HBM (docs/ROADMAP.md, 2026-07-31).
_GRAM_EIGH_MAX_R = 4096

#: fixed sweep budget for the multi-component orthogonal iteration; the
#: alignment-or-Ritz-stability early exit below usually stops far sooner
_ORTH_ITERS = 96

#: relative Ritz-value stability that counts a noise-bulk column as
#: settled when its vector keeps rotating — see _top_pcs_orth_iter's
#: convergence notes
_RITZ_RTOL = 1e-6
#: fraction of the dominant Ritz value under which a column counts as
#: noise bulk (eligible for the stability exemption above)
_BULK_FLOOR = 5e-3


def _decode_storage(x, fill, acc):
    """Filled f32/f64 view of sentinel-threaded storage (int8 lattice or
    NaN-threaded float) — the XLA-side mirror of
    pallas_kernels._decode_block + fill reconstruction, for the few
    elementwise passes (column squares, masked means) that are not worth
    a kernel."""
    if jnp.issubdtype(x.dtype, jnp.integer):
        return jnp.where(x < 0, fill.astype(acc), x.astype(acc) * 0.5)
    return jnp.where(jnp.isnan(x), fill.astype(x.dtype), x).astype(acc)


def _top_pcs_orth_iter(reports_filled, mu, denom, reputation,
                       n_components: int, n_iters: int = _ORTH_ITERS,
                       tol: float = 0.0, fill=None,
                       interpret: bool = False, v_init=None):
    """Top-``k`` principal subspace of the implicit weighted covariance by
    blocked orthogonal iteration (subspace/simultaneous power iteration) —
    the multi-component analogue of :func:`_first_pc_power`. Never
    materializes the centered matrix, the R×R Gram, or E×E: each sweep is
    two (R, E)-streaming matmuls against an (E, k) block plus an O(E·k²)
    thin-QR re-orthonormalization, so it scales to the north-star shape
    where the Gram eigh OOMs (see :data:`_GRAM_EIGH_MAX_R`).

    Returns ``(loadings (E, k), eigvals (k,), trace, scores-or-None)`` —
    eigenvalues are Ritz values of the converged block (sorted
    descending), ``trace`` is the matrix-free total variance
    ``(rep·X² - mu²)·1 / denom`` (so explained-variance fractions cost
    no extra (R, E) pass beyond the one ``rep @ X²`` contraction), and
    ``scores`` is the centered (R, k) score block FOLDED out of the
    final Rayleigh-Ritz application on the one-pass-kernel storage path
    (None on the XLA and separable-fallback paths, whose callers compute
    scores with their own sweep).

    Convergence (re-tuned round 3; each saved sweep is two HBM passes of
    the matrix): a column counts as settled when successive orthonormal
    blocks align (``|<q_i, v_i>| >= 1 - tol``) OR its Ritz value has
    stabilized to relative ``_RITZ_RTOL`` of the dominant one. The pure
    per-column-alignment exit made the noise bulk gate the loop: on a
    collusion matrix components beyond the planted structure sit in a
    near-degenerate cluster and keep rotating (the exact eigh is itself
    unstable there), so the loop burned its whole ``n_iters`` budget on
    directions that are statistically interchangeable — measured 0.64 s
    for ICA at 10k x 100k, ~15x the sztorc path. Ritz values of a bulk
    cluster stabilize as soon as the subspace stops rotating INTO the
    bulk, which is what actually matters. A bare eigenvalue-stability
    exit returned ~4e-3-off loadings (the reason round 2 rejected it);
    the **final Rayleigh-Ritz rotation** below fixes precisely that —
    eigh of the k x k projected covariance ``V^T C V`` rotates the block
    onto the optimal eigenvector approximations within the captured
    subspace, so decisively-separated components come out as accurate as
    the old run-to-alignment loop's (pinned by
    tests/test_kernels.py::test_orth_iter_matches_eigh at 1e-5).
    Start block: fixed-key normal (deterministic; measure-zero
    orthogonality risk — the ones vector is EXACTLY orthogonal to
    antisymmetric eigenvectors, see :func:`_power_seed`).

    ``v_init`` (optional, (E, k)) warm-starts the subspace — the
    iterative pipeline feeds each outer redistribution iteration the
    previous iteration's converged block, so the loop re-enters almost
    aligned and the exit fires after a sweep or two instead of a cold
    handful (each saved sweep is TWO HBM passes here). Same reachability
    blend as :func:`_power_loop`'s single-vector warm start and for the
    same reason: a stale block is an exactly invariant subspace of
    ``apply_cov_block``, so a pure warm start could pass the alignment
    exit while a newly-risen direction sits outside the span; mixing in
    the cold random block keeps every direction reachable. An all-zero
    ``v_init`` (outer iteration 1's scan carry) falls back to the cold
    start bitwise.

    With ``fill`` given, ``reports_filled`` is sentinel-threaded storage
    (int8 lattice / NaN-threaded float — the fused pipeline's compact
    encoding) and both block sweeps run through the Pallas storage
    kernels (``storage_matmat`` / ``storage_rows_matmat``): each sweep
    then streams 1-2 bytes per element instead of the XLA matmuls'
    storage width, and the filled matrix never exists in HBM (round 4,
    VERDICT r3 item 2)."""
    acc = reputation.dtype
    R, E = reports_filled.shape
    k = int(n_components)
    rep = reputation
    use_storage = fill is not None

    if use_storage:
        from .pallas_kernels import (apply_weighted_cov_block,
                                     cov_block_kernel_fits,
                                     matmat_tile_rows, storage_matmat,
                                     storage_rows_matmat, _pad_rows)

        # pad once, OUTSIDE the sweep loop (the same hoist
        # power_iteration_fused applies, and for the same reason): the
        # kernels' internal _pad_rows then no-ops instead of copying the
        # whole storage matrix on EVERY sweep when R is not a panel
        # multiple. Measured 2026-08-01 (ica, int8, interleaved A/Bs):
        # R=10000 at E=16384 ran 29.5 res/s vs 38+ for every
        # panel-divisible neighbor (9984/10240), and the anomalous clean
        # tie at E=49152 was exactly the width whose tile (40) divides
        # 10000 — the per-sweep repad WAS the "fused loses at large E"
        # effect that round 4 mis-attributed to width and gated with
        # _MULTI_FUSED_MAX_E. Zero-padded rows carry zero reputation, so
        # both contractions are unchanged (module padding contract).
        tile_r = matmat_tile_rows(E, reports_filled.dtype.itemsize,
                                  nan_fill=True)
        reports_filled, rep = _pad_rows(reports_filled, rep, tile_r)
        Rp = reports_filled.shape[0]

        if cov_block_kernel_fits(E, k, reports_filled.dtype.itemsize):
            # one-pass block kernel: both contractions off a single HBM
            # read per sweep (apply_weighted_cov_block) — the separable
            # pair below reads the matrix twice per sweep
            def apply_cov_block_t(V):    # (E, k) -> ((E, k), (R, k))
                y, t = apply_weighted_cov_block(
                    reports_filled, mu, rep, V.astype(acc), fill=fill,
                    interpret=interpret, emit_t=True)
                return y.astype(acc) / denom, t.astype(acc)

            def apply_cov_block(V):              # (E, k) -> (E, k)
                y, _ = apply_weighted_cov_block(
                    reports_filled, mu, rep, V.astype(acc), fill=fill,
                    interpret=interpret)
                return y.astype(acc) / denom
        else:
            apply_cov_block_t = None

            def apply_cov_block(V):              # (E, k) -> (E, k)
                t = (storage_matmat(reports_filled, V.astype(acc), fill=fill,
                                    interpret=interpret).astype(acc)
                     - jnp.ones((Rp, 1), acc) * (mu @ V)[None, :])  # (Rp, k)
                rt = rep[:, None] * t
                y = (storage_rows_matmat(reports_filled, rt.T.astype(acc),
                                         fill=fill,
                                         interpret=interpret).T.astype(acc)
                     - mu[:, None] * jnp.sum(rt, axis=0)[None, :])  # (E, k)
                return y / denom
    else:
        apply_cov_block_t = None

        def apply_cov_block(V):                  # (E, k) -> (E, k)
            t = (jnp.matmul(reports_filled, V.astype(reports_filled.dtype),
                            preferred_element_type=acc)
                 - jnp.ones((R, 1), acc) * (mu @ V)[None, :])  # (R, k)
            rt = rep[:, None] * t
            y = (jnp.matmul(reports_filled.T,
                            rt.astype(reports_filled.dtype),
                            preferred_element_type=acc)
                 - mu[:, None] * jnp.sum(rt, axis=0)[None, :])  # (E, k)
            return y / denom

    v0 = jax.random.normal(jax.random.key(0), (E, k), acc)
    V0, _ = jnp.linalg.qr(v0)
    if v_init is not None:
        ni = jnp.linalg.norm(v_init)
        # columns of a real v_init are unit (a converged orthonormal
        # block); 0.25 mirrors _power_loop's cold-seed blend weight
        blended = (v_init.astype(acc) / jnp.where(ni > 0.0, ni, 1.0)
                   * jnp.sqrt(jnp.asarray(float(k), acc)) + 0.25 * V0)
        Qw, _ = jnp.linalg.qr(blended)
        # whole-block fallback: an elementwise V0 substitution into a
        # partially non-finite QR result would leave a non-orthonormal
        # block (rank loss poisons columns, not entries), and the first
        # sweep's alignment/Ritz exit statistics would run on it
        V0 = jnp.where(jnp.isfinite(Qw).all() & (ni > 0.0), Qw, V0)

    tol = max(float(tol), 8.0 * float(jnp.finfo(acc).eps))

    def cond(state):
        i, _, _, _, done = state
        return (i < n_iters) & ~done

    # Orthonormalization stays HOUSEHOLDER ``jnp.linalg.qr`` — measured
    # ~2 ms/sweep at (100000, 5) on v5e, as expensive as the storage
    # sweep itself, and a CholeskyQR2 replacement (two MXU-shaped k x k
    # Grams + triangular solves) was tried round 5 and measured
    # CATASTROPHIC: 12.0 -> 1.96 res/s end-to-end. Mechanism: CholQR2's
    # stability needs kappa(Y)^2 * eps < 1, and Y = C V carries the
    # near-degenerate bulk's full condition number, so the
    # orthonormalization noise re-rotated the bulk every sweep and the
    # alignment/Ritz exit never fired — the loop burned its whole
    # 96-sweep budget (MEASUREMENTS_r05 cholqr2_ab). The QR cost is the
    # price of a numerically robust exit.
    def body(state):
        i, V, eig_prev, stable_prev, _ = state
        Y = apply_cov_block(V)
        eig = jnp.sum(V * Y, axis=0)             # per-column Ritz values
        Q, _ = jnp.linalg.qr(Y)
        # zero-norm guard (degenerate covariance): qr of a zero block can
        # produce NaN columns — keep the previous orthonormal block
        Q = jnp.where(jnp.isfinite(Q), Q, V)
        align = jnp.abs(jnp.sum(Q * V, axis=0))  # per-column |<q_i, v_i>|
        # The Ritz exemption applies ONLY to negligible columns: value
        # stability alone is NOT vector convergence (values converge
        # quadratically — a 1e-6-stable Ritz value can sit on a 1e-3-off
        # vector), so any column carrying real spectrum mass must align.
        # A column is exempt when its Ritz value has been stable for TWO
        # consecutive sweeps (ADVICE r3: a small-but-real component just
        # under the floor can show one accidentally-stable sweep while
        # the subspace is still rotating into it; two in a row means the
        # rotation has actually stopped feeding it) and sits under
        # _BULK_FLOOR of the dominant value — the noise-bulk directions
        # whose vectors are statistically interchangeable and whose
        # explained fractions round to zero.
        lead = jnp.maximum(jnp.max(jnp.abs(eig)), jnp.finfo(acc).tiny)
        ritz_stable = jnp.abs(eig - eig_prev) <= _RITZ_RTOL * lead
        negligible = jnp.abs(eig) <= _BULK_FLOOR * lead
        done_col = (align >= 1.0 - tol) | (ritz_stable & stable_prev
                                           & negligible)
        done = jnp.min(done_col.astype(acc)) > 0.0
        return i + 1, Q, eig, ritz_stable, done

    _, V, _, _, _ = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), V0,
                     jnp.full((k,), jnp.inf, acc),
                     jnp.zeros((k,), bool), jnp.asarray(False)))
    # Rayleigh-Ritz: one more application, then rotate the block onto the
    # eigenbasis of the projected covariance — optimal approximations
    # within span(V), and the step that makes the Ritz-stability exit
    # accurate (see docstring)
    if apply_cov_block_t is not None:
        # the final application's per-row projections rotate into the
        # component scores below — the caller's separate scores sweep
        # (a whole extra HBM read) is then unnecessary
        Y, t_c = apply_cov_block_t(V)
    else:
        Y, t_c = apply_cov_block(V), None
    M = V.T @ Y
    M = 0.5 * (M + M.T)                          # symmetrize roundoff
    ritz, W = jnp.linalg.eigh(M)                 # ascending
    # degenerate-covariance guard: if the k x k eigh itself goes
    # non-finite, fall back to the UNROTATED block with its (finite)
    # Rayleigh quotients, sorted descending — the pre-rotation behavior.
    # eig must fall back together with V: returning the failed eigh's
    # NaN ritz values against the unrotated vectors would poison every
    # downstream explained-variance fraction.
    raw = jnp.sum(V * Y, axis=0)
    order = jnp.argsort(-raw)
    ok = jnp.isfinite(W).all() & jnp.isfinite(ritz).all()
    eig = jnp.where(ok, jnp.clip(ritz[::-1], 0.0, None),
                    jnp.clip(raw[order], 0.0, None))
    V = jnp.where(ok, (V @ W)[:, ::-1], V[:, order])
    if t_c is not None:
        # scores of the ROTATED block, by linearity: (X - 1 mu^T)(V W)
        # = t_c W (same fallback ordering as V); sliced back to the
        # caller's row count (this function may have padded internally)
        scores = jnp.where(ok, (t_c @ W)[:, ::-1], t_c[:, order])[:R]
    else:
        scores = None
    # matrix-free trace: sum_j rep.x²_j - mu_j²  (Σrep = 1 after
    # normalize). Written as a fused elementwise+column-reduce so XLA
    # never materializes an (R, E) squared temp the way a matmul operand
    # would be. Storage mode decodes in the same fused pass (a 1-byte
    # read for int8).
    vals = (_decode_storage(reports_filled, fill, acc) if use_storage
            else reports_filled.astype(acc))
    col_sq = jnp.sum(vals ** 2 * rep[:, None], axis=0)
    trace = jnp.sum(col_sq - mu * mu) / denom
    return V, eig, jnp.clip(trace, 0.0, None), scores


def weighted_prin_comps(reports_filled, reputation, n_components: int,
                        method: str = "auto", v_init=None):
    """Top-k components + explained-variance fractions for the
    ``fixed-variance`` and ``ica`` variants
    (numpy_kernels.weighted_prin_comps). Uses the E×E eigh for small E,
    the R×R Gram trick while the eigh fits
    (R <= :data:`_GRAM_EIGH_MAX_R` — the full nonzero spectrum lives in
    the Gram matrix), and matrix-free orthogonal iteration beyond that
    (:func:`_top_pcs_orth_iter` — the Gram eigh's QDWH temporaries OOM a
    single chip at R=10k). An explicit ``"power"``-family request always
    takes the orthogonal-iteration path. ``v_init`` warm-starts the
    orthogonal iteration (:func:`_top_pcs_orth_iter`'s blend rule);
    closed-form eigh methods ignore it."""
    R, E = reports_filled.shape
    if method in ("power", "power-fused") or (
            method == "auto" and E > 1024 and R > _GRAM_EIGH_MAX_R):
        mu, denom = _mu_denom(reports_filled, reputation)
        loadings, eig, total, _ = _top_pcs_orth_iter(
            reports_filled, mu, denom, reputation, n_components,
            v_init=v_init)
        explained = jnp.where(total > 0.0,
                              eig / jnp.where(total > 0.0, total, 1.0),
                              jnp.zeros_like(eig))
        scores = (jnp.matmul(reports_filled,
                             loadings.astype(reports_filled.dtype),
                             preferred_element_type=reputation.dtype)
                  - jnp.ones((R, 1), reputation.dtype)
                  * (mu @ loadings)[None, :])
        return loadings, scores, explained
    dev, denom = _center(reports_filled, reputation)
    if method == "auto":
        method = "eigh-cov" if E <= 1024 else "eigh-gram"
    if method not in ("eigh-cov", "eigh-gram"):
        raise ValueError(f"unknown PCA method: {method!r}")
    if method == "eigh-cov":
        cov = (dev * reputation[:, None]).T @ dev / denom
        eigvals, eigvecs = jnp.linalg.eigh(cov)
        loadings = eigvecs[:, ::-1][:, :n_components]
        eig = jnp.clip(eigvals[::-1][:n_components], 0.0, None)
        total = jnp.sum(jnp.clip(eigvals, 0.0, None))
    else:
        sqrt_rep = jnp.sqrt(jnp.clip(reputation, 0.0, None))
        A = dev * sqrt_rep[:, None]
        G = (A @ A.T) / denom
        eigvals, eigvecs = jnp.linalg.eigh(G)
        U = eigvecs[:, ::-1][:, :n_components]         # (R, k)
        V = A.T @ U                                    # (E, k)
        norms = jnp.linalg.norm(V, axis=0)
        loadings = V / jnp.where(norms == 0.0, 1.0, norms)[None, :]
        eig = jnp.clip(eigvals[::-1][:n_components], 0.0, None)
        total = jnp.sum(jnp.clip(eigvals, 0.0, None))
    explained = jnp.where(total > 0.0, eig / jnp.where(total > 0.0, total, 1.0),
                          jnp.zeros_like(eig))
    scores = dev @ loadings
    return loadings, scores, explained


def weighted_prin_comps_storage(x, fill, mu, reputation, n_components: int,
                                interpret: bool = False,
                                n_rows: Optional[int] = None, v_init=None):
    """Top-k components + explained fractions straight off sentinel
    storage (the fused pipeline's compact encoding): orthogonal iteration
    through the Pallas storage kernels, with the scores folded out of
    the final Rayleigh-Ritz application on the one-pass-kernel path (one
    further ``storage_matmat`` sweep only on the separable fallback).
    The storage sibling of :func:`weighted_prin_comps`'s orth-iter
    branch — same convergence rules, same Rayleigh-Ritz rotation (parity
    pinned by tests/test_kernels.py at the shared tolerance).

    ``n_rows``: pre-padded-input contract, exactly as
    :func:`sztorc_scores_power_fused`'s — ``x``/``reputation`` arrive
    row-padded to the storage-kernel tile (so per-call re-pads no-op
    inside the iterated pipeline) and the returned scores are sliced
    back to the TRUE reporter count (pad rows' raw projections are
    ``-mu.loadings`` garbage after centering)."""
    from .pallas_kernels import storage_matmat

    acc = reputation.dtype
    R, E = x.shape
    denom = 1.0 - jnp.sum(reputation ** 2)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    loadings, eig, total, scores = _top_pcs_orth_iter(
        x, mu, denom, reputation, n_components, fill=fill,
        interpret=interpret, v_init=v_init)
    explained = jnp.where(total > 0.0,
                          eig / jnp.where(total > 0.0, total, 1.0),
                          jnp.zeros_like(eig))
    if scores is None:
        # separable-covariance fallback: one further storage sweep for
        # the scores (the one-pass kernel folds them into its final
        # Rayleigh-Ritz application instead)
        scores = (storage_matmat(x, loadings.astype(acc), fill=fill,
                                 interpret=interpret).astype(acc)
                  - jnp.ones((R, 1), acc) * (mu @ loadings)[None, :])
    if n_rows is not None:
        scores = scores[:n_rows]
    return loadings, scores, explained


def multi_dirfix_storage(scores, x, fill, mu, reputation,
                         interpret: bool = False):
    """Direction-fixed scores for a whole (R, k) block of component
    scores in ONE further HBM sweep of the storage matrix — the batched
    sibling of :func:`direction_fixed_scores` for the fused
    multi-component path. The stacked matmul collapses like
    :func:`sztorc_scores_power_fused`'s: with ``q_c = scores_c^T X`` and
    ``csum = 1^T X`` (one ``storage_rows_matmat`` stack of k+1 rows),

        new1_c = normalize(set1_c) @ X = (q_c + a1_c csum) / sum(set1_c)

    and ``old = rep @ X`` is exactly the weighted column means ``mu``
    already in hand. Same sign-canonical banded tie-break per component
    (numpy_kernels.DIRFIX_TIE_ATOL).

    ``x`` may arrive ROW-PADDED past ``scores`` (the iterated-pipeline
    pad hoist — :func:`sztorc_scores_power_fused`'s ``n_rows`` contract):
    ``scores`` always has the TRUE reporter count, and
    ``storage_rows_matmat`` zero-pads the stacked ``[scores; ones]``
    operand up to the matrix's padded rows — zero weights against the
    pad rows' zero storage values, so every contraction (including the
    ones-row column sums) is exactly the unpadded result. Do not replace
    that zero-pad with a shape assertion, and size any future row
    contraction here from ``scores``, not ``x``.
    Returns (R, k) direction-fixed scores, R = scores' row count."""
    from .pallas_kernels import storage_rows_matmat

    acc = reputation.dtype
    R, k = scores.shape
    # per-column sign canonicalization (numpy_kernels
    # .direction_fixed_scores rationale: a banded tie's winner must not
    # depend on the eigensolver's arbitrary sign); vmapped so the
    # tie-break convention is canon_sign_factor's by construction
    scores = scores * jax.vmap(canon_sign_factor, in_axes=1)(scores)[None, :]
    W = jnp.concatenate([scores.T.astype(acc),
                         jnp.ones((1, R), acc)])               # (k+1, R)
    qc = storage_rows_matmat(x, W, fill=fill,
                             interpret=interpret).astype(acc)  # (k+1, E)
    q, csum = qc[:k], qc[k]
    a1 = jnp.abs(jnp.min(scores, axis=0))                      # (k,)
    a2 = jnp.max(scores, axis=0)
    set1 = scores + a1[None, :]
    set2 = scores - a2[None, :]
    s1_tot = jnp.sum(set1, axis=0)
    s2_tot = jnp.sum(set2, axis=0)

    def _guard(num, tot):
        # normalize()'s zero-sum guard applied to the collapsed projection
        return jnp.where(tot[:, None] == 0.0, num,
                         num / jnp.where(tot == 0.0, 1.0, tot)[:, None])

    new1 = _guard(q + a1[:, None] * csum[None, :], s1_tot)     # (k, E)
    new2 = _guard(q - a2[:, None] * csum[None, :], s2_tot)
    d1 = jnp.sum((new1 - mu[None, :]) ** 2, axis=1)            # (k,)
    d2 = jnp.sum((new2 - mu[None, :]) ** 2, axis=1)
    set1_wins = d1 - d2 <= nk.DIRFIX_TIE_ATOL * (d1 + d2)
    return jnp.where(set1_wins[None, :], set1, -set2)


#: column-block width for the blocked weighted median (see
#: weighted_median_cols): large enough to saturate the VPU, small enough
#: that the per-block sort temporaries stay a rounding error next to the
#: matrix itself
_MEDIAN_BLOCK = 1024


def weighted_median_cols(values, weights, present,
                         block_cols: int = _MEDIAN_BLOCK):
    """Per-column weighted median, vectorized over events
    (numpy_kernels.weighted_median, same comparisons and midpoint rule).

    Absent entries get value +inf (sort last) and weight 0, replicating the
    numpy kernel's subsetting. ``values``/``present``: (R, E); ``weights``
    may be (R, E) or a per-reporter (R,) vector (preferred at scale — a
    broadcast (R, E) weights operand would be materialized across the
    block loop below, as large an allocation as the problem). Returns
    (E,).

    Above ``block_cols`` columns the computation runs as a ``lax.map``
    over column blocks: the argsort / take-along-axis / cumsum
    temporaries then peak at one (R, block) slab instead of several full
    (R, E) copies — the full-width form was the single allocation that
    pushed scaled-event resolution out of HBM at north-star scale
    (measured: 10k x 100k f32 OOMs on a 16 GB chip). The ragged tail is
    one separate direct call (padding the operands would copy them
    whole). Per-column results are bitwise identical either way (each
    column's math is self-contained).

    ``block_cols <= 0`` disables blocking (one direct full-width pass).
    REQUIRED on a multi-device event-sharded mesh: the block loop's
    ``dynamic_slice`` over the sharded axis is unpartitionable — GSPMD
    falls back to all-gathering the full (R, E) matrix onto every device
    (verified in tests/test_hlo_collectives.py), while the unblocked
    sort runs along the replicated R axis, fully local to each event
    shard, and each device's shard already bounds the sort temporaries
    to (R, E/n_devices)."""
    R, E = values.shape
    if block_cols > 0 and E > block_cols:
        n_full = E // block_cols

        # index-based map + dynamic_slice: the operands stay in their
        # original layout (a stacked/transposed operand would itself be
        # full (R, E) copies — as much memory as the problem)
        def one_block(i):
            sl = lambda a: lax.dynamic_slice_in_dim(  # noqa: E731
                a, i * block_cols, block_cols, axis=1)
            w = weights if weights.ndim == 1 else sl(weights)
            return _weighted_median_cols_block(sl(values), w, sl(present))

        blocks = lax.map(one_block, jnp.arange(n_full)).reshape(-1)
        tail = E - n_full * block_cols
        if not tail:
            return blocks
        start = n_full * block_cols
        tail_med = _weighted_median_cols_block(
            values[:, start:],
            weights if weights.ndim == 1 else weights[:, start:],
            present[:, start:])
        return jnp.concatenate([blocks, tail_med])
    return _weighted_median_cols_block(values, weights, present)


def _weighted_median_cols_block(values, weights, present):
    """The full-width weighted-median computation on one column block.
    ``weights`` may be (R,) (broadcast here, one block at a time) or
    (R, cols). Values are upcast HERE — a caller-side astype of the whole
    matrix would be another full (R, E) copy.

    The weights ride through ONE variadic stable ``lax.sort`` as a value
    operand (same permutation as the old stable argsort — ties keep index
    order) instead of argsort + two ``take_along_axis`` gathers: the
    axis-0 gathers dominated the whole scaled-resolution budget on v5e
    (measured 10k x 4096: 1052 ms argsort+gather -> 121 ms variadic,
    8.7x; the per-column crossing selection is unchanged). Crossing
    selection remains ulp-sensitive to XLA's cumsum lowering — true of
    the argsort form too (vs numpy's sequential cumsum); exactly-tied
    cumweights can resolve to a neighboring value across lowerings, which
    generic (post-redistribution) reputation weights never hit."""
    if weights.ndim == 1:
        weights = jnp.broadcast_to(weights[:, None], values.shape)
    values = values.astype(jnp.promote_types(values.dtype, weights.dtype))
    R = values.shape[0]
    big = jnp.where(present, values, jnp.inf)
    w_raw = jnp.where(present, weights, 0.0)
    v, w = lax.sort((big, w_raw), dimension=0, is_stable=True, num_keys=1)
    total = jnp.sum(w, axis=0)
    safe_total = jnp.where(total > 0.0, total, 1.0)
    cw = jnp.cumsum(w / safe_total[None, :], axis=0)
    # the shared tie tolerance, floored at what THIS dtype's arithmetic
    # can resolve: under f32 (TPU default) a true tie's cumulative weight
    # lands up to ~ulp(0.5)=6e-8 off, so the f64-sized 1e-9 window would
    # collapse to exact equality and diverge from the (always-f64) numpy
    # kernel on genuine ties (code-review r4, numerically verified at
    # 12 uniform reporters). 32*eps: f64 -> 1e-9 floor binds (matches
    # numpy bitwise); f32 -> 3.8e-6, around the pre-round-4 band.
    tie_atol = max(nk.MEDIAN_TIE_ATOL, 32.0 * float(jnp.finfo(cw.dtype).eps))
    # selection threshold lowered by the tie tolerance, like the numpy
    # kernel: a true tie one ulp below 0.5 must select the tie index
    ge = cw >= 0.5 - tie_atol
    idx = jnp.argmax(ge, axis=0)                      # first crossing
    idx = jnp.where(jnp.any(ge, axis=0), idx, R - 1)
    # take_along_axis, NOT fancy `a[idx, arange(E)]` indexing: the latter
    # lowers to a gather whose (E, 2) index tensor the GSPMD partitioner
    # all-gathers across event shards; a per-column take along the
    # replicated R axis stays shard-local
    take_col = lambda a, i: jnp.take_along_axis(  # noqa: E731
        a, i[None, :], axis=0)[0]
    cw_i = take_col(cw, idx)
    v_i = take_col(v, idx)
    nxt = jnp.clip(idx + 1, 0, R - 1)
    v_n = take_col(v, nxt)
    # the shared absolute tie tolerance (numpy_kernels.MEDIAN_TIE_ATOL,
    # dtype-floored above — replaces round-3's accidental np.isclose
    # rtol=1e-5; see the sizing notes)
    exact = jnp.abs(cw_i - 0.5) <= tie_atol
    has_next = (idx + 1 < R) & jnp.isfinite(v_n)
    med = jnp.where(exact & has_next, 0.5 * (v_i + v_n), v_i)
    return jnp.where(total > 0.0, med, 0.5)


def direction_fixed_scores(scores, reports_filled, reputation):
    """PCA sign/direction fix (numpy_kernels.direction_fixed_scores). Runs
    inside the jitted graph; the sign-canonical banded tie-break
    (numpy_kernels.DIRFIX_TIE_ATOL) is identical to the
    numpy kernel so both backends pick the same orientation.

    The three candidate-outcome projections are stacked into one (3, R) x
    (R, E) matmul so the matrix is swept once, not three times — at
    north-star scale each avoided sweep is a multi-GB HBM pass."""
    acc = scores.dtype
    # sign-canonicalize before building candidates: at a banded tie
    # "pick set1" is not sign-invariant (numpy_kernels
    # .direction_fixed_scores has the full rationale)
    scores = canon_sign(scores)
    set1 = scores + jnp.abs(jnp.min(scores))
    set2 = scores - jnp.max(scores)
    W = jnp.stack([reputation.astype(acc), normalize(set1), normalize(set2)])
    M = jnp.matmul(W.astype(reports_filled.dtype), reports_filled,
                   preferred_element_type=acc)
    old, new1, new2 = M[0], M[1], M[2]
    d1 = jnp.sum((new1 - old) ** 2)
    d2 = jnp.sum((new2 - old) ** 2)
    # the winning orientation in non-negative form (numpy_kernels
    # .direction_fixed_scores: -set2, an exact no-op through normalize for
    # one component, simplex-safe for blends); banded tie per
    # nk.DIRFIX_TIE_ATOL
    return jnp.where(d1 - d2 <= nk.DIRFIX_TIE_ATOL * (d1 + d2),
                     set1, -set2)


def matvec_narrow(x, matvec_dtype: str):
    """Apply the matvec-dtype narrowing cast to a storage matrix — unless
    the storage is integer (int8 sentinel storage is already the
    narrowest encoding; casting it to a float dtype would destroy the
    sentinel/lattice). The ONE copy of the rule shared by the fused
    pipeline's hoisted cast and the per-call fallbacks here."""
    if matvec_dtype and not jnp.issubdtype(x.dtype, jnp.integer):
        return x.astype(jnp.dtype(matvec_dtype))
    return x


def sztorc_scores_power_fused(reports_filled, reputation, power_iters: int,
                              power_tol: float, matvec_dtype: str = "",
                              interpret: bool = False, fill=None, mu=None,
                              v_init=None, n_rows: Optional[int] = None):
    """The whole sztorc scoring step on the Pallas fast path: power-iteration
    PCA (one HBM sweep per step, pallas_kernels.apply_weighted_cov) followed
    by the scores + direction-fix contractions in ONE further sweep
    (pallas_kernels.scores_dirfix_pass) — the XLA composition
    (:func:`weighted_prin_comp` + :func:`direction_fixed_scores`) needs two.

    Algebraically identical to the two-pass form: with raw projection
    ``t = X @ loading`` and ``ml = mu . loading``,

        scores   = t - ml
        scores^T X = t^T X - ml * colsum(X)
        set1^T X = scores^T X + |min scores| * colsum(X)   (set2 analogous)

    so the stacked (3, R) x (R, E) direction-fix matmul collapses to O(E)
    arithmetic on the pass outputs. Same sign-canonical banded tie-break
    (numpy_kernels.DIRFIX_TIE_ATOL).
    Returns ``(adj_scores (R,), loading (E,))`` in the reputation dtype.

    With ``fill`` (and the matching precomputed ``mu``) the input is
    NaN-threaded storage — absent entries NaN, filled values reconstructed
    in-register by the kernels — so the filled matrix never exists in HBM.
    (A single-launch fixed-trip "power-mono" variant existed through round
    2; the on-chip A/B measured it 36% slower than this early-exit loop —
    docs/PERFORMANCE.md — and it was removed.)

    ``n_rows``: pre-padded-input contract (the iterated-pipeline pad
    hoist, same rationale as pallas_kernels.matmat_tile_rows' note): the
    caller passes ``reports_filled`` and ``reputation`` already row-padded
    to the kernels' panel tile — the kernels' internal ``_pad_rows`` then
    no-op instead of copying the whole matrix through HBM on EVERY outer
    redistribution iteration — and ``n_rows`` is the TRUE reporter count.
    The pad rows (zero storage values, zero reputation) contribute exactly
    zero to every row contraction (q, c, o and the power sweeps all weight
    by reputation or multiply the zero values), but their raw projections
    ``t`` are garbage (``-mu.loading`` after centering), so the scores are
    sliced back to ``n_rows`` BEFORE the direction-fix statistics.
    Returns (n_rows,)-sized scores. Default None: unpadded input, R from
    the matrix.
    """
    from .pallas_kernels import power_iteration_fused, scores_dirfix_pass

    acc = reputation.dtype
    if fill is None:
        mu, denom = _mu_denom(reports_filled, reputation)
    else:
        denom = 1.0 - jnp.sum(reputation ** 2)
        denom = jnp.where(denom == 0.0, 1.0, denom)
    xmm = matvec_narrow(reports_filled, matvec_dtype)
    loading = power_iteration_fused(xmm, mu, denom, reputation,
                                    power_iters, power_tol, fill=fill,
                                    interpret=interpret,
                                    v_init=v_init).astype(acc)
    t, q, c, o = scores_dirfix_pass(xmm, reputation, loading, fill=fill,
                                    interpret=interpret)
    if n_rows is not None:
        t = t[:n_rows]           # drop the pad rows' garbage projections
    ml = mu @ loading
    scores = t.astype(acc) - ml
    qs = q.astype(acc) - ml * c.astype(acc)        # scores^T X
    # sign-canonicalize scores (and qs, linear in them) before the
    # candidates — see numpy_kernels.direction_fixed_scores
    sgn = canon_sign_factor(scores)
    scores = scores * sgn
    qs = qs * sgn
    a1 = jnp.abs(jnp.min(scores))
    a2 = jnp.max(scores)
    set1 = scores + a1
    set2 = scores - a2
    R = scores.shape[0]
    sum_s = jnp.sum(scores)
    s1_tot = sum_s + R * a1
    s2_tot = sum_s - R * a2
    set1X = qs + a1 * c.astype(acc)
    set2X = qs - a2 * c.astype(acc)
    # normalize()'s zero-sum guard, applied to the projected form
    new1 = jnp.where(s1_tot == 0.0, set1X,
                     set1X / jnp.where(s1_tot == 0.0, 1.0, s1_tot))
    new2 = jnp.where(s2_tot == 0.0, set2X,
                     set2X / jnp.where(s2_tot == 0.0, 1.0, s2_tot))
    old = o.astype(acc)
    d1 = jnp.sum((new1 - old) ** 2)
    d2 = jnp.sum((new2 - old) ** 2)
    # non-negative winning orientation, as in direction_fixed_scores
    # (banded tie per nk.DIRFIX_TIE_ATOL)
    return jnp.where(d1 - d2 <= nk.DIRFIX_TIE_ATOL * (d1 + d2),
                     set1, -set2), loading


def row_reward_weighted(adj_scores, reputation):
    """normalize(adj * rep / mean(rep)); unchanged reputation when the
    adjusted scores vanish (numpy_kernels.row_reward_weighted)."""
    degenerate = jnp.max(jnp.abs(adj_scores)) == 0.0
    candidate = normalize(adj_scores * (reputation / jnp.mean(reputation)))
    return jnp.where(degenerate, reputation, candidate)


def smooth(this_rep, old_rep, alpha):
    """alpha-blend with prior reputation (numpy_kernels.smooth)."""
    return alpha * this_rep + (1.0 - alpha) * old_rep


def gather_median_pays(n_scaled: int, n_events: int) -> bool:
    """Whether the static-gather median (sort only the scaled columns)
    beats the full-width sort — the ONE copy of the gate shared by
    :func:`resolve_outcomes`, ``Oracle``'s params wiring, and the sharded
    front-end's ``_xla_path_n_scaled``.

    The gather pays one O(R*n_scaled) copy to skip the multi-pass sort of
    the binary columns, so it wins for any minority AND for majorities
    (round-4 A/B at 60% scaled: 1.54 s -> 1.01 s blocking). Sizing of the
    9/10 cutoff: per-column costs measured on v5e at 10k x 100k put the
    full-width sort at ~14 us/col and gather+sort at ~14.5 us/col, so the
    break-even sits near n_scaled/E ~ 0.93-0.97; 0.9 keeps a margin, and
    also bounds the degenerate tail where a near-whole-matrix copy (plus
    a per-count jit recompile — n_scaled is a static cache key) would buy
    the sort of a handful of columns."""
    return 0 < n_scaled and n_scaled * 10 <= n_events * 9


def resolve_outcomes(present, reports_filled, smooth_rep, scaled, tolerance,
                     any_scaled: bool = True, has_na: bool = True,
                     median_block: int = _MEDIAN_BLOCK,
                     n_scaled: int = 0):
    """Vectorized outcome resolution (numpy_kernels.resolve_outcomes):
    participation-restricted renormalized reputation; weighted mean for binary
    columns, weighted median for scaled; catch-snap binary outcomes.

    ``present`` is the bool participation mask from
    :func:`interpolate_masked` (ignored, may be None, when ``has_na`` is
    False) — threading it here instead of re-deriving ``isnan`` saves a
    full sweep of the raw f32 matrix, and lets ``reports_filled`` live in a
    compact storage dtype (the mask is the only memory of where the NaNs
    were). All contractions accumulate in the reputation dtype.

    ``any_scaled`` / ``has_na`` are *static* hints: when ``any_scaled`` is
    False (host knows every event is binary) the per-column weighted-median
    sort — the only O(R log R * E) phase of resolution — is skipped entirely;
    when ``has_na`` is False the participation-restriction reduces to the
    single full-reputation matvec (the mask is all-True), eliding two
    (R, E) contractions. ``median_block`` is threaded to
    :func:`weighted_median_cols` (<= 0 disables blocking — mandatory on a
    multi-device event-sharded mesh, see that docstring).

    ``n_scaled`` (static; 0 = unknown): the EXACT number of scaled events.
    When known, single-device (``median_block > 0``), and within
    :func:`gather_median_pays`' envelope (up to 90% of columns — sizing
    note there), the median runs on a static gather of just the
    scaled columns instead of all E — the sort phase, resolution's only
    O(R log R * E) cost, shrinks by E/n_scaled (25x at the scaled-heavy
    bench shape of 4k scaled x 100k events), and scaled MAJORITIES win
    too (round-4 same-session A/B at 60k of 100k scaled: 1.54 -> 1.01 s
    blocking, 0.69 -> 1.10 res/s). Near-all-scaled and all-scaled
    matrices run full-width (the gather would copy ~the whole matrix to
    skip a handful of sorted columns). Not used on the sharded path:
    a cross-shard column gather would move (R, n_scaled) over ICI, while
    the per-shard full median moves nothing. A WRONG count silently
    corrupts outcomes (the gather pads/truncates) — callers must pass the
    exact host-side ``scaled.sum()`` or 0, the same contract as the fused
    path's gather-and-fix.
    """
    acc = smooth_rep.dtype
    full_total = jnp.sum(smooth_rep)
    full_mean = (jnp.matmul(smooth_rep.astype(reports_filled.dtype),
                            reports_filled, preferred_element_type=acc)
                 / jnp.where(full_total == 0.0, 1.0, full_total))
    R, E = reports_filled.shape
    if has_na:
        w = jnp.where(present, smooth_rep[:, None].astype(acc), 0.0)
        tw = jnp.sum(w, axis=0)
        safe_tw = jnp.where(tw > 0.0, tw, 1.0)
        mean_present = jnp.sum(w * reports_filled.astype(acc),
                               axis=0) / safe_tw
        means = jnp.where(tw > 0.0, mean_present, full_mean)
    else:
        present = jnp.ones((R, E), dtype=bool)
        tw = jnp.broadcast_to(full_total, (E,))
        means = full_mean
    if any_scaled:
        if gather_median_pays(n_scaled, E) and median_block > 0:
            idx = jnp.nonzero(scaled, size=n_scaled)[0]
            med_s = weighted_median_cols(
                jnp.take(reports_filled, idx, axis=1), smooth_rep,
                jnp.take(present, idx, axis=1), block_cols=median_block)
            # scatter back; binary positions of `medians` are never read
            # (the where(scaled, ...) below masks them with the means)
            medians = jnp.zeros((E,), dtype=med_s.dtype).at[idx].set(med_s)
        else:
            medians = weighted_median_cols(reports_filled, smooth_rep,
                                           present,
                                           block_cols=median_block)
        outcomes_raw = jnp.where(tw > 0.0, jnp.where(scaled, medians, means),
                                 means)
    else:
        outcomes_raw = means
    outcomes_adjusted = jnp.where(scaled, outcomes_raw, catch(outcomes_raw, tolerance))
    return outcomes_raw, outcomes_adjusted


def certainty_and_bonuses(present, reports_filled, smooth_rep, outcomes_adjusted,
                          scaled, tolerance, has_na: bool = True):
    """Certainty / participation / bonus accounting
    (numpy_kernels.certainty_and_bonuses). Binary agreement is exact equality
    on catch-snapped {0, 0.5, 1} values, so it is dtype-independent.

    ``present`` is the participation mask from :func:`interpolate_masked`
    (ignored, may be None, when ``has_na`` is False); the NA contractions
    run on it directly rather than re-deriving ``isnan`` from the raw
    matrix. Reductions accumulate in the reputation dtype.

    ``has_na=False`` (static, host-known dense matrix) short-circuits the NA
    accounting to its closed form — an all-zero ``na_mat`` makes
    participation exactly 1 and every bonus collapse onto its base weight —
    eliding two (R, E) contractions over the full matrix.
    """
    R, E = reports_filled.shape
    dtype = smooth_rep.dtype
    agree = jnp.where(
        scaled[None, :],
        jnp.abs(reports_filled.astype(dtype)
                - outcomes_adjusted[None, :]) <= tolerance,
        reports_filled.astype(dtype) == outcomes_adjusted[None, :],
    )
    certainty = jnp.sum(agree * smooth_rep[:, None], axis=0)
    consensus_reward = normalize(certainty)
    avg_certainty = jnp.mean(certainty)

    if has_na:
        na_mat = (~present).astype(dtype)
        participation_columns = 1.0 - smooth_rep @ na_mat
        participation_rows = 1.0 - na_mat @ consensus_reward
        percent_na = 1.0 - jnp.mean(participation_columns)
        na_bonus_rows = normalize(participation_rows)
        reporter_bonus = (na_bonus_rows * percent_na
                          + smooth_rep * (1.0 - percent_na))
        na_bonus_cols = normalize(participation_columns)
        author_bonus = (na_bonus_cols * percent_na
                        + consensus_reward * (1.0 - percent_na))
    else:
        participation_columns = jnp.ones((E,), dtype=dtype)
        participation_rows = jnp.ones((R,), dtype=dtype)
        percent_na = jnp.asarray(0.0, dtype=dtype)
        na_bonus_rows = jnp.full((R,), 1.0 / R, dtype=dtype)
        reporter_bonus = smooth_rep
        na_bonus_cols = jnp.full((E,), 1.0 / E, dtype=dtype)
        author_bonus = consensus_reward

    return {
        "certainty": certainty,
        "consensus_reward": consensus_reward,
        "avg_certainty": avg_certainty,
        "participation_columns": participation_columns,
        "participation_rows": participation_rows,
        "percent_na": percent_na,
        "na_bonus_rows": na_bonus_rows,
        "reporter_bonus": reporter_bonus,
        "na_bonus_cols": na_bonus_cols,
        "author_bonus": author_bonus,
    }
