"""Memory-bandwidth roofline model for consensus resolutions
(ISSUE 13 tentpole d).

The BENCH trajectory needs to say WHY a rung is slow: a host-bound rung
(encode passes, synchronous dispatch, fetch round-trips) is fixed by
the ingestion/pipelining work this subsystem exists for, while a
bandwidth-bound rung is already running as fast as the memory system
allows and only storage compression or more chips move it. The bench
``roofline`` block reports, per bucket class, the ACHIEVED
resolutions/sec against the MEMORY-BANDWIDTH-BOUND rate:

- :func:`stream_bandwidth_bytes_per_s` measures the device's achievable
  stream bandwidth with a jitted read+write triad over a matrix-scale
  buffer — the same kind of HBM traffic the resolution kernels issue,
  so the bound is an achievable roof, not a datasheet number;
- :func:`resolution_traffic_bytes` models one light-pipeline
  resolution's HBM traffic from the docs/PERFORMANCE.md pass
  accounting: the fill pass reads the accumulation-dtype matrix once
  and writes storage once, then every power sweep, the scores+dirfix
  pass, and the fused back half each read storage once per outer
  iteration;
- :func:`bound_resolutions_per_sec` divides the two.

The model's one free parameter is the power sweep count (the early
exit makes it data-dependent and the fused kernels do not export it);
callers pass their measured or assumed value and the bench block
records which it was — an honest bracket beats a silently wrong point
estimate.
"""

from __future__ import annotations

import time

import numpy as np

__all__ = ["stream_bandwidth_bytes_per_s", "resolution_traffic_bytes",
           "bound_resolutions_per_sec", "classify_regime"]


def stream_bandwidth_bytes_per_s(mbytes: int = 64, repeats: int = 5):
    """Measured device stream bandwidth (bytes/s): a jitted
    read+modify+write pass over an ``mbytes`` f32 buffer, timed to a
    blocking fetch, median over ``repeats``. Bytes counted = one read
    + one write of the buffer per pass."""
    import jax
    import jax.numpy as jnp

    n = max(1, int(mbytes) * (1 << 20) // 4)
    x = jnp.ones((n,), dtype=jnp.float32)
    f = jax.jit(lambda v: v * 1.0000001 + 0.5)
    jax.block_until_ready(f(x))                 # compile + warm
    samples = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        jax.block_until_ready(f(x))
        samples.append(time.perf_counter() - t0)
    dt = float(np.median(samples))
    return 2.0 * n * 4 / dt


def resolution_traffic_bytes(R: int, E: int, storage_itemsize: int,
                             sweeps: int, iterations: int = 1,
                             acc_itemsize: int = 4) -> int:
    """Modeled HBM bytes of one light-pipeline resolution at (R, E):
    fill/encode pass (one acc-dtype read + one storage write) plus, per
    outer iteration, ``sweeps`` power-sweep storage reads and two more
    storage passes (scores+direction-fix; the fused back half)."""
    cells = int(R) * int(E)
    fill = cells * (int(acc_itemsize) + int(storage_itemsize))
    per_iter = (int(sweeps) + 2) * cells * int(storage_itemsize)
    return fill + max(1, int(iterations)) * per_iter


def bound_resolutions_per_sec(bandwidth_bytes_per_s: float,
                              traffic_bytes: int) -> float:
    """The memory-bandwidth-bound resolution rate for a traffic model —
    the roof the achieved rate is compared against."""
    return float(bandwidth_bytes_per_s) / max(1, int(traffic_bytes))


def classify_regime(achieved: float, bound: float,
                    threshold: float = 0.5) -> str:
    """``"bandwidth-bound"`` when the achieved rate is within
    ``threshold`` of the roof, else ``"host-bound"`` — the distinction
    the BENCH trajectory exists to make (a host-bound rung is fixed by
    ingestion/pipelining work; a bandwidth-bound one by storage
    compression or more chips)."""
    if bound <= 0:
        return "unknown"
    return ("bandwidth-bound" if achieved / bound >= threshold
            else "host-bound")
