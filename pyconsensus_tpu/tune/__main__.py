"""Re-tune the Pallas block shapes for a target shape:

    python -m pyconsensus_tpu.tune --reporters 10000 --events 100000 \
        --storage-dtype int8 [--cache PATH] [--force] [--interpret]

Runs the cov-sweep and resolution sweeps for the shape's classes,
persists the winners (atomic write), and prints one JSON summary line.
On a non-TPU backend pass ``--interpret`` — the sweep then validates the
machinery through the Pallas interpreter and persists the deterministic
analytic winner (see tune.autotune's module docstring).
"""

import argparse
import json

from .autotune import autotune_cov, autotune_resolve, cache_path


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="python -m pyconsensus_tpu.tune",
                                 description=__doc__)
    ap.add_argument("--reporters", type=int, default=10_000)
    ap.add_argument("--events", type=int, default=100_000)
    ap.add_argument("--storage-dtype", default="",
                    help="storage encoding to tune for ('', 'bfloat16', "
                         "'int8')")
    ap.add_argument("--cache", default=None,
                    help="cache file (default: $PYCONSENSUS_AUTOTUNE_CACHE "
                         "or ~/.cache/pyconsensus_tpu/autotune.json)")
    ap.add_argument("--force", action="store_true",
                    help="re-sweep even when a cache entry exists")
    ap.add_argument("--interpret", action="store_true",
                    help="Pallas interpreter sweep (off-TPU validation; "
                         "deterministic analytic winner)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--probe-events", type=int, default=512,
                    help="event width of the resolution-sweep probe "
                         "matrix (the winner is keyed by reporter class "
                         "only)")
    ap.add_argument("--probe-reporters", type=int, default=256,
                    help="reporter count of the cov-sweep probe matrix "
                         "(the winner is keyed by event class only)")
    args = ap.parse_args(argv)

    cov = autotune_cov(args.events, n_reporters=args.probe_reporters,
                       storage_dtype=args.storage_dtype,
                       interpret=args.interpret, path=args.cache,
                       force=args.force, repeats=args.repeats)
    res = autotune_resolve(args.reporters, n_events=args.probe_events,
                           storage_dtype=args.storage_dtype,
                           interpret=args.interpret, path=args.cache,
                           force=args.force, repeats=args.repeats)
    # sort_keys: the winner dicts ride through from the sweep —
    # canonical key order keeps two identical runs byte-identical
    print(json.dumps({
        "cache": str(cache_path(args.cache)),
        "cov_tile_rows": cov,
        "resolve_block_cols": res,
    }, sort_keys=True))


if __name__ == "__main__":
    main()
