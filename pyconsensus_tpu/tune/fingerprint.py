"""Shared cache-key fingerprint helpers (ISSUE 10 satellite).

Two subsystems persist compiled-artifact caches keyed by "what hardware
and toolchain produced this": the block-shape winner cache
(``tune.autotune.TuneCache``) and the AOT bucket-executable cache
(``serve.aotcache.AotCache``). Both need the same answer to "is this
entry from a compatible world?", and PR 7 + PR 10 each growing a private
copy is exactly the drift the CATCH_TIE_ATOL unification (PR 7) killed
for the tie bands — so the fingerprint logic lives HERE, once, and both
caches import it (tests/test_aotcache.py pins both to these
definitions).

- :func:`device_generation` — the accelerator-generation component
  (``device_kind`` of device 0, spaces dashed: ``"TPU-v5e"``, ``"cpu"``)
  shared by tune winner keys, ``serve.sharded.mesh_fingerprint``'s
  device-kind convention, and the AOT compatibility fingerprint. A
  winner (or executable) measured on one generation must never be
  adopted on another.
- :func:`runtime_fingerprint` — the full toolchain/topology fingerprint
  the AOT cache refuses on: jax + jaxlib versions (a serialized
  StableHLO module is only guaranteed to deserialize into the same
  program under the toolchain that produced it), backend platform,
  device generation, visible-device count, and the x64 flag (it changes
  every array dtype in the exported calling convention).

Both resolve the environment at CALL time, not import time — they run
host-side at cache load/store, never inside a trace (the CL401
import-time-hoist discipline applies to trace-time reads; these are
boot-time reads that must see the real runtime).
"""

from __future__ import annotations

__all__ = ["device_generation", "runtime_fingerprint"]


def device_generation() -> str:
    """The accelerator-generation component of every persisted cache
    key — ``device_kind`` of device 0 with spaces dashed (``"TPU-v5e"``;
    ``"cpu"`` on CPU hosts), matching
    ``serve.sharded.mesh_fingerprint``'s device-kind convention."""
    import jax

    return str(jax.devices()[0].device_kind).replace(" ", "-")


def runtime_fingerprint() -> dict:
    """The compatibility fingerprint of this process's compile
    toolchain + visible hardware — the runtime half of an AOT cache
    key. Every field participates in the refuse-vs-adopt decision: a
    mismatch in ANY of them means the persisted executable was built
    for a different world and must be recompiled, never loaded."""
    import jax
    import jaxlib

    return {
        "jax": str(jax.__version__),
        "jaxlib": str(jaxlib.__version__),
        "platform": str(jax.default_backend()),
        "generation": device_generation(),
        "n_devices": int(jax.device_count()),
        "x64": bool(jax.config.jax_enable_x64),
    }
