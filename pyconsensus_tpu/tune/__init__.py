"""Block-shape autotuner for the Pallas kernel tier (ISSUE 7 tentpole b).

The fused kernels' block shapes (row-panel size of the storage/cov
sweeps, column-block width of the fused resolution kernel) were
hand-measured on v5e and hard-coded. This package makes them
self-tuning: :func:`autotune_cov` / :func:`autotune_resolve` sweep the
legal configurations (``pallas_kernels.cov_tile_candidates`` /
``resolve_block_candidates`` — every candidate satisfies the scoped-VMEM
fit predicates by construction), persist the winner keyed by
``(TPU generation, storage dtype, shape class)`` through the
crash-safe ``io.atomic_write`` machinery, and :func:`install` (or the
lazy default provider the kernels load at build time) replays persisted
winners into ``pallas_kernels.set_tune_provider``. With no cache entry
the provider falls through to :data:`FALLBACK_TABLE` and finally to the
in-kernel measured-good v5e heuristics — always deterministic.

See docs/PERFORMANCE.md ("Autotuned kernel block shapes") for the cache
key layout, the fallback rules, and how to re-tune
(``python -m pyconsensus_tpu.tune``).
"""

from .autotune import (FALLBACK_TABLE, TuneCache, autotune_cov,
                       autotune_pipeline_depth, autotune_resolve,
                       cache_path, default_provider, depth_candidates,
                       install, shape_class, tpu_generation,
                       tuned_pipeline_depth)
from .fingerprint import device_generation, runtime_fingerprint
from .roofline import (bound_resolutions_per_sec, classify_regime,
                       resolution_traffic_bytes,
                       stream_bandwidth_bytes_per_s)

__all__ = ["autotune_cov", "autotune_resolve", "autotune_pipeline_depth",
           "tuned_pipeline_depth", "depth_candidates", "default_provider",
           "install", "TuneCache", "cache_path", "shape_class",
           "tpu_generation", "FALLBACK_TABLE",
           "device_generation", "runtime_fingerprint",
           "stream_bandwidth_bytes_per_s", "resolution_traffic_bytes",
           "bound_resolutions_per_sec", "classify_regime"]
