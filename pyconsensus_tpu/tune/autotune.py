"""Sweep, persist, and replay Pallas block-shape configurations.

Design rules:

- **Legality is the kernels' own fit predicates.** The sweep spaces come
  from ``pallas_kernels.cov_tile_candidates`` /
  ``resolve_block_candidates``; every candidate fits scoped VMEM by the
  same models the kernels gate on, and the provider re-validates on
  lookup — a stale or hand-edited cache entry can cost performance but
  can never compile an illegal kernel.
- **Block shapes never change results.** Each sweep runs every candidate
  on the same seeded inputs and asserts the outputs agree before a
  winner may be persisted (catch-snapped outputs bit-identically, the
  continuous accumulations to reduction-order tolerance) — an autotuner
  that could trade correctness for speed would be a bug farm.
- **Deterministic off-TPU.** ``interpret=True`` sweeps (CPU tests, the
  CI smoke) still execute every candidate through the Pallas
  interpreter, but rank by the analytic measured-good model (the
  in-kernel heuristic) instead of interpreter wall time — interpreter
  timings reflect nothing about the TPU and would make the persisted
  winner a coin flip. On a real TPU the median of timed runs decides.
- **Crash-safe, replay-stable persistence.** Winners go through
  ``io.atomic_write`` (fsynced tmp + rename — the ledger/sweep-chunk
  machinery) under the ``tune.cache_write`` fault site; a torn or
  corrupt cache file is detected on load and treated as empty (the
  fallback chain still serves), never trusted.
- **Import-time environment resolution.** ``PYCONSENSUS_AUTOTUNE_CACHE``
  is read ONCE at import (the ``_FILL_STATS_KERNEL`` hoist precedent —
  a per-trace ``os.environ`` read could compile different programs per
  host, consensus-lint CL401's bug class), and the default provider
  disables itself on multi-process meshes: per-host cache files could
  otherwise install different block shapes on different hosts of one
  program, the classic compile-divergence deadlock.
"""

from __future__ import annotations

import json
import os
import pathlib
import time

import numpy as np

from .. import io as pio
from .. import obs
from ..faults import plan as _faults
from ..ops import pallas_kernels as pk
from .fingerprint import device_generation

__all__ = ["autotune_cov", "autotune_resolve", "autotune_pipeline_depth",
           "tuned_pipeline_depth", "depth_candidates", "default_provider",
           "install", "TuneCache", "cache_path", "shape_class",
           "tpu_generation", "FALLBACK_TABLE"]

_VERSION = 1

#: env override for the cache location — read once at import time (a
#: per-call read would be a per-trace host divergence source, CL401)
_CACHE_PATH_ENV = os.environ.get("PYCONSENSUS_AUTOTUNE_CACHE", "")

#: deterministic measured-good fallback rows consulted when no cache
#: entry exists, keyed ``(kind, generation)`` with ``"*"`` wildcard
#: generation. ``None`` (and any missing row) means "use the in-kernel
#: v5e-measured heuristic" (``_panel_rows`` / ``_resolve_block_cols``) —
#: the heuristics ARE the measured-good defaults, so the table only
#: carries rows where a generation is known to want something else.
#: The interpreter row pins the width the interpret path always used.
FALLBACK_TABLE = {
    ("resolve_block_cols", "cpu"): 128,
    ("cov_tile_rows", "*"): None,
    ("resolve_block_cols", "*"): None,
    # dispatch pipeline depth (ISSUE 13): 2 overlaps one host transfer
    # under one device compute — the measured-good default everywhere;
    # deeper rings only pay off when per-dispatch host time exceeds
    # device time, which the sweep detects per generation
    ("pipeline_depth", "*"): 2,
}


#: the accelerator-generation component of every winner-cache key — ONE
#: definition shared with the AOT executable cache (ISSUE 10 satellite:
#: ``tune.fingerprint.device_generation``; the historical name stays
#: exported because the sweeps and tests key on it)
tpu_generation = device_generation


def shape_class(n: int) -> str:
    """Power-of-two shape-class bucket (``"p4096"``): winners generalize
    across nearby sizes but not across decades, and the padded serving
    buckets land exactly on class boundaries."""
    p = 1
    while p < max(1, int(n)):
        p *= 2
    return f"p{p}"


def _entry_key(kind: str, generation: str, itemsize: int, cls: str,
               nan_fill=None) -> str:
    key = f"{generation}/{kind}/i{int(itemsize)}/{cls}"
    if nan_fill is not None:
        key += "/nan" if nan_fill else "/dense"
    return key


def cache_path(path=None) -> pathlib.Path:
    """The autotune cache file: explicit ``path`` >
    ``PYCONSENSUS_AUTOTUNE_CACHE`` (resolved at import) >
    ``~/.cache/pyconsensus_tpu/autotune.json``."""
    p = path or _CACHE_PATH_ENV or "~/.cache/pyconsensus_tpu/autotune.json"
    return pathlib.Path(p).expanduser()


def _sweeps_counter():
    return obs.counter(
        "pyconsensus_autotune_sweeps_total",
        "autotune sweeps executed (cache misses that measured candidates)",
        labels=("kind",))


def _hits_counter():
    return obs.counter(
        "pyconsensus_autotune_cache_hits_total",
        "autotune lookups served from the persisted cache",
        labels=("kind",))


def _configs_counter():
    return obs.counter(
        "pyconsensus_autotune_configs_total",
        "candidate block configurations evaluated by autotune sweeps",
        labels=("kind",))


def _fallback_counter():
    return obs.counter(
        "pyconsensus_autotune_fallback_total",
        "provider lookups that fell through to the fallback table or the "
        "in-kernel heuristic", labels=("kind",))


class TuneCache:
    """The persisted winner table — one JSON file, atomically replaced
    on every ``put`` (crash leaves old content or new, never torn). A
    corrupt/torn/foreign-version file loads as EMPTY with a stderr
    warning: the fallback chain still serves, and the next sweep's
    ``put`` rewrites a clean file."""

    def __init__(self, path=None) -> None:
        self.path = cache_path(path)
        self.entries: dict = {}
        self.load()

    def load(self) -> None:
        self.entries = {}
        try:
            raw = json.loads(self.path.read_text())
            if raw.get("version") == _VERSION and \
                    isinstance(raw.get("entries"), dict):
                self.entries = raw["entries"]
            else:
                import sys

                print(f"WARNING: autotune cache {self.path} has "
                      f"version {raw.get('version')!r} != {_VERSION}; "
                      f"ignoring it", file=sys.stderr)
        except FileNotFoundError:
            pass
        except (ValueError, OSError) as exc:
            import sys

            print(f"WARNING: autotune cache {self.path} unreadable "
                  f"({type(exc).__name__}: {exc}); treating as empty",
                  file=sys.stderr)

    def get(self, key: str):
        return self.entries.get(key)

    def put(self, key: str, entry: dict) -> None:
        self.entries[key] = entry
        payload = json.dumps({"version": _VERSION, "entries": self.entries},
                             indent=1, sort_keys=True)
        _faults.fire("tune.cache_write", path=self.path)

        def writer(tmp):
            pathlib.Path(tmp).write_text(payload)

        pio.atomic_write(self.path, writer)


# -- provider (kernel-build-time lookup) -----------------------------------


def _fallback(kind: str, generation: str):
    row = FALLBACK_TABLE.get((kind, generation))
    if row is None:
        row = FALLBACK_TABLE.get((kind, "*"))
    return row


def default_provider(path=None):
    """The provider ``pallas_kernels`` lazily installs at kernel-build
    time: persisted winner first (counted as a cache hit), then the
    deterministic :data:`FALLBACK_TABLE`, then None (the in-kernel
    heuristic). Resolves the cache file and device generation ONCE — the
    provider itself is pure dict lookup, deterministic for the process
    lifetime (trace-time code must never re-read the environment).

    On a multi-process program the provider is inert (always falls back):
    per-host cache files could install different block shapes — and
    therefore different compiled programs — on different hosts.
    """
    import jax

    if jax.process_count() > 1:
        def inert(kind, **ctx):
            _fallback_counter().inc(kind=kind)
            return _fallback(kind, "multiprocess")
        return inert

    cache = TuneCache(path)
    generation = tpu_generation()
    hits, fallbacks = _hits_counter(), _fallback_counter()

    def provider(kind, **ctx):
        if kind == "cov_tile_rows":
            key = _entry_key(kind, generation, ctx["itemsize"],
                             shape_class(ctx["n_events"]),
                             nan_fill=ctx.get("nan_fill"))
        elif kind == "resolve_block_cols":
            key = _entry_key(kind, generation, ctx["itemsize"],
                             shape_class(ctx["n_reporters"]))
        else:
            return None
        entry = cache.get(key)
        if entry is not None:
            hits.inc(kind=kind)
            return entry.get("value")
        fallbacks.inc(kind=kind)
        return _fallback(kind, generation)

    return provider


def install(path=None):
    """Build the default provider from ``path`` (or the default cache)
    and install it into ``pallas_kernels`` — the explicit form of the
    lazy kernel-build-time autoload. Returns the provider."""
    provider = default_provider(path)
    pk.set_tune_provider(provider)
    return provider


# -- sweeps ----------------------------------------------------------------


def _median_time(fn, repeats: int) -> float:
    fn()                                    # warm (compile) untimed
    samples = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def _synth_storage(rng, R: int, E: int, storage_dtype: str, na_frac: float):
    """Seeded synthetic storage matrix + fill stats for the sweeps —
    binary lattice values with NaN absences, in the requested storage
    encoding."""
    import jax.numpy as jnp

    vals = rng.choice([0.0, 0.5, 1.0], size=(R, E))
    na = rng.random((R, E)) < na_frac
    if storage_dtype == "int8":
        enc = np.where(na, -1, np.round(2 * vals)).astype(np.int8)
        x = jnp.asarray(enc)
    else:
        dt = jnp.dtype(storage_dtype or jnp.asarray(0.0).dtype)
        x = jnp.asarray(np.where(na, np.nan, vals), dt)
    rep = jnp.asarray(np.full(R, 1.0 / R), jnp.float32)
    fill = jnp.asarray(rng.choice([0.0, 0.5, 1.0], size=E), jnp.float32)
    return x, rep, fill


def _agreeing_winner(results, candidates, pick, kind: str):
    """Assert every candidate produced the same outputs, then return the
    picked winner. ``results`` maps candidate -> tuple of np arrays; the
    catch-snapped arrays must be bit-identical, continuous ones within
    reduction-order tolerance (block width changes accumulation order,
    the same ulp class the XLA tilings already carry)."""
    base_c = candidates[0]
    base = results[base_c]
    for c in candidates[1:]:
        for i, (a, b) in enumerate(zip(base, results[c])):
            np.testing.assert_allclose(
                a, b, rtol=0, atol=1e-5, equal_nan=True,
                err_msg=(f"autotune {kind}: candidate {c} output {i} "
                         f"disagrees with candidate {base_c} — block "
                         f"shapes must never change results"))
    return pick


def autotune_resolve(n_reporters: int, n_events: int = 512,
                     storage_dtype: str = "", *, interpret: bool = False,
                     path=None, force: bool = False, repeats: int = 5,
                     na_frac: float = 0.05, seed: int = 0) -> dict:
    """Sweep the fused resolution kernel's column-block width for this
    reporter shape class and persist the winner. Returns the cache entry
    (``{"value": C, ...}``). Cache hit (same key, ``force=False``) skips
    the sweep entirely."""
    import jax
    import jax.numpy as jnp

    itemsize = (jnp.dtype(storage_dtype).itemsize if storage_dtype
                else jnp.asarray(0.0).dtype.itemsize)
    Rp = n_reporters + (-n_reporters) % 8
    generation = "interpret" if interpret else tpu_generation()
    key = _entry_key("resolve_block_cols", generation, itemsize,
                     shape_class(Rp))
    cache = TuneCache(path)
    if not force:
        hit = cache.get(key)
        if hit is not None:
            _hits_counter().inc(kind="resolve_block_cols")
            return hit
    candidates = pk.resolve_block_candidates(Rp, itemsize)
    if not candidates:
        raise ValueError(f"R={n_reporters} (padded {Rp}) has no legal "
                         f"resolution block width at itemsize {itemsize}; "
                         f"the XLA path serves this shape")
    _sweeps_counter().inc(kind="resolve_block_cols")
    rng = np.random.default_rng(seed)
    x, rep, fill = _synth_storage(rng, Rp, n_events, storage_dtype, na_frac)
    total = jnp.sum(rep)
    timings, results = {}, {}
    for C in candidates:
        _configs_counter().inc(kind="resolve_block_cols")

        def run(C=C):
            out = pk.resolve_certainty_fused(x, rep, fill, total, 0.1,
                                             block_cols=C,
                                             interpret=interpret)
            jax.block_until_ready(out)
            return out

        results[C] = tuple(np.asarray(o) for o in run())
        timings[C] = None if interpret else _median_time(run, repeats)
    if interpret:
        # deterministic analytic ranking — interpreter wall time says
        # nothing about the TPU (module docstring)
        pick = pk._resolve_block_cols(Rp, itemsize) or candidates[0]
        if pick not in candidates:
            pick = candidates[-1]
    else:
        pick = min(candidates, key=lambda c: (timings[c], c))
    pick = _agreeing_winner(results, candidates, pick, "resolve")
    entry = {"value": int(pick), "kind": "resolve_block_cols",
             "candidates": [int(c) for c in candidates],
             "mode": "interpret" if interpret else "timed",
             "probe_shape": [int(Rp), int(n_events)],
             "storage_dtype": storage_dtype or "full"}
    if not interpret:
        entry["timings_ms"] = {str(c): round(t * 1e3, 4)
                               for c, t in timings.items()}
    cache.put(key, entry)
    return entry


def depth_candidates(max_depth: int = 4) -> tuple:
    """The dispatch pipeline-depth sweep space (ISSUE 13 tentpole d):
    1 (synchronous) through ``max_depth`` in-flight dispatches. Depth
    is a HOST dispatch-loop knob, never a compile-time constant, so
    every candidate is trivially "legal" — the sweep's job is ranking
    and the depth-never-changes-results assertion."""
    return tuple(range(1, max(1, int(max_depth)) + 1))


def tuned_pipeline_depth(n_events: int, path=None) -> int:
    """The dispatch pipeline depth for this event shape class:
    persisted winner first (cache hit), then the deterministic
    :data:`FALLBACK_TABLE` row (2 everywhere). The
    ``ServeConfig.pipeline_depth = 0`` auto policy resolves through
    here. Multi-process programs take the fallback unconditionally —
    depth does not change compiled programs (no compile-divergence
    hazard), but per-host winner files must not make two hosts of one
    fleet pace their rings differently under one load-balancing
    model."""
    import jax
    import jax.numpy as jnp

    if jax.process_count() > 1:
        _fallback_counter().inc(kind="pipeline_depth")
        return int(_fallback("pipeline_depth", "multiprocess") or 2)
    itemsize = jnp.asarray(0.0).dtype.itemsize
    generation = tpu_generation()
    key = _entry_key("pipeline_depth", generation, itemsize,
                     shape_class(n_events))
    entry = TuneCache(path).get(key)
    if entry is not None:
        _hits_counter().inc(kind="pipeline_depth")
        return int(entry["value"])
    _fallback_counter().inc(kind="pipeline_depth")
    return int(_fallback("pipeline_depth", generation) or 2)


def autotune_pipeline_depth(n_reporters: int = 32, n_events: int = 256,
                            *, deterministic: bool = False, path=None,
                            force: bool = False, repeats: int = 3,
                            dispatches: int = 8, seed: int = 0) -> dict:
    """Sweep the dispatch pipeline depth for this event shape class and
    persist the winner (the block-shape sweeps' winner-cache
    discipline, keyed generation/itemsize/shape-class). Each candidate
    drives ``dispatches`` seeded padded-bucket dispatches through the
    REAL serve bucket executable with a depth-``d`` in-flight ring —
    the batcher's hot loop in miniature — and every candidate's
    retired outputs are asserted identical before a winner persists
    (depth changes WHEN results are fetched, never what they are).
    ``deterministic=True`` (CPU tests, the CI smoke) still executes
    every candidate but ranks by the analytic fallback instead of wall
    time — CPU ring timings say nothing about the TPU dispatch
    overlap. On hardware the median of timed runs decides."""
    import jax.numpy as jnp

    from ..models.pipeline import ConsensusParams
    from ..serve.kernels import bucket_inputs, make_bucket_executable

    itemsize = jnp.asarray(0.0).dtype.itemsize
    generation = "interpret" if deterministic else tpu_generation()
    key = _entry_key("pipeline_depth", generation, itemsize,
                     shape_class(n_events))
    cache = TuneCache(path)
    if not force:
        hit = cache.get(key)
        if hit is not None:
            _hits_counter().inc(kind="pipeline_depth")
            return hit
    candidates = depth_candidates()
    _sweeps_counter().inc(kind="pipeline_depth")
    p = ConsensusParams(algorithm="sztorc", pca_method="power",
                        has_na=True, any_scaled=False, n_scaled=0)
    fn = make_bucket_executable(p)          # undonated: the sweep owns
    rng = np.random.default_rng(seed)       # no template discipline
    panels = [rng.choice([0.0, 1.0], size=(n_reporters, n_events))
              for _ in range(max(2, dispatches))]
    for m in panels:
        m[0, 0] = np.nan                    # exercise the fill graph
    lanes = [bucket_inputs(m, np.full(n_reporters, 1.0 / n_reporters),
                           np.zeros(n_events, bool), np.zeros(n_events),
                           np.ones(n_events), n_reporters, n_events,
                           has_na=True) for m in panels]
    timings, results = {}, {}
    for d in candidates:
        _configs_counter().inc(kind="pipeline_depth")

        def run(d=d):
            import jax.numpy as jnp

            def fetch(raw):  # the blocking step the ring schedules
                return {k: np.asarray(v) for k, v in raw.items()}

            ring, out = [], []
            for lane in lanes:
                ring.append(fn(*[jnp.asarray(a) for a in lane], p))
                while len(ring) >= d:
                    out.append(fetch(ring.pop(0)))
            out.extend(fetch(r) for r in ring)
            return [o["outcomes_adjusted"] for o in out] + \
                   [o["smooth_rep"] for o in out]

        results[d] = tuple(run())           # also warms the executable
        timings[d] = None if deterministic else _median_time(run, repeats)
    if deterministic:
        pick = int(_fallback("pipeline_depth", generation) or 2)
        if pick not in candidates:
            pick = candidates[-1]
    else:
        pick = min(candidates, key=lambda d: (timings[d], d))
    pick = _agreeing_winner(results, candidates, pick, "pipeline_depth")
    entry = {"value": int(pick), "kind": "pipeline_depth",
             "candidates": [int(c) for c in candidates],
             "mode": "deterministic" if deterministic else "timed",
             "probe_shape": [int(n_reporters), int(n_events)],
             "dispatches": int(len(lanes))}
    if not deterministic:
        entry["timings_ms"] = {str(c): round(t * 1e3, 4)
                               for c, t in timings.items()}
    cache.put(key, entry)
    return entry


def autotune_cov(n_events: int, n_reporters: int = 256,
                 storage_dtype: str = "", nan_fill: bool = True, *,
                 interpret: bool = False, path=None, force: bool = False,
                 repeats: int = 5, na_frac: float = 0.05,
                 seed: int = 0) -> dict:
    """Sweep the storage/cov sweep kernels' row-panel size for this event
    shape class and persist the winner. Candidate tiles are forced
    through a scoped provider override and a FRESH jit per candidate —
    the tile is a trace-time constant, so re-calling the module-level
    jitted kernel would silently reuse the first candidate's
    executable."""
    import functools

    import jax
    import jax.numpy as jnp

    itemsize = (jnp.dtype(storage_dtype).itemsize if storage_dtype
                else jnp.asarray(0.0).dtype.itemsize)
    generation = "interpret" if interpret else tpu_generation()
    key = _entry_key("cov_tile_rows", generation, itemsize,
                     shape_class(n_events), nan_fill=nan_fill)
    cache = TuneCache(path)
    if not force:
        hit = cache.get(key)
        if hit is not None:
            _hits_counter().inc(kind="cov_tile_rows")
            return hit
    candidates = pk.cov_tile_candidates(n_events, itemsize, nan_fill)
    if not candidates:
        raise ValueError(f"E={n_events} has no legal cov row panel at "
                         f"itemsize {itemsize}; the XLA path serves "
                         f"this shape")
    _sweeps_counter().inc(kind="cov_tile_rows")
    rng = np.random.default_rng(seed)
    x, rep, fill = _synth_storage(rng, n_reporters, n_events, storage_dtype,
                                  na_frac if nan_fill else 0.0)
    mu = jnp.asarray(rng.random(n_events), jnp.float32)
    v = jnp.asarray(rng.standard_normal(n_events), jnp.float32)
    timings, results = {}, {}
    for tile in candidates:
        _configs_counter().inc(kind="cov_tile_rows")
        fn = jax.jit(functools.partial(
            pk.apply_weighted_cov.__wrapped__, interpret=interpret))

        def run(fn=fn, tile=tile):
            # scoped override saving the module state DIRECTLY —
            # set_tune_provider would latch autoload off, so a sweep in
            # a fresh process would permanently disconnect the kernels
            # from the winner it is about to persist
            prev_p, prev_a = pk._TUNE_PROVIDER, pk._TUNE_AUTOLOAD
            pk._TUNE_PROVIDER = (
                lambda kind, **ctx: tile if kind == "cov_tile_rows"
                else None)
            pk._TUNE_AUTOLOAD = False
            try:
                out = fn(x, mu, rep, v, fill if nan_fill else None)
                jax.block_until_ready(out)
                return out
            finally:
                pk._TUNE_PROVIDER, pk._TUNE_AUTOLOAD = prev_p, prev_a

        results[tile] = (np.asarray(run()),)
        timings[tile] = None if interpret else _median_time(run, repeats)
    if interpret:
        pick = pk._panel_rows(
            n_events, itemsize,
            pk._PANEL_BYTES // 2 if nan_fill else pk._PANEL_BYTES)
        if pick not in candidates:
            pick = candidates[-1]
    else:
        pick = min(candidates, key=lambda t: (timings[t], t))
    pick = _agreeing_winner(results, candidates, pick, "cov")
    entry = {"value": int(pick), "kind": "cov_tile_rows",
             "candidates": [int(c) for c in candidates],
             "mode": "interpret" if interpret else "timed",
             "probe_shape": [int(n_reporters), int(n_events)],
             "nan_fill": bool(nan_fill),
             "storage_dtype": storage_dtype or "full"}
    if not interpret:
        entry["timings_ms"] = {str(c): round(t * 1e3, 4)
                               for c, t in timings.items()}
    cache.put(key, entry)
    return entry
