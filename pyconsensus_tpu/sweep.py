"""Algorithm-variant sweep: resolve one reports matrix under several
``algorithm=`` backends concurrently.

SURVEY.md §2 ("Parallelism components") maps expert parallelism onto
"dispatching different ``algorithm=`` variants across devices in a sweep" —
the reference has no parallelism at all, and its users compare variants by
re-running the library serially. Here every jit-compatible variant is
dispatched asynchronously (XLA queues the compiled programs back-to-back,
so device work for variant k overlaps host dispatch of variant k+1; on a
multi-controller deployment each process can pass a disjoint
``algorithms=`` slice to spread variants across hosts), and the hybrid
host-clustering variants run while the device queue drains.

>>> from pyconsensus_tpu.sweep import compare_algorithms
>>> res = compare_algorithms(reports, max_iterations=3)
>>> res["sztorc"]["events"]["outcomes_final"]
>>> disagreement_matrix(res)          # which variants disagree where
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from . import obs
from .models.pipeline import HYBRID_ALGORITHMS, JIT_ALGORITHMS
from .oracle import ALGORITHMS, Oracle

__all__ = ["compare_algorithms", "disagreement_matrix"]


def compare_algorithms(reports, algorithms: Optional[Sequence[str]] = None,
                       event_bounds=None, reputation=None,
                       **oracle_kwargs) -> Dict[str, dict]:
    """Resolve ``reports`` under every algorithm in ``algorithms`` (default:
    all seven), returning ``{algorithm: consensus-result-dict}``.

    The jit variants are dispatched first without blocking — their XLA
    programs queue on the device and execute back-to-back — then the hybrid
    (host-clustering) variants run on CPU while that queue drains, and only
    afterwards are the queued device results fetched. ``oracle_kwargs``
    pass through to :class:`Oracle` (``backend`` is forced to ``"jax"``).
    """
    algorithms = tuple(algorithms if algorithms is not None else
                       sorted(ALGORITHMS))
    for a in algorithms:
        if a not in ALGORITHMS:
            raise ValueError(f"unknown algorithm {a!r}; "
                             f"choose from {sorted(ALGORITHMS)}")
    oracle_kwargs.pop("backend", None)
    oracle_kwargs.pop("algorithm", None)

    def make(a):
        return Oracle(reports=reports, event_bounds=event_bounds,
                      reputation=reputation, algorithm=a, backend="jax",
                      **oracle_kwargs)

    with obs.span("sweep.compare_algorithms",
                  algorithms=",".join(algorithms)):
        # async device dispatch for the jit variants...
        raw: Dict[str, dict] = {}
        with obs.span("sweep.dispatch_jit"):
            for a in algorithms:
                if a in JIT_ALGORITHMS:
                    raw[a] = make(a).resolve_raw()
        # ...hybrid variants overlap the draining device queue...
        results: Dict[str, dict] = {}
        for a in algorithms:
            if a in HYBRID_ALGORITHMS:
                results[a] = make(a).consensus()
        # ...then fetch the queued device results
        from .oracle import assemble_result
        with obs.span("sweep.fetch_jit"):
            for a, r in raw.items():
                results[a] = assemble_result(
                    {k: np.asarray(v) for k, v in r.items()})
    return {a: results[a] for a in algorithms}


def disagreement_matrix(results: Dict[str, dict]) -> np.ndarray:
    """(n_algorithms, n_algorithms) count of events whose final outcomes
    differ between each pair of variants in a :func:`compare_algorithms`
    result — the quick "which lie detectors disagree" diagnostic."""
    names = list(results)
    outs = [np.asarray(results[a]["events"]["outcomes_final"])
            for a in names]
    n = len(names)
    m = np.zeros((n, n), dtype=np.int64)
    for i in range(n):
        for j in range(n):
            m[i, j] = int(np.sum(outs[i] != outs[j]))
    return m
