"""Device-mesh construction helpers (SURVEY.md §2 "Parallelism components",
§7 M5).

The framework's parallel axes:

- ``"event"`` — the scaling axis (the reference's 100k-event matrices held in
  one process are exactly what breaks at target scale `[B]`): the (R, E)
  reports matrix is sharded column-wise; every contraction over events
  becomes a per-shard partial + an XLA-inserted all-reduce over ICI.
- ``"batch"`` — embarrassingly parallel independent resolutions (the
  Monte-Carlo sweep, multi-market resolution): pure data parallelism, no
  cross-device traffic except the final metric gather.

Meshes here are ordinary ``jax.sharding.Mesh`` objects: on a real pod the
same code spans hosts (``jax.distributed.initialize`` + ``jax.devices()``),
on CPU tests an ``--xla_force_host_platform_device_count=8`` simulated mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "event_sharding", "batch_event_sharding",
           "replicated", "effective_median_block", "P", "Mesh",
           "NamedSharding"]


def effective_median_block(median_block: int, mesh: Optional[Mesh]) -> int:
    """The ONE place that encodes the blocked-median / GSPMD constraint:
    when the mesh actually shards the event axis, the blocked weighted
    median's ``dynamic_slice`` over that axis is unpartitionable — GSPMD
    falls back to all-gathering the full (R, E) operand onto every device
    (tests/test_hlo_collectives.py pins the bound) — so the median must
    run unblocked (0); each device's event shard then bounds the sort
    temporaries to (R, E/n_event). An unsharded event axis (``event=1``,
    including pure-batch meshes) keeps the caller's block width: there the
    blocking is partitionable AND is the only thing bounding the sort
    temporaries on a single device."""
    if mesh is not None and mesh.shape.get("event", 1) > 1:
        return 0
    return median_block


def make_mesh(batch: int = 1, event: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(batch, event)`` mesh. ``event`` defaults to using every
    remaining device. ``batch * event`` must divide the device count."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if event is None:
        if n % batch != 0:
            raise ValueError(f"batch={batch} does not divide {n} devices")
        event = n // batch
    if batch * event > n:
        raise ValueError(f"mesh {batch}x{event} needs {batch * event} devices, "
                         f"have {n}")
    grid = np.asarray(devices[:batch * event]).reshape(batch, event)
    return Mesh(grid, ("batch", "event"))


def event_sharding(mesh: Mesh) -> NamedSharding:
    """(R, E) matrix sharded over events, replicated over reporters."""
    return NamedSharding(mesh, P(None, "event"))


def batch_event_sharding(mesh: Mesh) -> NamedSharding:
    """(B, R, E) batch of matrices: batch axis over "batch", events over
    "event" — data parallelism composed with the long-axis sharding."""
    return NamedSharding(mesh, P("batch", None, "event"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
