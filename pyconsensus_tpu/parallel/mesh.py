"""Device-mesh construction helpers (SURVEY.md §2 "Parallelism components",
§7 M5).

The framework's parallel axes:

- ``"event"`` — the scaling axis (the reference's 100k-event matrices held in
  one process are exactly what breaks at target scale `[B]`): the (R, E)
  reports matrix is sharded column-wise; every contraction over events
  becomes a per-shard partial + an XLA-inserted all-reduce over ICI.
- ``"batch"`` — embarrassingly parallel independent resolutions (the
  Monte-Carlo sweep, multi-market resolution): pure data parallelism, no
  cross-device traffic except the final metric gather.

Meshes here are ordinary ``jax.sharding.Mesh`` objects: on a real pod the
same code spans hosts (``jax.distributed.initialize`` + ``jax.devices()``),
on CPU tests an ``--xla_force_host_platform_device_count=8`` simulated mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "event_sharding", "batch_event_sharding",
           "replicated", "P", "Mesh", "NamedSharding"]


def make_mesh(batch: int = 1, event: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(batch, event)`` mesh. ``event`` defaults to using every
    remaining device. ``batch * event`` must divide the device count."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if event is None:
        if n % batch != 0:
            raise ValueError(f"batch={batch} does not divide {n} devices")
        event = n // batch
    if batch * event > n:
        raise ValueError(f"mesh {batch}x{event} needs {batch * event} devices, "
                         f"have {n}")
    grid = np.asarray(devices[:batch * event]).reshape(batch, event)
    return Mesh(grid, ("batch", "event"))


def event_sharding(mesh: Mesh) -> NamedSharding:
    """(R, E) matrix sharded over events, replicated over reporters."""
    return NamedSharding(mesh, P(None, "event"))


def batch_event_sharding(mesh: Mesh) -> NamedSharding:
    """(B, R, E) batch of matrices: batch axis over "batch", events over
    "event" — data parallelism composed with the long-axis sharding."""
    return NamedSharding(mesh, P("batch", None, "event"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
