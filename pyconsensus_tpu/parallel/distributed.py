"""Multi-host / multi-slice runtime: process initialization and hybrid
ICI x DCN meshes.

The reference is one Python process (SURVEY.md §2 "Parallelism components":
no NCCL/MPI/Gloo, zero IPC). Its rebuild equivalent is the JAX distributed
runtime: every host runs the same SPMD program, ``jax.distributed`` wires
the processes into one system, and XLA compiles the collectives — intra-
slice reductions ride ICI, cross-slice traffic rides DCN. The design rule
(scaling-book recipe) is to put the *bandwidth-hungry* axis on ICI and the
*embarrassingly parallel* axis on DCN:

- ``"event"`` (sharded covariance/Gram contractions, the per-step
  all-reduces of power iteration) -> **ICI within a slice**;
- ``"batch"`` (independent oracle resolutions: Monte-Carlo trials,
  multi-market sweeps — one small metric gather at the end) -> **DCN
  across slices**.

:func:`initialize` is the one call a launcher makes on each host;
:func:`make_hybrid_mesh` builds the (batch, event) mesh with DCN on the
batch axis whenever the platform reports multiple slices, and degrades to
the flat single-slice mesh (:func:`.mesh.make_mesh`) everywhere else, so
the same user code runs from 1 chip to a multi-pod fleet unchanged.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import make_mesh

__all__ = ["initialize", "make_hybrid_mesh", "num_slices", "is_distributed"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None) -> None:
    """Join this process to the distributed JAX runtime (idempotent).

    Must be the first jax call in the process (before anything that
    initializes the XLA backend — ``jax.devices()``, any computation), the
    same contract as ``jax.distributed.initialize``, which this wraps.

    With no arguments, defers to jax's own cluster auto-detection (Cloud
    TPU metadata, SLURM, Open MPI, ``JAX_COORDINATOR_ADDRESS``-style env);
    when nothing is detectable — a plain single-host run — it degrades to
    a no-op instead of raising, so the same launcher code runs from one
    chip to a pod. Explicit arguments mirror ``jax.distributed.initialize``
    and *do* raise on failure (a misconfigured multi-host launch must not
    silently continue as N single-process jobs).
    """
    global _initialized
    if _initialized:
        return
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        _initialized = True
        return
    # ANY explicit argument means the caller is describing a multi-host
    # launch — a failure must raise, never silently degrade to N isolated
    # single-process jobs
    explicit = any(a is not None for a in (coordinator_address, num_processes,
                                           process_id, local_device_ids))
    # On a CPU platform, cross-process computations need the gloo
    # collectives client selected BEFORE the backend initializes — the
    # env-var spelling alone does not reach the XLA CpuClient on this
    # jax/jaxlib line, and a distributed CPU run without it fails at
    # the first collective with "Multiprocess computations aren't
    # implemented on the CPU backend" (ISSUE 15: this one line is what
    # stood between the multiprocess tests and the capability). The
    # platform decision reads the ENV, not jax.default_backend() —
    # querying the backend here would initialize it and break the
    # must-be-first contract above. An UNSET platform counts as
    # CPU-eligible (the common bare-machine case — and the same
    # decision ``transport.multihost.multihost_capability`` makes, so
    # the gate and this knob can never disagree); on accelerator
    # hosts the knob only configures the secondary CPU client.
    platform = os.environ.get("JAX_PLATFORMS", "")
    if explicit and (platform == "" or platform.startswith("cpu")):
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, ValueError):
            pass    # older/newer jax without the knob: leave defaults
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id,
                                   local_device_ids=local_device_ids)
    except ValueError:
        # jax's auto-detection found no cluster spec
        # ('coordinator_address should be defined') -> single-process run
        if explicit:
            raise
        return
    except RuntimeError:
        # 'distributed.initialize should only be called once' — someone
        # (launcher framework, user code) already joined the runtime
        if explicit:
            raise
    _initialized = True


def is_distributed() -> bool:
    return jax.process_count() > 1


def _slice_index(device) -> int:  # consensus-lint: host-divergent
    # TPU devices expose slice_index on multi-slice (Megascale/DCN)
    # topologies; everything else is one slice. Marked host-divergent for
    # the Layer 3 taint pass: slice attributes come from the
    # process-local runtime, so every flow into mesh/branch structure
    # gets audited (consumers that rely on the globally-synchronized
    # jax.devices() order pragma their use with that justification).
    return getattr(device, "slice_index", 0)


def num_slices(devices: Optional[Sequence] = None) -> int:  # consensus-lint: host-divergent
    devices = devices if devices is not None else jax.devices()
    return len({_slice_index(d) for d in devices})


def make_hybrid_mesh(batch: Optional[int] = None,
                     devices: Optional[Sequence] = None) -> Mesh:
    """(batch, event) mesh laid out so the event axis never crosses DCN.

    Multi-slice topology: batch axis = slices (DCN), event axis = chips
    within a slice (ICI); ``batch`` may further subdivide within slices if
    it is a multiple of the slice count. Single slice: plain
    :func:`.mesh.make_mesh` (batch defaults to 1 -> all chips on events).
    """
    devices = list(devices if devices is not None else jax.devices())
    slices = sorted({_slice_index(d) for d in devices})
    # CL401/CL403 pragmas below: the grid derives solely from the
    # GLOBALLY-SYNCHRONIZED jax.devices() list (same order and slice
    # attributes on every process — the runtime broadcasts the topology
    # at initialize()), so every host computes the identical mesh; the
    # host-divergent marker on _slice_index exists to audit flows like
    # this one, and this is the audited-consistent case.
    if len(slices) <= 1:  # consensus-lint: disable=CL401
        return make_mesh(batch=batch or 1, devices=devices)

    by_slice = [[d for d in devices if _slice_index(d) == s] for s in slices]
    per = len(by_slice[0])
    if any(len(g) != per for g in by_slice):
        raise ValueError("uneven chips per slice: "
                         f"{[len(g) for g in by_slice]}")
    n_slices = len(slices)
    batch = batch if batch is not None else n_slices
    if batch % n_slices != 0:
        raise ValueError(f"batch={batch} must be a multiple of the slice "
                         f"count {n_slices} so the event axis stays inside "
                         "a slice (ICI)")
    sub = batch // n_slices            # extra batch ways inside each slice
    if per % sub != 0:
        raise ValueError(f"chips-per-slice {per} not divisible by "
                         f"within-slice batch factor {sub}")
    # grid rows = batch groups; each row's event neighbors are same-slice
    grid = np.asarray([g[i * (per // sub):(i + 1) * (per // sub)]
                       for g in by_slice for i in range(sub)])
    return Mesh(grid, ("batch", "event"))  # consensus-lint: disable=CL403
