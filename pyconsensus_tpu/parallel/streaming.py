"""Out-of-core consensus: resolve matrices LARGER than device memory.

The reference (and the in-memory paths here) hold the full (R, E) matrix
resident. This module streams the event axis from host (numpy array,
``np.memmap``, or an ``.npy`` path) in panels and resolves in
``iterations + 1`` passes (two for the common single-iteration case),
because everything the PCA scoring step needs collapses into R x R
accumulators (R = reporters, the small axis):

pass 1 (per event panel ``F_p`` = filled panel, ``D_p`` centered,
``A_p = sqrt(rep) * D_p``):

    G += A_p A_p^T          # weighted Gram: the covariance's spectrum
    M += D_p A_p^T          # gives scores = M u / ||A^T u||
    S += F_p F_p^T          # gives the direction fix in closed form

- the top eigenvector ``u`` of ``G / (1 - sum(rep^2))`` is the Gram-trick
  principal component; ``||A^T u|| = sqrt(u^T G u)`` — no extra pass;
- ``scores = D @ loading = M u / ||A^T u||``;
- the direction fix needs only squared distances of projected outcome
  vectors, and ``||w^T F - rep^T F||^2 = (w - rep)^T S (w - rep)`` — so
  the ``ref_ind`` tie-break (identical to
  ``jax_kernels.direction_fixed_scores``, including normalize's zero-sum
  guard and the non-negative winning orientation) is O(R^2) arithmetic.

pass 2 (with the final reputation): per-panel outcome resolution,
certainty, and NA participation — all column-local given the reputation —
with the per-row ``na @ certainty`` partials accumulated panel by panel.

Host memory holds only E-vectors (fill, certainty, outcomes, ...); device
memory holds one panel plus three R x R accumulators. Restriction:
``algorithm="sztorc"``. Iterative redistribution (``max_iterations > 1``)
costs one accumulation pass per executed iteration, because G and M
follow the iterating reputation; S and the interpolate fill are pinned to
the initial reputation (reference semantics) and computed once.

Throughput is bound by the host->device link (every byte crosses twice):
on directly-attached hardware that is PCIe/DMA at tens of GB/s; through
the development tunnel it is orders of magnitude slower — verified
functionally there (outcomes bit-identical to the in-memory path at 1000
x 40k), sized for real deployments.
"""

from __future__ import annotations

import concurrent.futures
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.pipeline import ConsensusParams
from ..ops import jax_kernels as jk
from ..ops import numpy_kernels as nk
from ..oracle import parse_event_bounds

__all__ = ["streaming_consensus"]


@functools.partial(jax.jit, static_argnames=("tolerance", "with_s"))
def _pass1_panel(panel, fill_rep, weight_rep, scaled, mins, maxs, valid,
                 tolerance: float, with_s: bool):
    """One event panel -> (G, M[, S]) contributions.

    ``fill_rep`` is the INITIAL reputation (interpolate fills are computed
    once, reference semantics); ``weight_rep`` is the current iteration's
    reputation (weighted means and the Gram weighting follow it).
    ``S = F F^T`` depends only on the filled matrix, which is fixed across
    iterations — ``with_s`` skips it after the first accumulation pass.
    ``valid`` masks the zero-padded tail of the last panel out of every
    cross-panel accumulator."""
    acc = weight_rep.dtype
    rescaled = jk.rescale(panel, scaled, mins, maxs)
    filled, present = jk.interpolate_masked(rescaled, fill_rep, scaled,
                                            tolerance)
    F = jnp.where(valid[None, :], filled, 0.0)
    mu = weight_rep @ F                             # (P,), zero on padding
    D = jnp.where(valid[None, :], F - mu[None, :], 0.0)
    A = D * jnp.sqrt(jnp.clip(weight_rep, 0.0, None))[:, None]
    G = jnp.matmul(A, A.T, preferred_element_type=acc)
    M = jnp.matmul(D, A.T, preferred_element_type=acc)
    if with_s:
        S = jnp.matmul(F, F.T, preferred_element_type=acc)
        return G, M, S
    return G, M, jnp.zeros_like(G)


@functools.partial(jax.jit, static_argnames=("tolerance",))
def _pass2_panel(panel, fill_rep, score_rep, final_rep, u_over_nAu, scaled,
                 mins, maxs, tolerance: float):
    """Per-panel resolution with the final reputation: outcomes, certainty,
    participation columns, per-row NA partials, and this panel's slice of
    the first loading (``A^T u / ||A^T u||`` with ``score_rep``, the
    reputation of the last executed scoring iteration). The fill is
    recomputed with the INITIAL reputation (interpolate semantics)."""
    acc = final_rep.dtype
    rescaled = jk.rescale(panel, scaled, mins, maxs)
    filled, present = jk.interpolate_masked(rescaled, fill_rep, scaled,
                                            tolerance)
    raw, adjusted = jk.resolve_outcomes(present, filled, final_rep, scaled,
                                        tolerance)
    final = jk.unscale_outcomes(adjusted, scaled, mins, maxs)
    agree = jnp.where(
        scaled[None, :],
        jnp.abs(filled - adjusted[None, :]) <= tolerance,
        filled == adjusted[None, :])
    certainty = jnp.sum(agree * final_rep[:, None], axis=0)
    na = (~present).astype(acc)
    pcol = final_rep @ na                            # rep mass on NA
    prow = na @ certainty                            # per-row partials
    na_count = jnp.sum(na, axis=1)
    mu = score_rep @ filled
    A = (filled - mu[None, :]) * jnp.sqrt(
        jnp.clip(score_rep, 0.0, None))[:, None]
    loading = A.T @ u_over_nAu
    return raw, adjusted, final, certainty, pcol, prow, na_count, loading


def streaming_consensus(reports_src, reputation=None, event_bounds=None,
                        panel_events: int = 8192,
                        params: Optional[ConsensusParams] = None) -> dict:
    """Resolve an oracle whose reports matrix never fits on device.

    ``reports_src``: numpy array / ``np.memmap`` / path to an ``.npy``
    file (loaded memory-mapped). Returns the light result dict as host
    numpy arrays. See the module docstring for the pass structure
    (``executed iterations + 1``) and restrictions.
    """
    if isinstance(reports_src, (str, bytes)) or hasattr(reports_src,
                                                        "__fspath__"):
        from ..io import load_reports

        reports_src = load_reports(reports_src, mmap=True)
    if reports_src.ndim != 2:
        raise ValueError(f"reports must be 2-D, got {reports_src.shape}")
    R, E = reports_src.shape
    p = params if params is not None else ConsensusParams()
    if p.algorithm != "sztorc":
        raise ValueError("streaming_consensus supports algorithm='sztorc'")
    P = int(panel_events)
    if P < 1:
        raise ValueError("panel_events must be >= 1")

    scaled_all, mins_all, maxs_all = parse_event_bounds(event_bounds, E)
    dtype = jnp.asarray(0.0).dtype
    if reputation is None:
        reputation = np.full((R,), 1.0 / R)
    old_rep = nk.normalize(np.asarray(reputation, dtype=float))
    fill_rep = jnp.asarray(old_rep, dtype=dtype)
    tol = float(p.catch_tolerance)

    def _prepare(start: int):
        stop = min(start + P, E)
        # convert straight to the device dtype: one host copy per panel,
        # half the bytes of a float64 detour
        block = np.asarray(reports_src[:, start:stop], dtype=np.dtype(dtype))
        width = stop - start
        if width < P:                          # zero-pad the ragged tail
            block = np.pad(block, ((0, 0), (0, P - width)))
        valid = np.zeros(P, dtype=bool)
        valid[:width] = True
        sc = np.pad(scaled_all[start:stop], (0, P - width))
        mn = np.pad(mins_all[start:stop], (0, P - width))
        mx = np.pad(maxs_all[start:stop], (0, P - width),
                    constant_values=1.0)
        return (start, stop, jnp.asarray(block, dtype=dtype),
                jnp.asarray(sc), jnp.asarray(mn, dtype=dtype),
                jnp.asarray(mx, dtype=dtype), jnp.asarray(valid))

    def panels():
        # one-deep prefetch: the NEXT panel's memmap read / dtype
        # conversion / host->device transfer overlaps the CURRENT panel's
        # device compute (jax dispatch is async) — on directly-attached
        # hardware this hides most of the PCIe time behind the kernels
        starts = list(range(0, E, P))
        if not starts:                     # E == 0: nothing to stream
            return
        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            pending = pool.submit(_prepare, starts[0])
            for nxt in starts[1:]:
                ready = pending.result()
                pending = pool.submit(_prepare, nxt)
                yield ready
            yield pending.result()

    # ---- scoring iterations: one accumulation pass per iteration --------
    # (the G/M statistics follow the iterating reputation; S = F F^T is
    # fixed because the interpolate fill is pinned to the initial
    # reputation — reference semantics)
    rep_k = fill_rep
    this_rep = fill_rep
    S = None
    converged = False
    iterations = 0
    score_rep = fill_rep
    u_over_nAu = jnp.zeros((R,), dtype=dtype)
    for _ in range(max(p.max_iterations, 1)):
        G = jnp.zeros((R, R), dtype=dtype)
        M = jnp.zeros((R, R), dtype=dtype)
        with_s = S is None
        S_acc = jnp.zeros((R, R), dtype=dtype) if with_s else None
        for _, _, block, sc, mn, mx, valid in panels():
            dG, dM, dS = _pass1_panel(block, fill_rep, rep_k, sc, mn, mx,
                                      valid, tol, with_s)
            G, M = G + dG, M + dM
            if with_s:
                S_acc = S_acc + dS
        if with_s:
            S = S_acc

        denom = 1.0 - jnp.sum(rep_k ** 2)
        denom = jnp.where(denom == 0.0, 1.0, denom)
        _, eigvecs = jnp.linalg.eigh(G / denom)
        u = eigvecs[:, -1]
        nAu = jnp.sqrt(jnp.clip(u @ G @ u, 0.0, None))
        u_over_nAu = u / jnp.where(nAu == 0.0, 1.0, nAu)
        scores = M @ u_over_nAu

        set1 = scores + jnp.abs(jnp.min(scores))
        set2 = scores - jnp.max(scores)

        def sq_dist_to_old(w, rep_ref=rep_k):
            d = w - rep_ref
            return d @ S @ d

        ref_ind = (sq_dist_to_old(jk.normalize(set1))
                   - sq_dist_to_old(jk.normalize(set2)))
        adj = jnp.where(ref_ind <= 0.0, set1, -set2)
        this_rep = jk.row_reward_weighted(adj, rep_k)
        new_rep = jk.smooth(this_rep, rep_k, p.alpha)
        delta = float(jnp.max(jnp.abs(new_rep - rep_k)))
        score_rep = rep_k
        rep_k = new_rep
        iterations += 1
        if delta <= p.convergence_tolerance:
            converged = True
            break
    smooth_rep = rep_k

    # ---- pass 2: per-panel resolution with the final reputation ---------
    outcomes_raw = np.empty(E)
    outcomes_adjusted = np.empty(E)
    outcomes_final = np.empty(E)
    certainty = np.empty(E)
    pcols = np.empty(E)
    first_loading = np.empty(E)
    prow = np.zeros(R)
    na_count = np.zeros(R)
    for start, stop, block, sc, mn, mx, _ in panels():
        raw, adjd, fin, cert, pc, pr, nc, ld = _pass2_panel(
            block, fill_rep, score_rep, smooth_rep, u_over_nAu, sc, mn, mx,
            tol)
        width = stop - start
        outcomes_raw[start:stop] = np.asarray(raw)[:width]
        outcomes_adjusted[start:stop] = np.asarray(adjd)[:width]
        outcomes_final[start:stop] = np.asarray(fin)[:width]
        certainty[start:stop] = np.asarray(cert)[:width]
        pcols[start:stop] = 1.0 - np.asarray(pc)[:width]
        first_loading[start:stop] = np.asarray(ld)[:width]
        prow += np.asarray(pr)       # padded cols: certainty * na(=0) = 0
        na_count += np.asarray(nc)
    first_loading = nk.canon_sign(first_loading)

    # ---- finalize the bonus accounting (numpy_kernels semantics) --------
    total_cert = certainty.sum()
    consensus_reward = nk.normalize(certainty)
    participation_rows = 1.0 - (prow if total_cert == 0.0
                                else prow / total_cert)
    percent_na = 1.0 - pcols.mean()
    na_bonus_rows = nk.normalize(participation_rows)
    smooth_np = np.asarray(smooth_rep, dtype=float)
    reporter_bonus = (na_bonus_rows * percent_na
                      + smooth_np * (1.0 - percent_na))
    na_bonus_cols = nk.normalize(pcols)
    author_bonus = (na_bonus_cols * percent_na
                    + consensus_reward * (1.0 - percent_na))
    return {
        "old_rep": old_rep,
        "this_rep": np.asarray(this_rep, dtype=float),
        "smooth_rep": smooth_np,
        "na_row": na_count > 0,
        "outcomes_raw": outcomes_raw,
        "outcomes_adjusted": outcomes_adjusted,
        "outcomes_final": outcomes_final,
        "iterations": iterations,
        "convergence": converged,
        "first_loading": first_loading,
        "certainty": certainty,
        "consensus_reward": consensus_reward,
        "avg_certainty": float(certainty.mean()),
        "participation_columns": pcols,
        "participation_rows": participation_rows,
        "percent_na": float(percent_na),
        "na_bonus_rows": na_bonus_rows,
        "reporter_bonus": reporter_bonus,
        "na_bonus_cols": na_bonus_cols,
        "author_bonus": author_bonus,
    }
