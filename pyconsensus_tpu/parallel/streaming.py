"""Out-of-core consensus: resolve matrices LARGER than device memory.

The reference (and the in-memory paths here) hold the full (R, E) matrix
resident. This module streams the event axis from host (numpy array,
``np.memmap``, or an ``.npy`` path) in panels and resolves in
``iterations + 1`` passes (two for the common single-iteration case),
because everything the PCA scoring step needs collapses into R x R
accumulators (R = reporters, the small axis):

pass 1 (per event panel ``F_p`` = filled panel, ``D_p`` centered,
``A_p = sqrt(rep) * D_p``):

    G += A_p A_p^T          # weighted Gram: the covariance's spectrum
    M += D_p A_p^T          # gives scores = M u / ||A^T u||
    S += F_p F_p^T          # gives the direction fix in closed form

- the top eigenvector ``u`` of ``G / (1 - sum(rep^2))`` is the Gram-trick
  principal component; ``||A^T u|| = sqrt(u^T G u)`` — no extra pass;
- ``scores = D @ loading = M u / ||A^T u||``;
- the direction fix needs only squared distances of projected outcome
  vectors, and ``||w^T F - rep^T F||^2 = (w - rep)^T S (w - rep)`` — so
  the ``ref_ind`` tie-break (identical to
  ``jax_kernels.direction_fixed_scores``, including normalize's zero-sum
  guard and the non-negative winning orientation) is O(R^2) arithmetic.

pass 2 (with the final reputation): per-panel outcome resolution,
certainty, and NA participation — all column-local given the reputation —
with the per-row ``na @ certainty`` partials accumulated panel by panel.

Host memory holds only E-vectors (fill, certainty, outcomes, ...); device
memory holds one panel plus three R x R accumulators. Algorithms (round 4
extended streaming to the FULL algorithm table):

- ``"sztorc"`` — as above;
- ``"fixed-variance"`` / ``"ica"`` — the full nonzero covariance spectrum
  already lives in the SAME Gram accumulator G (the eigh-gram route,
  streamed): top-k scores are ``M (U / ||A^T u_c||)``, explained
  fractions come from G's eigenvalues, per-component direction fixes run
  through the same S-based closed form, and ica's whitening/FastICA loop
  operates on the small (R, k) score block — no extra pass over the
  source beyond sztorc's;
- ``"hierarchical"`` / ``"dbscan"`` / ``"dbscan-jit"`` — the clustering
  variants: the R x R squared-distance matrix derives from S alone
  (``S_ii - 2 S_ij + S_jj``), so ONE pass accumulates it and every
  redistribution iteration is clustering arithmetic — host-side for the
  hybrids (pipeline._consensus_hybrid semantics, fill-pinned
  distances), fully on-device for dbscan-jit;
- ``"k-means"`` (out-of-core Lloyd — host-resident
  (k, E) centroids, two passes per Lloyd iteration; conformity = cluster
  reputation mass, the in-memory variant's rule; cross-panel accumulation
  order differs, so agreement is to accumulation precision — bit-exact in
  the x64 test harness, float-noise-level on an f32 device). Multi-host,
  the centroids stay event-local (each host owns the slices of its own
  panels) and only the (R, k) distance accumulator crosses hosts, once
  per Lloyd assignment pass.

Iterative redistribution (``max_iterations > 1``)
costs one accumulation pass per executed iteration, because G and M
follow the iterating reputation; S and the interpolate fill are pinned to
the initial reputation (reference semantics) and computed once.

Throughput is bound by the host->device link (every byte crosses twice):
on directly-attached hardware that is PCIe/DMA at tens of GB/s; through
the development tunnel it is orders of magnitude slower — verified
functionally there (outcomes bit-identical to the in-memory path at 1000
x 40k), sized for real deployments.
"""

from __future__ import annotations

import concurrent.futures
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..faults import plan as _faults
from ..models.pipeline import ConsensusParams
from ..ops import jax_kernels as jk
from ..ops import numpy_kernels as nk
from ..oracle import parse_event_bounds
from .mesh import effective_median_block

__all__ = ["streaming_consensus", "gram_dirfix", "gram_top_components",
           "gram_warm_pc", "gram_pc_scores", "assemble_light_result"]

#: R above which the streamed spectrum comes from orthogonal iteration on
#: the explicit Gram accumulator instead of ``jnp.linalg.eigh`` — the
#: same R<=4096 rule as jax_kernels.resolve_pca_method's Gram-eigh route.
#: First hardware contact (round 5): QDWH eigh at R=10000 allocated
#: dozens of ~300 MB triangular-solve temporaries and OOM'd the v5e HBM,
#: while one orth-iter sweep is a single 4R^2-byte matmul.
STREAM_EIGH_MAX_R = 4096


def _sym_topk(Gd, k: int, n_iters: int = 96, tol: float = 1e-7):
    """Top-``k`` eigenpairs of an explicit symmetric PSD matrix by
    blocked orthogonal iteration + final Rayleigh-Ritz rotation (the
    jax_kernels._top_pcs_orth_iter recipe, for a matrix that is already
    materialized): deterministic fixed-key start block, per-column
    alignment exit, ``eigh`` of the k x k projected matrix to rotate the
    converged block onto its eigenvector approximations. Returns
    ``(eigvals (k,) descending clipped, V (R, k))``."""
    R = Gd.shape[0]
    dtype = Gd.dtype
    v0 = jax.random.normal(jax.random.key(0), (R, k), dtype)
    V0, _ = jnp.linalg.qr(v0)

    def cond(state):
        i, _, done = state
        return (i < n_iters) & ~done

    def body(state):
        i, V, _ = state
        Q, _ = jnp.linalg.qr(Gd @ V)
        # degenerate-spectrum guard: QR of a ZERO product block yields
        # NaN columns — keep the previous orthonormal block, WHOLE-BLOCK
        # (the jax_kernels._top_pcs_orth_iter form): an elementwise
        # substitution would splice finite Q entries into V's columns,
        # handing a non-orthonormal mixed block to the alignment exit
        # (rank loss poisons whole columns, and |sum(Q*V)| >= 1-tol on a
        # mixed block can fire spuriously). A non-finite Gd must still
        # fail loudly (the poison below), not exit with the start block.
        Q = jnp.where(jnp.isfinite(Q).all(), Q, V)
        align = jnp.abs(jnp.sum(Q * V, axis=0))
        return i + 1, Q, jnp.all(align >= 1.0 - tol)

    _, V, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), V0, jnp.asarray(False)))
    H = V.T @ (Gd @ V)                          # (k, k) projected matrix
    hvals, W = jnp.linalg.eigh((H + H.T) * 0.5)
    order = jnp.argsort(hvals)[::-1]
    lam = jnp.clip(hvals[order], 0.0, None)
    V = V @ W[:, order]
    # loud-failure parity with the eigh branch: a non-finite accumulator
    # must surface as NaN outputs, not as a silently "converged" random
    # subspace (the in-loop guard above would otherwise mask it)
    gd_finite = jnp.all(jnp.isfinite(Gd))
    poison = jnp.asarray(jnp.nan, dtype)
    return (jnp.where(gd_finite, lam, poison),
            jnp.where(gd_finite, V, poison))


@functools.partial(jax.jit, static_argnames=("tolerance", "with_s",
                                             "with_gm"))
def _pass1_panel(panel, fill_rep, weight_rep, scaled, mins, maxs, valid,
                 tolerance: float, with_s: bool, with_gm: bool = True):
    """One event panel -> (G, M[, S]) contributions.

    ``fill_rep`` is the INITIAL reputation (interpolate fills are computed
    once, reference semantics); ``weight_rep`` is the current iteration's
    reputation (weighted means and the Gram weighting follow it).
    ``S = F F^T`` depends only on the filled matrix, which is fixed across
    iterations — ``with_s`` skips it after the first accumulation pass;
    ``with_gm=False`` (the hybrid-clustering pass, which only needs S)
    skips the centering and the two spectrum contractions instead.
    ``valid`` masks the zero-padded tail of the last panel out of every
    cross-panel accumulator."""
    acc = weight_rep.dtype
    rescaled = jk.rescale(panel, scaled, mins, maxs)
    filled, present = jk.interpolate_masked(rescaled, fill_rep, scaled,
                                            tolerance)
    F = jnp.where(valid[None, :], filled, 0.0)
    if with_gm:
        mu = weight_rep @ F                         # (P,), zero on padding
        D = jnp.where(valid[None, :], F - mu[None, :], 0.0)
        A = D * jnp.sqrt(jnp.clip(weight_rep, 0.0, None))[:, None]
        G = jnp.matmul(A, A.T, preferred_element_type=acc)
        M = jnp.matmul(D, A.T, preferred_element_type=acc)
    else:
        G = M = jnp.zeros((panel.shape[0], panel.shape[0]), dtype=acc)
    if with_s:
        S = jnp.matmul(F, F.T, preferred_element_type=acc)
        return G, M, S
    return G, M, jnp.zeros_like(G)


@functools.partial(jax.jit, static_argnames=("tolerance", "with_loading",
                                             "median_block"))
def _pass2_panel(panel, fill_rep, score_rep, final_rep, u_over_nAu, scaled,
                 mins, maxs, tolerance: float, with_loading: bool = True,
                 median_block: int = jk._MEDIAN_BLOCK):
    """Per-panel resolution with the final reputation: outcomes, certainty,
    participation columns, per-row NA partials, and this panel's slice of
    the first loading (``A^T u / ||A^T u||`` with ``score_rep``, the
    reputation of the last executed scoring iteration). The fill is
    recomputed with the INITIAL reputation (interpolate semantics)."""
    acc = final_rep.dtype
    rescaled = jk.rescale(panel, scaled, mins, maxs)
    filled, present = jk.interpolate_masked(rescaled, fill_rep, scaled,
                                            tolerance)
    raw, adjusted = jk.resolve_outcomes(present, filled, final_rep, scaled,
                                        tolerance, median_block=median_block)
    final = jk.unscale_outcomes(adjusted, scaled, mins, maxs)
    agree = jnp.where(
        scaled[None, :],
        jnp.abs(filled - adjusted[None, :]) <= tolerance,
        filled == adjusted[None, :])
    certainty = jnp.sum(agree * final_rep[:, None], axis=0)
    na = (~present).astype(acc)
    pcol = final_rep @ na                            # rep mass on NA
    prow = na @ certainty                            # per-row partials
    na_count = jnp.sum(na, axis=1)
    if with_loading:
        mu = score_rep @ filled
        A = (filled - mu[None, :]) * jnp.sqrt(
            jnp.clip(score_rep, 0.0, None))[:, None]
        loading = A.T @ u_over_nAu
    else:       # k-means has no loading; skip the centering matvec
        loading = jnp.zeros((panel.shape[1],), dtype=acc)
    return raw, adjusted, final, certainty, pcol, prow, na_count, loading


@functools.partial(jax.jit, static_argnames=("tolerance",))
def _kmeans_assign_panel(panel, fill_rep, cent_slice, valid,
                         scaled, mins, maxs, tolerance: float):
    """Partial squared distances of every reporter to every centroid over
    one event panel: sum_e (x_ie - c_je)^2, accumulated across panels on
    host. Fill semantics identical to the scoring passes."""
    rescaled = jk.rescale(panel, scaled, mins, maxs)
    filled, _ = jk.interpolate_masked(rescaled, fill_rep, scaled, tolerance)
    F = jnp.where(valid[None, :], filled, 0.0)
    C = jnp.where(valid[None, :], cent_slice, 0.0)       # (k, P)
    x2 = jnp.sum(F * F, axis=1)                          # (R,)
    c2 = jnp.sum(C * C, axis=1)                          # (k,)
    cross = F @ C.T                                      # (R, k)
    return x2[:, None] - 2.0 * cross + c2[None, :]


@functools.partial(jax.jit, static_argnames=("tolerance", "k"))
def _kmeans_update_panel(panel, fill_rep, labels, weight_rep, valid,
                         scaled, mins, maxs, tolerance: float, k: int):
    """Per-cluster weighted sums over one event panel — the numerators of
    the reputation-weighted centroid update (the (R,)-sized weights and
    counts are panel-invariant and computed on host)."""
    rescaled = jk.rescale(panel, scaled, mins, maxs)
    filled, _ = jk.interpolate_masked(rescaled, fill_rep, scaled, tolerance)
    F = jnp.where(valid[None, :], filled, 0.0)
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(F.dtype)
    weighted = (onehot * weight_rep[:, None]).T @ F       # (k, P)
    plain = onehot.T @ F                                  # (k, P)
    return weighted, plain


def _streaming_kmeans_seeds(panels, fill_rep, E, R, k: int, tol: float):
    """Seed centroids = the FILLED rows at the evenly-spaced seed indices,
    gathered on device panel by panel ((k, P) crosses the link, not the
    full panel). Depends only on the pinned fill reputation — computed
    once, reused across redistribution iterations."""
    from ..models import clustering as cl

    k = int(min(k, R))
    seeds = jnp.asarray(cl._seed_indices(R, k))
    # zeros, not empty: under multi-host each host fills only its own
    # panels' slices; the others stay zero and are never read (assignment
    # and update passes touch only local slices)
    centroids = np.zeros((k, E))
    for start, stop, block, sc, mn, mx, valid in panels():
        rows = _fill_rows_panel(block, fill_rep, seeds, sc, mn, mx, tol)
        centroids[:, start:stop] = np.asarray(rows)[:, :stop - start]
    return centroids


def _streaming_kmeans_conformity(panels, fill_rep, rep, seed_centroids,
                                 P, k: int,
                                 n_iters: int, tol: float, dtype,
                                 allreduce=None):
    """Out-of-core Lloyd following clustering.kmeans_conformity_np's
    rules (summation order differs across panels — agreement is to
    accumulation precision): evenly-spaced-row seeding, reputation-weighted centroid updates (empty
    clusters keep their centroid, zero-reputation clusters fall back to
    the plain mean), final assignment against the final centroids. Two
    passes over the source per Lloyd iteration plus one final assignment
    pass; centroids live on host as a (k, E) array.

    Multi-host (``allreduce`` given): centroids stay EVENT-LOCAL — every
    centroid slice derives solely from the panels of the host that owns
    them (seed rows, update numerators, and the keep-old fallback are all
    per-event), so the only cross-host state is the (R, k) squared-
    distance accumulator, summed once per assignment pass. Labels, the
    global cluster weights/counts, and the returned conformity are then
    identical on every host."""
    R = rep.shape[0]
    k = int(min(k, R))
    centroids = seed_centroids.copy()

    def assign(cents):
        """One full assignment pass: accumulate squared distances panel by
        panel against ``cents`` and argmin on host."""
        d2 = np.zeros((R, k))
        for start, stop, block, sc, mn, mx, valid in panels():
            cent = jnp.asarray(
                np.pad(cents[:, start:stop],
                       ((0, 0), (0, P - (stop - start)))), dtype=dtype)
            d2 += np.asarray(_kmeans_assign_panel(
                block, fill_rep, cent, valid, sc, mn, mx, tol))
        if allreduce is not None:     # disjoint event partials -> full d2
            d2 = np.asarray(allreduce(d2), dtype=float)
        return np.argmin(d2, axis=1)

    for _ in range(n_iters):
        labels = assign(centroids)
        onehot = labels[:, None] == np.arange(k)[None, :]
        wsum = (onehot * np.asarray(rep)[:, None]).sum(axis=0)   # (k,)
        counts = onehot.sum(axis=0)
        new_centroids = centroids.copy()
        for start, stop, block, sc, mn, mx, valid in panels():
            weighted, plain = _kmeans_update_panel(
                block, fill_rep, jnp.asarray(labels), rep, valid,
                sc, mn, mx, tol, k)
            w = np.asarray(weighted)[:, :stop - start]
            pl = np.asarray(plain)[:, :stop - start]
            upd = np.where(
                wsum[:, None] > 0.0,
                w / np.where(wsum > 0.0, wsum, 1.0)[:, None],
                np.where(counts[:, None] > 0.0,
                         pl / np.clip(counts, 1.0, None)[:, None],
                         centroids[:, start:stop]))
            new_centroids[:, start:stop] = upd
        centroids = new_centroids

    # final assignment against the final centroids (parity with the
    # in-memory post-loop assignment)
    labels = assign(centroids)
    onehot = labels[:, None] == np.arange(k)[None, :]
    mass = (onehot * np.asarray(rep)[:, None]).sum(axis=0)
    return jnp.asarray(mass[labels], dtype=dtype)


@functools.partial(jax.jit, static_argnames=("tolerance",))
def _fill_rows_panel(panel, fill_rep, rows, scaled, mins, maxs,
                     tolerance: float):
    """The filled values of ``rows`` only — a (k, P) gather on device, so
    the seeding pass never ships the full (R, P) panel to host."""
    rescaled = jk.rescale(panel, scaled, mins, maxs)
    filled, _ = jk.interpolate_masked(rescaled, fill_rep, scaled, tolerance)
    return filled[rows]


def gram_dirfix(scores, rep_ref, S):
    """``direction_fixed_scores`` in closed form over the ``S = F F^T``
    accumulator: ``||w^T F - rep^T F||^2 = (w-rep)^T S (w-rep)`` — same
    normalize guard, tie-break, and non-negative winning orientation as
    every other decision site. Module-level (extracted from the
    streaming driver's closure) so the serving layer's market sessions
    score off their incrementally-accumulated statistics through the
    IDENTICAL arithmetic."""
    scores = jk.canon_sign(scores)
    set1 = scores + jnp.abs(jnp.min(scores))
    set2 = scores - jnp.max(scores)

    def sq_dist_to_old(w):
        d = w - rep_ref
        return d @ S @ d

    d1 = sq_dist_to_old(jk.normalize(set1))
    d2 = sq_dist_to_old(jk.normalize(set2))
    # banded tie, identical rule to every other decision site
    # (ops.numpy_kernels.DIRFIX_TIE_ATOL — see its sizing note)
    return jnp.where(d1 - d2 <= nk.DIRFIX_TIE_ATOL * (d1 + d2),
                     set1, -set2)


def gram_warm_pc(G, rep_ref, warm_u, n_iters: int = 96,
                 tol: float = 0.0):
    """Dominant eigenpair of the normalized Gram accumulator
    ``Gd = G / (1 - sum(rep^2))`` by power iteration warm-started from
    ``warm_u`` — the previous round's principal component. Across
    serving rounds the reputation and the market's report distribution
    move a little, so ``Gd`` moves a little and the stale eigenvector is
    an excellent start: the alignment early exit fires after a few
    O(R²) matvecs where a cold eigh pays O(R³) every time (the
    ``bucket_incremental`` marginal-resolve algebra). Safety inherits
    :func:`..ops.jax_kernels._power_loop`'s warm-seed blend — a stale
    vector can never pass the self-consistency exit while sitting on a
    demoted eigenvector, because the cold dense seed is mixed back in.
    ``warm_u=None`` / all-zero falls back to the cold deterministic
    seed (bitwise the cold start). Returns ``(u, sweeps)`` — the
    unit-norm dominant eigenvector approximation and the executed
    in-loop matvec count (the warm-start savings observable)."""
    denom = 1.0 - jnp.sum(rep_ref ** 2)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    Gd = G / denom
    return jk._power_loop(lambda v: Gd @ v, G.shape[0], Gd.dtype,
                          n_iters, tol, v_init=warm_u)


def gram_pc_scores(G, M, u):
    """Scores + first-loading operand from ONE principal component of
    the Gram accumulator: ``||A^T u|| = sqrt(u^T G u)`` (no extra pass
    over the source), ``scores = M (u / ||A^T u||)``. The SINGLE copy
    of the k=1 scoring identity — :func:`gram_top_components`' warm
    branch and the serve layer's ``bucket_incremental`` kernel both
    score through here, so the parity the tier's drift band depends on
    can never drift between two hand-maintained copies. Returns
    ``(scores (R,), u_over_nAu (R,), nAu scalar)``."""
    nAu = jnp.sqrt(jnp.clip(u @ (G @ u), 0.0, None))
    u_over_nAu = u / jnp.where(nAu == 0.0, 1.0, nAu)
    return M @ u_over_nAu, u_over_nAu, nAu


def gram_top_components(G, M, rep_ref, k: int, warm_u=None, delta=None,
                        warm_iters: int = 96, warm_tol: float = 0.0):
    """Top-k loadings' scores + explained fractions off the Gram
    accumulator (the full nonzero covariance spectrum lives in G —
    jax_kernels.weighted_prin_comps' eigh-gram route, streamed).
    Returns ``(scores (R, k), explained (k,), U (R, k), nAu (k,))``.

    ``delta`` (optional, ``(dG, dM)``): an appended-block low-rank
    update folded in before solving — callers holding pinned base
    statistics (e.g. a speculative resolve that must not mutate a
    session's accumulators) pass the block's ``_pass1_panel``
    contributions here instead of materializing updated copies.
    ``warm_u`` (optional, k=1 only): an eigenpair warm start — the
    spectrum comes from :func:`gram_warm_pc`'s warm-started power
    iteration instead of a cold ``eigh``/orthogonal-iteration solve.
    This is the ``bucket_incremental`` serve tier's marginal-resolve
    path (docs/SERVING.md): continuous outputs then sit within the
    documented drift band of the exact solve rather than matching it
    bitwise, which is why the tier pins an exact refresh every K
    rounds.

    Above ``STREAM_EIGH_MAX_R`` reporters the top-k subspace comes
    from blocked orthogonal iteration on the explicit symmetric
    accumulator instead of ``jnp.linalg.eigh`` — round-5 first
    hardware contact (VERDICT r4 item 1 precedent confirmed): the
    QDWH eigh's triangular-solve temporaries at R=10000 exceeded the
    chip's HBM (dozens of ~300 MB buffers), while an orth-iter sweep
    is one 4R² byte matmul. The threshold mirrors
    ``jax_kernels.resolve_pca_method``'s R<=4096 Gram-eigh rule; the
    total variance uses ``trace(G)/denom`` (= the full eigvalue sum)
    so explained fractions need no full spectrum. Module-level
    (extracted from the streaming driver's closure) — shared with the
    serving layer's session resolution."""
    if delta is not None:
        dG, dM = delta
        G = G + dG
        M = M + dM
    R = G.shape[0]
    denom = 1.0 - jnp.sum(rep_ref ** 2)
    denom = jnp.where(denom == 0.0, 1.0, denom)
    Gd = G / denom
    if warm_u is not None:
        if k != 1:
            raise ValueError(
                f"gram_top_components: an eigenpair warm start serves "
                f"the dominant component only (k=1), got k={k}")
        u, _ = gram_warm_pc(G, rep_ref, warm_u, n_iters=warm_iters,
                            tol=warm_tol)
        U = u[:, None]                                # (R, 1)
        lam = jnp.clip(u @ (Gd @ u), 0.0, None)[None]  # Rayleigh value
        total = jnp.clip(jnp.trace(Gd), 0.0, None)
        scores_1, _, nAu_1 = gram_pc_scores(G, M, u)
        scores, nAu = scores_1[:, None], nAu_1[None]
    elif R <= STREAM_EIGH_MAX_R:
        eigvals, eigvecs = jnp.linalg.eigh(Gd)
        lam = jnp.clip(eigvals[::-1][:k], 0.0, None)
        U = eigvecs[:, ::-1][:, :k]                   # (R, k)
        total = jnp.sum(jnp.clip(eigvals, 0.0, None))
    else:
        obs.counter(
            "pyconsensus_streaming_topk_fallback_total",
            "streamed spectra taken via orthogonal iteration instead "
            "of eigh (R > STREAM_EIGH_MAX_R)").inc()
        lam, U = _sym_topk(Gd, k)
        total = jnp.clip(jnp.trace(Gd), 0.0, None)
    if warm_u is None:
        # ||A^T u_c|| = sqrt(u_c^T G u_c) — no extra pass over the
        # source (the warm branch scored above via gram_pc_scores)
        nAu = jnp.sqrt(jnp.clip(jnp.sum(U * (G @ U), axis=0), 0.0,
                                None))
        scores = M @ (U / jnp.where(nAu == 0.0, 1.0, nAu)[None, :])
    # explained-variance discrepancy bound across the
    # STREAM_EIGH_MAX_R switch: below the cap, lam and total come
    # from the SAME eigh, so the fractions equal the in-memory
    # eigh-gram route exactly. Above it, lam are Rayleigh-Ritz
    # values of the converged orth-iter block — each lam_c lies in
    # [eig_c - r_c, eig_c] with r_c the block residual, and the
    # per-column alignment exit at 1 - tol (tol = 1e-7) bounds the
    # principal angle by sqrt(2*tol), hence r_c <= 2*tol*eig_1 —
    # while total = trace(Gd) is the exact full eigenvalue sum. Each
    # fraction is therefore UNDER-estimated by at most
    # 2*tol*eig_1/total ~ 2e-7: orders of magnitude below the
    # variance_threshold granularity fixed-variance cuts on, so the
    # component count never flips across the switch.
    explained = jnp.where(total > 0.0,
                          lam / jnp.where(total > 0.0, total, 1.0),
                          jnp.zeros_like(lam))
    return scores, explained, U, nAu


def _default_allreduce(x):
    """Cross-process sum via the jax distributed runtime (requires
    ``parallel.initialize``); the ``allreduce=`` hook exists so tests and
    custom deployments can substitute their own reduction."""
    from jax.experimental import multihost_utils

    return jnp.sum(multihost_utils.process_allgather(jnp.asarray(x)), axis=0)


def streaming_consensus(reports_src, reputation=None, event_bounds=None,
                        panel_events: int = 8192,
                        params: Optional[ConsensusParams] = None,
                        mesh=None, host_id: Optional[int] = None,
                        n_hosts: Optional[int] = None,
                        allreduce=None, staging_dir=None) -> dict:
    """Resolve an oracle whose reports matrix never fits on device.

    ``reports_src``: numpy array / ``np.memmap`` / path to an ``.npy``
    file (loaded memory-mapped) or a ``.csv`` file (staged incrementally
    to a temporary ``.npy`` via :func:`..io.csv_to_npy` — chunked parse,
    so peak host memory stays one row-chunk even for text files bigger
    than RAM; the staging file is removed after resolution). The staging
    file goes to ``staging_dir`` if given, else beside the source CSV —
    NOT the system temp dir, which is often a RAM-backed tmpfs where a
    bigger-than-RAM staging file would defeat the out-of-core design —
    falling back to the system temp dir only when the source directory
    is not writable.
    Returns the light result dict as host numpy arrays. See the module
    docstring for the pass structure (``executed iterations + 1``) and
    restrictions.

    ``mesh``: optional device mesh — each streamed panel is placed with
    its event axis sharded over the mesh, so the out-of-core path uses
    EVERY chip's HBM bandwidth (the per-panel contractions reduce over
    the sharded axis; GSPMD inserts the partial-sum collectives and the
    R×R accumulators come back replicated). ``panel_events`` is rounded
    up to a multiple of the mesh's event-axis size.

    ``n_hosts > 1``: multi-host out-of-core (every algorithm) — each host
    streams only panels ``host_id::n_hosts`` (``host_id`` defaults to
    ``jax.process_index()``), the R×R sufficient statistics all-reduce
    across hosts once per iteration (k-means instead all-reduces its
    (R, k) distance accumulator once per Lloyd assignment pass — its
    centroid slices are event-local and never leave the owning host),
    and the disjoint per-panel output slices sum-reduce at the end, so
    every host returns the identical full result. ``allreduce`` defaults to a
    ``jax.distributed``/``process_allgather`` sum; pass a custom
    callable for other transports. Composes with ``mesh`` (each host's
    local chips shard its panels).
    """
    staged = None
    is_path = (isinstance(reports_src, (str, bytes))
               or hasattr(reports_src, "__fspath__"))
    if is_path:
        import pathlib
        import tempfile

        src_path = pathlib.Path(
            reports_src if not isinstance(reports_src, bytes)
            else reports_src.decode())
        if src_path.suffix == ".csv":
            # a per-call unique temp file (a fixed name would let two
            # concurrent resolutions of the same CSV truncate each other's
            # staging mid-mmap), placed on real disk beside the source —
            # the system temp dir is often RAM-backed tmpfs, where a
            # bigger-than-RAM staging file would defeat out-of-core — with
            # a tempdir fallback only for read-only source directories
            kw = dict(suffix=".npy", prefix=f"{src_path.stem}-stage-")
            try:
                fd, name = tempfile.mkstemp(
                    dir=staging_dir if staging_dir is not None
                    else src_path.parent, **kw)
            except OSError:
                if staging_dir is not None:
                    raise
                fd, name = tempfile.mkstemp(**kw)
            os.close(fd)
            staged = pathlib.Path(name)
    # the unlink must also cover a failure *during* staging (ENOSPC,
    # malformed CSV row) — especially now that staging lands beside the
    # user's data instead of in the system temp dir
    try:
        if staged is not None:
            from ..io import csv_to_npy, load_reports

            csv_to_npy(src_path, staged)
            reports_src = load_reports(staged, mmap=True)
        elif is_path:
            from ..io import load_reports

            reports_src = load_reports(reports_src, mmap=True)
        p = params if params is not None else ConsensusParams()
        shape = tuple(getattr(reports_src, "shape", ()))  # impl validates
        with obs.span("streaming.consensus", algorithm=p.algorithm,
                      shape=str(shape), panel_events=int(panel_events),
                      multihost=bool(n_hosts and int(n_hosts) > 1)):
            return _streaming_consensus_impl(reports_src, reputation,
                                             event_bounds, panel_events,
                                             params, mesh, host_id, n_hosts,
                                             allreduce)
    finally:
        if staged is not None:
            staged.unlink(missing_ok=True)


def _streaming_consensus_impl(reports_src, reputation, event_bounds,
                              panel_events, params, mesh=None,
                              host_id=None, n_hosts=None, allreduce=None):
    if reports_src.ndim != 2:
        raise ValueError(f"reports must be 2-D, got {reports_src.shape}")
    R, E = reports_src.shape
    p = params if params is not None else ConsensusParams()
    if p.algorithm not in ("sztorc", "k-means", "ica", "fixed-variance",
                           "hierarchical", "dbscan", "dbscan-jit"):
        raise ValueError(
            f"streaming_consensus: unknown algorithm {p.algorithm!r} "
            "(every algorithm streams since round 4: the multi-component "
            "spectrum comes from the same R x R Gram accumulator, and "
            "the clustering distance matrices derive from the S = F F^T "
            "accumulator)")
    P = int(panel_events)
    if P < 1:
        raise ValueError("panel_events must be >= 1")
    multi = n_hosts is not None and int(n_hosts) > 1
    if multi:
        if host_id is None:
            host_id = jax.process_index()
        host_id, n_hosts = int(host_id), int(n_hosts)
        if not 0 <= host_id < n_hosts:
            raise ValueError(f"host_id {host_id} not in [0, {n_hosts})")
        if allreduce is None:
            # the default reduction spans jax.process_count() processes:
            # fewer declared hosts would deadlock the surplus processes
            # inside the collective, more would silently drop the panels
            # assigned to hosts that don't exist
            if n_hosts != jax.process_count():
                raise ValueError(
                    f"n_hosts={n_hosts} but the jax distributed runtime "
                    f"has {jax.process_count()} process(es); pass a "
                    "custom allreduce to use a different host group")
            allreduce = _default_allreduce
    else:
        if allreduce is not None:
            raise ValueError("allreduce given without n_hosts > 1 — the "
                             "multi-host split never engages; pass "
                             "n_hosts (and optionally host_id)")
        allreduce = None
    panel_shard = vec_shard = None
    if mesh is not None:
        if "event" not in mesh.axis_names:
            raise ValueError(f"streaming mesh must have an 'event' axis to "
                             f"shard panels over, got axes "
                             f"{mesh.axis_names}")
        P = -(-P // mesh.shape["event"]) * mesh.shape["event"]  # shardable
        panel_shard = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, "event"))
        vec_shard = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("event"))

    scaled_all, mins_all, maxs_all = parse_event_bounds(event_bounds, E)
    dtype = jnp.asarray(0.0).dtype
    if reputation is None:
        reputation = np.full((R,), 1.0 / R)
    old_rep = nk.normalize(np.asarray(reputation, dtype=float))
    fill_rep = jnp.asarray(old_rep, dtype=dtype)
    tol = float(p.catch_tolerance)

    def _prepare(start: int):
        stop = min(start + P, E)
        # convert straight to the device dtype: one host copy per panel,
        # half the bytes of a float64 detour
        block = np.asarray(reports_src[:, start:stop], dtype=np.dtype(dtype))
        # chaos hook (host-side, pre-device): a poisoned panel exercises
        # the accumulator NaN-poison contract (_sym_topk / eigh parity —
        # loud failure, never a silently wrong spectrum). Zero overhead
        # disarmed (one global None test).
        block = _faults.corrupt("streaming.panel", block)
        width = stop - start
        if width < P:                          # zero-pad the ragged tail
            block = np.pad(block, ((0, 0), (0, P - width)))
        valid = np.zeros(P, dtype=bool)
        valid[:width] = True
        sc = np.pad(scaled_all[start:stop], (0, P - width))
        mn = np.asarray(np.pad(mins_all[start:stop], (0, P - width)),
                        dtype=np.dtype(dtype))
        mx = np.asarray(np.pad(maxs_all[start:stop], (0, P - width),
                               constant_values=1.0), dtype=np.dtype(dtype))
        if panel_shard is not None:
            # place this panel event-sharded across the mesh straight
            # from the HOST arrays (device_put on numpy ships each shard
            # to its own device once — an asarray detour would stage the
            # whole panel through the default device and double the
            # traffic on the bandwidth-bound ingest link); the panel
            # contractions then reduce over the sharded axis on every
            # chip, with GSPMD inserting the psum of the R x R partials
            return (start, stop,
                    jax.device_put(block, panel_shard),
                    jax.device_put(sc, vec_shard),
                    jax.device_put(mn, vec_shard),
                    jax.device_put(mx, vec_shard),
                    jax.device_put(valid, vec_shard))
        return (start, stop, jnp.asarray(block, dtype=dtype),
                jnp.asarray(sc), jnp.asarray(mn, dtype=dtype),
                jnp.asarray(mx, dtype=dtype), jnp.asarray(valid))

    def panels():
        # one-deep prefetch: the NEXT panel's memmap read / dtype
        # conversion / host->device transfer overlaps the CURRENT panel's
        # device compute (jax dispatch is async) — on directly-attached
        # hardware this hides most of the PCIe time behind the kernels
        starts = list(range(0, E, P))
        if multi:                          # this host's round-robin slice
            starts = starts[host_id::n_hosts]
        if not starts:                     # E == 0 / more hosts than panels
            return
        panel_count = obs.counter(
            "pyconsensus_streaming_panels_total",
            "event panels streamed from the source (all passes)")
        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            pending = pool.submit(_prepare, starts[0])
            for nxt in starts[1:]:
                ready = pending.result()
                pending = pool.submit(_prepare, nxt)
                panel_count.inc()
                yield ready
            panel_count.inc()
            yield pending.result()

    # ---- scoring iterations: one accumulation pass per iteration --------
    # (the G/M statistics follow the iterating reputation; S = F F^T is
    # fixed because the interpolate fill is pinned to the initial
    # reputation — reference semantics)
    rep_k = fill_rep
    this_rep = fill_rep
    S = None
    kmeans_seeds = None
    sq_dists = None
    dbscan_same = None
    ica_converged = None
    converged = False
    iterations = 0
    score_rep = fill_rep
    u_over_nAu = jnp.zeros((R,), dtype=dtype)

    def dirfix_S(scores, rep_ref):
        """:func:`gram_dirfix` against the run's fill-pinned S."""
        return gram_dirfix(scores, rep_ref, S)

    def accumulate_stats(weight_rep, with_s, with_gm=True):
        """One pass over the source: (G, M[, S]) with the given Gram
        weighting, allreduced across hosts when multi-host.
        ``with_gm=False`` accumulates S only (the hybrid-clustering
        pass — the spectrum contractions would be discarded)."""
        G = jnp.zeros((R, R), dtype=dtype)
        M = jnp.zeros((R, R), dtype=dtype)
        S_acc = jnp.zeros((R, R), dtype=dtype) if with_s else None
        with obs.span("streaming.accumulate_pass", with_s=with_s,
                      with_gm=with_gm) as sp:
            for _, _, block, sc, mn, mx, valid in panels():
                dG, dM, dS = _pass1_panel(block, fill_rep, weight_rep, sc,
                                          mn, mx, valid, tol, with_s,
                                          with_gm)
                if with_gm:
                    G, M = G + dG, M + dM
                if with_s:
                    S_acc = S_acc + dS
            sp.observe([x for x in (G, M, S_acc) if x is not None])
        if allreduce is not None:
            # sum the R x R partials across hosts in ONE stacked
            # collective (each allreduce is a blocking DCN round-trip);
            # every host then runs the identical eigh/score/
            # redistribution arithmetic
            stats = ([G, M] if with_gm else []) + ([S_acc] if with_s
                                                  else [])
            reduced = allreduce(jnp.stack(stats))
            if with_gm:
                G, M = reduced[0], reduced[1]
            if with_s:
                S_acc = reduced[-1]
        return G, M, S_acc

    def top_components(G, M, rep_ref, k):
        """:func:`gram_top_components` (module-level since the serve
        refactor — sessions share the identical scoring arithmetic)."""
        return gram_top_components(G, M, rep_ref, k)

    for _ in range(max(p.max_iterations, 1)):
        if p.algorithm == "k-means":
            from ..models.clustering import KMEANS_ITERS

            if kmeans_seeds is None:        # fill-pinned: compute once
                kmeans_seeds = _streaming_kmeans_seeds(
                    panels, fill_rep, E, R, p.num_clusters, tol)
            adj = _streaming_kmeans_conformity(
                panels, fill_rep, rep_k, kmeans_seeds, P,
                p.num_clusters, KMEANS_ITERS, tol, dtype,
                allreduce=allreduce)
        elif p.algorithm in ("hierarchical", "dbscan", "dbscan-jit"):
            from ..models import clustering as cl

            if sq_dists is None:
                # the clustering inputs are fill-pinned, so ONE pass over
                # the source serves every redistribution iteration: the
                # R x R squared distances derive from S alone —
                # ||f_i - f_j||^2 = S_ii - 2 S_ij + S_jj
                _, _, S = accumulate_stats(fill_rep, True, with_gm=False)
                d = jnp.diag(S)
                sq_dists = jnp.clip(d[:, None] - 2.0 * S + d[None, :],
                                    0.0, None)
                if p.algorithm != "dbscan-jit":   # host clustering input
                    sq_dists = np.asarray(sq_dists, dtype=np.float64)
            if p.algorithm == "dbscan-jit":
                # fully on-device: the label propagation is
                # reputation-independent, so cluster ONCE against the
                # fill-pinned distances and pay one matvec per iteration
                if dbscan_same is None:
                    dbscan_same = jax.jit(cl.dbscan_jit_same_matrix_jax,
                                          static_argnames=(
                                              "eps", "min_samples",
                                              "dtype"))(
                        sq_dists, eps=float(p.dbscan_eps),
                        min_samples=int(p.dbscan_min_samples),
                        dtype=dtype)
                adj = dbscan_same @ rep_k
            else:
                placeholder = np.empty((R, 0))
                rep_host = np.asarray(rep_k, dtype=np.float64)
                if p.algorithm == "hierarchical":
                    adj = cl.hierarchical_conformity(
                        placeholder, rep_host, p.hierarchy_threshold,
                        sq_dists=sq_dists)
                else:
                    adj = cl.dbscan_conformity(
                        placeholder, rep_host, p.dbscan_eps,
                        p.dbscan_min_samples, sq_dists=sq_dists)
                adj = jnp.asarray(adj, dtype=dtype)
        else:
            G, M, S_acc = accumulate_stats(rep_k, S is None)
            if S is None:
                S = S_acc
            if p.algorithm == "sztorc":
                # k=1 of the shared eigh-gram scorer (eigvecs[:, -1] is
                # exactly U[:, 0])
                scores_k, _, U, nAu = top_components(G, M, rep_k, 1)
                u_over_nAu = U[:, 0] / jnp.where(nAu[0] == 0.0, 1.0,
                                                 nAu[0])
                adj = dirfix_S(scores_k[:, 0], rep_k)
            elif p.algorithm == "fixed-variance":
                from ..models.sztorc import _component_weights_jax

                k = int(min(p.max_components, min(R, E)))
                scores, explained, U, nAu = top_components(G, M, rep_k, k)
                w = _component_weights_jax(explained, p.variance_threshold)
                adj = jnp.zeros((R,), dtype=dtype)
                for c in range(k):
                    adj = adj + w[c] * dirfix_S(scores[:, c], rep_k)
                u_over_nAu = U[:, 0] / jnp.where(nAu[0] == 0.0, 1.0,
                                                 nAu[0])
            else:                            # ica
                from ..models.ica import (_EPS, _canon_signs_jax,
                                          _conv_tol, _fastica_one_unit)

                k = max(1, int(min(p.max_components, min(R, E) - 1)))
                scores, _, _, _ = top_components(G, M, rep_k, k)
                std = jnp.sqrt(jnp.clip(jnp.var(scores, axis=0), _EPS,
                                        None))
                Z = _canon_signs_jax(scores / std[None, :])
                w_ica, conv = _fastica_one_unit(Z, _conv_tol(Z.dtype))
                ica_converged = bool(conv)
                adj = dirfix_S(Z @ w_ica, rep_k)
        this_rep = jk.row_reward_weighted(adj, rep_k)
        new_rep = jk.smooth(this_rep, rep_k, p.alpha)
        delta = float(jnp.max(jnp.abs(new_rep - rep_k)))
        obs.histogram(
            "pyconsensus_convergence_residual",
            "max-abs reputation change per redistribution iteration",
            labels=("backend",), buckets=obs.MAGNITUDE_BUCKETS).observe(
                delta, backend="streaming")
        score_rep = rep_k
        rep_k = new_rep
        iterations += 1
        if delta <= p.convergence_tolerance:
            converged = True
            break
    smooth_rep = rep_k
    obs.counter(
        "pyconsensus_consensus_total",
        "finished consensus() resolutions",
        labels=("algorithm", "backend", "converged")).inc(
            algorithm=p.algorithm, backend="streaming",
            converged=str(bool(converged)).lower())
    obs.histogram(
        "pyconsensus_consensus_iterations",
        "reputation-redistribution iterations per consensus() call",
        labels=("algorithm", "backend"),
        buckets=obs.ITERATION_BUCKETS).observe(
            iterations, algorithm=p.algorithm, backend="streaming")

    # ---- pass 2: per-panel resolution with the final reputation ---------
    # (zeros, not empty: under multi-host each host fills only its
    # disjoint panel slices and the final sum-allreduce assembles them)
    outcomes_raw = np.zeros(E)
    outcomes_adjusted = np.zeros(E)
    outcomes_final = np.zeros(E)
    certainty = np.zeros(E)
    pcols = np.zeros(E)
    first_loading = np.zeros(E)
    prow = np.zeros(R)
    na_count = np.zeros(R)
    with obs.span("streaming.resolve_pass", algorithm=p.algorithm):
        for start, stop, block, sc, mn, mx, _ in panels():
            raw, adjd, fin, cert, pc, pr, nc, ld = _pass2_panel(
                block, fill_rep, score_rep, smooth_rep, u_over_nAu, sc, mn,
                mx, tol,
                with_loading=p.algorithm in ("sztorc", "fixed-variance"),
                median_block=effective_median_block(p.median_block, mesh))
            width = stop - start
            outcomes_raw[start:stop] = np.asarray(raw)[:width]
            outcomes_adjusted[start:stop] = np.asarray(adjd)[:width]
            outcomes_final[start:stop] = np.asarray(fin)[:width]
            certainty[start:stop] = np.asarray(cert)[:width]
            pcols[start:stop] = 1.0 - np.asarray(pc)[:width]
            first_loading[start:stop] = np.asarray(ld)[:width]
            prow += np.asarray(pr)   # padded cols: certainty * na(=0) = 0
            na_count += np.asarray(nc)
    if allreduce is not None:
        # disjoint panel slices + zero elsewhere: the cross-host sum IS
        # the assembly; the row partials are genuine additive reductions.
        # Stacked into two collectives (one (6, E), one (2, R)) — each
        # allreduce is a blocking DCN round-trip, so eight sequential
        # calls would serialize eight of them per resolution
        e_stack = np.asarray(allreduce(np.stack(
            [outcomes_raw, outcomes_adjusted, outcomes_final, certainty,
             pcols, first_loading])), dtype=float)
        (outcomes_raw, outcomes_adjusted, outcomes_final, certainty,
         pcols, first_loading) = e_stack
        r_stack = np.asarray(allreduce(np.stack([prow, na_count])),
                             dtype=float)
        prow, na_count = r_stack
    first_loading = nk.canon_sign(first_loading)
    result_extra = ({"first_loading": first_loading}
                    if p.algorithm in ("sztorc", "fixed-variance") else {})
    if p.algorithm == "ica":
        # the chaotic-fallback observability flag, like every other path
        result_extra["ica_converged"] = bool(ica_converged)

    return assemble_light_result(
        old_rep, this_rep, smooth_rep, na_count, outcomes_raw,
        outcomes_adjusted, outcomes_final, iterations, converged,
        certainty, pcols, prow, result_extra)


def assemble_light_result(old_rep, this_rep, smooth_rep, na_count,
                          outcomes_raw, outcomes_adjusted, outcomes_final,
                          iterations, converged, certainty, pcols, prow,
                          result_extra=None) -> dict:
    """Finalize the bonus accounting (numpy_kernels semantics) from the
    panel-accumulated pieces and assemble the light result dict — the
    shared tail of the streaming driver and the serve layer's market
    sessions (which accumulate the identical pieces incrementally).
    ``pcols`` is ``participation_columns``; ``prow`` the per-row
    ``na @ certainty`` partials."""
    total_cert = certainty.sum()
    consensus_reward = nk.normalize(certainty)
    participation_rows = 1.0 - (prow if total_cert == 0.0
                                else prow / total_cert)
    percent_na = 1.0 - pcols.mean()
    na_bonus_rows = nk.normalize(participation_rows)
    smooth_np = np.asarray(smooth_rep, dtype=float)
    reporter_bonus = (na_bonus_rows * percent_na
                      + smooth_np * (1.0 - percent_na))
    na_bonus_cols = nk.normalize(pcols)
    author_bonus = (na_bonus_cols * percent_na
                    + consensus_reward * (1.0 - percent_na))
    return {
        "old_rep": old_rep,
        "this_rep": np.asarray(this_rep, dtype=float),
        "smooth_rep": smooth_np,
        "na_row": na_count > 0,
        "outcomes_raw": outcomes_raw,
        "outcomes_adjusted": outcomes_adjusted,
        "outcomes_final": outcomes_final,
        "iterations": iterations,
        "convergence": converged,
        "certainty": certainty,
        "consensus_reward": consensus_reward,
        "avg_certainty": float(certainty.mean()),
        "participation_columns": pcols,
        "participation_rows": participation_rows,
        "percent_na": float(percent_na),
        "na_bonus_rows": na_bonus_rows,
        "reporter_bonus": reporter_bonus,
        "na_bonus_cols": na_bonus_cols,
        "author_bonus": author_bonus,
        **(result_extra or {}),
    }
