"""Event-sharded consensus on the FUSED storage kernels (shard_map).

The GSPMD path (``sharded.py``) treats a Pallas kernel as a black box, so
multi-chip meshes previously fell back to XLA matvecs — paying two HBM
passes per power sweep on bf16 storage where the single-device fused path
pays one on int8. This module recovers the kernel path on meshes by
placing the collectives EXPLICITLY: each shard runs the storage kernels
on its local (R, E/n) block under :func:`jax.shard_map`, and the (R,)- or
scalar-sized cross-shard reductions are hand-placed ``psum``\\ s
(docs/SCALING.md's round-4 lever, pulled into round 3).

What is fundamentally different from the single-device fused path: the
one-pass covariance application (``apply_weighted_cov``) fuses ``t = Dv``
and ``y = D^T(rep*t)`` into one HBM sweep, which requires all of ``t``
locally — but on an event-sharded mesh ``t`` is a cross-shard sum, so the
sweep necessarily splits into two kernel passes with a 40 KB (R,) psum
between them (:func:`pallas_kernels.storage_matvec` then
:func:`pallas_kernels.storage_rows_matmat`). The win over the XLA mesh
path is therefore NOT pass count (both pay two) but storage bytes: the
kernels decode int8 sentinel storage in-register, so each pass streams
1-byte elements instead of the XLA path's bf16 — and the entire back half
(outcomes + certainty + participation) stays ONE fused kernel sweep per
shard (its outputs are per-column, hence shard-local).

Scope (gate-enforced by ``sharded._use_fused_resolution``): sztorc,
power-family PCA, binary events only (the scaled-column gather would
cross shards), E divisible by the event-axis size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.pipeline import ConsensusParams, _fill_stats, _masked_mu
from ..ops import jax_kernels as jk
from .mesh import Mesh

__all__ = ["fused_sharded_consensus"]


def _psum(x):
    return lax.psum(x, "event")


def _gnorm(v):
    """Global L2 norm of an event-sharded vector."""
    return jnp.sqrt(_psum(jnp.sum(v * v)))


def _sharded_power(apply_cov, seed, base_unit, n_iters: int, tol: float,
                   v_init=None):
    """jax_kernels._power_loop with every norm / alignment dot promoted to
    a global (psum) reduction; iterates are the local (E_loc,) slices of
    the global vector. Semantics mirrored exactly: cold start applies the
    covariance to the fixed seed slice; warm ``v_init`` is blended with
    the base direction (see _power_loop's crossing rationale); ``tol < 0``
    disables the early exit."""
    dtype = seed.dtype
    no_exit = tol < 0
    tol = max(float(tol), 8.0 * float(jnp.finfo(dtype).eps))

    if v_init is None:
        start = seed
    else:
        v_init = v_init.astype(dtype)
        n_i = _gnorm(v_init)
        blended = (v_init / jnp.where(n_i > 0.0, n_i, 1.0)
                   + 0.25 * base_unit)
        start = jnp.where(n_i > 0.0, blended, seed)
    v0 = apply_cov(start)
    n0 = _gnorm(v0)
    v0 = jnp.where(n0 == 0.0, base_unit, v0 / jnp.where(n0 == 0.0, 1.0, n0))

    def cond(state):
        i, _, done = state
        return (i < n_iters) & ~done

    def body(state):
        i, v, _ = state
        w = apply_cov(v)
        n = _gnorm(w)
        w = jnp.where(n == 0.0, v, w / jnp.where(n == 0.0, 1.0, n))
        if no_exit:
            done = jnp.asarray(False)
        else:
            done = jnp.abs(_psum(jnp.vdot(w, v))) >= 1.0 - tol
        return i + 1, w, done

    _, loading, _ = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), v0, jnp.asarray(False)))
    return loading


def _canon_sign_sharded(v, e_start, E_loc):
    """jk.canon_sign across shards: flip so the entry of largest |value|
    (first-index tie-break, globally) is positive."""
    absr = jnp.abs(v)
    li = jnp.argmax(absr)
    lv = absr[li]
    gmax = lax.pmax(lv, "event")
    big = jnp.iinfo(jnp.int32).max
    cand = jnp.where(lv == gmax, e_start + li.astype(jnp.int32), big)
    gidx = lax.pmin(cand, "event")
    mine = (gidx >= e_start) & (gidx < e_start + E_loc)
    local = jnp.clip(gidx - e_start, 0, E_loc - 1)
    sgn = _psum(jnp.where(mine, jnp.sign(v[local]), 0.0))
    return v * jnp.where(sgn == 0.0, 1.0, sgn)


def _guard_div(vec, total):
    """normalize()'s zero-sum guard on an already-summed total."""
    return jnp.where(total == 0.0, vec,
                     vec / jnp.where(total == 0.0, 1.0, total))


def _local_consensus(x_blk, rep, seed, base_unit, p: ConsensusParams,
                     n_event: int, interpret: bool):
    """The per-shard body (runs under shard_map): mirrors
    pipeline._consensus_core_fused with explicit cross-shard psums."""
    from ..ops.pallas_kernels import (resolve_certainty_fused,
                                      storage_matvec, storage_rows_matmat)

    R, E_loc = x_blk.shape
    E_total = E_loc * n_event
    e_start = (lax.axis_index("event") * E_loc).astype(jnp.int32)
    old_rep = jk.normalize(rep)
    acc = old_rep.dtype

    x, fill, tw0, numer0 = _fill_stats(x_blk, old_rep, p.catch_tolerance,
                                       p.storage_dtype, None)
    full0 = jnp.sum(old_rep)
    mu1 = numer0 + (full0 - tw0) * fill            # (E_loc,) local

    def scores_at(rep_k, mu_k, v_init=None):
        """sztorc_scores_power_fused, shard-aware: two kernel passes per
        sweep with one (R,)+scalar psum between, then the direction-fix
        contractions per shard + O(1) psums."""
        denom = 1.0 - jnp.sum(rep_k ** 2)
        denom = jnp.where(denom == 0.0, 1.0, denom)

        def apply_cov(v_loc):
            t_part = storage_matvec(x, v_loc, fill=fill,
                                    interpret=interpret).astype(acc)
            muv_part = mu_k @ v_loc
            t, muv = _psum((t_part, muv_part))
            rt = rep_k * (t - muv)                 # (R,) replicated
            y = storage_rows_matmat(x, rt[None, :], fill=fill,
                                    interpret=interpret)[0].astype(acc)
            return (y - mu_k * jnp.sum(rt)) / denom

        loading = _sharded_power(apply_cov, seed, base_unit,
                                 p.power_iters, p.power_tol, v_init=v_init)
        t_part = storage_matvec(x, loading, fill=fill,
                                interpret=interpret).astype(acc)
        ml_part = mu_k @ loading
        t_raw, ml = _psum((t_part, ml_part))
        W = jnp.stack([t_raw, rep_k.astype(acc), jnp.ones_like(rep_k, acc)])
        qco = storage_rows_matmat(x, W, fill=fill,
                                  interpret=interpret).astype(acc)
        q, o, c = qco[0], qco[1], qco[2]
        scores = t_raw - ml                        # (R,) replicated
        qs = q - ml * c                            # scores^T X, local cols
        a1 = jnp.abs(jnp.min(scores))
        a2 = jnp.max(scores)
        set1 = scores + a1
        set2 = scores - a2
        sum_s = jnp.sum(scores)
        s1_tot = sum_s + R * a1
        s2_tot = sum_s - R * a2
        new1 = _guard_div(qs + a1 * c, s1_tot)
        new2 = _guard_div(qs - a2 * c, s2_tot)
        ref_ind = _psum(jnp.sum((new1 - o) ** 2) - jnp.sum((new2 - o) ** 2))
        return jnp.where(ref_ind <= 0.0, set1, -set2), loading

    if p.max_iterations <= 1:
        adj, loading = scores_at(old_rep, mu1)
        this_rep = jk.row_reward_weighted(adj, old_rep)
        rep_f = jk.smooth(this_rep, old_rep, p.alpha)
        converged = (jnp.max(jnp.abs(rep_f - old_rep))
                     <= p.convergence_tolerance)
        iters = jnp.asarray(1, dtype=jnp.int32)
    else:
        def step(carry, _):
            rep_c, this_prev, loading_prev, conv, it = carry
            adj, loading = scores_at(rep_c, _masked_mu(x, fill, rep_c),
                                     v_init=loading_prev)
            this_rep = jk.row_reward_weighted(adj, rep_c)
            new_rep = jk.smooth(this_rep, rep_c, p.alpha)
            delta = jnp.max(jnp.abs(new_rep - rep_c))
            rep_out = jnp.where(conv, rep_c, new_rep)
            this_out = jnp.where(conv, this_prev, this_rep)
            loading_out = jnp.where(conv, loading_prev, loading)
            it_out = jnp.where(conv, it, it + 1)
            conv_out = conv | (delta <= p.convergence_tolerance)
            return (rep_out, this_out, loading_out, conv_out, it_out), None

        init = (old_rep, old_rep, jnp.zeros((E_loc,), dtype=acc),
                jnp.asarray(False), jnp.asarray(0, dtype=jnp.int32))
        (rep_f, this_rep, loading, converged, iters), _ = lax.scan(
            step, init, None, length=p.max_iterations)

    raw, adjusted, certainty, pcol, prow_part, narow_part = (
        resolve_certainty_fused(x, rep_f, fill, jnp.sum(rep_f),
                                float(p.catch_tolerance),
                                interpret=interpret))
    raw = raw.astype(acc)
    adjusted = adjusted.astype(acc)
    certainty = certainty.astype(acc)
    prow, narow = _psum((prow_part.astype(acc), narow_part))

    participation_columns = (1.0 - pcol).astype(acc)
    cert_sum = _psum(jnp.sum(certainty))
    consensus_reward = _guard_div(certainty, cert_sum)
    participation_rows = 1.0 - _guard_div(prow, cert_sum)
    pc_sum = _psum(jnp.sum(participation_columns))
    percent_na = 1.0 - pc_sum / E_total
    na_bonus_rows = jk.normalize(participation_rows)
    reporter_bonus = (na_bonus_rows * percent_na
                      + rep_f * (1.0 - percent_na))
    na_bonus_cols = _guard_div(participation_columns, pc_sum)
    author_bonus = (na_bonus_cols * percent_na
                    + consensus_reward * (1.0 - percent_na))
    return {
        "old_rep": old_rep,
        "this_rep": this_rep,
        "smooth_rep": rep_f,
        "na_row": narow > 0.0,
        "outcomes_raw": raw,
        "outcomes_adjusted": adjusted,
        "outcomes_final": adjusted,            # binary: no rescale
        "iterations": iters,
        "convergence": converged,
        "first_loading": _canon_sign_sharded(loading, e_start, E_loc),
        "certainty": certainty,
        "consensus_reward": consensus_reward,
        "avg_certainty": cert_sum / E_total,
        "participation_columns": participation_columns,
        "participation_rows": participation_rows,
        "percent_na": percent_na,
        "na_bonus_rows": na_bonus_rows,
        "reporter_bonus": reporter_bonus,
        "na_bonus_cols": na_bonus_cols,
        "author_bonus": author_bonus,
    }


#: result keys that are per-event vectors (stay event-sharded); everything
#: else is an O(R) replicated vector or a scalar
_EVENT_KEYS = frozenset([
    "outcomes_raw", "outcomes_adjusted", "outcomes_final", "certainty",
    "consensus_reward", "participation_columns", "na_bonus_cols",
    "author_bonus", "first_loading",
])


@functools.lru_cache(maxsize=16)
def _seed_placed(mesh: Mesh, E: int, dtype_name: str):
    """Device-resident event-sharded power seed + unit base direction,
    cached per (mesh, E, dtype): these are constants, and per-call
    placement of (E,)-vectors costs ~70-100 ms through the tunneled-TPU
    link at E=100k (see sharded._default_bounds_placed — same
    rationale)."""
    dtype = jnp.dtype(dtype_name)
    e_shard = NamedSharding(mesh, P("event"))
    seed = jax.device_put(jk._power_seed(E, dtype), e_shard)
    base_unit = jax.device_put(seed / jnp.linalg.norm(seed), e_shard)
    return seed, base_unit


@functools.lru_cache(maxsize=32)
def _build(mesh: Mesh, p: ConsensusParams, interpret: bool):
    """One jitted shard-mapped executable per (mesh, params, mode)."""
    n_event = mesh.shape["event"]
    out_specs = {k: (P("event") if k in _EVENT_KEYS else P())
                 for k in [
                     "old_rep", "this_rep", "smooth_rep", "na_row",
                     "outcomes_raw", "outcomes_adjusted", "outcomes_final",
                     "iterations", "convergence", "first_loading",
                     "certainty", "consensus_reward", "avg_certainty",
                     "participation_columns", "participation_rows",
                     "percent_na", "na_bonus_rows", "reporter_bonus",
                     "na_bonus_cols", "author_bonus"]}
    fn = jax.shard_map(
        functools.partial(_local_consensus, p=p, n_event=n_event,
                          interpret=interpret),
        mesh=mesh,
        in_specs=(P(None, "event"), P(), P("event"), P("event")),
        out_specs=out_specs,
        # replication of the P() outputs is established by explicit psums;
        # shard_map's static rep-checker cannot see through the Pallas
        # custom calls, so the check is disabled rather than fought
        check_vma=False,
    )
    return jax.jit(fn)


def fused_sharded_consensus(reports, reputation, mesh: Mesh,
                            p: ConsensusParams):
    """Resolve one large all-binary oracle with the events axis sharded
    over ``mesh`` ON THE FUSED KERNEL PATH (see module docstring).

    ``reports``/``reputation`` must already be placed
    (event-sharded / replicated) by the caller (``sharded_consensus``
    routes here after placement). Returns the light result dict, outputs
    left on device (event vectors sharded)."""
    if p.any_scaled:
        raise ValueError("the sharded fused path is binary-only: scaled "
                         "columns need a cross-shard gather — use the XLA "
                         "path (allow_fused=False or pca_method='power')")
    R, E = reports.shape
    n_event = mesh.shape["event"]
    if E % n_event != 0:
        raise ValueError(f"E={E} not divisible by event axis {n_event}")
    interpret = jax.default_backend() != "tpu"
    acc = jnp.asarray(0.0).dtype
    seed, base_unit = _seed_placed(mesh, E, acc.name)
    return _build(mesh, p, interpret)(reports, reputation, seed, base_unit)
