"""Event-sharded consensus on the FUSED storage kernels (shard_map).

The GSPMD path (``sharded.py``) treats a Pallas kernel as a black box, so
multi-chip meshes previously fell back to XLA matvecs — paying two HBM
passes per power sweep on bf16 storage where the single-device fused path
pays one on int8. This module recovers the kernel path on meshes by
placing the collectives EXPLICITLY: each shard runs the storage kernels
on its local (R, E/n) block under :func:`jax.shard_map`, and the (R,)- or
scalar-sized cross-shard reductions are hand-placed ``psum``\\ s
(docs/SCALING.md's round-4 lever, pulled into round 3).

What is fundamentally different from the single-device fused path: the
one-pass covariance application (``apply_weighted_cov``) fuses ``t = Dv``
and ``y = D^T(rep*t)`` into one HBM sweep, which requires all of ``t``
locally — but on an event-sharded mesh ``t`` is a cross-shard sum, so the
sweep necessarily splits into two kernel passes with a 40 KB (R,) psum
between them (:func:`pallas_kernels.storage_matvec` then
:func:`pallas_kernels.storage_rows_matmat`). The win over the XLA mesh
path is therefore NOT pass count (both pay two) but storage bytes: the
kernels decode int8 sentinel storage in-register, so each pass streams
1-byte elements instead of the XLA path's bf16 — and the entire back half
(outcomes + certainty + participation) stays ONE fused kernel sweep per
shard (its outputs are per-column, hence shard-local).

Scope (gate-enforced by ``sharded._use_fused_resolution``): sztorc,
power-family PCA. Scaled events are handled the same way the
single-device fused path handles them — a statically-counted gather of
the scaled columns re-resolved with the exact sort-based weighted
median — except the gather is SHARD-LOCAL: the event sharding puts every
column wholly on one shard, so each shard re-resolves the scaled columns
it owns and no value ever crosses the mesh (round-4, VERDICT r3 item 1).
A non-divisible event count is closed by padding the matrix with
present-everywhere constant-0.5 binary columns; every cross-column
statistic masks the pad columns out exactly (Python-static masking — the
divisible case compiles to the identical graph as before).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import obs
from ..models.pipeline import ConsensusParams, _fill_stats, _masked_mu
from ..ops import jax_kernels as jk
from ..ops import numpy_kernels as nk
from .mesh import Mesh
from .ring import shard_map

__all__ = ["fused_sharded_consensus"]


def _psum(x):
    return lax.psum(x, "event")


def _gnorm(v):
    """Global L2 norm of an event-sharded vector."""
    return jnp.sqrt(_psum(jnp.sum(v * v)))


def _sharded_power(apply_cov, seed, base_unit, n_iters: int, tol: float,
                   v_init=None):
    """jax_kernels._power_loop with every norm / alignment dot promoted to
    a global (psum) reduction; iterates are the local (E_loc,) slices of
    the global vector. Semantics mirrored exactly: cold start applies the
    covariance to the fixed seed slice; warm ``v_init`` is blended with
    the base direction (see _power_loop's crossing rationale); ``tol < 0``
    disables the early exit."""
    dtype = seed.dtype
    no_exit = tol < 0
    tol = max(float(tol), 8.0 * float(jnp.finfo(dtype).eps))

    if v_init is None:
        start = seed
    else:
        v_init = v_init.astype(dtype)
        n_i = _gnorm(v_init)
        blended = (v_init / jnp.where(n_i > 0.0, n_i, 1.0)
                   + 0.25 * base_unit)
        start = jnp.where(n_i > 0.0, blended, seed)
    v0 = apply_cov(start)
    n0 = _gnorm(v0)
    v0 = jnp.where(n0 == 0.0, base_unit, v0 / jnp.where(n0 == 0.0, 1.0, n0))

    def cond(state):
        i, _, done = state
        return (i < n_iters) & ~done

    def body(state):
        i, v, _ = state
        w = apply_cov(v)
        n = _gnorm(w)
        w = jnp.where(n == 0.0, v, w / jnp.where(n == 0.0, 1.0, n))
        if no_exit:
            done = jnp.asarray(False)
        else:
            done = jnp.abs(_psum(jnp.vdot(w, v))) >= 1.0 - tol
        return i + 1, w, done

    _, loading, _ = lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), v0, jnp.asarray(False)))
    return loading


def _canon_sign_sharded(v, e_start, E_loc):
    """jk.canon_sign across shards: flip so the entry of largest |value|
    (first-index tie-break, globally) is positive."""
    absr = jnp.abs(v)
    li = jnp.argmax(absr)
    lv = absr[li]
    gmax = lax.pmax(lv, "event")
    big = jnp.iinfo(jnp.int32).max
    cand = jnp.where(lv == gmax, e_start + li.astype(jnp.int32), big)
    gidx = lax.pmin(cand, "event")
    mine = (gidx >= e_start) & (gidx < e_start + E_loc)
    local = jnp.clip(gidx - e_start, 0, E_loc - 1)
    sgn = _psum(jnp.where(mine, jnp.sign(v[local]), 0.0))
    return v * jnp.where(sgn == 0.0, 1.0, sgn)


def _guard_div(vec, total):
    """normalize()'s zero-sum guard on an already-summed total."""
    return jnp.where(total == 0.0, vec,
                     vec / jnp.where(total == 0.0, 1.0, total))


def _local_consensus(x_blk, rep, seed, base_unit, bounds,
                     p: ConsensusParams, n_event: int, n_valid: int,
                     interpret: bool):
    """The per-shard body (runs under shard_map): mirrors
    pipeline._consensus_core_fused with explicit cross-shard psums.

    ``bounds`` is ``None`` (all-binary) or the local ``(scaled, mins,
    maxs)`` event-vector slices. ``n_valid`` is the REAL event count: when
    the global (padded) width ``E_loc * n_event`` exceeds it, the trailing
    pad columns (constant 0.5, all present) are masked out of every
    cross-column statistic — exactly, because the masking zeroes their
    contributions before any reduction rather than correcting after."""
    from ..ops.pallas_kernels import (resolve_certainty_fused,
                                      storage_matvec, storage_rows_matmat)

    R, E_loc = x_blk.shape
    e_start = (lax.axis_index("event") * E_loc).astype(jnp.int32)
    old_rep = jk.normalize(rep)
    acc = old_rep.dtype
    needs_pad = n_valid < E_loc * n_event          # Python-static
    valid = ((e_start + jnp.arange(E_loc, dtype=jnp.int32)) < n_valid
             if needs_pad else None)

    raw_blk = x_blk
    if p.any_scaled:
        sc, mn, mx = bounds
        x_blk = jk.rescale(x_blk, sc, mn, mx)      # NaN stays NaN
    x, fill, tw0, numer0 = _fill_stats(x_blk, old_rep, p.catch_tolerance,
                                       p.storage_dtype,
                                       sc if p.any_scaled else None,
                                       interpret=interpret)
    full0 = jnp.sum(old_rep)
    mu1 = numer0 + (full0 - tw0) * fill            # (E_loc,) local
    # matvec_dtype: like sztorc_scores_power_fused, the power sweeps and
    # the scores/direction-fix pass read a narrowed copy of the storage;
    # the back-half kernel reads full storage
    xm = jk.matvec_narrow(x, p.matvec_dtype)

    def scores_at(rep_k, mu_k, v_init=None):
        """sztorc_scores_power_fused, shard-aware: two kernel passes per
        sweep with one (R,)+scalar psum between, then the direction-fix
        contractions per shard + O(1) psums."""
        denom = 1.0 - jnp.sum(rep_k ** 2)
        denom = jnp.where(denom == 0.0, 1.0, denom)

        def apply_cov(v_loc):
            if needs_pad:
                # zeroing the iterate on pad columns keeps the whole
                # power iteration EXACTLY blind to them: their t/muv
                # contributions are 0 * x, and their output entries are
                # re-zeroed so the invariant holds across sweeps
                v_loc = jnp.where(valid, v_loc, 0.0)
            t_part = storage_matvec(xm, v_loc, fill=fill,
                                    interpret=interpret).astype(acc)
            muv_part = mu_k @ v_loc
            t, muv = _psum((t_part, muv_part))
            rt = rep_k * (t - muv)                 # (R,) replicated
            y = storage_rows_matmat(xm, rt[None, :], fill=fill,
                                    interpret=interpret)[0].astype(acc)
            y = (y - mu_k * jnp.sum(rt)) / denom
            return jnp.where(valid, y, 0.0) if needs_pad else y

        loading = _sharded_power(apply_cov, seed, base_unit,
                                 p.power_iters, p.power_tol, v_init=v_init)
        if needs_pad:
            # the degenerate all-zero-covariance branch of _sharded_power
            # falls back to base_unit, which is nonzero on pad columns
            loading = jnp.where(valid, loading, 0.0)
        t_part = storage_matvec(xm, loading, fill=fill,
                                interpret=interpret).astype(acc)
        ml_part = mu_k @ loading
        t_raw, ml = _psum((t_part, ml_part))
        W = jnp.stack([t_raw, rep_k.astype(acc), jnp.ones_like(rep_k, acc)])
        qco = storage_rows_matmat(xm, W, fill=fill,
                                  interpret=interpret).astype(acc)
        q, o, c = qco[0], qco[1], qco[2]
        scores = t_raw - ml                        # (R,) replicated
        qs = q - ml * c                            # scores^T X, local cols
        # sign-canonicalize scores (+ qs, linear in them) before the
        # candidates — identical on every shard since scores is
        # replicated (nk.DIRFIX_TIE_ATOL rationale in numpy_kernels)
        sgn = jk.canon_sign_factor(scores)
        scores = scores * sgn
        qs = qs * sgn
        a1 = jnp.abs(jnp.min(scores))
        a2 = jnp.max(scores)
        set1 = scores + a1
        set2 = scores - a2
        sum_s = jnp.sum(scores)
        s1_tot = sum_s + R * a1
        s2_tot = sum_s - R * a2
        new1 = _guard_div(qs + a1 * c, s1_tot)
        new2 = _guard_div(qs - a2 * c, s2_tot)
        d = (new1 - o) ** 2 - (new2 - o) ** 2
        t = (new1 - o) ** 2 + (new2 - o) ** 2
        if needs_pad:
            d = jnp.where(valid, d, 0.0)
            t = jnp.where(valid, t, 0.0)
        # one stacked psum carries both the decision value and the tie
        # band's scale (nk.DIRFIX_TIE_ATOL — identical rule on every path)
        dt = _psum(jnp.stack([jnp.sum(d), jnp.sum(t)]))
        set1_wins = dt[0] <= nk.DIRFIX_TIE_ATOL * dt[1]
        return jnp.where(set1_wins, set1, -set2), loading

    if p.max_iterations <= 1:
        adj, loading = scores_at(old_rep, mu1)
        this_rep = jk.row_reward_weighted(adj, old_rep)
        rep_f = jk.smooth(this_rep, old_rep, p.alpha)
        converged = (jnp.max(jnp.abs(rep_f - old_rep))
                     <= p.convergence_tolerance)
        iters = jnp.asarray(1, dtype=jnp.int32)
    else:
        def step(carry, _):
            rep_c, this_prev, loading_prev, conv, it = carry
            adj, loading = scores_at(rep_c, _masked_mu(x, fill, rep_c),
                                     v_init=loading_prev)
            this_rep = jk.row_reward_weighted(adj, rep_c)
            new_rep = jk.smooth(this_rep, rep_c, p.alpha)
            delta = jnp.max(jnp.abs(new_rep - rep_c))
            rep_out = jnp.where(conv, rep_c, new_rep)
            this_out = jnp.where(conv, this_prev, this_rep)
            loading_out = jnp.where(conv, loading_prev, loading)
            it_out = jnp.where(conv, it, it + 1)
            conv_out = conv | (delta <= p.convergence_tolerance)
            return (rep_out, this_out, loading_out, conv_out, it_out), None

        init = (old_rep, old_rep, jnp.zeros((E_loc,), dtype=acc),
                jnp.asarray(False), jnp.asarray(0, dtype=jnp.int32))
        (rep_f, this_rep, loading, converged, iters), _ = lax.scan(
            step, init, None, length=p.max_iterations)

    raw, adjusted, certainty, pcol, prow_part, narow_part = (
        resolve_certainty_fused(x, rep_f, fill, jnp.sum(rep_f),
                                float(p.catch_tolerance),
                                interpret=interpret))
    if p.n_scaled:
        # same barrier as the single-device path: keep the scatter updates
        # below from being fused into the kernel's output buffers (that
        # fusion pins (1, E) outputs into scoped VMEM and blows the
        # kernel's budget at scale — pipeline._consensus_core_fused)
        raw, adjusted, certainty, pcol, prow_part, narow_part = (
            lax.optimization_barrier(
                (raw, adjusted, certainty, pcol, prow_part, narow_part)))
    raw = raw.astype(acc)
    adjusted = adjusted.astype(acc)
    certainty = certainty.astype(acc)
    prow_part = prow_part.astype(acc)
    outcomes_final = adjusted                      # binary: no rescale
    if p.n_scaled:
        # scaled columns, shard-locally: the event sharding places every
        # column wholly on one shard, so each shard gathers the scaled
        # columns IT owns and re-resolves them with the exact sort-based
        # weighted median (pipeline._consensus_core_fused semantics; no
        # cross-shard value motion). The static gather capacity is the
        # global count clipped to the shard width; slots beyond this
        # shard's actual scaled count point at E_loc and are dropped by
        # the out-of-bounds scatter mode.
        cap = min(p.n_scaled, E_loc)
        idx = jnp.nonzero(sc, size=cap, fill_value=E_loc)[0]
        mvalid = idx < E_loc
        safe = jnp.clip(idx, 0, E_loc - 1)
        # gather RAW columns and redo the rescale on the slice (not the
        # rescaled intermediate: a second consumer flips XLA's buffering
        # for the kernel operand — see the single-device path's note)
        xs = jk.rescale(raw_blk[:, safe], sc[safe], mn[safe], mx[safe])
        if p.storage_dtype:
            xs = xs.astype(jnp.dtype(p.storage_dtype))  # XLA-path rounding
        xs = xs.astype(acc)
        pres = ~jnp.isnan(xs)
        filled_s = jnp.where(pres, xs, fill[safe].astype(acc)[None, :])
        med = jk.weighted_median_cols(
            filled_s, jnp.broadcast_to(rep_f[:, None], filled_s.shape),
            pres)
        tw_s = jnp.sum(jnp.where(pres, rep_f[:, None], 0.0), axis=0)
        out_s = jnp.where(tw_s > 0.0, med, raw[safe])
        agree_s = jnp.abs(filled_s - out_s[None, :]) <= p.catch_tolerance
        cert_s = jnp.sum(agree_s * rep_f[:, None], axis=0)
        # prow used the kernel's binary certainty for these columns; the
        # correction is shard-local, so apply it BEFORE the psum below
        # (garbage slots contribute an exactly-zero delta)
        delta_cert = jnp.where(mvalid, cert_s - certainty[safe], 0.0)
        prow_part = prow_part + (~pres).astype(acc) @ delta_cert
        certainty = certainty.at[idx].set(cert_s, mode="drop")
        raw = raw.at[idx].set(out_s, mode="drop")
        adjusted = adjusted.at[idx].set(out_s, mode="drop")  # no catch snap
        outcomes_final = adjusted.at[idx].set(
            out_s * (mx[safe] - mn[safe]) + mn[safe], mode="drop")
    if needs_pad:
        # pad columns: all-present constant 0.5, so the kernel reports
        # them fully certain and fully participating (and contributes
        # nothing to prow/narow — they hold no NaN); zero both before any
        # cross-column reduction
        certainty = jnp.where(valid, certainty, 0.0)
        pcol = jnp.where(valid, pcol, 1.0)
    prow, narow = _psum((prow_part, narow_part))

    participation_columns = (1.0 - pcol).astype(acc)
    cert_sum = _psum(jnp.sum(certainty))
    consensus_reward = _guard_div(certainty, cert_sum)
    participation_rows = 1.0 - _guard_div(prow, cert_sum)
    pc_sum = _psum(jnp.sum(participation_columns))
    percent_na = 1.0 - pc_sum / n_valid
    na_bonus_rows = jk.normalize(participation_rows)
    reporter_bonus = (na_bonus_rows * percent_na
                      + rep_f * (1.0 - percent_na))
    na_bonus_cols = _guard_div(participation_columns, pc_sum)
    author_bonus = (na_bonus_cols * percent_na
                    + consensus_reward * (1.0 - percent_na))
    return {
        "old_rep": old_rep,
        "this_rep": this_rep,
        "smooth_rep": rep_f,
        "na_row": narow > 0.0,
        "outcomes_raw": raw,
        "outcomes_adjusted": adjusted,
        "outcomes_final": outcomes_final,
        "iterations": iters,
        "convergence": converged,
        "first_loading": _canon_sign_sharded(loading, e_start, E_loc),
        "certainty": certainty,
        "consensus_reward": consensus_reward,
        "avg_certainty": cert_sum / n_valid,
        "participation_columns": participation_columns,
        "participation_rows": participation_rows,
        "percent_na": percent_na,
        "na_bonus_rows": na_bonus_rows,
        "reporter_bonus": reporter_bonus,
        "na_bonus_cols": na_bonus_cols,
        "author_bonus": author_bonus,
    }


#: result keys that are per-event vectors (stay event-sharded); everything
#: else is an O(R) replicated vector or a scalar
_EVENT_KEYS = frozenset([
    "outcomes_raw", "outcomes_adjusted", "outcomes_final", "certainty",
    "consensus_reward", "participation_columns", "na_bonus_cols",
    "author_bonus", "first_loading",
])


@functools.lru_cache(maxsize=16)
def _seed_placed(mesh: Mesh, E: int, pad: int, dtype_name: str):
    """Device-resident event-sharded power seed + unit base direction,
    cached per (mesh, E, pad, dtype): these are constants, and per-call
    placement of (E,)-vectors costs ~70-100 ms through the tunneled-TPU
    link at E=100k (see sharded._default_bounds_placed — same
    rationale). The seed is ``_power_seed(E)`` — the SAME draw the
    single-device path uses — zero-extended over the pad columns, so the
    padded path's cold start is bitwise the unpadded start (and the
    degenerate-covariance fallback direction is already pad-masked)."""
    dtype = jnp.dtype(dtype_name)
    e_shard = NamedSharding(mesh, P("event"))
    seed = jk._power_seed(E, dtype)
    if pad:
        seed = jnp.concatenate([seed, jnp.zeros((pad,), dtype)])
    seed = jax.device_put(seed, e_shard)
    base_unit = jax.device_put(seed / jnp.linalg.norm(seed), e_shard)
    return seed, base_unit


@functools.lru_cache(maxsize=32)
def _build(mesh: Mesh, p: ConsensusParams, interpret: bool, n_valid: int,
           with_bounds: bool):
    """One jitted shard-mapped executable per (mesh, params, mode, real
    event count, bounds arity)."""
    n_event = mesh.shape["event"]
    out_specs = {k: (P("event") if k in _EVENT_KEYS else P())
                 for k in [
                     "old_rep", "this_rep", "smooth_rep", "na_row",
                     "outcomes_raw", "outcomes_adjusted", "outcomes_final",
                     "iterations", "convergence", "first_loading",
                     "certainty", "consensus_reward", "avg_certainty",
                     "participation_columns", "participation_rows",
                     "percent_na", "na_bonus_rows", "reporter_bonus",
                     "na_bonus_cols", "author_bonus"]}
    kw = dict(p=p, n_event=n_event, n_valid=n_valid, interpret=interpret)
    if with_bounds:
        def body(x_blk, rep, seed, base_unit, sc, mn, mx):
            return _local_consensus(x_blk, rep, seed, base_unit,
                                    (sc, mn, mx), **kw)
        in_specs = (P(None, "event"), P(), P("event"), P("event"),
                    P("event"), P("event"), P("event"))
    else:
        def body(x_blk, rep, seed, base_unit):
            return _local_consensus(x_blk, rep, seed, base_unit, None, **kw)
        in_specs = (P(None, "event"), P(), P("event"), P("event"))
    # replication of the P() outputs is established by explicit psums;
    # shard_map's static rep-checker cannot see through the Pallas
    # custom calls, so the check is disabled rather than fought (the
    # ring module's wrapper also papers over the jax.shard_map /
    # jax.experimental.shard_map location and check_vma/check_rep
    # spelling differences across jax versions)
    fn = shard_map(body, mesh, in_specs, out_specs)
    # retrace observability: the lru_cache above means one wrapper per
    # (mesh, params, ...) build — repeat resolutions of the same config
    # must keep pyconsensus_jit_retraces_total{entry="fused_sharded"}
    # stable (the CL304 invariant, measured at runtime)
    return obs.instrument_jit(jax.jit(fn), "fused_sharded")


def fused_sharded_consensus(reports, reputation, mesh: Mesh,
                            p: ConsensusParams, scaled=None, mins=None,
                            maxs=None):
    """Resolve one large oracle with the events axis sharded over ``mesh``
    ON THE FUSED KERNEL PATH (see module docstring).

    ``reports``/``reputation`` (and, for scaled workloads, the
    ``scaled``/``mins``/``maxs`` event vectors) must already be placed
    (event-sharded / replicated) by the caller (``sharded_consensus``
    routes here after placement). Returns the light result dict, outputs
    left on device (event vectors sharded). A non-divisible event count
    costs one padded copy of the matrix (masked exactly — see
    ``_local_consensus``)."""
    if p.algorithm != "sztorc":
        raise ValueError(
            f"the sharded fused path scores with sztorc power iteration "
            f"only; algorithm={p.algorithm!r} must route through "
            f"sharded_consensus (which gates on this) instead")
    if p.pca_method not in ("power", "power-fused"):
        raise ValueError(
            f"the sharded fused path requires a power-family pca_method, "
            f"got {p.pca_method!r} — an exact-eigh request must not be "
            f"silently swapped for power iteration (use sharded_consensus)")
    if p.storage_dtype == "int8" and p.any_scaled:
        raise ValueError(
            "storage_dtype='int8' supports binary/categorical events only: "
            "scaled columns rescale to continuous values in [0, 1] that "
            "the half-unit int8 lattice would corrupt — use "
            "storage_dtype='bfloat16' for scaled workloads")
    if p.any_scaled:
        if scaled is None or mins is None or maxs is None:
            raise ValueError(
                "any_scaled=True needs the placed (scaled, mins, maxs) "
                "event vectors — sharded_consensus passes them through")
        if p.n_scaled <= 0:
            raise ValueError(
                "any_scaled=True needs the static scaled-column count in "
                "params.n_scaled (sharded_consensus sets it from the "
                "bounds)")
    R, E = reports.shape
    n_event = mesh.shape["event"]
    pad = (-E) % n_event
    interpret = jax.default_backend() != "tpu"
    acc = jnp.asarray(0.0).dtype
    if pad:
        from .mesh import event_sharding

        e_shard = NamedSharding(mesh, P("event"))
        reports = jax.device_put(
            jnp.concatenate(
                [reports, jnp.full((R, pad), 0.5, reports.dtype)], axis=1),
            event_sharding(mesh))
        if p.any_scaled:
            scaled = jax.device_put(
                jnp.concatenate([scaled, jnp.zeros((pad,), scaled.dtype)]),
                e_shard)
            mins = jax.device_put(
                jnp.concatenate([mins, jnp.zeros((pad,), mins.dtype)]),
                e_shard)
            maxs = jax.device_put(
                jnp.concatenate([maxs, jnp.ones((pad,), maxs.dtype)]),
                e_shard)
    seed, base_unit = _seed_placed(mesh, E, pad, acc.name)
    # dispatch-only span (the result stays on device); the per-sweep (R,)
    # psums this path places are counted in wire terms by the ring module
    # when the explicit ring backend is used — here the shard width is
    # the load-bearing attribute
    with obs.span("fused_sharded.dispatch", event_shards=n_event,
                  reporters=R, events=E, padded=bool(pad)):
        if p.any_scaled:
            out = _build(mesh, p, interpret, E, True)(
                reports, reputation, seed, base_unit, scaled, mins, maxs)
        else:
            out = _build(mesh, p, interpret, E, False)(
                reports, reputation, seed, base_unit)
    if pad:
        out = {k: (v[:E] if k in _EVENT_KEYS else v)
               for k, v in out.items()}
    return out
