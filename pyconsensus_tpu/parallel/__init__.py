"""Device-mesh parallelism: event-axis sharding for large oracles (the
long-context analogue, SURVEY.md §5) and batch sharding for sweeps.
XLA/GSPMD inserts the ICI collectives; no hand-written communication."""

from .mesh import (Mesh, NamedSharding, P, batch_event_sharding,
                   event_sharding, make_mesh, replicated)
from .sharded import ShardedOracle, sharded_consensus

__all__ = ["make_mesh", "event_sharding", "batch_event_sharding",
           "replicated", "Mesh", "NamedSharding", "P",
           "ShardedOracle", "sharded_consensus"]
