"""Device-mesh parallelism: event-axis sharding for large oracles (the
long-context analogue, SURVEY.md §5), batch sharding for sweeps, explicit
ring collectives (``ring``), and the multi-host ICI x DCN runtime
(``distributed``). The production path is GSPMD (XLA inserts the ICI
collectives); the ring module is the hand-written backend for panel-wise
accumulation and fixed reduction order."""

from .distributed import (initialize, is_distributed, make_hybrid_mesh,
                          num_slices)
from .mesh import (Mesh, NamedSharding, P, batch_event_sharding,
                   event_sharding, make_mesh, replicated)
from .ring import ring_allreduce, ring_first_pc, ring_gram, ring_matvec
from .sharded import (PlacedBounds, ShardedOracle, place_event_bounds,
                      resolve_auto_storage, resolve_params,
                      sharded_consensus)
from .streaming import streaming_consensus

__all__ = ["make_mesh", "event_sharding", "batch_event_sharding",
           "replicated", "Mesh", "NamedSharding", "P",
           "ShardedOracle", "sharded_consensus", "streaming_consensus",
           "PlacedBounds", "place_event_bounds",
           "resolve_auto_storage", "resolve_params",
           "ring_allreduce", "ring_gram", "ring_matvec", "ring_first_pc",
           "initialize", "is_distributed", "make_hybrid_mesh", "num_slices"]
